# Convenience targets (the Python package needs no build; the native
# library compiles itself on first use into the source-hash cache — the
# `native` target just runs that one real build path eagerly).

.PHONY: all native lint lint-ir lint-threads lint-exchange lint-programs lint-memory mem-smoke plan-check test verify bench bench-gate obs-smoke serve-smoke serve-obs serve-bench serve-slo merge-smoke snapshot-smoke serve-sharded-smoke gas-smoke gas-sharded-smoke exchange-smoke prof-smoke ledger-smoke tune-smoke race-stress chaos-stress clean

all: native

native:
	python -c "from lux_tpu.native.build import load_library; load_library(); print('native library ready')"

lint:
	python tools/luxlint.py

lint-ir:
	python tools/luxlint.py --ir

# Concurrency tier: thread-shared state vs lock guards, the cross-file
# lock-order graph, blocking-under-lock, unjoined threads, publish
# discipline (LUX301-305).
lint-threads:
	python tools/luxlint.py --threads

# Exchange tier: ExchangePlan structure/coverage/profitability proofs
# plus the overlap, sentinel-annihilator, byte-accounting, and
# frontier-coverage dataflow rules over every full+compact+frontier
# sharded registry target (LUX401-407).
lint-exchange:
	python tools/luxlint.py --exchange

# Program-contract tier: prove each registered program's combiner
# identity/exactness, push/pull duality, frontier annihilation, and
# monotone convergence (LUX601-606), assert parity between the derived
# gascap.v1 capability matrix and the committed artifact, and show a
# seeded broken program is caught — all inside a 2s wall budget.
lint-programs:
	env JAX_PLATFORMS=cpu python tools/gasck_smoke.py

# Memory tier: donation-aware buffer-liveness walk over every traced
# registry target deriving per-device peak live bytes and the closed
# footprint model f(nv, ne, P, K, exchange_mode), checked against the
# committed content-addressed memcap.v1 artifact (LUX701-706).
lint-memory:
	env JAX_PLATFORMS=cpu python tools/luxlint.py --memory

# Memory-tier acceptance: registry priced clean inside the 2s proof
# budget, derived memcap.v1 id equal to the committed artifact, the
# seeded LUX702 donation-leak fixture caught, footprint-LRU pool
# eviction with zero warm-hit recompiles, and an over-budget engine
# build shed at the HTTP front end with a typed 503 + Retry-After.
mem-smoke:
	env JAX_PLATFORMS=cpu python tools/memck_smoke.py

plan-check:
	python tools/plan_check.py

test:
	python -m pytest tests/ -q

verify: lint lint-ir lint-threads lint-exchange lint-programs lint-memory mem-smoke plan-check test serve-obs snapshot-smoke serve-sharded-smoke gas-smoke gas-sharded-smoke exchange-smoke prof-smoke ledger-smoke tune-smoke race-stress chaos-stress bench-gate

bench:
	python bench.py

# Regression gate: a fast tiny-graph bench round (CPU-safe, <30s) emits
# bench_gate.v1 JSON and ratchets against the newest BENCH_r0N.json
# baseline with per-metric tolerances (LUX_BENCH_GATE_TOL).
bench-gate:
	env JAX_PLATFORMS=cpu python tools/bench_gate.py --fast

obs-smoke:
	python tools/obs_smoke.py

serve-smoke:
	python tools/serve_smoke.py

# serve-smoke including the observability acceptance: one trace-id
# across admission->batch->engine->cache, Prometheus /metrics, /statusz,
# and a flight.v1 postmortem on an injected deadline miss.
serve-obs:
	python tools/serve_smoke.py

merge-smoke:
	python tools/merge_smoke.py

# Dynamic-graph acceptance: hot-swap under in-flight traffic, FIFO drain
# barrier, incremental cache refresh, zero recompiles, one swap trace-id.
snapshot-smoke:
	python tools/snapshot_smoke.py

# Multi-chip serving acceptance: sharded engines on a virtual 8-way CPU
# mesh behind the warm pool — bitwise parity vs single-chip, hot-swap of
# the whole engine mesh under load, zero recompiles, /statusz mesh view.
serve-sharded-smoke:
	python tools/serve_sharded_smoke.py

# GAS subsystem acceptance: every registry app served over HTTP with
# host-oracle agreement, >= 1 adaptive mid-run direction switch on the
# single-lane BFS, zero recompiles, /statusz direction-split block.
gas-smoke:
	python tools/gas_smoke.py

# Sharded GAS acceptance (LUX_EXCHANGE=frontier): every registry app
# answered from a 2x4 virtual mesh bitwise against the host oracles,
# >= 1 adaptive direction switch on the single-lane BFS, an empty
# mesh-fallback surface (counter at zero), zero recompiles across
# switches and frontier downgrades, and the frontier-vs-compact
# exchange-byte budget report.
gas-sharded-smoke:
	python tools/gas_sharded_smoke.py

# Compacted-exchange acceptance (LUX_EXCHANGE=compact): bitwise parity
# full-vs-compact for SSSP + PageRank on a 2x4 virtual mesh, >= 5x
# exchange-byte drop on the halo locality graph, zero recompiles, and
# a phase-fenced exchange_hidden_frac report.
exchange-smoke:
	python tools/exchange_smoke.py

# Profiler acceptance (obs/prof.py): a REAL jax.profiler capture around
# warm sharded steps parsed by the stdlib profile.v1 parser — both
# region tags classified, interval math consistent, zero recompiles
# with regions armed, /profilez guarded (403/429/200) under a
# concurrent burst, /statusz budget labeling.
prof-smoke:
	python tools/prof_smoke.py

# Observability-ledger acceptance: two-tenant warm HTTP burst with
# LUX_LEDGER_DIR armed — X-Lux-Cost on every reply, /costz totals equal
# to the lux_query_cost_* metric values, crc-clean runrec.v1 records
# whose config_hash reproduces, a CLEAN lux_doctor verdict, zero
# recompiles.
ledger-smoke:
	env JAX_PLATFORMS=cpu python tools/ledger_smoke.py

# Auto-tuner acceptance: seeded synthetic where a known-better
# non-default exchange mode must be selected, real probe records in
# the ledger, luxlint --tune clean over the artifacts, serving warmup
# applying the tuned config with zero recompiles and bitwise-identical
# BFS results, lux_doctor --tuned attribution.
tune-smoke:
	env JAX_PLATFORMS=cpu python tools/tune_smoke.py

# Concurrency acceptance: burst + mid-burst swap + forced compaction
# with LockWatch armed — zero lock-order inversions, zero failed
# queries, zero recompiles, bounded hold-time p99.
race-stress:
	python tools/race_stress.py

# Robustness acceptance: burst with every fault point armed (all
# requests terminal), breaker open->half_open->closed lifecycle, and an
# injected crash recovered bitwise from the WAL with zero steady-state
# recompiles. The WAL torn-write unit tests run under `test`.
chaos-stress:
	python tools/chaos_stress.py

serve-bench:
	python tools/serve_bench.py --scale 12 --workers 16 --duration 10

# SLO gate: bench -> serve_bench.v1 JSON -> compare against the pinned
# baseline (written on first run; commit bench/serve_slo_baseline.json).
serve-slo:
	python tools/serve_bench.py --scale 10 --workers 8 --duration 5 \
		--json-out /tmp/lux_serve_bench.json
	python tools/slo_check.py --input /tmp/lux_serve_bench.json \
		--baseline bench/serve_slo_baseline.json

clean:
	rm -rf build ~/.cache/lux_tpu_native
