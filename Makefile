# Convenience targets (the Python package needs no build; the native
# library compiles itself on first use into the source-hash cache — the
# `native` target just runs that one real build path eagerly).

.PHONY: all native test bench obs-smoke clean

all: native

native:
	python -c "from lux_tpu.native.build import load_library; load_library(); print('native library ready')"

test:
	python -m pytest tests/ -q

bench:
	python bench.py

obs-smoke:
	python tools/obs_smoke.py

clean:
	rm -rf build ~/.cache/lux_tpu_native
