#!/usr/bin/env python3
"""Headline benchmark: PageRank GTEPS on an R-MAT graph, one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline derivation: the reference repo publishes no numbers
(BASELINE.md); its VLDB'17 paper's 8-GPU Twitter-2010 PageRank throughput
is on the order of 10 GTEPS. BASELINE.json's north star is ">=1x the
8xV100 GTEPS on Twitter-2010 PageRank on v5e-8"; this bench runs on ONE
v5e chip, so we report vs_baseline against BASELINE_GTEPS / 8 (the per-GPU
share), keeping the number honest for single-chip hardware.

Knobs (env): LUX_BENCH_SCALE (default 22 → 4.19M vertices, 67.1M edges),
LUX_BENCH_EF (16), LUX_BENCH_ITERS (50), LUX_BENCH_CACHE (.bench_cache),
LUX_BENCH_LAYOUT (tiled|flat), LUX_BENCH_LEVELS (e.g. "8/4" or
"32/8,8/3,2/2"), LUX_BENCH_TILE_MB (strip budget). Hybrid plans are
cached next to the graph (planning is minutes of host np.unique time).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_GTEPS = 10.0      # assumed 8xV100 Twitter-2010 PageRank (see above)
PER_CHIP_BASELINE = BASELINE_GTEPS / 8.0


def get_graph(scale: int, ef: int, cache_dir: str):
    from lux_tpu.graph import generate, read_lux, write_lux

    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"rmat{scale}_{ef}.lux")
    if os.path.exists(path):
        t0 = time.time()
        g = read_lux(path)
        print(f"# loaded cached {path} in {time.time()-t0:.1f}s", file=sys.stderr)
        return g
    t0 = time.time()
    g = generate.rmat(scale, ef, seed=42)
    print(f"# generated rmat{scale} in {time.time()-t0:.1f}s", file=sys.stderr)
    write_lux(path, g)
    return g


def main():
    scale = int(os.environ.get("LUX_BENCH_SCALE", "22"))
    ef = int(os.environ.get("LUX_BENCH_EF", "16"))
    iters = int(os.environ.get("LUX_BENCH_ITERS", "50"))
    cache = os.environ.get("LUX_BENCH_CACHE",
                           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                        ".bench_cache"))

    from lux_tpu.utils.platform import ensure_backend

    platform = ensure_backend()
    print(f"# platform: {platform}", file=sys.stderr)

    g = get_graph(scale, ef, cache)
    from lux_tpu.engine.pull import PullExecutor, hard_sync
    from lux_tpu.models import PageRank

    layout = os.environ.get("LUX_BENCH_LAYOUT", "tiled")
    if layout not in ("tiled", "flat"):
        raise SystemExit(f"LUX_BENCH_LAYOUT must be 'tiled' or 'flat', got {layout!r}")
    if layout == "tiled":
        from lux_tpu.engine.tiled import TiledPullExecutor, get_cached_plan

        budget = int(os.environ.get("LUX_BENCH_TILE_MB", "8192")) << 20
        levels = tuple(
            tuple(int(v) for v in part.split("/"))
            for part in os.environ.get("LUX_BENCH_LEVELS", "8/2").split(",")
        )
        lev_tag = "_".join(f"{r}x{t}" for r, t in levels)
        plan_path = os.path.join(
            cache, f"plan_rmat{scale}_{ef}_{lev_tag}_{budget >> 20}.luxplan"
        )
        t0 = time.time()
        plan = get_cached_plan(
            g, plan_path, levels=levels, budget_bytes=budget,
            log=lambda m: print(f"# {m}", file=sys.stderr),
        )
        print(f"# plan ready ({lev_tag}) in {time.time()-t0:.1f}s",
              file=sys.stderr)
        ex = TiledPullExecutor(g, PageRank(), plan=plan)
        print(
            f"# hybrid plan: {ex.plan.num_strips} strips "
            f"({ex.plan.strip_bytes/1e9:.2f} GB), "
            f"coverage={ex.plan.coverage:.1%}",
            file=sys.stderr,
        )
    else:
        ex = PullExecutor(g, PageRank())
    ex.warmup()

    # Timed: `iters` iterations, async-pipelined, one hard sync at the end
    # (the reference's measurement discipline, pagerank.cc:106-118;
    # hard_sync because block_until_ready returns early on tunneled
    # backends and would fake a ~1000x speedup). The second settle run
    # goes through the vals= path so every jitted helper (including the
    # tiled executor's permutation converters) compiles before t0.
    vals = hard_sync(ex.run(1, flush_every=0))
    vals = hard_sync(ex.run(1, vals=vals, flush_every=0))
    t0 = time.perf_counter()
    vals = ex.run(iters, vals=vals, flush_every=0)
    elapsed = time.perf_counter() - t0

    gteps = g.ne * iters / elapsed / 1e9
    print(
        f"# nv={g.nv} ne={g.ne} iters={iters} elapsed={elapsed:.4f}s "
        f"({elapsed/iters*1e3:.2f} ms/iter)",
        file=sys.stderr,
    )

    # Achieved HBM bandwidth: primary per-iteration byte streams of the
    # executor (strip arrays + per-strip x-row gathers + per-tail-edge
    # row gather and metadata + boundary-extraction gathers + the apply
    # pass), against the v5e spec peak. Attributes regressions: a GTEPS
    # drop with flat GB/s means added bytes; with dropping GB/s, lost
    # pipeline efficiency.
    HBM_PEAK_GBPS = 819.0  # v5e HBM2E spec
    if layout == "tiled":
        p = ex.plan
        tail_edges = p.tail_sb.shape[0]
        nrb_rows = sum(
            p.nvb * (128 // lev.r) for lev in p.levels
        )
        bytes_iter = (
            p.strip_bytes                     # int8 strip reads
            + p.num_strips * 512              # x-block row gather per strip
            + tail_edges * (512 + 5)          # tail row gather + sb/lane
            + (g.nv + 1 + nrb_rows) * 2 * 512  # boundary extraction gathers
            + 4 * g.nv * 4                    # apply + output passes
        )
    else:
        bytes_iter = g.ne * (512 + 8) + 4 * g.nv * 4
    gbps = bytes_iter * iters / elapsed / 1e9
    print(
        json.dumps(
            {
                "metric": f"pagerank_rmat{scale}_gteps_1chip",
                "value": round(gteps, 4),
                "unit": "GTEPS",
                "vs_baseline": round(gteps / PER_CHIP_BASELINE, 4),
                "layout": layout,
                "achieved_gbps": round(gbps, 1),
                "hbm_peak_frac": round(gbps / HBM_PEAK_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
