#!/usr/bin/env python3
"""Headline benchmark + suite. Prints ONE JSON line.

Headline: PageRank GTEPS on R-MAT scale-22, one TPU chip (the
adversarial Kronecker-uniform workload — see PERF.md's hardware-floor
analysis). The ``suite`` key carries single-chip stand-ins for the
remaining BASELINE.json configs (the reference's graphs are not
downloadable here — BASELINE.md):

- pagerank_smallworld22: locality-rich stand-in for the web/social
  configs (Hollywood/Indochina; real graphs cluster, R-MAT's tail does
  not) — same nv/ne as the headline graph.
- sssp_rmat22: the push engine to fixpoint (config 3's shape).
- cc_rmat22: Connected Components on the undirected closure (config 2).
- cf_bipartite: NetFlix-shaped weighted bipartite SGD (config 4),
  exercising the edge-chunked engine (flat contributions exceed HBM).

Baseline derivation: the reference publishes no numbers (BASELINE.md);
its VLDB'17 paper's 8-GPU Twitter-2010 PageRank throughput is on the
order of 10 GTEPS. BASELINE.json's north star is ">=1x the 8xV100
GTEPS on Twitter-2010 PageRank on v5e-8"; this bench runs on ONE v5e
chip, so vs_baseline compares against BASELINE_GTEPS / 8 (the per-GPU
share; see BASELINE.md for the sensitivity discussion).

Output contract (the driver parses stdout): the headline JSON line is
printed IMMEDIATELY after the headline measurement — before the suite
runs — so a timeout mid-suite can never erase the round's number (the
round-2 failure mode: rc=124 with the only print at the very end). If
the suite completes, a second, enriched JSON line with the suite
attached is printed (both lines share the headline schema, so either
first-line or last-line parsing yields a valid result), and the suite
is also written to ``BENCH_SUITE.json`` next to this script. Suite
items run under a wall-clock deadline and are skipped (recorded as
``{"skipped": ...}``) rather than risking the driver's budget.

Knobs (env): LUX_BENCH_SCALE (22), LUX_BENCH_EF (16), LUX_BENCH_ITERS
(50), LUX_BENCH_CACHE (.bench_cache), LUX_BENCH_LAYOUT (tiled|flat),
LUX_BENCH_LEVELS ("8/2"), LUX_BENCH_TILE_MB (8192), LUX_BENCH_SUITE
(1; 0 = headline only), LUX_BENCH_DEADLINE (480 — total seconds of
wall clock after which remaining suite items are skipped),
LUX_GROUPED_TAIL (0; 1 = tiled layout runs the source-block-grouped
merge-network tail instead of lane-select — see PERF.md round-5 and
`make merge-smoke`).

``--profile``: wrap the headline run in a device-timeline capture
window (obs/prof.py) under LUX_PROF_DIR (default
``<cache>/profile``), parse it into a ``profile.v1`` report
(realized_hidden_frac, per-device phase split), log the table, and
write ``profile_v1.json`` next to the trace. A profiled run's GTEPS is
overlap evidence, not a headline record — the capture perturbs the
measurement (PERF.md evidence policy v4).

``--tuned``: GAS suite entries additionally run under their TuneCache
winner (lux_tpu/tune; searched and persisted under ``LUX_TUNE_DIR`` on
first use), emitting ``<name>_tuned`` rows next to the default rows in
the same artifact. The headline JSON carries ``tuned: true/false`` and
the gate context records it (tools/bench_gate.py), so tuned and
default rounds never ratchet against each other.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Persistent XLA compilation cache: the tiled executor's compiles cost
# minutes through the tunneled backend and ate the round-2 driver
# budget; cached executables cut reruns (including the driver's) to
# seconds. Must be set before the backend initializes.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".bench_cache", "xla_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from lux_tpu.obs import (  # noqa: E402
    IterationRecorder, gteps as lux_gteps, ledger,
)

BASELINE_GTEPS = 10.0      # assumed 8xV100 Twitter-2010 PageRank (see above)
PER_CHIP_BASELINE = BASELINE_GTEPS / 8.0


def log(msg: str):
    print(f"# {msg}", file=sys.stderr, flush=True)


class SkipItem(Exception):
    """Raised inside a suite item to record it as skipped (with reason)
    instead of failed."""


def cached_graph(cache_dir: str, name: str, build, remaining: float = 1e9,
                 gen_cost: float = 0.0):
    """Load ``name`` from the bench cache, else generate it — but only
    when ``remaining`` budget covers the estimated first-run ``gen_cost``
    (generation runs on a 2-core host and is the suite's long pole; an
    item must skip cleanly rather than blow the driver's budget
    mid-generation)."""
    from lux_tpu.graph import read_lux, write_lux

    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, name + ".lux")
    if os.path.exists(path):
        t0 = time.time()
        g = read_lux(path)
        log(f"loaded cached {path} in {time.time()-t0:.1f}s")
        return g
    if remaining < gen_cost:
        raise SkipItem(
            f"{name} not cached and est. generation {gen_cost:.0f}s > "
            f"{remaining:.0f}s of remaining budget"
        )
    t0 = time.time()
    g = build()
    log(f"generated {name} in {time.time()-t0:.1f}s")
    write_lux(path, g)
    return g


def _git_head() -> str:
    import subprocess

    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)), timeout=10,
        ).stdout.strip()
    except Exception:
        return "unknown"


def compact_telemetry(summary: dict) -> dict:
    """The run summary with floats rounded for the one-line JSON
    contract (full precision lives in the LUX_METRICS dump)."""
    out = {
        "engine": summary["engine"],
        "num_iters": summary["num_iters"],
        "compile_s": round(summary["compile_s"], 4),
        "execute_s": round(summary["execute_s"], 6),
        "gteps": round(summary["gteps"], 4),
        "iterations": [
            {
                "iter": r["iter"],
                "t_iter_s": round(r["t_iter_s"], 7),
                "t_cum_s": round(r["t_cum_s"], 6),
                **({"frontier": r["frontier"]} if "frontier" in r else {}),
            }
            for r in summary["iterations"]
        ],
    }
    if summary.get("exchange_bytes_per_iter"):
        out["exchange_bytes_per_iter"] = summary["exchange_bytes_per_iter"]
    return out


def tiled_bytes_per_iter(plan, nv: int) -> int:
    """Primary per-iteration HBM byte streams of the tiled executor."""
    tail_edges = plan.tail_sb.shape[0]
    nrb_rows = sum(plan.nvb * (128 // lev.r) for lev in plan.levels)
    return (
        plan.strip_bytes                      # int8 strip reads
        + plan.num_strips * 512               # x-block row gather per strip
        + tail_edges * (512 + 5)              # tail row gather + sb/lane
        + (nv + 1 + nrb_rows) * 2 * 512       # boundary extraction gathers
        + 4 * nv * 4                          # apply + output passes
    )


def bench_pagerank(g, cache: str, tag: str, iters: int, layout: str,
                   levels, budget: int, profile_dir: str = None):
    from lux_tpu.engine.pull import PullExecutor, hard_sync
    from lux_tpu.models import PageRank
    from lux_tpu.obs import prof, report

    if layout == "tiled":
        from lux_tpu.engine.tiled import TiledPullExecutor, get_cached_plan

        lev_tag = "_".join(f"{r}x{t}" for r, t in levels)
        plan_path = os.path.join(
            cache, f"plan_{tag}_{lev_tag}_{budget >> 20}.luxplan"
        )
        t0 = time.time()
        plan = get_cached_plan(
            g, plan_path, levels=levels, budget_bytes=budget, log=log
        )
        log(f"plan ready ({lev_tag}) in {time.time()-t0:.1f}s")
        ex = TiledPullExecutor(g, PageRank(), plan=plan)
        log(
            f"{tag} hybrid plan: {plan.num_strips} strips "
            f"({plan.strip_bytes/1e9:.2f} GB), coverage={plan.coverage:.1%}"
        )
        bytes_iter = tiled_bytes_per_iter(plan, g.nv)
    else:
        ex = PullExecutor(g, PageRank())
        bytes_iter = g.ne * (512 + 8) + 4 * g.nv * 4
    ex.warmup()

    # Timed: `iters` iterations, async-pipelined, one hard sync at the end
    # (the reference's measurement discipline, pagerank.cc:106-118;
    # hard_sync because block_until_ready returns early on tunneled
    # backends and would fake a ~1000x speedup). The second settle run
    # goes through the vals= path so every jitted helper compiles first.
    vals = hard_sync(ex.run(1, flush_every=0))
    vals = hard_sync(ex.run(1, vals=vals, flush_every=0))
    # Explicit recorder: the headline run always carries its iteration
    # telemetry into the JSON line (LUX_METRICS/LUX_TRACE additionally
    # dump it when set). The recorder's execute_s is the measurement —
    # the external bracket would include the recorder's zero-trip
    # compile probe.
    rec = IterationRecorder(
        "tiled" if layout == "tiled" else "pull",
        int(g.nv), int(g.ne), program="PageRank",
    )
    t0 = time.perf_counter()
    # --profile wraps THE headline run in a capture window (a profiled
    # number is a number you can explain; the capture itself perturbs
    # the measurement, so a profiled run's GTEPS is evidence about
    # overlap, not the headline record).
    with prof.trace(profile_dir):
        vals = ex.run(iters, vals=vals, flush_every=0, recorder=rec)
    elapsed = time.perf_counter() - t0
    telemetry = rec.summary()
    if telemetry["execute_s"] > 0:
        elapsed = telemetry["execute_s"]

    gteps = lux_gteps(g.ne, iters, elapsed)
    gbps = bytes_iter * iters / elapsed / 1e9
    log(
        f"{tag}: nv={g.nv} ne={g.ne} iters={iters} elapsed={elapsed:.4f}s "
        f"({elapsed/iters*1e3:.2f} ms/iter, {gteps:.3f} GTEPS, "
        f"{gbps:.0f} GB/s)"
    )
    peak = report.device_profile()["hbm_peak_gbps"]
    out = {
        "gteps": round(gteps, 4),
        "ms_per_iter": round(elapsed / iters * 1e3, 2),
        "achieved_gbps": round(gbps, 1),
        "hbm_peak_frac": round(gbps / peak, 3) if peak else None,
        "telemetry": compact_telemetry(telemetry),
    }
    if profile_dir:
        try:
            rep = prof.parse_dir(profile_dir, steps=iters,
                                 iterlog_summary=telemetry)
            path = os.path.join(profile_dir, "profile_v1.json")
            with open(path, "w") as f:
                json.dump(rep, f, indent=1)
            log(f"profile.v1 -> {path}")
            for line in prof.format_report(rep).splitlines():
                log(line)
            out["profile"] = {
                "realized_hidden_frac": rep["realized_hidden_frac"],
                "path": path,
            }
        except prof.ProfileParseError as e:
            log(f"profile parse failed: {e}")
    return out


def bench_push(g, program, tag: str, max_iters: int, **init_kw):
    """Shared push-app fixpoint bench (SSSP, CC): one timing/GTEPS
    discipline for both."""
    from lux_tpu.engine.push import PushExecutor

    ex = PushExecutor(g, program)
    ex.warmup(**init_kw)
    t0 = time.perf_counter()
    state, iters = ex.run(max_iters=max_iters, **init_kw)
    elapsed = time.perf_counter() - t0
    gteps = lux_gteps(g.ne, iters, elapsed)
    log(
        f"{tag}: {iters} iters ({ex.sparse_iters} sparse) in "
        f"{elapsed:.2f}s ({gteps:.3f} GTEPS)"
    )
    return {
        "gteps": round(gteps, 4),
        "iters": iters,
        "sparse_iters": ex.sparse_iters,
        "ms_per_iter": round(elapsed / max(iters, 1) * 1e3, 2),
    }


def bench_sssp(g, max_iters: int = 12):
    from lux_tpu.models.sssp import SSSP

    return bench_push(g, SSSP(), "sssp", max_iters, start=0)


def bench_cc(g):
    from lux_tpu.models.components import ConnectedComponents

    return bench_push(g, ConnectedComponents(), "cc", 32)


def bench_gas(g, program, tag: str, max_iters: int, **init_kw):
    """Shared GAS-app fixpoint bench (BFS, delta-SSSP, label
    propagation, k-core): the push-bench timing discipline through the
    direction-adaptive executor."""
    from lux_tpu.engine.gas import AdaptiveExecutor

    ex = AdaptiveExecutor(g, program)
    ex.warmup(**init_kw)
    t0 = time.perf_counter()
    state, iters = ex.run(max_iters=max_iters, **init_kw)
    elapsed = time.perf_counter() - t0
    gteps = lux_gteps(g.ne, iters, elapsed)
    log(
        f"{tag}: {iters} iters ({ex.push_iters} push/{ex.pull_iters} "
        f"pull, {ex.direction_switches} switches) in {elapsed:.2f}s "
        f"({gteps:.3f} GTEPS)"
    )
    return {
        "gteps": round(gteps, 4),
        "iters": iters,
        "push_iters": ex.push_iters,
        "direction_switches": ex.direction_switches,
        "ms_per_iter": round(elapsed / max(iters, 1) * 1e3, 2),
    }


def bench_gas_tuned(g, program, app: str, max_iters: int, **init_kw):
    """The bench_gas measurement with engines built under the TuneCache
    winner for (g, app) — searched and persisted on first use, reused
    from the artifact store after. Emitted NEXT TO the default row so
    tuned-vs-default is one artifact; the gate context carries
    ``tuned: true`` so these rounds never ratchet against default ones
    (tools/bench_gate.py)."""
    from lux_tpu.engine.gas import as_gas
    from lux_tpu.obs import report
    from lux_tpu.tune import make_key, tune, tune_cache
    from lux_tpu.utils import flags
    from lux_tpu.utils.checkpoint import fingerprint_hex

    tc = tune_cache()
    if not tc.enabled():
        raise SkipItem("--tuned needs LUX_TUNE_DIR for the artifact store")
    fp = fingerprint_hex(g)
    key = make_key(fp, app, "gas", "1",
                   report.device_profile()["device_kind"])
    art = tc.get(key)
    if art is None:
        log(f"{app}: no tuneconf.v1 for {fp[:12]}..; searching")
        t0 = time.time()
        art = tune(g, as_gas(program), "gas", program_name=app,
                   graph_fingerprint=fp, init_kw=init_kw)
        tc.put(art)
        log(f"{app}: searched {art['id']} in {time.time()-t0:.1f}s")
    log(f"{app}: tuned config {art['id']} score={art['score']:.4g}s/iter "
        f"{art['config']}")
    with flags.overrides(art["config"]):
        res = bench_gas(g, program, f"{app}_tuned", max_iters, **init_kw)
    res["tune_artifact"] = art["id"]
    res["tune_config"] = art["config"]
    return res


def bench_gas_sharded(g, program, tag: str, max_iters: int, **init_kw):
    """Direction-adaptive GAS over the full device mesh (the sharded
    form of bench_gas, LUX_EXCHANGE-sensitive — the gate context keys
    on the mode). Skipped on a single device, where the exchange is
    inert and the number would just alias bench_gas."""
    import jax

    from lux_tpu.engine.gas_sharded import ShardedAdaptiveExecutor

    if jax.device_count() < 2:
        raise SkipItem("needs >= 2 devices for a sharded mesh")
    ex = ShardedAdaptiveExecutor(g, program,
                                 num_parts=jax.device_count())
    ex.warmup(**init_kw)
    t0 = time.perf_counter()
    state, iters = ex.run(max_iters=max_iters, **init_kw)
    elapsed = time.perf_counter() - t0
    gteps = lux_gteps(g.ne, iters, elapsed)
    log(
        f"{tag}: P={ex.num_parts} exchange={ex.exchange_mode}: {iters} "
        f"iters ({ex.push_iters} push/{ex.pull_iters} pull, "
        f"{ex.direction_switches} switches, {ex.exchange_downgrades} "
        f"downgrades) in {elapsed:.2f}s ({gteps:.3f} GTEPS)"
    )
    return {
        "gteps": round(gteps, 4),
        "iters": iters,
        "push_iters": ex.push_iters,
        "direction_switches": ex.direction_switches,
        "exchange_downgrades": ex.exchange_downgrades,
        "exchange_mode": ex.exchange_mode,
        "exchange_bytes_per_iter": ex.exchange_bytes_per_iter(),
        "ms_per_iter": round(elapsed / max(iters, 1) * 1e3, 2),
    }


def bench_cf(g, iters: int = 5):
    from lux_tpu.engine.pull import PullExecutor, hard_sync
    from lux_tpu.models.colfilter import CollaborativeFiltering

    ex = PullExecutor(g, CollaborativeFiltering())
    log(f"cf: edge_chunk={ex.edge_chunk}")
    ex.warmup()
    vals = hard_sync(ex.run(1, flush_every=0))
    t0 = time.perf_counter()
    vals = ex.run(iters, vals=vals, flush_every=0)
    elapsed = time.perf_counter() - t0
    gteps = lux_gteps(g.ne, iters, elapsed)
    log(
        f"cf: nv={g.nv} ne={g.ne} {iters} iters, "
        f"{elapsed/iters*1e3:.1f} ms/iter ({gteps:.3f} GTEPS)"
    )
    return {
        "gteps": round(gteps, 4),
        "ms_per_iter": round(elapsed / iters * 1e3, 2),
        "edge_chunked": bool(ex.edge_chunk),
    }


def main():
    t_start = time.monotonic()
    from lux_tpu.utils import flags

    scale = flags.get_int("LUX_BENCH_SCALE")
    ef = flags.get_int("LUX_BENCH_EF")
    iters = flags.get_int("LUX_BENCH_ITERS")
    cache = flags.get("LUX_BENCH_CACHE") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".bench_cache"
    )
    layout = flags.get("LUX_BENCH_LAYOUT")
    if layout not in ("tiled", "flat"):
        raise SystemExit(f"LUX_BENCH_LAYOUT must be tiled|flat, got {layout!r}")
    budget = flags.get_int("LUX_BENCH_TILE_MB") << 20
    levels = tuple(
        tuple(int(v) for v in part.split("/"))
        for part in flags.get("LUX_BENCH_LEVELS").split(",")
    )
    run_suite = flags.get_bool("LUX_BENCH_SUITE")
    deadline = flags.get_float("LUX_BENCH_DEADLINE")

    profile_dir = None
    if "--profile" in sys.argv[1:]:
        profile_dir = flags.get("LUX_PROF_DIR") or os.path.join(
            cache, "profile")
        log(f"profiling the headline run -> {profile_dir}")
    # --tuned: GAS suite entries additionally run under their TuneCache
    # winner (lux_tpu/tune), tuned rows next to the default ones in the
    # same artifact. The headline JSON carries tuned: true/false so the
    # gate never ratchets tuned and default rounds against each other.
    tuned_mode = "--tuned" in sys.argv[1:]
    if tuned_mode and not flags.get("LUX_TUNE_DIR"):
        raise SystemExit("--tuned needs LUX_TUNE_DIR (the tuneconf.v1 "
                         "artifact store)")

    from lux_tpu.utils.platform import ensure_backend

    log(f"platform: {ensure_backend()}")
    from lux_tpu.obs import report as obs_report

    # Chip identity for the gate's context block: baselines recorded on
    # a different device_kind never ratchet this run (tools/bench_gate.py).
    log(f"device_kind: {obs_report.device_profile()['device_kind']}")

    from lux_tpu.graph import generate

    g = cached_graph(
        cache, f"rmat{scale}_{ef}",
        lambda: generate.rmat(scale, ef, seed=42),
    )
    head = bench_pagerank(
        g, cache, f"rmat{scale}_{ef}", iters, layout, levels, budget,
        profile_dir=profile_dir,
    )

    out = {
        "metric": f"pagerank_rmat{scale}_gteps_1chip",
        "value": head["gteps"],
        "unit": "GTEPS",
        "vs_baseline": round(head["gteps"] / PER_CHIP_BASELINE, 4),
        "layout": layout,
        "achieved_gbps": head["achieved_gbps"],
        "hbm_peak_frac": head["hbm_peak_frac"],
        "tuned": tuned_mode,
        # Iteration telemetry of THE headline measurement (per-iteration
        # walls + compile/execute split), so the round artifact shows
        # not just the number but where the time went.
        "telemetry": head.get("telemetry"),
    }
    # The round's number goes out BEFORE the suite runs (see module
    # docstring) — mirrors the reference's always-printed ELAPSED TIME
    # (pagerank/pagerank.cc:115-118).
    print(json.dumps(out), flush=True)

    # Durable evidence: the headline as one runrec.v1 observation (the
    # A/B corpus tools/lux_doctor.py attributes regressions from). The
    # headline recorder goes through summary(), not finish(), so the
    # report.finalize feed-in never fires for it — this is its only
    # ledger entry. rmat{scale}_{ef} is a deterministic seeded graph, a
    # faithful fingerprint.
    tel = head.get("telemetry") or {}
    ledger.record_run(
        "bench_headline",
        {"gteps": head["gteps"], "achieved_gbps": head["achieved_gbps"],
         "hbm_peak_frac": head["hbm_peak_frac"],
         "compile_s": tel.get("compile_s"),
         "execute_s": tel.get("execute_s"),
         "nv": int(g.nv), "ne": int(g.ne)},
        graph_fingerprint=f"rmat{scale}_{ef}",
        program="PageRank", engine_kind=layout,
    )

    if run_suite:
        suite = {}

        def remaining():
            return deadline - (time.monotonic() - t_start)

        def suite_item(name, fn):
            if remaining() < 0:
                log(f"suite[{name}] skipped: past the "
                    f"{deadline:.0f}s deadline")
                suite[name] = {"skipped": "deadline"}
                return
            try:
                res = fn()
                # Suite items stay lean — full telemetry rides only on
                # the headline (and in LUX_METRICS dumps when set).
                res.pop("telemetry", None)
                suite[name] = res
                ledger.record_run(
                    "bench_suite",
                    {k: v for k, v in res.items()
                     if isinstance(v, (int, float))},
                    graph_fingerprint=f"suite-rmat{scale}_{ef}",
                    program=name, engine_kind=layout,
                )
            except SkipItem as e:
                log(f"suite[{name}] skipped: {e}")
                suite[name] = {"skipped": str(e)}
            except Exception as e:  # a broken suite item must not kill
                log(f"suite[{name}] FAILED: {e!r}")  # the gate
                suite[name] = {"error": repr(e)}

        # First-run generation cost estimates (2-core host, measured
        # order of magnitude at scale 22) for the budget gate.
        gen_cost = 60.0 * (1 << scale) / (1 << 22)

        def run_smallworld():
            nv_sw = 1 << scale
            g_sw = cached_graph(
                cache, f"smallworld{scale}_{ef}",
                lambda: generate.small_world(
                    nv_sw, k=ef, p_rewire=0.05, seed=7
                ),
                remaining=remaining(), gen_cost=gen_cost,
            )
            return bench_pagerank(
                g_sw, cache, f"smallworld{scale}_{ef}", iters, layout,
                levels, budget,
            )

        def run_cf():
            # NetFlix-shaped at the default scale (480K users x 17.8K
            # items x 50M ratings x 2 directions = 100M edges); shrinks
            # with LUX_BENCH_SCALE so smoke runs stay quick.
            n_users = min(480_000, 1 << max(scale - 3, 1))
            n_items = max(n_users // 27, 64)
            n_ratings = 12 << scale
            g_cf = cached_graph(
                cache, f"cf_netflix_like_{scale}",
                lambda: generate.bipartite_ratings(
                    n_users, n_items, n_ratings, seed=11
                ),
                remaining=remaining(), gen_cost=2 * gen_cost,
            )
            return bench_cf(g_cf)

        def run_cc():
            # Connected Components runs on the undirected closure (the
            # reference's example feeds CC an undirected graph and its
            # max-label propagation assumes symmetry — components.py).
            g_u = cached_graph(
                cache, f"rmat{scale}_{ef}_undirected",
                lambda: generate.undirected(g),
                remaining=remaining(), gen_cost=2 * gen_cost,
            )
            return bench_cc(g_u)

        def run_sssp_delta():
            from lux_tpu.models.sssp_delta import DeltaSSSP

            g_w = cached_graph(
                cache, f"rmat{scale}_{ef}_weighted",
                lambda: generate.rmat(scale, ef, seed=42, weighted=True),
                remaining=remaining(), gen_cost=gen_cost,
            )
            return bench_gas(g_w, DeltaSSSP(), "sssp_delta", 32, start=0)

        def run_bfs():
            from lux_tpu.models.bfs import BFS

            return bench_gas(g, BFS(), "bfs", 32, start=0)

        def run_labelprop():
            from lux_tpu.models.labelprop import LabelPropagation

            return bench_gas(g, LabelPropagation(), "labelprop", 16)

        def run_kcore():
            from lux_tpu.models.kcore import KCore

            # Coreness is an undirected notion — reuse the CC closure
            # (cache hit after cc_rmat generates it).
            g_u = cached_graph(
                cache, f"rmat{scale}_{ef}_undirected",
                lambda: generate.undirected(g),
                remaining=remaining(), gen_cost=2 * gen_cost,
            )
            return bench_gas(g_u, KCore(k=4), "kcore", 32)

        suite_item("sssp_rmat", lambda: bench_sssp(g))
        suite_item("pagerank_smallworld", run_smallworld)
        suite_item("cc_rmat", run_cc)
        suite_item("cf_bipartite", run_cf)
        # GAS-engine apps (PR 12) join the ratchet so direction-adaptive
        # regressions gate like everything else.
        suite_item("bfs_rmat", run_bfs)
        suite_item("sssp_delta_rmat", run_sssp_delta)
        suite_item("labelprop_rmat", run_labelprop)
        suite_item("kcore_rmat", run_kcore)
        if tuned_mode:
            # Tuned rows ride the same suite (and the same ledger), so
            # one artifact answers "what did the tuner buy" per app.
            from lux_tpu.models.bfs import BFS
            from lux_tpu.models.labelprop import LabelPropagation

            suite_item("bfs_rmat_tuned",
                       lambda: bench_gas_tuned(g, BFS(), "bfs", 32,
                                               start=0))
            suite_item("labelprop_rmat_tuned",
                       lambda: bench_gas_tuned(g, LabelPropagation(),
                                               "labelprop", 16))
        # Mesh GAS (PR 17): the direction-adaptive engine over every
        # available device; runs only on a real multi-device backend
        # (virtual-CPU mesh evidence lives in `make gas-sharded-smoke`
        # and tools/bench_sharded.py — wall time there measures
        # dispatch, not scaling).
        def run_bfs_sharded():
            from lux_tpu.models.bfs import BFS

            return bench_gas_sharded(g, BFS(), "bfs_sharded", 32,
                                     start=0)

        suite_item("bfs_sharded_rmat", run_bfs_sharded)
        # Deadline-skipped items fall back to the most recent completed
        # measurement of the SAME code (git HEAD match), clearly labeled
        # — tunnel upload/compile throughput varies run to run, and a
        # skip would otherwise erase a measured capability from the
        # round artifact.
        head = _git_head()
        prior = {}
        cache_f = os.path.join(cache, "suite_results.json")
        try:
            with open(cache_f) as f:
                prior = json.load(f)
        except Exception:
            prior = {}
        for name, res in suite.items():
            key = f"{name}@{scale}_{ef}_{layout}"
            if "gteps" in res:
                prior[key] = {
                    "head": head, "at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                      time.gmtime()),
                    "result": res,
                }
            elif "skipped" in res and prior.get(key, {}).get("head") == head:
                suite[name] = dict(
                    prior[key]["result"],
                    cached_same_commit_run=prior[key]["at"],
                )
        try:
            with open(cache_f, "w") as f:
                json.dump(prior, f, indent=1)
        except OSError:
            pass
        out["suite"] = suite
        # Co-headline (VERDICT r2 #9): the locality-rich counterpart to
        # the adversarial Kronecker headline, surfaced at top level.
        sw = suite.get("pagerank_smallworld", {})
        if "gteps" in sw:
            out["smallworld_gteps"] = sw["gteps"]

        side = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_SUITE.json"
        )
        try:
            with open(side, "w") as f:
                json.dump(out, f, indent=1)
        except OSError as e:
            log(f"could not write {side}: {e}")
        # Enriched final line, same schema as the first — a parser taking
        # either the first or the last JSON line gets a valid headline.
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
