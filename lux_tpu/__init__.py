"""lux_tpu — a TPU-native distributed graph-processing framework.

A from-scratch rebuild of the capability set of Lux (Jia et al., VLDB'17;
reference sources under /root/reference) designed for TPUs:

- vertex programs in two execution models: **pull** (gather-apply over all
  vertices) and **push** (frontier-driven relaxation with adaptive
  direction switching), expressed as jitted XLA computations instead of
  CUDA kernels;
- **edge-balanced contiguous partitioning** of the vertex space
  (reference: core/pull_model.inl:108-131) mapped onto a
  `jax.sharding.Mesh`, with ghost-vertex exchange via ICI collectives
  (`all_gather`) instead of Legion zero-copy memory;
- the four reference applications — PageRank, SSSP, Connected Components,
  Collaborative Filtering — plus the `.lux` binary CSC graph format and
  an edge-list converter (reference: tools/converter.cc).

Layout:
    lux_tpu.graph     — .lux format, Graph data model, partitioner, generators
    lux_tpu.ops       — segment reductions and Pallas kernels (device compute)
    lux_tpu.parallel  — mesh construction, sharded graph layout, exchange
    lux_tpu.engine    — pull/push executors, invariant checkers
    lux_tpu.models    — the applications (vertex programs + CLI drivers)
    lux_tpu.utils     — config/flags, logging, timing, checkpointing
    lux_tpu.native    — C++ fast paths for IO (converter, loader, CSR build)
"""

__version__ = "0.1.0"

from lux_tpu.graph.graph import Graph  # noqa: F401
from lux_tpu.graph.partition import edge_balanced_bounds  # noqa: F401
