"""Project-native static analysis (luxlint) + runtime discipline sentinels.

Static side (stdlib-only, no jax import — ``tools/luxlint.py`` must lint
the tree in well under a second per file):

- :mod:`lux_tpu.analysis.core` — rule engine: ``Rule``/``Finding``,
  inline ``# luxlint: disable=RULE`` suppressions, JSON + human output;
- :mod:`lux_tpu.analysis.rules` — the rule set targeting this repo's
  real failure modes (host syncs in engine hot loops, recompile hygiene,
  kernel BlockSpec layout contracts, the LUX_* env-flag registry);
- :mod:`lux_tpu.analysis.threads` — the concurrency tier (LUX301-305):
  thread-shared state vs lock guards, the cross-file lock-order graph,
  blocking-under-lock, unjoined threads, and atomic-publish discipline.
  Its runtime twin is ``lux_tpu/utils/locks.py`` (LockWatch);
- :mod:`lux_tpu.analysis.gasck` — the program-algebra tier (LUX601-606,
  ``luxlint --programs``): proves each registry program's combiner
  identity/exactness, push<->pull duality, monotone convergence, and
  frontier annihilation on seeded probes, derives the capability matrix
  as a content-addressed ``gascap.v1`` artifact, and flags declaration
  drift. numpy at import; jax only through the program hooks (import it
  lazily from stdlib-only callers).

Runtime side (imports jax; import it lazily):

- :mod:`lux_tpu.analysis.sentinel` — ``RecompileSentinel`` (per-key XLA
  compile counts; serve/pool.py's zero-recompiles-after-warmup evidence)
  and ``HostTransferGuard`` (fails tests that device-transfer inside a
  guarded iteration region).
"""

from lux_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintReport,
    Rule,
    run_paths,
    run_source,
)
from lux_tpu.analysis.rules import all_rules  # noqa: F401
from lux_tpu.analysis.threads import (  # noqa: F401
    all_thread_rules,
    run_threads,
)
