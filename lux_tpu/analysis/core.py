"""luxlint rule engine: findings, suppressions, file runner, output.

Deliberately stdlib-only (ast + re + json): ``make lint`` walks ~90
files and must finish in seconds, so nothing here may import jax or
numpy. Rules are AST visitors over one file at a time; cross-file state
(the declared-flag set) is loaded once per run and handed to rules via
:class:`FileContext`.

Suppressions are inline, per line, per rule::

    jax.device_get(x)  # luxlint: disable=LUX001 -- one batched sync/chunk

``disable=all`` silences every rule on that line. A comment-only line
directly above the finding also counts (multi-line calls put the marker
where it reads best). Suppressed findings are counted and reported —
silence is visible, never free.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*luxlint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s*--.*)?$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str       # rule id, e.g. LUX001
    path: str       # file path as given to the runner
    line: int       # 1-based
    col: int        # 0-based
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileContext:
    """Per-file state handed to every rule's ``check``."""

    def __init__(self, path: str, source: str,
                 declared_flags: Optional[Set[str]] = None):
        self.path = path
        # Rules scope by path fragment (e.g. "engine/"); normalize so the
        # same rule set works on Windows-style separators and relpaths.
        self.posix_path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.declared_flags = declared_flags if declared_flags is not None \
            else set()


class Rule:
    """One lint rule: an id, a one-line doc, and an AST check."""

    id = "LUX000"
    title = "base rule"
    doc = ""

    def applies_to(self, ctx: FileContext) -> bool:
        return True

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, ctx.path, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


def suppressions_for(lines: Sequence[str]) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of suppressed rule ids ({'all'}
    for blanket disables). A comment-only line extends its suppression
    to the following line."""
    out: Dict[int, Set[str]] = {}
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
        out.setdefault(i, set()).update(ids)
        if raw.lstrip().startswith("#"):
            out.setdefault(i + 1, set()).update(ids)
    return out


def _is_suppressed(f: Finding, supp: Dict[int, Set[str]]) -> bool:
    ids = supp.get(f.line)
    return bool(ids) and ("all" in ids or f.rule in ids)


@dataclasses.dataclass
class FileResult:
    path: str
    findings: List[Finding]
    suppressed: List[Finding]
    error: Optional[str] = None   # syntax/read error, reported as-is


def run_source(source: str, path: str, rules: Sequence[Rule],
               declared_flags: Optional[Set[str]] = None) -> FileResult:
    ctx = FileContext(path, source, declared_flags)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return FileResult(path, [], [], error=f"{path}:{e.lineno}: {e.msg}")
    supp = suppressions_for(ctx.lines)
    kept: List[Finding] = []
    quiet: List[Finding] = []
    for rule in rules:
        if not rule.applies_to(ctx):
            continue
        for f in rule.check(tree, ctx):
            (quiet if _is_suppressed(f, supp) else kept).append(f)
    kept.sort(key=lambda f: (f.line, f.col, f.rule))
    return FileResult(path, kept, quiet)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "build")
                )
                out.extend(
                    os.path.join(root, f) for f in sorted(files)
                    if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return out


@dataclasses.dataclass
class LintReport:
    results: List[FileResult]
    elapsed_s: float
    # The AST tier reports "luxlint.v1"; the jaxpr tier (analysis/ir.py)
    # and the plan-artifact tier (analysis/planck.py) stamp their own
    # schemas so one grep distinguishes which pass produced a line.
    schema: str = "luxlint.v1"

    @property
    def findings(self) -> List[Finding]:
        return [f for r in self.results for f in r.findings]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for r in self.results for f in r.suppressed]

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self.results if r.error]

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def summary(self) -> dict:
        """One-line greppable summary payload (the merge_smoke idiom)."""
        by_rule: Dict[str, int] = {}
        for f in self.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {
            "schema": self.schema,
            "files": len(self.results),
            "findings": len(self.findings),
            "suppressed": len(self.suppressed),
            "errors": len(self.errors),
            "by_rule": dict(sorted(by_rule.items())),
            "elapsed_s": round(self.elapsed_s, 3),
            "ok": self.ok,
        }

    def to_json(self) -> str:
        return json.dumps({
            "summary": self.summary(),
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
            "errors": self.errors,
        }, indent=2, sort_keys=True)

    def format_human(self) -> str:
        lines = [f.format() for f in self.findings]
        lines.extend(f"{e} (syntax error)" for e in self.errors)
        s = self.summary()
        lines.append(
            f"luxlint: {s['files']} files, {s['findings']} findings "
            f"({s['suppressed']} suppressed) in {s['elapsed_s']}s"
        )
        return "\n".join(lines)


def load_declared_flags() -> Set[str]:
    """Declared LUX_* flag names from the central registry.

    flags.py is stdlib-only by contract, so importing it is cheap and
    keeps the lint's view identical to the runtime's."""
    from lux_tpu.utils import flags

    return set(flags.names())


def run_paths(paths: Sequence[str], rules: Sequence[Rule],
              declared_flags: Optional[Set[str]] = None) -> LintReport:
    t0 = time.perf_counter()
    if declared_flags is None:
        declared_flags = load_declared_flags()
    results = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            results.append(FileResult(path, [], [], error=f"{path}: {e}"))
            continue
        results.append(run_source(source, path, rules, declared_flags))
    return LintReport(results, time.perf_counter() - t0)
