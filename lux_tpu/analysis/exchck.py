"""exchck: static verifier for ExchangePlan tables (the exchange tier).

The compact exchange path (LUX_EXCHANGE=compact, PR 13) replaces the
full per-part all-gather with a packed ``all_to_all`` driven by pure
data — the ``ExchangePlan`` tables in graph/partition.py — that the
unchanged compute bodies then trust blindly. A wrong table silently
corrupts results (a dropped row reads the zero-filled receive buffer;
a misrouted row reads a neighbor's value), so the tables are verified
statically, as a full proof rather than the bitwise-parity smoke's
sampling:

- LUX401 structure: scalar bounds (capacity/max_units/unit_rows >= 1),
  static table shapes ``(P, P*capacity)``, integer dtypes, capacity
  holds the densest (sender, receiver) pair, diagonal pairs all
  sentinel, and prefix density — the first ``counts[q, p]`` slots of a
  pair are real, every later slot is the sentinel on BOTH sides, so pad
  traffic and real traffic can never share a slot.
- LUX402 coverage/conservation: per off-diagonal pair the real send
  rows are strictly ascending (hence each sent exactly once) and
  ``recv_pos`` scatters row r of sender p to flat index
  ``p * max_units + r`` — exactly where the unchanged compute bodies
  index — with all real receive positions distinct per receiver. With
  ``remote_read_counts`` attached, ``counts * unit_rows`` must equal
  that matrix elementwise: every remote row the receiver's real edges
  read crosses the wire exactly once. Together these are a permutation
  proof, not a sample.
- LUX403 profitability-honesty: the packed bytes the plan prices
  (``exchanged_units_per_iter * unit_rows * row_bytes``) must equal the
  executor's declared ``exchange_bytes_per_iter``, the ``profitable``
  claim must match ``capacity < max_units``, and the exchange ledger's
  ``useful_bytes_per_iter`` model (obs/engobs.useful_exchange) must
  re-derive from the counts matrix — so the advertised packed-vs-useful
  ratio can never drift from the code that computes it.
- LUX407 frontier-coverage (plans carrying frontier evidence only —
  the LUX_EXCHANGE=frontier activity-packed send): the frontier
  capacity must fit inside the static compact capacity (the frontier
  send reuses the compact plan's routing, only shorter), the
  executor's per-pair send slots must fit that capacity, the packer
  must never truncate active rows (``frontier_fill_active == 0`` — a
  dense frontier downgrades to the compact send instead of dropping
  rows), and the advertised frontier bytes must re-derive from
  ``P * (P-1) * slots * frontier_row_bytes``.

numpy + stdlib only, mirroring planck.py: plans are host arrays and a
verifier must not drag in jax. The IR half of the tier (LUX404-406,
dependence-walk rules over the traced step) lives in analysis/ir.py.
"""

from __future__ import annotations

import importlib.util
import json
import os
import time
import types
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from lux_tpu.analysis.core import FileResult, Finding, LintReport

EXCHANGE_SCHEMA = "luxlint-exchange.v1"

# Mirror of the artifact format (graph/partition.EXCHANGE_PLAN_ARRAYS /
# EXCHANGE_PLAN_FORMAT). Duplicated on purpose, like planck's mirror of
# the grouped-plan format: this module must verify saved artifacts from
# a cold jax-free interpreter. tests/test_exchck.py asserts the two
# stay identical.
EXCH_ARRAYS = ("counts", "send_units", "recv_pos")
EXCH_FORMAT = 1


def plan_view(plan, remote_read_counts=None, row_bytes: Optional[int] = None,
              declared_bytes_per_iter: Optional[int] = None,
              ledger: Optional[dict] = None,
              frontier_capacity: Optional[int] = None,
              frontier_max_sends: Optional[int] = None,
              frontier_row_bytes: Optional[int] = None,
              frontier_bytes_per_iter: Optional[int] = None,
              frontier_fill_active: Optional[int] = None
              ) -> types.SimpleNamespace:
    """Wrap an in-memory ExchangePlan (or anything attribute-compatible)
    plus optional evidence into the namespace the LUX40x rules read.

    ``remote_read_counts`` is the ShardedGraph value-row matrix (LUX402
    conservation); ``row_bytes``/``declared_bytes_per_iter``/``ledger``
    feed the LUX403 pricing checks; the ``frontier_*`` fields are the
    adaptive GAS engine's frontier-exchange evidence (LUX407). Evidence
    left as None skips only the checks that need it."""
    def _i(x):
        return None if x is None else int(x)

    return types.SimpleNamespace(
        num_parts=int(plan.num_parts),
        max_units=int(plan.max_units),
        unit_rows=int(plan.unit_rows),
        capacity=int(plan.capacity),
        counts=np.asarray(plan.counts),
        send_units=np.asarray(plan.send_units),
        recv_pos=np.asarray(plan.recv_pos),
        profitable=bool(getattr(plan, "profitable",
                                int(plan.capacity) < int(plan.max_units))),
        remote_read_counts=(None if remote_read_counts is None
                            else np.asarray(remote_read_counts)),
        row_bytes=_i(row_bytes),
        declared_bytes_per_iter=_i(declared_bytes_per_iter),
        ledger=dict(ledger) if ledger is not None else None,
        frontier_capacity=_i(frontier_capacity),
        frontier_max_sends=_i(frontier_max_sends),
        frontier_row_bytes=_i(frontier_row_bytes),
        frontier_bytes_per_iter=_i(frontier_bytes_per_iter),
        frontier_fill_active=_i(frontier_fill_active),
    )


def load_exchange_artifact(path: str, mmap: bool = True
                           ) -> types.SimpleNamespace:
    """jax-free loader for a saved exchange-plan directory
    (graph/partition.save_exchange_artifact)."""
    with open(os.path.join(path, "meta.json")) as fh:
        meta = json.load(fh)
    if meta.get("format") != EXCH_FORMAT:
        raise ValueError(
            f"exchange plan {path}: unknown format {meta.get('format')}")
    arrs = {
        name: np.load(os.path.join(path, name + ".npy"),
                      mmap_mode="r" if mmap else None,
                      allow_pickle=False)
        for name in EXCH_ARRAYS
    }
    rrc_path = os.path.join(path, "remote_read_counts.npy")
    rrc = (np.load(rrc_path, mmap_mode="r" if mmap else None,
                   allow_pickle=False)
           if os.path.exists(rrc_path) else None)
    view = plan_view(
        types.SimpleNamespace(
            num_parts=meta["num_parts"], max_units=meta["max_units"],
            unit_rows=meta["unit_rows"], capacity=meta["capacity"],
            profitable=meta.get(
                "profitable",
                int(meta["capacity"]) < int(meta["max_units"])),
            **arrs,
        ),
        remote_read_counts=rrc,
        row_bytes=meta.get("row_bytes"),
        declared_bytes_per_iter=meta.get("exchange_bytes_per_iter"),
        ledger=meta.get("ledger"),
    )
    return view


class ExchRule:
    """One exchange-plan rule; ``line`` in findings is the receiver part
    index + 1 (0 = a plan-level finding)."""

    id = "LUX400"
    title = "base exchange rule"
    doc = ""

    def check(self, view, path: str) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, path: str, receiver: int, message: str) -> Finding:
        return Finding(self.id, path, receiver, 0, message)


def _tables(view) -> Tuple[np.ndarray, np.ndarray]:
    """send/recv reshaped to (P, P, capacity); raises on shape drift
    (reported by LUX401, defended against by the others)."""
    P, cap = view.num_parts, view.capacity
    return (np.asarray(view.send_units).reshape(P, P, cap),
            np.asarray(view.recv_pos).reshape(P, P, cap))


def _shape_ok(view) -> bool:
    P, cap = view.num_parts, view.capacity
    return (P >= 1 and cap >= 1 and view.max_units >= 1
            and view.unit_rows >= 1
            and np.asarray(view.counts).shape == (P, P)
            and np.asarray(view.send_units).shape == (P, P * cap)
            and np.asarray(view.recv_pos).shape == (P, P * cap))


class ExchStructure(ExchRule):
    id = "LUX401"
    title = "exchange-structure"
    doc = ("static (P, P*capacity) tables, capacity holds the densest "
           "pair, diagonal all sentinel, prefix-dense real slots "
           "disjoint from sentinel pads")

    def check(self, view, path: str) -> Iterable[Finding]:
        P = view.num_parts
        for name in ("num_parts", "max_units", "unit_rows", "capacity"):
            if int(getattr(view, name)) < 1:
                yield self.finding(
                    path, 0, f"{name} = {getattr(view, name)}, must be >= 1")
                return
        counts = np.asarray(view.counts)
        if counts.shape != (P, P):
            yield self.finding(
                path, 0, f"counts shape {counts.shape} != ({P}, {P})")
            return
        if counts.dtype.kind not in "iu":
            yield self.finding(
                path, 0, f"counts dtype {counts.dtype} is not integral")
            return
        if counts.size and counts.min() < 0:
            yield self.finding(path, 0, "counts contains negative entries")
            return
        cap = view.capacity
        for name in ("send_units", "recv_pos"):
            a = np.asarray(getattr(view, name))
            if a.shape != (P, P * cap):
                yield self.finding(
                    path, 0,
                    f"{name} shape {a.shape} != ({P}, {P * cap}) — the "
                    "static all_to_all layout (zero-recompile contract)")
                return
            if a.dtype.kind not in "iu":
                yield self.finding(
                    path, 0, f"{name} dtype {a.dtype} is not integral")
                return
        send, recv = _tables(view)
        mu = view.max_units
        if send.min() < 0 or send.max() > mu:
            yield self.finding(
                path, 0,
                f"send_units out of [0, {mu}] (sentinel {mu}): "
                f"min {int(send.min())}, max {int(send.max())}")
        if recv.min() < 0 or recv.max() > P * mu:
            yield self.finding(
                path, 0,
                f"recv_pos out of [0, {P * mu}] (trash row {P * mu}): "
                f"min {int(recv.min())}, max {int(recv.max())}")
        diag = np.arange(P)
        if np.any(send[diag, diag] != mu) or \
                np.any(recv[diag, diag] != P * mu):
            yield self.finding(
                path, 0,
                "diagonal (p == p) table slots carry real entries — own "
                "rows never cross the wire")
        off = counts - np.diag(np.diag(counts))
        required = int(off.max()) if P > 1 else 0
        if cap < required:
            yield self.finding(
                path, 0,
                f"capacity {cap} cannot hold the {required} needed units "
                "of the densest (sender, receiver) pair — the exchange "
                "is truncated")
            return
        # Prefix density: for each (sender p -> receiver q) pair the
        # first counts[q, p] slots are real and EVERY later slot is the
        # sentinel on both sides. n indexed as counts.T because tables
        # are laid out sender-major: send[p, q] pairs with counts[q, p].
        lanes = np.arange(cap)
        n = counts.T[:, :, None]                   # (sender, receiver, 1)
        realzone = lanes[None, None, :] < n
        offmask = ~np.eye(P, dtype=bool)[:, :, None]
        aligned = recv_t(recv)                     # [p, q, i] sender-major
        pad_leak = ((send != mu) | (aligned != P * mu)) \
            & ~realzone & offmask
        if np.any(pad_leak):
            bad = np.argwhere(pad_leak.any(axis=2))
            p, q = (int(x) for x in bad[0])
            yield self.finding(
                path, q + 1,
                f"{int(pad_leak.any(axis=2).sum())} pairs carry real "
                f"entries in the sentinel zone (first: sender {p} -> "
                f"receiver {q} beyond counts[{q}, {p}] = "
                f"{int(counts[q, p])}) — pad and real slots must be "
                "disjoint")
        real_hole = ((send == mu) | (aligned == P * mu)) \
            & realzone & offmask
        if np.any(real_hole):
            bad = np.argwhere(real_hole.any(axis=2))
            p, q = (int(x) for x in bad[0])
            yield self.finding(
                path, q + 1,
                f"{int(real_hole.any(axis=2).sum())} pairs carry "
                f"sentinels inside the real prefix (first: sender {p} "
                f"-> receiver {q}, counts[{q}, {p}] = "
                f"{int(counts[q, p])}) — the pair's rows are not "
                "prefix-dense")


def recv_t(recv: np.ndarray) -> np.ndarray:
    """Receiver tables aligned to sender-major layout: element
    [p, q, i] is where RECEIVER q scatters slot i from SENDER p
    (recv_pos is receiver-major: recv[q, p, i])."""
    return recv.transpose(1, 0, 2)


class ExchCoverage(ExchRule):
    id = "LUX402"
    title = "exchange-coverage"
    doc = ("permutation proof: real send rows strictly ascending, "
           "recv_pos == sender * max_units + row, receive positions "
           "distinct per receiver; counts * unit_rows == "
           "remote_read_counts when attached")

    def check(self, view, path: str) -> Iterable[Finding]:
        if not _shape_ok(view):
            return   # LUX401 territory
        P, cap, mu = view.num_parts, view.capacity, view.max_units
        counts = np.asarray(view.counts, np.int64)
        off = counts - np.diag(np.diag(counts))
        if cap < (int(off.max()) if P > 1 else 0):
            return   # truncated tables; LUX401 already reports it
        send, recv = _tables(view)
        aligned = recv_t(recv)                    # [p, q, i] sender-major
        lanes = np.arange(cap)
        realzone = (lanes[None, None, :] < counts.T[:, :, None]) \
            & ~np.eye(P, dtype=bool)[:, :, None]
        # (a) strictly ascending real send rows per pair: each needed
        # row appears at most once in the pair's stream.
        nondec = (np.diff(send, axis=2) <= 0) & realzone[:, :, 1:]
        if np.any(nondec):
            p, q = (int(x) for x in np.argwhere(nondec.any(axis=2))[0])
            yield self.finding(
                path, q + 1,
                f"send_units[{p} -> {q}] is not strictly ascending in "
                "its real prefix — a row is duplicated or unsorted, so "
                "it is not sent exactly once")
        # (b) scatter alignment: received slot i of sender p lands at
        # flat index p * max_units + send_row — the exact position the
        # unchanged compute bodies read for that remote row.
        want = (np.arange(P, dtype=np.int64)[:, None, None] * mu
                + send.astype(np.int64))
        misrouted = (aligned.astype(np.int64) != want) & realzone
        if np.any(misrouted):
            p, q = (int(x) for x in np.argwhere(misrouted.any(axis=2))[0])
            i = int(np.flatnonzero(misrouted[p, q])[0])
            yield self.finding(
                path, q + 1,
                f"recv_pos[{q}, sender {p}, slot {i}] scatters row "
                f"{int(send[p, q, i])} to flat index "
                f"{int(aligned[p, q, i])}, compute reads it at "
                f"{int(want[p, q, i])} — the row is misrouted")
        # (c) per-receiver distinctness: no two real slots of receiver q
        # scatter to the same flat position (a collision would let one
        # sender's row overwrite another's).
        for q in range(P):
            pos = aligned[:, q][realzone[:, q]]
            if pos.size != np.unique(pos).size:
                yield self.finding(
                    path, q + 1,
                    f"receiver {q} has colliding recv_pos slots — two "
                    "exchanged rows scatter to the same flat index")
        # (d) conservation against the remote-read index: every remote
        # value row the receiver's real edges read is exchanged exactly
        # once, nothing more.
        rrc = view.remote_read_counts
        if rrc is not None:
            rrc = np.asarray(rrc, np.int64)
            got = counts * view.unit_rows
            if rrc.shape != got.shape:
                yield self.finding(
                    path, 0,
                    f"remote_read_counts shape {rrc.shape} != counts "
                    f"shape {got.shape}")
            elif np.any(got != rrc):
                q, p = (int(x) for x in np.argwhere(got != rrc)[0])
                yield self.finding(
                    path, q + 1,
                    f"plan exchanges {int(got[q, p])} value rows for "
                    f"(receiver {q}, sender {p}) but the remote-read "
                    f"index requires {int(rrc[q, p])} — a needed row is "
                    "dropped or sent twice")


class ExchProfitability(ExchRule):
    id = "LUX403"
    title = "exchange-profitability"
    doc = ("declared exchange_bytes_per_iter == capacity pricing; "
           "profitable iff capacity < max_units; ledger useful-bytes "
           "model re-derives from the counts matrix")

    def check(self, view, path: str) -> Iterable[Finding]:
        if not _shape_ok(view):
            return   # LUX401 territory
        P = view.num_parts
        units = P * (P - 1) * view.capacity
        packed_rows = units * view.unit_rows
        profitable = view.capacity < view.max_units
        if bool(view.profitable) != profitable:
            yield self.finding(
                path, 0,
                f"plan claims profitable={view.profitable} but capacity "
                f"{view.capacity} vs max_units {view.max_units} says "
                f"{profitable} — the fallback decision is lying")
        declared = view.declared_bytes_per_iter
        rb = view.row_bytes
        if declared is not None and rb is None:
            # No independent row price: the declared figure must still
            # be an exact multiple of the packed row count.
            if packed_rows and declared % packed_rows:
                yield self.finding(
                    path, 0,
                    f"declared exchange_bytes_per_iter {declared} is not "
                    f"a multiple of the {packed_rows} packed value rows "
                    "the plan moves per iteration")
        if rb is not None:
            packed_bytes = packed_rows * rb
            if declared is not None and declared != packed_bytes:
                yield self.finding(
                    path, 0,
                    f"declared exchange_bytes_per_iter {declared} != "
                    f"plan pricing {packed_bytes} ({units} units x "
                    f"{view.unit_rows} rows x {rb} B) — the advertised "
                    "byte figure drifted from the tables")
            full_bytes = P * (P - 1) * view.max_units * view.unit_rows * rb
            if profitable and packed_bytes >= full_bytes:
                yield self.finding(
                    path, 0,
                    f"profitable plan prices {packed_bytes} B >= the "
                    f"full all-gather's {full_bytes} B")
        counts = np.asarray(view.counts, np.int64)
        useful_rows = int(counts.sum() - np.trace(counts)) * view.unit_rows
        if useful_rows > packed_rows:
            yield self.finding(
                path, 0,
                f"the counts matrix requires {useful_rows} useful value "
                f"rows per iteration but the plan only moves "
                f"{packed_rows} — capacity cannot cover the advertised "
                "useful traffic")
        led = view.ledger
        if led is not None:
            checks = [("useful_rows", useful_rows),
                      ("exchanged_rows", packed_rows)]
            if rb is not None:
                checks.append(("useful_bytes_per_iter", useful_rows * rb))
            for key, want in checks:
                got = led.get(key)
                if got is not None and int(got) != want:
                    yield self.finding(
                        path, 0,
                        f"ledger {key} = {int(got)} but the counts "
                        f"matrix re-derives {want} — the "
                        "useful_bytes_per_iter model drifted from the "
                        "plan")
            ratio = led.get("ratio")
            if ratio is not None and packed_rows:
                want_ratio = useful_rows / packed_rows
                if abs(float(ratio) - want_ratio) > 1e-9:
                    yield self.finding(
                        path, 0,
                        f"ledger ratio {float(ratio):.6f} != re-derived "
                        f"useful/exchanged {want_ratio:.6f}")


class FrontierCoverage(ExchRule):
    id = "LUX407"
    title = "frontier-coverage"
    doc = ("frontier-exchange evidence must be admissible: frontier "
           "capacity within [1, capacity], per-pair send slots within "
           "that capacity, zero truncated active rows (dense frontiers "
           "downgrade, never drop), and the advertised frontier bytes "
           "re-derived from P * (P-1) * slots * frontier_row_bytes")

    def check(self, view, path: str) -> Iterable[Finding]:
        fcap = getattr(view, "frontier_capacity", None)
        if fcap is None:
            return   # no frontier evidence attached; nothing to verify
        if not _shape_ok(view):
            return   # LUX401 territory
        P = view.num_parts
        if not 1 <= fcap <= view.capacity:
            yield self.finding(
                path, 0,
                f"frontier_capacity {fcap} outside [1, {view.capacity}] "
                "— the frontier send must reuse (a prefix of) the "
                "compact plan's per-pair slots, never exceed them")
            return
        sends = getattr(view, "frontier_max_sends", None)
        if sends is not None and not 0 <= sends <= fcap:
            yield self.finding(
                path, 0,
                f"frontier_max_sends {sends} exceeds frontier_capacity "
                f"{fcap} — the packer can emit more rows than the "
                "admissibility check budgets, so active rows truncate")
        fill = getattr(view, "frontier_fill_active", None)
        if fill:
            yield self.finding(
                path, 0,
                f"frontier_fill_active = {int(fill)}: the packer "
                "truncated active rows instead of downgrading to the "
                "static compact send — results can silently drop "
                "frontier vertices")
        frb = getattr(view, "frontier_row_bytes", None)
        fbytes = getattr(view, "frontier_bytes_per_iter", None)
        if frb is not None and frb < 1:
            yield self.finding(
                path, 0, f"frontier_row_bytes {frb} must be >= 1")
        elif fbytes is not None and frb is not None:
            slots = fcap if sends is None else sends
            want = P * (P - 1) * slots * frb
            if int(fbytes) != want:
                yield self.finding(
                    path, 0,
                    f"frontier_bytes_per_iter {fbytes} != re-derived "
                    f"{want} (P*(P-1) pairs x {slots} slots x {frb} B) "
                    "— the frontier byte model drifted from the packer")


def all_exchange_rules() -> List[ExchRule]:
    return [ExchStructure(), ExchCoverage(), ExchProfitability(),
            FrontierCoverage()]


def verify_exchange_plan(view, path: str = "<exchange-plan>",
                         rules: Optional[Sequence[ExchRule]] = None
                         ) -> FileResult:
    """Run the LUX40x plan rules over one plan view."""
    if rules is None:
        rules = all_exchange_rules()
    findings: List[Finding] = []
    errors: List[str] = []
    for rule in rules:
        try:
            findings.extend(rule.check(view, path))
        except Exception as e:   # corrupted arrays can break numpy ops
            errors.append(f"{path}: {rule.id} crashed: {e!r}")
    findings.sort(key=lambda f: (f.line, f.rule))
    return FileResult(path, findings, [], error="; ".join(errors) or None)


def verify_exchange_dirs(paths: Sequence[str],
                         rules: Optional[Sequence[ExchRule]] = None
                         ) -> LintReport:
    """Load (mmap) and verify saved exchange-plan directories."""
    t0 = time.perf_counter()
    results: List[FileResult] = []
    for path in paths:
        try:
            view = load_exchange_artifact(path, mmap=True)
        except Exception as e:
            results.append(FileResult(
                path, [], [], error=f"{path}: unloadable plan: {e!r}"))
            continue
        results.append(verify_exchange_plan(view, path, rules))
    return LintReport(results, time.perf_counter() - t0,
                      schema=EXCHANGE_SCHEMA)


def load_fixture_plans(path: str) -> List[Tuple[str, types.SimpleNamespace]]:
    """Load a fixture module exposing ``PLANS`` — a list of dicts with
    a ``name`` plus the plan_view keyword fields (tests/exch_fixtures
    idiom). Returns [] when the module has no PLANS (it may carry only
    TRACES for the IR half of the tier)."""
    spec = importlib.util.spec_from_file_location(
        "exch_fixture_" + os.path.basename(path).removesuffix(".py"), path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out: List[Tuple[str, types.SimpleNamespace]] = []
    for entry in getattr(mod, "PLANS", []):
        entry = dict(entry)
        name = entry.pop("name")
        plan = entry.pop("plan")
        out.append((f"{path}::{name}", plan_view(plan, **entry)))
    return out


def audit_exchange(engine, name: str) -> List[Finding]:
    """Build-time audit for a plan-carrying executor (EnginePool hook,
    LUX_EXCH_POOL_AUDIT). Duck-typed and advisory: engines without a
    compact plan audit to zero findings."""
    plan = getattr(engine, "_xplan", None)
    if plan is None:
        return []
    try:
        counts = None
        sg = getattr(engine, "sg", None)
        if sg is not None and hasattr(sg, "remote_read_counts"):
            counts = sg.remote_read_counts()
        if counts is None:
            counts = getattr(engine, "_remote_read_counts", None)
        declared = None
        bytes_fn = getattr(engine, "exchange_bytes_per_iter", None)
        if callable(bytes_fn):
            try:
                declared = int(bytes_fn())
            except Exception:
                declared = None
        fe = getattr(engine, "frontier_evidence", None)
        frontier = (fe() or {}) if callable(fe) else {}
        view = plan_view(plan, remote_read_counts=counts,
                         declared_bytes_per_iter=declared, **frontier)
        res = verify_exchange_plan(view, path=name)
    # luxlint: disable=LUX007 -- advisory audit: a malformed plan must surface as a finding, never take down an engine build
    except Exception as e:
        return [Finding("LUX401", name, 0, 0, f"audit crashed: {e!r}")]
    findings = list(res.findings)
    if res.error:
        findings.append(Finding("LUX401", name, 0, 0,
                                f"audit crashed: {res.error}"))
    return findings
