"""gasck: the program-algebra prover behind ``luxlint --programs``.

Every correctness guarantee the engine family leans on — sentinel
annihilation for ``LUX_EXCHANGE=frontier``, part-order-independent
sharded accumulation, push<->pull bitwise duality, incremental
warm-start soundness — rests on algebraic properties of each
:class:`GasProgram`'s combiner that used to be hand-declared class
attrs or a docstring proof. This tier proves them offline, on seeded
probe graphs and per-dtype probe grids, and *derives* the capability
matrix instead of trusting declarations:

- LUX601 combiner-identity: the declared init/sentinel value
  annihilates under ``combine`` over a per-dtype probe grid including
  +-inf, dtype extremes, and a symmetric NaN-propagation policy.
- LUX602 combiner-algebra: associativity + commutativity over seeded
  probe triples — the license for ``segment_reduce`` reordering and
  part-order-independent sharded accumulation.
- LUX603 direction-duality: the push and pull accumulators are
  bitwise-equal on every iteration of a seeded trace — the
  AdaptiveExecutor / ShardedAdaptiveExecutor eligibility gate.
- LUX604 monotone-convergence: idempotence, merge-apply agreement, and
  gather inflation/monotonicity w.r.t. the declared order — the
  machine-checked form of the proof engine/incremental.py used to
  carry in its docstring.
- LUX605 frontier annihilation: applying an identity-filled
  accumulator leaves state bitwise unchanged and scatters an empty
  frontier — the program-level ``frontier_ok`` license complementing
  the trace-level LUX405.
- LUX606 capability-declaration drift: declared ``rooted`` /
  ``servable`` / ``frontier_ok`` / ``incremental_ok`` attrs must match
  the derived proof matrix (over- and under-claiming both flagged).

Proof results persist as a content-addressed ``gascap.v1`` artifact
(the committed ``analysis/gascap.json``); ``lux_tpu.models`` derives
``ROOTED_APPS`` and engine eligibility from it and the serving layer
consults it at warmup. Checks are interdependent (a failed identity
voids the trace-based proofs), so one driver, :func:`prove_program`,
runs them in dependency order; the rule classes here are metadata for
``--list-rules``.

Module import stays numpy + stdlib; jax arrives lazily through the
program hooks themselves (``_call_hook``), so ``--list-rules`` and
artifact loading never pay the backend init.
"""

from __future__ import annotations

import dataclasses
import hashlib
import importlib.util
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from lux_tpu.analysis.core import FileResult, Finding, LintReport
from lux_tpu.utils import flags

PROGRAMS_SCHEMA = "luxlint-programs.v1"
CAP_SCHEMA = "gascap.v1"
CAP_FILENAME = "gascap.json"

_MAX_ITERS = 32          # trace cap; probe graphs converge far earlier
_PROBE_CAP = 48          # per-program combine-probe grid bound
_SNAP_CAP = 6            # state snapshots fed to the LUX605 check

__all__ = [
    "PROGRAMS_SCHEMA", "CAP_SCHEMA", "CAP_FILENAME", "ProgramRule",
    "ProgramContractError", "all_program_rules", "prove_program",
    "prove_registry", "verify_registry", "verify_fixture_paths",
    "build_capmap", "save_capmap", "load_capmap", "capmap_path",
    "audit_program", "require_incremental",
]


class ProgramContractError(TypeError):
    """An engine was asked to run a program whose machine-checked
    algebra does not license it; the message names the failed rule."""


@dataclasses.dataclass(frozen=True)
class ProgramRule:
    id: str
    title: str
    doc: str


PROGRAM_RULES = (
    ProgramRule(
        "LUX601", "combiner-identity",
        "the declared init/sentinel value annihilates under combine "
        "over a per-dtype probe grid (incl. +-inf, extremes) with a "
        "symmetric NaN policy — the license for identity-masked pull "
        "and sentinel-padded frontier exchange"),
    ProgramRule(
        "LUX602", "combiner-algebra",
        "combine is exactly associative and commutative over seeded "
        "probe triples — the license for segment_reduce reordering "
        "and part-order-independent sharded accumulation"),
    ProgramRule(
        "LUX603", "direction-duality",
        "push and pull accumulators are bitwise-equal on every "
        "iteration of seeded probe-graph traces — the adaptive/"
        "sharded-adaptive executor eligibility gate"),
    ProgramRule(
        "LUX604", "monotone-convergence",
        "idempotent merge, apply == combine, and inflationary+monotone "
        "gather w.r.t. the declared order — required before "
        "IncrementalExecutor may warm-start from stale state"),
    ProgramRule(
        "LUX605", "frontier-annihilation",
        "applying an identity-filled accumulator leaves state bitwise "
        "unchanged and scatters an empty frontier — the program-level "
        "frontier_ok license (complements trace-level LUX405)"),
    ProgramRule(
        "LUX606", "capability-drift",
        "declared rooted/servable/frontier_ok/incremental_ok attrs "
        "exactly match the derived proof matrix; over- and "
        "under-claiming both flagged"),
)


def all_program_rules() -> List[ProgramRule]:
    return list(PROGRAM_RULES)


# -- numpy-side algebra helpers -------------------------------------------


def _np_op(combiner: str):
    try:
        return {"min": np.minimum, "max": np.maximum, "sum": np.add}[combiner]
    except KeyError:
        raise ValueError(f"unknown combiner {combiner!r}") from None


def _bitwise_eq(a: np.ndarray, b: np.ndarray) -> bool:
    a = np.asarray(a)
    b = np.asarray(b)
    return (a.shape == b.shape and a.dtype == b.dtype
            and a.tobytes() == b.tobytes())


def _call_hook(fn, *args) -> np.ndarray:
    """Run a program hook with jnp inputs, return a host numpy array.
    Hooks mix np/jnp freely (labelprop masks with jnp scalars), so the
    conversion happens here, once, not in every caller."""
    import jax.numpy as jnp

    conv = [jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args]
    return np.asarray(fn(*conv))


def _np_dtype(value_dtype) -> np.dtype:
    return np.dtype(getattr(value_dtype, "dtype", value_dtype))


def _identity_np(combiner: str, dtype: np.dtype):
    """The engine's own identity (ops/segment.py), as a numpy scalar —
    the proof must use the exact value the executors mask with."""
    from lux_tpu.ops.segment import identity_for

    return np.asarray(identity_for(combiner, dtype)).astype(dtype)[()]


def _dtype_extremes(dtype: np.dtype) -> np.ndarray:
    if np.issubdtype(dtype, np.floating):
        fi = np.finfo(dtype)
        return np.array(
            [0.0, 1.0, -1.0, 0.5, 1e-3, 65536.0,
             fi.max, -fi.max, np.inf, -np.inf], dtype=dtype)
    ii = np.iinfo(dtype)
    return np.array(
        [0, 1, 2, ii.max // 2, ii.max - 1, ii.max], dtype=dtype)


def _clean_probes(p: np.ndarray) -> np.ndarray:
    """NaN gets its own symmetric-policy probe, and -0.0 is excluded
    everywhere: np.minimum/np.maximum return the *second* operand on an
    equal compare, so +-0.0 would fail bitwise commutativity without
    telling us anything about the program."""
    if np.issubdtype(p.dtype, np.floating):
        p = p[~np.isnan(p)]
        p = p[~((p == 0) & np.signbit(p))]
    return np.unique(p)


def _probe_grid(values: np.ndarray, ident, dtype: np.dtype,
                seed: int) -> np.ndarray:
    """Combine-probe grid: trace-reachable values + dtype extremes +
    the identity, deduped, -0.0/NaN-cleaned, capped at _PROBE_CAP
    (extremes and identity always survive the cap)."""
    vals = _clean_probes(values.ravel().astype(dtype, copy=False))
    if vals.size > _PROBE_CAP:
        rng = np.random.default_rng(seed)
        vals = vals[np.sort(rng.choice(vals.size, _PROBE_CAP,
                                       replace=False))]
    return _clean_probes(np.concatenate(
        [vals, _dtype_extremes(dtype), np.array([ident], dtype=dtype)]))


# -- the individual proofs ------------------------------------------------


def _check_identity(combine, ident, probes: np.ndarray,
                    dtype: np.dtype) -> Tuple[bool, str, Optional[str]]:
    """(ok, counterexample, nan_policy). ``combine`` is the effective
    scalar combine (custom hook or the builtin op); failure text quotes
    the first violating probe."""
    ia = np.full_like(probes, ident)
    with np.errstate(all="ignore"):
        left = np.asarray(combine(ia, probes))
        right = np.asarray(combine(probes, ia))
    for got, side in ((left, "combine(ident, p)"), (right, "combine(p, ident)")):
        if not _bitwise_eq(got.astype(probes.dtype, copy=False), probes):
            bad = np.flatnonzero(
                np.frombuffer(got.astype(probes.dtype).tobytes(), np.uint8)
                .reshape(probes.size, -1)
                != np.frombuffer(probes.tobytes(), np.uint8)
                .reshape(probes.size, -1))
            i = int(bad[0]) // max(1, probes.dtype.itemsize)
            return (False,
                    f"{side} != p at p={probes[i]!r}: got "
                    f"{got.reshape(-1)[i]!r} (ident={ident!r})", None)
    if not np.issubdtype(dtype, np.floating):
        return True, "", None
    nan = np.array([np.nan], dtype=dtype)
    ione = np.array([ident], dtype=dtype)
    with np.errstate(all="ignore"):
        l = np.asarray(combine(nan, ione))
        r = np.asarray(combine(ione, nan))
    if not _bitwise_eq(l, r):
        return (False,
                f"asymmetric NaN policy: combine(NaN, ident)={l[0]!r} but "
                f"combine(ident, NaN)={r[0]!r}", None)
    return True, "", ("propagate" if np.isnan(l[0]) else "absorb")


def _check_algebra(op, probes: np.ndarray, seed: int,
                   triples: int) -> Tuple[bool, str]:
    """Exact (bitwise) associativity + commutativity of the builtin op
    over the full extremes cube plus ``triples`` seeded random triples
    drawn from the probe grid."""
    ext = _clean_probes(np.concatenate(
        [_dtype_extremes(probes.dtype),
         probes[:1] if probes.size else probes]))
    ga, gb, gc = np.meshgrid(ext, ext, ext, indexing="ij")
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, max(1, probes.size), size=(3, max(1, triples)))
    a = np.concatenate([ga.ravel(), probes[idx[0]]])
    b = np.concatenate([gb.ravel(), probes[idx[1]]])
    c = np.concatenate([gc.ravel(), probes[idx[2]]])
    with np.errstate(all="ignore"):
        lhs = op(op(a, b), c)
        rhs = op(a, op(b, c))
    if not _bitwise_eq(lhs, rhs):
        i = int(np.flatnonzero(
            lhs.view(np.uint8).reshape(a.size, -1)
            != rhs.view(np.uint8).reshape(a.size, -1))[0]) \
            // max(1, lhs.dtype.itemsize)
        return (False,
                f"not associative at (a={a[i]!r}, b={b[i]!r}, c={c[i]!r}): "
                f"(a+b)+c={lhs[i]!r} != a+(b+c)={rhs[i]!r}")
    with np.errstate(all="ignore"):
        ab = op(a, b)
        ba = op(b, a)
    if not _bitwise_eq(ab, ba):
        i = int(np.flatnonzero(
            ab.view(np.uint8).reshape(a.size, -1)
            != ba.view(np.uint8).reshape(a.size, -1))[0]) \
            // max(1, ab.dtype.itemsize)
        return (False,
                f"not commutative at (a={a[i]!r}, b={b[i]!r}): "
                f"{ab[i]!r} != {ba[i]!r}")
    return True, ""


@dataclasses.dataclass
class _Trace:
    duality_ok: bool
    mismatch: str           # first push/pull divergence, "" if none
    snaps: List[np.ndarray]
    gather_in: np.ndarray   # frontier-source state values (trace-reachable)
    msgs: np.ndarray        # in-play gather outputs
    iters: int
    converged: bool


def _trace(gas, graph, start: int, ident, op,
           max_iters: int = _MAX_ITERS) -> _Trace:
    """Run the fixpoint in numpy, computing BOTH direction's
    accumulators each iteration exactly as the engine builds them
    (engine/gas.py _pull_acc / _push_acc): pull gathers every CSC edge
    and masks non-frontier messages to the identity; push gathers only
    frontier-selected edges (through ``gather_push`` when declared)
    into an identity-filled accumulator."""
    src = graph.col_src.astype(np.int64)
    dst = graph.col_dst.astype(np.int64)
    w = graph.weights
    vals = np.asarray(gas.init_values(graph, start=start))
    front = np.asarray(gas.init_frontier(graph, start=start)).astype(bool)
    push_gather = getattr(gas, "gather_push", None)
    snaps = [vals.copy()]
    gin: List[np.ndarray] = []
    msgs: List[np.ndarray] = []
    duality_ok, mismatch, converged = True, "", False
    it = 0
    for it in range(max_iters):
        if not front.any():
            converged = True
            break
        sel = front[src]
        m = np.asarray(_call_hook(gas.gather, vals[src], w)) \
            .astype(vals.dtype, copy=False)
        masked = m.copy()
        masked[~sel] = ident
        acc_pull = np.full(graph.nv, ident, dtype=vals.dtype)
        with np.errstate(all="ignore"):
            op.at(acc_pull, dst, masked)
        # Push side: gather_push (when declared) is evaluated over the
        # same fixed-shape full edge list and selected after — an edge
        # function is elementwise, so the frontier slice is bitwise
        # identical, and the static shape means each jnp op in the hook
        # traces once instead of once per frontier size. Without a
        # declared gather_push the push direction runs the very same
        # edge function, so the pull messages are reused as-is.
        mp_full = (np.asarray(_call_hook(push_gather, vals[src], w))
                   .astype(vals.dtype, copy=False)
                   if push_gather is not None else m)
        acc_push = np.full(graph.nv, ident, dtype=vals.dtype)
        with np.errstate(all="ignore"):
            op.at(acc_push, dst[sel], mp_full[sel])
        if duality_ok and not _bitwise_eq(acc_pull, acc_push):
            duality_ok = False
            bad = int(np.flatnonzero(
                acc_pull.view(np.uint8).reshape(graph.nv, -1)
                != acc_push.view(np.uint8).reshape(graph.nv, -1))[0]) \
                // max(1, vals.dtype.itemsize)
            mismatch = (f"iter {it} vertex {bad}: pull={acc_pull[bad]!r} "
                        f"push={acc_push[bad]!r}")
        if sel.any():
            gin.append(np.unique(vals[front]))
            msgs.append(np.unique(m[sel]))
        new = np.asarray(_call_hook(gas.apply, vals, acc_pull)) \
            .astype(vals.dtype, copy=False)
        front = np.asarray(_call_hook(gas.scatter, vals, new)).astype(bool)
        vals = new
        snaps.append(vals.copy())
    empty = np.array([], dtype=vals.dtype)
    return _Trace(
        duality_ok, mismatch, snaps,
        np.unique(np.concatenate(gin)) if gin else empty,
        np.unique(np.concatenate(msgs)) if msgs else empty,
        it + (0 if converged else 1), converged)


def _check_annihilation(gas, snaps: Sequence[np.ndarray],
                        ident) -> Tuple[bool, str]:
    """LUX605: an identity-only accumulator must leave state bitwise
    unchanged and scatter nothing — a vertex that received no messages
    must not move."""
    picks = list(snaps[:1]) + list(snaps[-(_SNAP_CAP - 1):]) \
        if len(snaps) > _SNAP_CAP else list(snaps)
    for k, s in enumerate(picks):
        acc = np.full_like(s, ident)
        new = np.asarray(_call_hook(gas.apply, s, acc)) \
            .astype(s.dtype, copy=False)
        if not _bitwise_eq(new, s):
            bad = int(np.flatnonzero(
                new.view(np.uint8).reshape(s.size, -1)
                != s.view(np.uint8).reshape(s.size, -1))[0]) \
                // max(1, s.dtype.itemsize)
            return (False,
                    f"apply(state, identity-acc) mutates state snapshot "
                    f"{k} at vertex {bad}: {s[bad]!r} -> {new[bad]!r}")
        fired = np.asarray(_call_hook(gas.scatter, s, new)).astype(bool)
        if fired.any():
            return (False,
                    f"scatter fires {int(fired.sum())} vertices on an "
                    f"identity-only accumulator (snapshot {k})")
    return True, ""


def _check_monotone(gas, op, ident, gather_in: np.ndarray,
                    msgs: np.ndarray) -> Tuple[bool, str]:
    """LUX604 sub-checks, in order: monotone combiner; idempotent
    combine; apply == combiner merge; gather inflationary and monotone
    w.r.t. the order, over trace-reachable state values only (dtype
    extremes would manufacture uint wraparound the fixpoint can never
    reach)."""
    if gas.combiner not in ("min", "max"):
        return (False,
                f"combiner {gas.combiner!r} is not a monotone merge "
                "order (needs min or max)")
    probes = _clean_probes(np.concatenate(
        [gather_in, msgs, np.array([ident], dtype=gather_in.dtype)])) \
        if gather_in.size else np.array([ident])
    if not _bitwise_eq(op(probes, probes), probes):
        return False, "combine is not idempotent over the probe grid"
    accs = _clean_probes(np.concatenate(
        [msgs, np.array([ident], dtype=probes.dtype)])) \
        if msgs.size else probes
    x = np.repeat(probes, accs.size)
    a = np.tile(accs, probes.size)
    got = np.asarray(_call_hook(gas.apply, x, a)) \
        .astype(probes.dtype, copy=False)
    want = op(x, a)
    if not _bitwise_eq(got, want):
        i = int(np.flatnonzero(
            got.view(np.uint8).reshape(x.size, -1)
            != want.view(np.uint8).reshape(x.size, -1))[0]) \
            // max(1, got.dtype.itemsize)
        return (False,
                f"apply(old={x[i]!r}, acc={a[i]!r})={got[i]!r} is not the "
                f"{gas.combiner}-merge {want[i]!r}")
    if not gather_in.size:
        return False, "no trace-reachable gather inputs to probe"
    s = np.sort(gather_in)
    wprobes: List[Optional[np.ndarray]] = [None]
    if gas.needs_weights:
        wprobes = [np.full(s.shape, wv, dtype=np.int32)
                   for wv in (1, 2, 50, 100)]
    for wp in wprobes:
        g = np.asarray(_call_hook(gas.gather, s, wp)) \
            .astype(s.dtype, copy=False)
        wtxt = "" if wp is None else f" (weight {int(wp[0])})"
        if not _bitwise_eq(op(g, s), s):
            i = int(np.flatnonzero(
                op(g, s).view(np.uint8).reshape(s.size, -1)
                != s.view(np.uint8).reshape(s.size, -1))[0]) \
                // max(1, s.dtype.itemsize)
            return (False,
                    f"gather is not inflationary{wtxt}: "
                    f"gather({s[i]!r})={g[i]!r} moves against the "
                    f"{gas.combiner} order")
        if g.size > 1 and not bool(np.all(g[:-1] <= g[1:])):
            i = int(np.flatnonzero(g[:-1] > g[1:])[0])
            return (False,
                    f"gather is not monotone{wtxt}: inputs "
                    f"{s[i]!r} <= {s[i + 1]!r} but messages "
                    f"{g[i]!r} > {g[i + 1]!r}")
    return True, ""


def _derive_rooted(gas, graph) -> bool:
    try:
        v0 = np.asarray(gas.init_values(graph, start=0))
        f0 = np.asarray(gas.init_frontier(graph, start=0))
        v1 = np.asarray(gas.init_values(graph, start=1))
        f1 = np.asarray(gas.init_frontier(graph, start=1))
    except TypeError:
        return False
    return not (_bitwise_eq(v0, v1) and _bitwise_eq(f0, f1))


# -- seed graphs ----------------------------------------------------------


def _seed_graphs(nv: int, seed: int) -> Dict[str, object]:
    """Deterministic probe graphs: a ring (every vertex reachable) plus
    3*nv random edges, in an unweighted and a same-structure weighted
    (1..100, the generate.py convention) variant."""
    from lux_tpu.graph.graph import Graph

    rng = np.random.default_rng(seed)
    ring_src = np.arange(nv, dtype=np.int64)
    ring_dst = (ring_src + 1) % nv
    extra = rng.integers(0, nv, size=(2, 3 * nv))
    src = np.concatenate([ring_src, extra[0]])
    dst = np.concatenate([ring_dst, extra[1]])
    keep = src != dst
    src, dst = src[keep], dst[keep]
    w = rng.integers(1, 101, size=src.size).astype(np.int32)
    return {
        "plain": Graph.from_edges(src, dst, nv),
        "weighted": Graph.from_edges(src, dst, nv, weights=w),
    }


# -- the per-program driver -----------------------------------------------


def _declared_caps(raw) -> Dict[str, bool]:
    return {
        "rooted": bool(getattr(raw, "rooted", False)),
        "servable": bool(getattr(raw, "servable", True)),
        "frontier_ok": bool(getattr(raw, "frontier_ok", False)),
        "incremental_ok": bool(getattr(raw, "incremental_ok", False)),
    }


def prove_program(name: str, program, graphs: Dict[str, object],
                  path: str, seed: int = 7,
                  triples: int = 64) -> Tuple[FileResult, dict]:
    """Prove one program's algebra; returns the lint result and its
    gascap.v1 capability entry. Checks run in dependency order: a
    failed identity (LUX601) voids every trace-based proof, so those
    are skipped (derived capabilities go False) rather than reported
    as cascading noise."""
    findings: List[Finding] = []

    def fail(rule: str, msg: str) -> None:
        findings.append(Finding(rule, path, 0, 0, f"{name}: {msg}"))

    caps: dict = {"combiner": "?", "value_dtype": "?", "frontier": False,
                  "declared": {}, "derived": {}, "checks": {},
                  "evidence": {}}
    try:
        from lux_tpu.engine.program import as_gas

        raw = program() if isinstance(program, type) else program
        gas = as_gas(raw)
        combiner = gas.combiner
        dtype = _np_dtype(gas.value_dtype)
        op = _np_op(combiner)
        declared = _declared_caps(raw)
        graph = graphs["weighted"] if gas.needs_weights else graphs["plain"]
        ident_builtin = _identity_np(combiner, dtype)
        ident_fn = getattr(gas, "combine_identity", None)
        ident = (np.asarray(ident_fn(dtype)).astype(dtype)[()]
                 if callable(ident_fn) else ident_builtin)
        custom = getattr(gas, "combine", None)
        combine = custom if callable(custom) else op

        init_v = np.asarray(gas.init_values(graph, start=0))
        frontier = bool(gas.frontier) and init_v.ndim == 1
        derived_rooted = _derive_rooted(gas, graph)

        probes = _probe_grid(init_v, ident, dtype, seed)
        id_ok, id_msg, nan_policy = _check_identity(
            combine, ident, probes, dtype)

        traces: List[_Trace] = []
        if frontier and id_ok:
            roots = [0, 1] if derived_rooted else [0]
            traces = [_trace(gas, graph, s, ident, op) for s in roots]
            reach = np.concatenate(
                [init_v] + [t.gather_in for t in traces]
                + [t.msgs for t in traces])
            probes = _probe_grid(reach, ident, dtype, seed)
            id_ok, id_msg, nan_policy = _check_identity(
                combine, ident, probes, dtype)
        if frontier and not id_ok:
            fail("LUX601",
                 f"identity is not an annihilator — {id_msg}; "
                 "identity-masked pull and sentinel-padded frontier "
                 "exchange would corrupt values")

        alg_ok, alg_msg = _check_algebra(op, probes, seed, triples)
        if frontier and not alg_ok:
            fail("LUX602",
                 f"combine is not exact over the probe grid — {alg_msg}; "
                 "segment_reduce reordering and part-order-independent "
                 "sharded accumulation are unlicensed")
        if callable(custom):
            got = np.asarray(_call_hook(
                custom, probes, probes[::-1].copy())) \
                .astype(dtype, copy=False)
            if not _bitwise_eq(got, op(probes, probes[::-1])):
                fail("LUX602",
                     f"declared combine() disagrees with the builtin "
                     f"{combiner!r} the engines actually run")

        duality_ok = bool(traces) and all(t.duality_ok for t in traces)
        if frontier and id_ok and traces and not duality_ok:
            first = next(t for t in traces if not t.duality_ok)
            fail("LUX603",
                 f"push and pull accumulators diverge ({first.mismatch}); "
                 "direction-adaptive execution is unlicensed")

        annihil_ok, annihil_msg = False, "no trace"
        if traces:
            annihil_ok, annihil_msg = True, ""
            for t in traces:
                ok, msg = _check_annihilation(gas, t.snaps, ident)
                if not ok:
                    annihil_ok, annihil_msg = False, msg
                    break
        if frontier and id_ok and traces and not annihil_ok:
            fail("LUX605",
                 f"identity does not annihilate at the program level — "
                 f"{annihil_msg}; frontier_ok is unlicensed")

        monotone_ok, monotone_msg = False, "no trace"
        if traces and id_ok:
            gin = np.unique(np.concatenate([t.gather_in for t in traces]))
            msgs = np.unique(np.concatenate([t.msgs for t in traces]))
            monotone_ok, monotone_msg = _check_monotone(
                gas, op, ident, gin, msgs)
        has_relax = callable(getattr(raw, "relax", None))
        derived_incr = monotone_ok and has_relax
        if declared["incremental_ok"]:
            if not monotone_ok:
                fail("LUX604",
                     f"declared incremental_ok but the monotone-"
                     f"convergence proof fails — {monotone_msg}")
            elif not has_relax:
                fail("LUX604",
                     "declared incremental_ok but the program has no "
                     "host relax hook for column re-relaxation")
        elif derived_incr:
            fail("LUX606",
                 "capability under-claim: the monotone proof holds and a "
                 "relax hook exists, but incremental_ok is declared "
                 "False — declare it (or the serving layer will refuse "
                 "warm-started refresh it is entitled to)")

        derived_frontier_ok = (frontier and id_ok and alg_ok
                               and duality_ok and annihil_ok)
        if derived_rooted != declared["rooted"]:
            fail("LUX606",
                 f"rooted drift: declared {declared['rooted']} but "
                 f"init_values/init_frontier "
                 f"{'do' if derived_rooted else 'do not'} depend on "
                 "start")
        if declared["frontier_ok"] != derived_frontier_ok:
            fail("LUX606",
                 f"frontier_ok drift: declared {declared['frontier_ok']} "
                 f"but the proof matrix derives {derived_frontier_ok}")
        if declared["servable"] and frontier and not derived_frontier_ok:
            fail("LUX606",
                 "servable over-claim: a frontier program without the "
                 "derived frontier_ok license must not be exposed "
                 "through the serving frontier lane")

        caps = {
            "combiner": combiner,
            "value_dtype": str(dtype),
            "frontier": bool(gas.frontier),
            "declared": declared,
            "derived": {
                "rooted": bool(derived_rooted),
                "frontier_ok": bool(derived_frontier_ok),
                "incremental_ok": bool(derived_incr),
            },
            "checks": {
                "identity": bool(id_ok),
                "exact_combiner": bool(alg_ok),
                "duality": bool(duality_ok),
                "annihilation": bool(annihil_ok),
                "monotone": bool(monotone_ok),
            },
            "evidence": {
                "probes": int(probes.size),
                "iters": int(sum(t.iters for t in traces)),
                "roots": [0, 1] if (traces and derived_rooted) else
                         ([0] if traces else []),
                "nan_policy": nan_policy,
                "monotone_detail": monotone_msg,
            },
        }
    except Exception as e:   # a broken program must report, not crash the tier
        return FileResult(
            path, [], [],
            error=f"{path}: {name}: prover crashed: {e!r}"), caps
    findings.sort(key=lambda f: (f.rule, f.message))
    return FileResult(path, findings, []), caps


def _filter_select(result: FileResult,
                   select: Optional[Sequence[str]]) -> None:
    if select:
        keep = tuple(select)
        result.findings = [f for f in result.findings
                           if f.rule.startswith(keep)]


# -- registry + fixture drivers -------------------------------------------


def prove_registry(select: Optional[Sequence[str]] = None
                   ) -> Tuple[LintReport, dict]:
    """Prove all registered programs; returns (report, gascap.v1 dict)."""
    t0 = time.perf_counter()
    from lux_tpu import models

    seed = flags.get_int("LUX_GASCK_SEED")
    nv = flags.get_int("LUX_GASCK_NV")
    triples = flags.get_int("LUX_GASCK_TRIPLES")
    graphs = _seed_graphs(nv, seed)
    results: List[FileResult] = []
    programs_block: Dict[str, dict] = {}
    for name in sorted(models.PROGRAMS):
        res, caps = prove_program(
            name, models.PROGRAMS[name], graphs,
            f"<registry:{name}>", seed=seed, triples=triples)
        _filter_select(res, select)
        results.append(res)
        programs_block[name] = caps
    art = build_capmap(programs_block,
                       {"seed": seed, "nv": nv, "triples": triples})
    return (LintReport(results, time.perf_counter() - t0,
                       schema=PROGRAMS_SCHEMA), art)


def verify_registry(select: Optional[Sequence[str]] = None,
                    capmap_out: Optional[str] = None) -> LintReport:
    report, art = prove_registry(select)
    if capmap_out and report.ok:
        save_capmap(art, capmap_out)
    return report


_FIXTURE_SEQ = [0]


def _load_fixture_programs(path: str) -> List[Tuple[str, object]]:
    from lux_tpu.engine.gas import GasProgram
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.push import PushProgram

    _FIXTURE_SEQ[0] += 1
    modname = f"_gasck_fixture_{_FIXTURE_SEQ[0]}"
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)   # type: ignore[union-attr]
    if isinstance(getattr(mod, "PROGRAMS", None), dict):
        return sorted(mod.PROGRAMS.items())
    out = []
    for v in vars(mod).values():
        if (isinstance(v, type) and v.__module__ == modname
                and issubclass(v, (GasProgram, PushProgram, PullProgram))):
            out.append((getattr(v, "name", v.__name__), v))
    return sorted(out)


def verify_fixture_paths(paths: Sequence[str],
                         select: Optional[Sequence[str]] = None
                         ) -> LintReport:
    """Prove programs defined in standalone .py files (the seeded
    failing fixtures under tests/gas_fixtures/)."""
    from lux_tpu.analysis.core import iter_python_files

    t0 = time.perf_counter()
    seed = flags.get_int("LUX_GASCK_SEED")
    graphs = _seed_graphs(flags.get_int("LUX_GASCK_NV"), seed)
    triples = flags.get_int("LUX_GASCK_TRIPLES")
    results: List[FileResult] = []
    for path in iter_python_files(paths):
        try:
            progs = _load_fixture_programs(path)
        except Exception as e:
            results.append(FileResult(
                path, [], [], error=f"{path}: unloadable fixture: {e!r}"))
            continue
        if not progs:
            results.append(FileResult(
                path, [], [],
                error=f"{path}: defines no GAS/push/pull programs"))
            continue
        for name, prog in progs:
            res, _ = prove_program(name, prog, graphs, path,
                                   seed=seed, triples=triples)
            _filter_select(res, select)
            results.append(res)
    return LintReport(results, time.perf_counter() - t0,
                      schema=PROGRAMS_SCHEMA)


# -- the gascap.v1 artifact -----------------------------------------------


def _cap_id(programs: dict, probe: dict) -> str:
    blob = json.dumps({"probe": probe, "programs": programs},
                      sort_keys=True)
    return "gascap-" + hashlib.sha1(blob.encode()).hexdigest()[:12]


def build_capmap(programs: dict, probe: dict) -> dict:
    return {
        "schema": CAP_SCHEMA,
        "id": _cap_id(programs, probe),
        "probe": probe,
        "programs": programs,
        "created_at": time.time(),
    }


def save_capmap(art: dict, path: str) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(art, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def load_capmap(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        art = json.load(fh)
    if art.get("schema") != CAP_SCHEMA:
        raise ValueError(
            f"{path}: schema {art.get('schema')!r}, expected {CAP_SCHEMA!r}")
    want = _cap_id(art.get("programs") or {}, art.get("probe") or {})
    if art.get("id") != want:
        raise ValueError(
            f"{path}: id {art.get('id')!r} does not match content hash "
            f"{want!r} (tampered or hand-edited capability artifact)")
    return art


def capmap_path() -> str:
    d = flags.get("LUX_GASCAP_DIR")
    if d:
        return os.path.join(d, CAP_FILENAME)
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        CAP_FILENAME)


# -- consumers: pool audit + the incremental gate -------------------------


def _program_key(obj) -> tuple:
    cls = type(obj)
    extras = tuple(sorted(
        (k, v) for k, v in vars(obj).items()
        if isinstance(v, (int, float, str, bool))))
    return (cls.__module__, cls.__qualname__, extras)


_POOL_AUDIT_CACHE: Dict[tuple, List[Finding]] = {}


def audit_program(program, label: str = "<pool>") -> List[Finding]:
    """Light LUX601/602/605 audit for serving pool builds: probe-grid
    algebra only, no graph trace — cheap enough to run on every engine
    build, cached per program identity."""
    from lux_tpu.engine.program import as_gas

    gas = as_gas(program)
    key = _program_key(gas)
    if key in _POOL_AUDIT_CACHE:
        return [dataclasses.replace(f, path=label)
                for f in _POOL_AUDIT_CACHE[key]]
    findings: List[Finding] = []
    name = getattr(gas, "name", type(gas).__name__)
    combiner = gas.combiner
    dtype = _np_dtype(gas.value_dtype)
    op = _np_op(combiner)
    ident_fn = getattr(gas, "combine_identity", None)
    ident = (np.asarray(ident_fn(dtype)).astype(dtype)[()]
             if callable(ident_fn)
             else _identity_np(combiner, dtype))
    custom = getattr(gas, "combine", None)
    probes = _probe_grid(np.array([], dtype=dtype), ident, dtype, seed=0)
    id_ok, id_msg, _ = _check_identity(
        custom if callable(custom) else op, ident, probes, dtype)
    if not id_ok:
        findings.append(Finding(
            "LUX601", label, 0, 0,
            f"{name}: identity is not an annihilator — {id_msg}"))
    if gas.frontier:
        alg_ok, alg_msg = _check_algebra(op, probes, seed=0, triples=16)
        if not alg_ok:
            findings.append(Finding(
                "LUX602", label, 0, 0,
                f"{name}: combine is not exact — {alg_msg}"))
        if id_ok:
            ok, msg = _check_annihilation(gas, [probes], ident)
            if not ok:
                findings.append(Finding(
                    "LUX605", label, 0, 0,
                    f"{name}: identity does not annihilate at the "
                    f"program level — {msg}"))
    _POOL_AUDIT_CACHE[key] = findings
    return findings


_INCR_CACHE: Dict[tuple, Optional[str]] = {}
_INCR_GRAPHS: Dict[str, object] = {}


def require_incremental(program) -> None:
    """Gate for IncrementalExecutor: raise :class:`ProgramContractError`
    naming the failed LUX604 sub-check unless the program carries a
    host relax hook AND passes the monotone-convergence proof on a
    seeded probe graph. Cached per program identity — the proof runs
    once per process, not per executor."""
    key = _program_key(program)
    if key not in _INCR_CACHE:
        _INCR_CACHE[key] = _incremental_error(program)
    err = _INCR_CACHE[key]
    if err:
        raise ProgramContractError(err)


def _incremental_error(program) -> Optional[str]:
    from lux_tpu.engine.program import as_gas

    name = getattr(program, "name", type(program).__name__)
    if not callable(getattr(program, "relax", None)):
        return (f"{name}: LUX604 monotone-convergence: no host relax hook "
                "— IncrementalExecutor re-relaxes invalidated columns on "
                "the host, so a relax(src_vals, weights) method is part "
                "of the incremental contract")
    gas = as_gas(program)
    if not bool(gas.frontier):
        return (f"{name}: LUX604 monotone-convergence: frontier-less "
                "programs have no activation signal to warm-start from")
    try:
        gkey = "weighted" if gas.needs_weights else "plain"
        if gkey not in _INCR_GRAPHS:
            _INCR_GRAPHS.update(
                _seed_graphs(12, flags.get_int("LUX_GASCK_SEED")))
        graph = _INCR_GRAPHS[gkey]
        op = _np_op(gas.combiner)
        dtype = _np_dtype(gas.value_dtype)
        ident = _identity_np(gas.combiner, dtype)
        t = _trace(gas, graph, 0, ident, op)
        ok, reason = _check_monotone(gas, op, ident, t.gather_in, t.msgs)
    except Exception as e:
        return (f"{name}: LUX604 monotone-convergence: proof crashed "
                f"({e!r})")
    if not ok:
        return f"{name}: LUX604 monotone-convergence: {reason}"
    return None
