"""luxlint-IR: rules over *traced* programs (jaxprs), not source text.

The AST tier (analysis/rules.py) sees what the code says; this tier sees
what the traced computation actually does. Every registered program ×
executor step is traced to a ClosedJaxpr on a tiny synthetic graph —
abstract eval only, nothing runs on a device — and the equations are
walked by the LUX1xx rules:

- LUX101 dtype-drift: a carry leaf whose dtype differs between loop
  input and output reshapes/retraces every iteration; silent promotion
  to a 64-bit dtype doubles HBM and halves VPU throughput.
- LUX102 host-callback: ``pure_callback``/``debug_callback``/
  ``io_callback`` inside a jitted step is a hidden device->host round
  trip per iteration (the LUX001 failure mode, visible post-trace even
  when the AST can't see it).
- LUX103 footprint-blowup: a static per-eqn cost model flags any traced
  intermediate larger than ``LUX_IR_BLOWUP`` x the step's total input
  bytes — the O(nnz)-broadcast class of bugs, caught before a 2^31-edge
  run OOMs.
- LUX104 donation-audit: args declared in ``donate_argnums`` whose
  buffers the lowered executable does not actually alias (the donation
  silently buys nothing and HBM holds two copies).
- LUX105 collective-audit: collectives in a single-shard trace, or a
  sharded exchange trace with no collective at all (the ZC-exchange
  surface wired wrong).

Tracing is cheap (~ms per target) but imports jax — keep this module
OUT of the AST tier's import path; ``tools/luxlint.py`` loads it only
under ``--ir``.

Executors participate by exposing ``trace_step(**init_kw)`` returning a
plain dict (no dependency on this module)::

    {"kind": "pull",            # executor kind, for the target name
     "fn": self._step,          # the jitted step callable itself
     "args": (vals, dgraph),    # example args exactly as run() passes
     "donate": (0,),            # argnums the jit donates
     "carry": (0,),             # argnums whose leaves are the carry
     "sharded": False}          # True when collectives are expected

with optional ``call``/``lower`` overrides when the jit takes static
arguments the example args don't show (MultiSourcePushExecutor). The
contract relied on by LUX101: the step's flattened outputs begin with
the new carry, leaf-for-leaf against the flattened carry args.
"""

from __future__ import annotations

import dataclasses
import re
import time
import warnings
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from lux_tpu.analysis.core import FileResult, Finding, LintReport
from lux_tpu.utils import flags

IR_SCHEMA = "luxlint.ir.v1"

# Primitive-name fragments identifying host callbacks (LUX102) and
# cross-device collectives (LUX105). Matched by name, not identity, so
# the rule set survives jax moving primitives between modules.
CALLBACK_PRIMS = ("pure_callback", "debug_callback", "io_callback")
COLLECTIVE_PRIMS = (
    "psum", "pmax", "pmin", "ppermute", "pgather", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter",
)


@dataclasses.dataclass
class TraceTarget:
    """One traceable step: a callable + example args + audit metadata."""

    name: str                       # e.g. "pagerank@pull"
    call: Callable                  # callable(*args) -> step outputs
    args: Tuple = ()                # example args (dynamic only)
    donate: Tuple[int, ...] = ()    # argnums donated by the real jit
    carry: Tuple[int, ...] = (0,)   # argnums whose leaves are the carry
    sharded: bool = False           # collectives expected iff True
    lower: Optional[Callable] = None  # () -> jax.stages.Lowered
    axis_env: Tuple = ()            # [(name, size)] for axis-using fns
    # Exchange-tier metadata (LUX404-406); plan-carrying sharded
    # executors expose these in their trace dicts, everything else
    # leaves the defaults and the LUX40x IR rules skip the target.
    exchange_mode: str = ""   # "full" / "compact" / "frontier" ("" = flat)
    exchange_bytes: Optional[int] = None  # exchange_bytes_per_iter claim
    combiner: str = ""              # program combiner ("min"/"max"/"sum")
    value_dtype: str = ""           # dtype of the exchanged value rows
    num_parts: int = 0              # mesh parts the step is mapped over
    plan: object = None             # the live ExchangePlan (compact only)


def target_from_spec(name: str, spec: dict) -> TraceTarget:
    """Normalize an executor's (or fixture's) trace dict to a target."""
    fn = spec.get("fn")
    call = spec.get("call", fn)
    if call is None:
        raise ValueError(f"trace spec {name!r} has neither 'call' nor 'fn'")
    args = tuple(spec.get("args", ()))
    lower = spec.get("lower")
    if lower is None and hasattr(fn, "lower"):
        lower = lambda fn=fn, args=args: fn.lower(*args)  # noqa: E731
    eb = spec.get("exchange_bytes")
    return TraceTarget(
        name=name, call=call, args=args,
        donate=tuple(spec.get("donate", ())),
        carry=tuple(spec.get("carry", (0,))),
        sharded=bool(spec.get("sharded", False)),
        lower=lower,
        axis_env=tuple(spec.get("axis_env", ())),
        exchange_mode=str(spec.get("exchange_mode", "")),
        exchange_bytes=None if eb is None else int(eb),
        combiner=str(spec.get("combiner", "")),
        value_dtype=str(spec.get("value_dtype", "")),
        num_parts=int(spec.get("num_parts", 0)),
        plan=spec.get("plan"),
    )


def trace_target(target: TraceTarget):
    """Abstract-eval the target to a ClosedJaxpr (no device work)."""
    import jax

    if target.axis_env:
        mk = jax.make_jaxpr(target.call, axis_env=list(target.axis_env))
    else:
        mk = jax.make_jaxpr(target.call)
    return mk(*target.args)


# -- jaxpr walking ------------------------------------------------------

def _as_jaxprs(v) -> List:
    from jax import core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        return [v.jaxpr]
    if isinstance(v, jcore.Jaxpr):
        return [v]
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            out.extend(_as_jaxprs(x))
        return out
    return []


def iter_eqns(jaxpr) -> Iterable:
    """Depth-first walk over every eqn, descending into sub-jaxprs
    (pjit/scan/while/cond/shard_map/custom_* all carry theirs in
    params; matching by type keeps the walk version-proof)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _as_jaxprs(v):
                yield from iter_eqns(sub)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _carry_leaf_indices(target: TraceTarget) -> List[int]:
    """Flat in_aval indices of the carry args (args flatten in order)."""
    import jax

    out: List[int] = []
    pos = 0
    for i, a in enumerate(target.args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in target.carry:
            out.extend(range(pos, pos + n))
        pos += n
    return out


# -- the rules ----------------------------------------------------------

class IRRule:
    """One IR rule: an id, a one-line doc, a check over a ClosedJaxpr."""

    id = "LUX100"
    title = "base ir rule"
    doc = ""

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, target: TraceTarget, line: int, message: str) -> Finding:
        # `line` is the 1-based eqn ordinal in the depth-first walk
        # (0 = a target-level finding with no single eqn to blame).
        return Finding(self.id, target.name, line, 0, message)


class DtypeDrift(IRRule):
    id = "LUX101"
    title = "dtype-drift"
    doc = ("carry dtype must be identical between loop input and output; "
           "no silent promotion to 64-bit dtypes inside the step")

    @staticmethod
    def _wide(dtype) -> bool:
        dt = np.dtype(dtype)
        return dt.kind in "fiuc" and dt.itemsize >= 8

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        carry_idx = _carry_leaf_indices(target)
        in_avals, out_avals = closed.in_avals, closed.out_avals
        if len(carry_idx) > len(out_avals):
            yield self.finding(
                target, 0,
                f"carry has {len(carry_idx)} leaves but the step returns "
                f"only {len(out_avals)} outputs — the carry cannot round-"
                "trip through this step",
            )
            return
        for j, idx in enumerate(carry_idx):
            din = getattr(in_avals[idx], "dtype", None)
            dout = getattr(out_avals[j], "dtype", None)
            if din is not None and dout is not None and din != dout:
                yield self.finding(
                    target, 0,
                    f"carry leaf {j} enters as {din} and leaves as {dout} "
                    "— every iteration converts (or retraces) the carry",
                )
        if any(self._wide(a.dtype) for a in in_avals
               if getattr(a, "dtype", None) is not None):
            return   # 64-bit inputs make 64-bit intermediates legitimate
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            for ov in eqn.outvars:
                dt = getattr(ov.aval, "dtype", None)
                if dt is not None and self._wide(dt):
                    yield self.finding(
                        target, k,
                        f"`{eqn.primitive.name}` silently promotes to "
                        f"{np.dtype(dt).name} with no 64-bit input — "
                        "x64 drift doubles HBM for the affected values",
                    )


class HostCallback(IRRule):
    id = "LUX102"
    title = "host-callback"
    doc = ("no pure_callback/debug_callback/io_callback inside a jitted "
           "hot-path step (hidden host round trip per iteration)")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            name = eqn.primitive.name
            if name in CALLBACK_PRIMS or name.endswith("callback"):
                yield self.finding(
                    target, k,
                    f"host callback `{name}` in the jitted step — every "
                    "iteration stalls on a device->host->device round "
                    "trip",
                )


class FootprintBlowup(IRRule):
    id = "LUX103"
    title = "footprint-blowup"
    doc = ("no traced intermediate may exceed LUX_IR_BLOWUP x the "
           "step's total input bytes (static per-eqn cost model)")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        ratio = flags.get_float("LUX_IR_BLOWUP")
        base = sum(_aval_bytes(a) for a in closed.in_avals)
        base += sum(int(getattr(c, "nbytes", 0)) for c in closed.consts)
        limit = ratio * max(base, 1)
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            for ov in eqn.outvars:
                nbytes = _aval_bytes(ov.aval)
                if nbytes > limit:
                    aval = ov.aval
                    yield self.finding(
                        target, k,
                        f"`{eqn.primitive.name}` materializes "
                        f"{tuple(aval.shape)} {np.dtype(aval.dtype).name} "
                        f"({nbytes / 2**20:.1f} MiB) = "
                        f"{nbytes / max(base, 1):.0f}x the step inputs "
                        f"(limit {ratio:g}x, LUX_IR_BLOWUP)",
                    )


def _main_arg_attrs(mlir_text: str) -> Optional[str]:
    """The argument list of the entry function in lowered StableHLO
    text (between ``@main(`` and its closing paren), or None."""
    m = re.search(r"func\.func (?:public )?@main\(", mlir_text)
    if m is None:
        return None
    start = m.end()
    depth = 1
    for i in range(start, len(mlir_text)):
        ch = mlir_text[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return mlir_text[start:i]
    return None


class DonationAudit(IRRule):
    id = "LUX104"
    title = "donation-audit"
    doc = ("every donate_argnums buffer must actually be aliased to an "
           "output by the lowered executable (else the donation buys "
           "nothing and HBM holds two copies)")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        import jax

        if not target.donate or target.lower is None:
            return
        donated = []
        for i in target.donate:
            if i < len(target.args):
                donated.extend(jax.tree_util.tree_leaves(target.args[i]))
        expected = len(donated)
        if expected == 0:
            return
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            lowered = target.lower()
        sig = _main_arg_attrs(lowered.as_text())
        if sig is None:
            yield self.finding(
                target, 0,
                "could not locate @main in the lowered module — donation "
                "audit impossible for this target",
            )
            return
        # Single-shard lowerings resolve aliasing right away
        # (`tf.aliasing_output = N`); sharded lowerings defer the pairing
        # to the compiler and only mark `jax.buffer_donor = true`.
        aliased = sig.count("tf.aliasing_output")
        deferred = sig.count("jax.buffer_donor")
        if aliased + deferred < expected:
            notes = "; ".join(
                str(w.message) for w in caught
                if "donat" in str(w.message).lower()
            )
            detail = f" ({notes})" if notes else ""
            yield self.finding(
                target, 0,
                f"{expected - (aliased + deferred)} of {expected} donated "
                "buffers are not aliased to any output — the executable "
                f"copies instead of reusing them{detail}",
            )
            return
        if deferred:
            # The compiler will alias a deferred donor only if some
            # output matches its shape+dtype — check that statically.
            if closed is not None:
                out_leaves = [
                    a for a in closed.out_avals if hasattr(a, "shape")
                ]
            else:
                out_tree = jax.eval_shape(target.call, *target.args)
                out_leaves = jax.tree_util.tree_leaves(out_tree)
            pool = [
                (tuple(a.shape), np.dtype(a.dtype)) for a in out_leaves
            ]
            unmatched = []
            for leaf in donated:
                key = (tuple(leaf.shape), np.dtype(leaf.dtype))
                if key in pool:
                    pool.remove(key)
                else:
                    unmatched.append(key)
            for shape, dtype in unmatched:
                yield self.finding(
                    target, 0,
                    f"donated buffer {shape} {dtype.name} has no shape/"
                    "dtype-matching output to alias — the donation buys "
                    "nothing",
                )


class CollectiveAudit(IRRule):
    id = "LUX105"
    title = "collective-audit"
    doc = ("collectives (psum/all_gather/...) must not appear in single-"
           "shard traces and must appear in sharded exchange traces")

    @staticmethod
    def _is_collective(name: str) -> bool:
        return any(
            name == c or name.startswith(c + "_") for c in COLLECTIVE_PRIMS
        )

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        seen: List[Tuple[int, str]] = []
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            if self._is_collective(eqn.primitive.name):
                seen.append((k, eqn.primitive.name))
        if target.sharded and not seen:
            yield self.finding(
                target, 0,
                "sharded exchange trace contains no collective — shards "
                "never communicate, so every shard computes on stale "
                "neighbor values",
            )
        if not target.sharded:
            for k, name in seen:
                yield self.finding(
                    target, k,
                    f"collective `{name}` in a single-shard trace — "
                    "either dead cross-device traffic or a program "
                    "traced with the wrong executor",
                )


def all_ir_rules() -> List[IRRule]:
    return [
        DtypeDrift(),
        HostCallback(),
        FootprintBlowup(),
        DonationAudit(),
        CollectiveAudit(),
    ]


# -- the exchange tier: collective-dataflow rules (LUX404-406) ----------
#
# The IR half of ``luxlint --exchange``. The plan tables are verified
# jax-free in analysis/exchck.py (LUX401-403); these rules prove the
# properties only the traced step can show: that the local-edge
# contribution is data-independent of the collective (the overlap
# contract), that pad values annihilate under the program's combiner,
# and that the advertised per-iteration collective bytes match what the
# jaxpr actually moves.

# The exchange data plane: collectives that MOVE VALUE ROWS between
# shards. psum/psum_scatter/ppermute are merge- or control-plane (they
# combine, not transport) and are deliberately excluded from the byte
# accounting — the executors' exchange_bytes_per_iter models price only
# the row transport.
DATA_COLLECTIVE_PRIMS = ("all_gather", "all_to_all")


def _is_data_collective(name: str) -> bool:
    return any(
        name == c or name.startswith(c + "_") for c in DATA_COLLECTIVE_PRIMS
    )


def _walk_jaxprs(jaxpr) -> Iterable:
    """Depth-first walk over a jaxpr and every sub-jaxpr it carries."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            for sub in _as_jaxprs(v):
                yield from _walk_jaxprs(sub)


def _is_lit(v) -> bool:
    """Literal operands carry ``val``; Vars don't (identity-free check
    that survives jax moving Literal between modules)."""
    return hasattr(v, "val")


# Per-trace memo for the dataflow/scalar analyses: LUX404 and LUX405
# both need the same global walk, and recomputing it doubles the
# exchange tier's wall cost. Keyed by identity with the closed jaxpr
# pinned in the entry so a recycled id can never alias a stale result.
_FLOW_MEMO: dict = {}


def _flow_memo(closed, key: str, builder):
    ent = _FLOW_MEMO.get(id(closed))
    if ent is None or ent[0] is not closed:
        if len(_FLOW_MEMO) > 32:
            _FLOW_MEMO.clear()
        ent = (closed, {})
        _FLOW_MEMO[id(closed)] = ent
    if key not in ent[1]:
        ent[1][key] = builder(closed)
    return ent[1][key]


def _global_dataflow(closed) -> Tuple[set, set, set]:
    return _flow_memo(closed, "flow", _global_dataflow_impl)


def _global_dataflow_impl(closed) -> Tuple[set, set, set]:
    """(tainted, axis, inputs) var sets over the WHOLE trace: vars
    transitively computed from a data collective's output, from
    ``axis_index``, and from the top jaxpr's invars respectively.

    Membership is propagated THROUGH sub-jaxpr boundaries (pjit /
    shard_map / cond / scan) by positional invar/outvar mapping — jnp
    helpers like ``jnp.where`` trace as nested pjit calls, so the
    local/remote merge usually sits one boundary below the collective
    and a per-jaxpr walk would be blind to it. Where an eqn's operand
    list cannot be aligned with a sub-jaxpr's invars (e.g. ``while``
    packing two consts lists), propagation degrades to the conservative
    union. Single forward pass: jaxpr equations are topologically
    ordered (loop-carried taint inside scan/while bodies is not chased
    to fixpoint; the step targets are single-iteration functions)."""
    tainted: set = set()
    axis: set = set()
    inputs: set = set()
    sets = (tainted, axis, inputs)

    def member(v) -> Tuple[bool, bool, bool]:
        if _is_lit(v):
            return (False, False, False)
        return tuple(v in s for s in sets)

    def mark(v, mem) -> None:
        for s, m in zip(sets, mem):
            if m:
                s.add(v)

    def union(mems):
        out = (False, False, False)
        for m in mems:
            out = tuple(a or b for a, b in zip(out, m))
        return out

    def visit(jaxpr) -> None:
        for eqn in jaxpr.eqns:
            nm = eqn.primitive.name
            subs: List = []
            for p in eqn.params.values():
                subs.extend(_as_jaxprs(p))
            if subs:
                outer = list(eqn.invars)
                if nm == "cond" and \
                        all(len(s.invars) == len(outer) - 1 for s in subs):
                    outer = outer[1:]   # predicate precedes the operands
                if all(len(s.invars) == len(outer) for s in subs):
                    for s in subs:
                        for o, iv in zip(outer, s.invars):
                            mark(iv, member(o))
                        visit(s)
                    if all(len(s.outvars) == len(eqn.outvars) for s in subs):
                        for s in subs:
                            for so, eo in zip(s.outvars, eqn.outvars):
                                mark(eo, member(so))
                        continue
                    mem = union(member(so) for s in subs
                                for so in s.outvars)
                    for eo in eqn.outvars:
                        mark(eo, mem)
                    continue
                # Unalignable boundary: conservative union in and out.
                mem = union(member(v) for v in eqn.invars)
                for s in subs:
                    for iv in s.invars:
                        mark(iv, mem)
                    visit(s)
                mem = union([mem] + [member(so) for s in subs
                                     for so in s.outvars])
                for eo in eqn.outvars:
                    mark(eo, mem)
                continue
            mem = union(member(v) for v in eqn.invars)
            if _is_data_collective(nm):
                mem = (True, mem[1], mem[2])
            if nm == "axis_index":
                mem = (mem[0], True, mem[2])
            for ov in eqn.outvars:
                mark(ov, mem)

    inputs.update(closed.jaxpr.invars)
    visit(closed.jaxpr)
    return tainted, axis, inputs


def _eqn_ordinals(jaxpr) -> dict:
    """id(eqn) -> 1-based ordinal in the same depth-first walk the
    other IR rules number findings by."""
    return {id(e): k for k, e in enumerate(iter_eqns(jaxpr), start=1)}


def _lit_scalar(v) -> Optional[float]:
    """The numeric value of a scalar Literal (or None)."""
    if not _is_lit(v):
        return None
    a = np.asarray(v.val)
    if a.size != 1 or a.dtype.kind not in "bifu":
        return None
    return float(a.reshape(-1)[0])


# Primitives through which a known scalar constant keeps its value
# (shape/dtype bookkeeping only — dtype conversion of +-inf and the
# integer identities is exact for the cases LUX405 compares).
_VALUE_PRESERVING_PRIMS = (
    "broadcast_in_dim", "reshape", "convert_element_type", "squeeze",
    "expand_dims", "copy", "slice",
)


def _closed_subs(v) -> List[Tuple[object, tuple]]:
    """(jaxpr, consts) pairs for sub-jaxprs, keeping ClosedJaxpr consts
    paired with their constvars (``_as_jaxprs`` drops them)."""
    from jax import core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        return [(v.jaxpr, tuple(v.consts))]
    if isinstance(v, jcore.Jaxpr):
        return [(v, ())]
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            out.extend(_closed_subs(x))
        return out
    return []


def _scalar_env(closed) -> dict:
    return _flow_memo(closed, "scalars", _scalar_env_impl)


def _scalar_env_impl(closed) -> dict:
    """Global scalar constant propagation: Var -> float for every var
    that provably holds one scalar value, across pjit/shard_map/cond
    boundaries (positional invar mapping) and through shape-only ops.
    This is how LUX405 recovers the pad constants the executors build
    with ``identity_for`` — by trace time they are consts threaded into
    the shard_map body, not Literals at the select."""
    env: dict = {}

    def value_of(v):
        lv = _lit_scalar(v)
        if lv is not None:
            return lv
        return env.get(v)

    def seed(jaxpr, consts):
        for cv, c in zip(jaxpr.constvars, consts):
            try:
                a = np.asarray(c)
            except Exception:
                continue
            if a.size == 1 and a.dtype.kind in "bifu":
                env[cv] = float(a.reshape(-1)[0])

    def visit(jaxpr):
        for eqn in jaxpr.eqns:
            nm = eqn.primitive.name
            if nm in _VALUE_PRESERVING_PRIMS and eqn.invars:
                val = value_of(eqn.invars[0])
                if val is not None:
                    for ov in eqn.outvars:
                        env[ov] = val
            for p in eqn.params.values():
                for sub, consts in _closed_subs(p):
                    seed(sub, consts)
                    outer = list(eqn.invars)
                    # cond consumes the predicate before the operands.
                    if nm == "cond" and len(outer) == len(sub.invars) + 1:
                        outer = outer[1:]
                    if len(outer) == len(sub.invars):
                        for o, iv in zip(outer, sub.invars):
                            val = value_of(o)
                            if val is not None:
                                env[iv] = val
                    visit(sub)

    seed(closed.jaxpr, tuple(closed.consts))
    visit(closed.jaxpr)
    return env


def _combiner_identity(combiner: str, dtype) -> Optional[float]:
    """The annihilator value for a combiner over ``dtype`` — mirrors
    ops/segment.identity_for (kept numerically identical by test)."""
    dt = np.dtype(dtype)
    if combiner == "sum":
        return 0.0
    if combiner == "min":
        return float(np.inf) if dt.kind == "f" else float(np.iinfo(dt).max)
    if combiner == "max":
        return float(-np.inf) if dt.kind == "f" else float(np.iinfo(dt).min)
    return None


class OverlapProof(IRRule):
    id = "LUX404"
    title = "overlap-proof"
    doc = ("compact targets must merge an untainted input-derived local "
           "contribution against the collective's result — proves the "
           "local-edge work is data-independent of the exchange")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        # Frontier targets keep the compact plan's packed all_to_all as
        # the dense-iteration branch, so the same merge proof applies.
        if target.exchange_mode not in ("compact", "frontier"):
            return
        ordinals = _eqn_ordinals(closed.jaxpr)
        tainted, axis, inputs = _global_dataflow(closed)
        good: List = []
        bad: List = []
        saw_collective = False
        for eqn in iter_eqns(closed.jaxpr):
            nm = eqn.primitive.name
            if _is_data_collective(nm):
                saw_collective = True
            elif nm in ("select_n", "select") and len(eqn.invars) >= 3:
                # The local/remote merge: predicate derived from
                # axis_index (ownership test), at least one case from
                # the collective. The merge is proven iff some case is
                # an untainted function of the step's own inputs — the
                # local contribution.
                pred, cases = eqn.invars[0], eqn.invars[1:]
                if _is_lit(pred) or pred not in axis or pred in tainted:
                    continue
                if not any((not _is_lit(c)) and c in tainted
                           for c in cases):
                    continue
                ok = any((not _is_lit(c)) and c not in tainted
                         and c in inputs for c in cases)
                (good if ok else bad).append(eqn)
            elif nm == "dynamic_update_slice" and len(eqn.invars) >= 3:
                # The tiled merge: own shard written into the gathered
                # table at an axis-derived offset.
                op, upd = eqn.invars[0], eqn.invars[1]
                starts = eqn.invars[2:]
                if not any((not _is_lit(s)) and s in axis
                           for s in starts):
                    continue
                if not any((not _is_lit(x)) and x in tainted
                           for x in (op, upd)):
                    continue
                ok = (not _is_lit(upd)) and upd not in tainted \
                    and upd in inputs
                (good if ok else bad).append(eqn)
        if not saw_collective:
            return   # no exchange traced at all — LUX105's finding
        if good:
            return   # overlap proven: local side never waits on the wire
        if bad:
            eqn = bad[0]
            yield self.finding(
                target, ordinals.get(id(eqn), 0),
                f"local/remote merge `{eqn.primitive.name}` consumes the "
                "collective's result on every data side — the local-edge "
                "contribution transitively depends on the exchange, so "
                "the advertised compute/communication overlap cannot "
                "exist",
            )
        else:
            yield self.finding(
                target, 0,
                "no local/remote merge point found downstream of the "
                "data collective — cannot prove the local-edge "
                "contribution is independent of the exchange",
            )


class SentinelAnnihilator(IRRule):
    id = "LUX405"
    title = "sentinel-annihilator"
    doc = ("pad values merged into the exchanged data path must be the "
           "program combiner's identity (+inf/int-max for min, 0 for "
           "sum) so sentinel traffic can never reach a result")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        if target.exchange_mode not in ("compact", "frontier") or \
                target.combiner not in ("min", "max", "sum"):
            return
        comb = target.combiner
        vdt = np.dtype(target.value_dtype) if target.value_dtype else None
        env = _scalar_env(closed)
        ordinals = _eqn_ordinals(closed.jaxpr)
        tainted, _, _ = _global_dataflow(closed)
        wrong: List[Tuple] = []
        found_ident = False
        saw_collective = False
        for eqn in iter_eqns(closed.jaxpr):
            nm = eqn.primitive.name
            if _is_data_collective(nm):
                saw_collective = True
            elif nm in ("select_n", "select") and len(eqn.invars) >= 3:
                cases = eqn.invars[1:]
                if not any((not _is_lit(c)) and c in tainted
                           for c in cases):
                    continue
                dt = np.dtype(getattr(eqn.outvars[0].aval, "dtype",
                                      np.float32))
                if dt.kind == "b":
                    continue   # frontier masks, no numeric identity
                if vdt is not None and dt != vdt:
                    continue   # index/queue plane, not the value rows
                ident = _combiner_identity(comb, dt)
                for c in cases:
                    val = _lit_scalar(c)
                    if val is None and not _is_lit(c):
                        val = env.get(c)
                    if val is None:
                        continue
                    if val == ident:
                        found_ident = True
                    else:
                        wrong.append((eqn, val, ident, dt))
            elif comb == "sum" and nm.startswith("scatter") and \
                    len(eqn.invars) >= 3:
                # Summing programs annihilate pads by scattering into a
                # zero-filled receive buffer: a nonzero fill would be
                # added into every touched row.
                op, upd = eqn.invars[0], eqn.invars[2]
                if _is_lit(upd) or upd not in tainted:
                    continue
                val = _lit_scalar(op)
                if val is None and not _is_lit(op):
                    val = env.get(op)
                if val is None:
                    continue
                dt = np.dtype(getattr(eqn.outvars[0].aval, "dtype",
                                      np.float32))
                if vdt is not None and dt != vdt:
                    continue   # index/queue plane, not the value rows
                if val == 0.0:
                    found_ident = True
                else:
                    wrong.append((eqn, val, 0.0, dt))
        for eqn, val, ident, dt in wrong:
            yield self.finding(
                target, ordinals.get(id(eqn), 0),
                f"pad constant {val:g} flows into the exchanged data "
                f"path through `{eqn.primitive.name}` but the {comb} "
                f"identity for {dt.name} is {ident:g} — sentinel slots "
                "leak into results",
            )
        if saw_collective and not wrong and not found_ident:
            yield self.finding(
                target, 0,
                f"no {comb}-identity pad constant guards the exchanged "
                "candidates — cannot prove sentinel traffic is "
                "annihilated before the combiner",
            )


def _collective_byte_totals(jaxpr, num_parts: int) -> set:
    """Set of possible per-iteration data-collective byte totals for
    one step. A set, not a number: ``cond`` branches are execution
    ALTERNATIVES (the push engine's sparse/dense split), so each branch
    contributes its own total; everything else composes additively.
    Pricing (whole-mesh bytes crossing the interconnect per iteration,
    operand = the per-shard array inside shard_map):

    - all_gather: every shard receives every OTHER shard's operand —
      ``P * (P-1) * operand_bytes``;
    - all_to_all: each shard keeps its own 1/P chunk and sends the
      rest — ``(P-1) * operand_bytes`` summed over the mesh.
    """
    P = num_parts
    totals = {0}
    for eqn in jaxpr.eqns:
        nm = eqn.primitive.name
        if _is_data_collective(nm):
            opb = sum(_aval_bytes(v.aval) for v in eqn.invars
                      if hasattr(v, "aval"))
            add = {P * (P - 1) * opb if nm.startswith("all_gather")
                   else (P - 1) * opb}
        elif nm == "cond":
            add = set()
            for sub in _as_jaxprs(eqn.params.get("branches", ())):
                add |= _collective_byte_totals(sub, P)
        else:
            add = {0}
            for p in eqn.params.values():
                for sub in _as_jaxprs(p):
                    sub_totals = _collective_byte_totals(sub, P)
                    add = {a + s for a in add for s in sub_totals}
        if add and add != {0}:
            totals = {t + a for t in totals for a in add}
            if len(totals) > 1024:   # runaway-branch backstop
                totals = set(sorted(totals)[:1024])
    return totals


class ExchangeByteAccounting(IRRule):
    id = "LUX406"
    title = "exchange-byte-accounting"
    doc = ("the executor's exchange_bytes_per_iter claim must equal the "
           "per-iteration data-collective bytes statically derived from "
           "the traced step")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        if target.exchange_bytes is None or target.num_parts < 2:
            return
        totals = _collective_byte_totals(closed.jaxpr, target.num_parts)
        if int(target.exchange_bytes) not in totals:
            shown = ", ".join(str(t) for t in sorted(totals)[:8])
            yield self.finding(
                target, 0,
                f"executor claims exchange_bytes_per_iter = "
                f"{target.exchange_bytes} but the traced step's data "
                f"collectives move {{{shown}}} bytes per iteration "
                "(all_gather P*(P-1)*operand, all_to_all (P-1)*operand; "
                "cond branches are alternatives) — the byte model "
                "drifted from the exchange the step performs",
            )


def exchange_ir_rules(select=None) -> List[IRRule]:
    rules: List[IRRule] = [
        OverlapProof(), SentinelAnnihilator(), ExchangeByteAccounting(),
    ]
    if select:
        rules = [r for r in rules if r.id in select]
    return rules


# -- runner -------------------------------------------------------------

def check_target(target: TraceTarget,
                 rules: Sequence[IRRule]) -> FileResult:
    """Trace one target and run the given rules over its jaxpr."""
    try:
        closed = trace_target(target)
    except Exception as e:   # traced user code: anything can raise
        return FileResult(
            target.name, [], [],
            error=f"{target.name}: trace failed: {e!r}")
    findings: List[Finding] = []
    errors: List[str] = []
    for rule in rules:
        try:
            findings.extend(rule.check(closed, target))
        except Exception as e:
            errors.append(f"{target.name}: {rule.id} crashed: {e!r}")
    findings.sort(key=lambda f: (f.line, f.rule))
    return FileResult(
        target.name, findings, [], error="; ".join(errors) or None)


def run_targets(targets: Sequence[TraceTarget],
                rules: Optional[Sequence[IRRule]] = None) -> LintReport:
    """Trace every target and run the IR rules over the jaxprs."""
    t0 = time.perf_counter()
    if rules is None:
        rules = all_ir_rules()
    results = [check_target(t, rules) for t in targets]
    return LintReport(results, time.perf_counter() - t0, schema=IR_SCHEMA)


# -- the registry trace matrix ------------------------------------------

def _tiny_graph(weighted: bool, seed: int):
    """Small synthetic graph: big enough to exercise every code path's
    shapes, small enough that building executors stays milliseconds."""
    from lux_tpu.graph.generate import gnp

    return gnp(96, 400, seed=seed, weighted=weighted)


def build_executor(kind: str, graph, program):
    """One executor of the given kind over (graph, program) — the same
    constructions cli.py / serve use, defaults throughout."""
    if kind == "pull":
        from lux_tpu.engine.pull import PullExecutor
        return PullExecutor(graph, program)
    if kind == "tiled":
        from lux_tpu.engine.tiled import TiledPullExecutor
        return TiledPullExecutor(graph, program)
    if kind == "push":
        from lux_tpu.engine.push import PushExecutor
        return PushExecutor(graph, program)
    if kind == "push_multi":
        from lux_tpu.engine.push import MultiSourcePushExecutor
        return MultiSourcePushExecutor(graph, program, k=4)
    if kind == "push_incremental":
        from lux_tpu.engine.incremental import IncrementalExecutor
        return IncrementalExecutor(graph, program)
    if kind == "pull_sharded":
        from lux_tpu.engine.pull_sharded import ShardedPullExecutor
        return ShardedPullExecutor(graph, program)
    if kind == "tiled_sharded":
        from lux_tpu.engine.tiled_sharded import ShardedTiledExecutor
        return ShardedTiledExecutor(graph, program)
    if kind == "push_sharded":
        from lux_tpu.engine.push import ShardedPushExecutor
        return ShardedPushExecutor(graph, program)
    if kind == "push_multi_sharded":
        from lux_tpu.engine.push import ShardedMultiSourcePushExecutor
        return ShardedMultiSourcePushExecutor(graph, program, k=4)
    if kind == "gas":
        from lux_tpu.engine.gas import AdaptiveExecutor, as_gas
        return AdaptiveExecutor(graph, as_gas(program))
    if kind == "gas_multi":
        from lux_tpu.engine.gas import MultiSourceGasExecutor
        return MultiSourceGasExecutor(graph, program, k=4)
    if kind == "gas_sharded":
        from lux_tpu.engine.gas_sharded import ShardedAdaptiveExecutor
        return ShardedAdaptiveExecutor(graph, program)
    if kind == "gas_multi_sharded":
        from lux_tpu.engine.gas_sharded import ShardedMultiSourceGasExecutor
        return ShardedMultiSourceGasExecutor(graph, program, k=4)
    raise ValueError(f"unknown executor kind {kind!r}")


def _compact_graph(kind: str, weighted: bool, seed: int):
    """Graph whose partition actually engages the compact exchange: the
    row-granular engines need read locality (small_world's ring plus a
    contiguous edge-balanced partition leaves only boundary reads), the
    tiled engine needs hub concentration (rmat's Kronecker skew keeps
    strip reads on the few hub blocks). The tiny gnp used for the plain
    targets is all-remote at this size, which would fall back to full
    and silently shrink audit coverage of the compact collectives."""
    from lux_tpu.graph.generate import rmat, small_world
    from lux_tpu.graph.graph import Graph

    if kind == "tiled_sharded":
        return rmat(12, 8, seed=seed, weighted=weighted)
    g = small_world(1024, k=4, p_rewire=0.05, seed=seed)
    if weighted:
        rng = np.random.default_rng(seed)
        g = Graph(nv=g.nv, ne=g.ne, row_ptr=g.row_ptr, col_src=g.col_src,
                  weights=rng.integers(1, 101, g.ne, dtype=np.int32))
    return g


def _registry_executors(include_sharded: bool = True,
                        sharded_only: bool = False):
    """Yield ``(name, kind, executor, init_kw)`` for every registered
    program x capable executor. Sharded kinds are built twice: once
    with the default full exchange and once under
    ``LUX_EXCHANGE=compact`` (``{name}@{kind}+compact``), so the audits
    cover the packed all_to_all path too."""
    import os

    from lux_tpu.models import PROGRAMS, ROOTED_APPS, engine_kinds
    from lux_tpu.utils.logging import get_logger

    for i, name in enumerate(sorted(PROGRAMS)):
        program = PROGRAMS[name]()
        weighted = bool(getattr(program, "needs_weights", False))
        graph = None
        init_kw = {"start": 0} if name in ROOTED_APPS else {}
        for kind in engine_kinds(name):
            sharded = kind.endswith("sharded")
            if sharded and not include_sharded:
                continue
            if sharded_only and not sharded:
                continue
            if graph is None:
                graph = _tiny_graph(weighted=weighted, seed=7 + i)
            ex = build_executor(kind, graph, program)
            yield f"{name}@{kind}", kind, ex, init_kw
            if not sharded:
                continue
            # luxlint: disable=LUX005 -- save/restore needs the raw set-vs-unset env entry, which the typed accessors erase
            prev = os.environ.get("LUX_EXCHANGE")
            os.environ["LUX_EXCHANGE"] = "compact"
            try:
                exc = build_executor(
                    kind, _compact_graph(kind, weighted, 7 + i), program)
            finally:
                if prev is None:
                    os.environ.pop("LUX_EXCHANGE", None)
                else:
                    os.environ["LUX_EXCHANGE"] = prev
            if getattr(exc, "exchange_mode", "full") != "compact":
                # Coverage loss must be visible, not silent.
                get_logger("luxlint").warning(
                    "%s@%s+compact fell back to the full exchange; "
                    "compact collectives untraced for this target",
                    name, kind)
                continue
            yield f"{name}@{kind}+compact", kind, exc, init_kw
            if kind != "gas_sharded":
                continue
            # The adaptive GAS engine additionally carries the
            # frontier-compacted send (LUX_EXCHANGE=frontier): trace it
            # too so LUX404-407 cover the activity-packed all_to_all.
            os.environ["LUX_EXCHANGE"] = "frontier"
            try:
                exf = build_executor(
                    kind, _compact_graph(kind, weighted, 7 + i), program)
            finally:
                if prev is None:
                    os.environ.pop("LUX_EXCHANGE", None)
                else:
                    os.environ["LUX_EXCHANGE"] = prev
            if getattr(exf, "exchange_mode", "full") != "frontier":
                # Frontier-less programs downgrade to compact by design
                # (no activity plane to pack); only a frontier program
                # landing elsewhere is lost coverage.
                if getattr(exf.program, "frontier", False):
                    get_logger("luxlint").warning(
                        "%s@%s+frontier fell back to %s; frontier "
                        "collectives untraced for this target",
                        name, kind, exf.exchange_mode)
                continue
            yield f"{name}@{kind}+frontier", kind, exf, init_kw


def registry_targets(include_sharded: bool = True) -> List[TraceTarget]:
    """Trace targets for every registered program x capable executor
    (see ``_registry_executors`` for the compact-variant policy)."""
    return [
        target_from_spec(name, ex.trace_step(**init_kw))
        for name, _, ex, init_kw in _registry_executors(include_sharded)
    ]


# Value-row byte price per exchanged unit row for each plan-carrying
# executor kind — the same figures the engines' exchange_bytes_per_iter
# models use (pull: program row width x value itemsize; push: 4 B
# uint32 value + 1 B bool frontier per lane; tiled: float32 elements).
def _exchange_row_bytes(kind: str, ex) -> Optional[int]:
    if kind == "pull_sharded":
        return int(ex._row_bytes())
    if kind == "push_sharded":
        return 5
    if kind == "push_multi_sharded":
        return 5 * int(ex.k)
    if kind == "tiled_sharded":
        return 4
    if kind in ("gas_sharded", "gas_multi_sharded"):
        return int(ex._row_bytes())
    return None


def _plan_evidence(kind: str, ex, plan) -> dict:
    """LUX402/403 evidence for a live plan-carrying executor: the
    remote-read counts matrix, the row price, and the exchange ledger
    exactly as the observatory would publish it."""
    from lux_tpu.obs import engobs

    row_bytes = _exchange_row_bytes(kind, ex)
    counts = None
    ledger = None
    sg = getattr(ex, "sg", None)
    if sg is not None and hasattr(sg, "remote_read_counts"):
        counts = sg.remote_read_counts()
        if counts is not None and row_bytes is not None:
            ledger = engobs.useful_exchange(
                sg, row_bytes,
                exchanged_rows=plan.exchanged_units_per_iter)
    if counts is None:
        counts = getattr(ex, "_remote_read_counts", None)
        if counts is not None and row_bytes is not None:
            # The tiled executor's block-granular ledger (its run()
            # computes the same figures inline).
            c = np.asarray(counts, np.int64)
            exchanged = plan.exchanged_units_per_iter * plan.unit_rows
            useful = int(c.sum() - np.trace(c))
            ledger = {
                "useful_rows": useful,
                "exchanged_rows": exchanged,
                "useful_bytes_per_iter": useful * row_bytes,
                "ratio": useful / max(exchanged, 1),
            }
    out = {"remote_read_counts": counts, "row_bytes": row_bytes,
           "ledger": ledger}
    # Frontier-exchange evidence (LUX407), present only on the adaptive
    # GAS executor built under LUX_EXCHANGE=frontier.
    fe = getattr(ex, "frontier_evidence", None)
    if callable(fe):
        out.update(fe() or {})
    return out


def run_exchange_matrix(select=None) -> LintReport:
    """``luxlint --exchange`` with no paths: the LUX404-406 dataflow
    rules over every full+compact sharded registry target, plus the
    jax-free LUX401-403 plan rules over each live compact plan
    (reported as ``{target}/plan``)."""
    from lux_tpu.analysis import exchck

    ir_rules = exchange_ir_rules(select)
    plan_rules = [r for r in exchck.all_exchange_rules()
                  if select is None or r.id in select]
    # Executor construction is environment setup, not verification —
    # keep it outside the timer exactly like the IR tier does (its
    # registry_targets build happens before run_targets starts timing).
    staged = list(_registry_executors(sharded_only=True))
    results: List[FileResult] = []
    t0 = time.perf_counter()
    for name, kind, ex, init_kw in staged:
        t = target_from_spec(name, ex.trace_step(**init_kw))
        results.append(check_target(t, ir_rules))
        if t.plan is not None:
            view = exchck.plan_view(
                t.plan, declared_bytes_per_iter=t.exchange_bytes,
                **_plan_evidence(kind, ex, t.plan))
            results.append(exchck.verify_exchange_plan(
                view, f"{name}/plan", plan_rules))
    return LintReport(results, time.perf_counter() - t0,
                      schema=exchck.EXCHANGE_SCHEMA)


def run_exchange_paths(paths: Sequence[str], select=None) -> LintReport:
    """``luxlint --exchange`` over explicit paths: ``.py`` fixtures
    exposing ``TRACES`` (IR rules) and/or ``PLANS`` (plan rules), and
    saved exchange-artifact directories."""
    import os

    from lux_tpu.analysis import exchck

    t0 = time.perf_counter()
    ir_rules = exchange_ir_rules(select)
    plan_rules = [r for r in exchck.all_exchange_rules()
                  if select is None or r.id in select]
    results: List[FileResult] = []
    for path in paths:
        if os.path.isdir(path):
            try:
                view = exchck.load_exchange_artifact(path)
            except Exception as e:
                results.append(FileResult(
                    path, [], [],
                    error=f"{path}: unloadable plan: {e!r}"))
                continue
            results.append(
                exchck.verify_exchange_plan(view, path, plan_rules))
            continue
        try:
            try:
                targets = load_fixture_targets(path)
            except ValueError:
                targets = []     # PLANS-only fixture
            plans = exchck.load_fixture_plans(path)
        except Exception as e:
            results.append(FileResult(
                path, [], [], error=f"{path}: unloadable fixture: {e!r}"))
            continue
        if not targets and not plans:
            results.append(FileResult(
                path, [], [],
                error=f"{path}: fixture exposes neither TRACES nor PLANS"))
            continue
        results.extend(check_target(t, ir_rules) for t in targets)
        results.extend(exchck.verify_exchange_plan(v, nm, plan_rules)
                       for nm, v in plans)
    return LintReport(results, time.perf_counter() - t0,
                      schema=exchck.EXCHANGE_SCHEMA)


def load_fixture_targets(path: str) -> List[TraceTarget]:
    """Targets from a fixture module exposing ``TRACES`` (a list of
    trace dicts with a ``name`` key) — the seeded-violation harness."""
    import importlib.util
    import os

    modname = "_luxlint_ir_fixture_" + \
        os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(modname, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load fixture module {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    traces = getattr(mod, "TRACES", None)
    if not traces:
        raise ValueError(f"fixture {path} exposes no TRACES")
    return [
        target_from_spec(t.get("name", f"{path}#{i}"), t)
        for i, t in enumerate(traces)
    ]


def audit_engine(engine, name: str, **init_kw) -> List[Finding]:
    """Build-time donation audit of one executor (serve/pool.py hook):
    LUX104 only — one abstract lowering, no trace walk, no execution.
    Engines without ``trace_step`` are silently fine."""
    ts = getattr(engine, "trace_step", None)
    if ts is None:
        return []
    target = target_from_spec(name, ts(**init_kw))
    rule = DonationAudit()
    try:
        # check() needs no jaxpr for LUX104; pass None explicitly.
        return list(rule.check(None, target))
    except Exception as e:
        return [Finding(rule.id, name, 0, 0,
                        f"donation audit crashed: {e!r}")]
