"""luxlint-IR: rules over *traced* programs (jaxprs), not source text.

The AST tier (analysis/rules.py) sees what the code says; this tier sees
what the traced computation actually does. Every registered program ×
executor step is traced to a ClosedJaxpr on a tiny synthetic graph —
abstract eval only, nothing runs on a device — and the equations are
walked by the LUX1xx rules:

- LUX101 dtype-drift: a carry leaf whose dtype differs between loop
  input and output reshapes/retraces every iteration; silent promotion
  to a 64-bit dtype doubles HBM and halves VPU throughput.
- LUX102 host-callback: ``pure_callback``/``debug_callback``/
  ``io_callback`` inside a jitted step is a hidden device->host round
  trip per iteration (the LUX001 failure mode, visible post-trace even
  when the AST can't see it).
- LUX103 footprint-blowup: a static per-eqn cost model flags any traced
  intermediate larger than ``LUX_IR_BLOWUP`` x the step's total input
  bytes — the O(nnz)-broadcast class of bugs, caught before a 2^31-edge
  run OOMs.
- LUX104 donation-audit: args declared in ``donate_argnums`` whose
  buffers the lowered executable does not actually alias (the donation
  silently buys nothing and HBM holds two copies).
- LUX105 collective-audit: collectives in a single-shard trace, or a
  sharded exchange trace with no collective at all (the ZC-exchange
  surface wired wrong).

Tracing is cheap (~ms per target) but imports jax — keep this module
OUT of the AST tier's import path; ``tools/luxlint.py`` loads it only
under ``--ir``.

Executors participate by exposing ``trace_step(**init_kw)`` returning a
plain dict (no dependency on this module)::

    {"kind": "pull",            # executor kind, for the target name
     "fn": self._step,          # the jitted step callable itself
     "args": (vals, dgraph),    # example args exactly as run() passes
     "donate": (0,),            # argnums the jit donates
     "carry": (0,),             # argnums whose leaves are the carry
     "sharded": False}          # True when collectives are expected

with optional ``call``/``lower`` overrides when the jit takes static
arguments the example args don't show (MultiSourcePushExecutor). The
contract relied on by LUX101: the step's flattened outputs begin with
the new carry, leaf-for-leaf against the flattened carry args.
"""

from __future__ import annotations

import dataclasses
import re
import time
import warnings
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from lux_tpu.analysis.core import FileResult, Finding, LintReport
from lux_tpu.utils import flags

IR_SCHEMA = "luxlint.ir.v1"

# Primitive-name fragments identifying host callbacks (LUX102) and
# cross-device collectives (LUX105). Matched by name, not identity, so
# the rule set survives jax moving primitives between modules.
CALLBACK_PRIMS = ("pure_callback", "debug_callback", "io_callback")
COLLECTIVE_PRIMS = (
    "psum", "pmax", "pmin", "ppermute", "pgather", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter",
)


@dataclasses.dataclass
class TraceTarget:
    """One traceable step: a callable + example args + audit metadata."""

    name: str                       # e.g. "pagerank@pull"
    call: Callable                  # callable(*args) -> step outputs
    args: Tuple = ()                # example args (dynamic only)
    donate: Tuple[int, ...] = ()    # argnums donated by the real jit
    carry: Tuple[int, ...] = (0,)   # argnums forming the iteration carry
    sharded: bool = False           # collectives expected iff True
    lower: Optional[Callable] = None  # () -> jax.stages.Lowered
    axis_env: Tuple = ()            # [(name, size)] for axis-using fns


def target_from_spec(name: str, spec: dict) -> TraceTarget:
    """Normalize an executor's (or fixture's) trace dict to a target."""
    fn = spec.get("fn")
    call = spec.get("call", fn)
    if call is None:
        raise ValueError(f"trace spec {name!r} has neither 'call' nor 'fn'")
    args = tuple(spec.get("args", ()))
    lower = spec.get("lower")
    if lower is None and hasattr(fn, "lower"):
        lower = lambda fn=fn, args=args: fn.lower(*args)  # noqa: E731
    return TraceTarget(
        name=name, call=call, args=args,
        donate=tuple(spec.get("donate", ())),
        carry=tuple(spec.get("carry", (0,))),
        sharded=bool(spec.get("sharded", False)),
        lower=lower,
        axis_env=tuple(spec.get("axis_env", ())),
    )


def trace_target(target: TraceTarget):
    """Abstract-eval the target to a ClosedJaxpr (no device work)."""
    import jax

    if target.axis_env:
        mk = jax.make_jaxpr(target.call, axis_env=list(target.axis_env))
    else:
        mk = jax.make_jaxpr(target.call)
    return mk(*target.args)


# -- jaxpr walking ------------------------------------------------------

def _as_jaxprs(v) -> List:
    from jax import core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        return [v.jaxpr]
    if isinstance(v, jcore.Jaxpr):
        return [v]
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            out.extend(_as_jaxprs(x))
        return out
    return []


def iter_eqns(jaxpr) -> Iterable:
    """Depth-first walk over every eqn, descending into sub-jaxprs
    (pjit/scan/while/cond/shard_map/custom_* all carry theirs in
    params; matching by type keeps the walk version-proof)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _as_jaxprs(v):
                yield from iter_eqns(sub)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _carry_leaf_indices(target: TraceTarget) -> List[int]:
    """Flat in_aval indices of the carry args (args flatten in order)."""
    import jax

    out: List[int] = []
    pos = 0
    for i, a in enumerate(target.args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in target.carry:
            out.extend(range(pos, pos + n))
        pos += n
    return out


# -- the rules ----------------------------------------------------------

class IRRule:
    """One IR rule: an id, a one-line doc, a check over a ClosedJaxpr."""

    id = "LUX100"
    title = "base ir rule"
    doc = ""

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, target: TraceTarget, line: int, message: str) -> Finding:
        # `line` is the 1-based eqn ordinal in the depth-first walk
        # (0 = a target-level finding with no single eqn to blame).
        return Finding(self.id, target.name, line, 0, message)


class DtypeDrift(IRRule):
    id = "LUX101"
    title = "dtype-drift"
    doc = ("carry dtype must be identical between loop input and output; "
           "no silent promotion to 64-bit dtypes inside the step")

    @staticmethod
    def _wide(dtype) -> bool:
        dt = np.dtype(dtype)
        return dt.kind in "fiuc" and dt.itemsize >= 8

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        carry_idx = _carry_leaf_indices(target)
        in_avals, out_avals = closed.in_avals, closed.out_avals
        if len(carry_idx) > len(out_avals):
            yield self.finding(
                target, 0,
                f"carry has {len(carry_idx)} leaves but the step returns "
                f"only {len(out_avals)} outputs — the carry cannot round-"
                "trip through this step",
            )
            return
        for j, idx in enumerate(carry_idx):
            din = getattr(in_avals[idx], "dtype", None)
            dout = getattr(out_avals[j], "dtype", None)
            if din is not None and dout is not None and din != dout:
                yield self.finding(
                    target, 0,
                    f"carry leaf {j} enters as {din} and leaves as {dout} "
                    "— every iteration converts (or retraces) the carry",
                )
        if any(self._wide(a.dtype) for a in in_avals
               if getattr(a, "dtype", None) is not None):
            return   # 64-bit inputs make 64-bit intermediates legitimate
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            for ov in eqn.outvars:
                dt = getattr(ov.aval, "dtype", None)
                if dt is not None and self._wide(dt):
                    yield self.finding(
                        target, k,
                        f"`{eqn.primitive.name}` silently promotes to "
                        f"{np.dtype(dt).name} with no 64-bit input — "
                        "x64 drift doubles HBM for the affected values",
                    )


class HostCallback(IRRule):
    id = "LUX102"
    title = "host-callback"
    doc = ("no pure_callback/debug_callback/io_callback inside a jitted "
           "hot-path step (hidden host round trip per iteration)")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            name = eqn.primitive.name
            if name in CALLBACK_PRIMS or name.endswith("callback"):
                yield self.finding(
                    target, k,
                    f"host callback `{name}` in the jitted step — every "
                    "iteration stalls on a device->host->device round "
                    "trip",
                )


class FootprintBlowup(IRRule):
    id = "LUX103"
    title = "footprint-blowup"
    doc = ("no traced intermediate may exceed LUX_IR_BLOWUP x the "
           "step's total input bytes (static per-eqn cost model)")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        ratio = flags.get_float("LUX_IR_BLOWUP")
        base = sum(_aval_bytes(a) for a in closed.in_avals)
        base += sum(int(getattr(c, "nbytes", 0)) for c in closed.consts)
        limit = ratio * max(base, 1)
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            for ov in eqn.outvars:
                nbytes = _aval_bytes(ov.aval)
                if nbytes > limit:
                    aval = ov.aval
                    yield self.finding(
                        target, k,
                        f"`{eqn.primitive.name}` materializes "
                        f"{tuple(aval.shape)} {np.dtype(aval.dtype).name} "
                        f"({nbytes / 2**20:.1f} MiB) = "
                        f"{nbytes / max(base, 1):.0f}x the step inputs "
                        f"(limit {ratio:g}x, LUX_IR_BLOWUP)",
                    )


def _main_arg_attrs(mlir_text: str) -> Optional[str]:
    """The argument list of the entry function in lowered StableHLO
    text (between ``@main(`` and its closing paren), or None."""
    m = re.search(r"func\.func (?:public )?@main\(", mlir_text)
    if m is None:
        return None
    start = m.end()
    depth = 1
    for i in range(start, len(mlir_text)):
        ch = mlir_text[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return mlir_text[start:i]
    return None


class DonationAudit(IRRule):
    id = "LUX104"
    title = "donation-audit"
    doc = ("every donate_argnums buffer must actually be aliased to an "
           "output by the lowered executable (else the donation buys "
           "nothing and HBM holds two copies)")

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        import jax

        if not target.donate or target.lower is None:
            return
        donated = []
        for i in target.donate:
            if i < len(target.args):
                donated.extend(jax.tree_util.tree_leaves(target.args[i]))
        expected = len(donated)
        if expected == 0:
            return
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            lowered = target.lower()
        sig = _main_arg_attrs(lowered.as_text())
        if sig is None:
            yield self.finding(
                target, 0,
                "could not locate @main in the lowered module — donation "
                "audit impossible for this target",
            )
            return
        # Single-shard lowerings resolve aliasing right away
        # (`tf.aliasing_output = N`); sharded lowerings defer the pairing
        # to the compiler and only mark `jax.buffer_donor = true`.
        aliased = sig.count("tf.aliasing_output")
        deferred = sig.count("jax.buffer_donor")
        if aliased + deferred < expected:
            notes = "; ".join(
                str(w.message) for w in caught
                if "donat" in str(w.message).lower()
            )
            detail = f" ({notes})" if notes else ""
            yield self.finding(
                target, 0,
                f"{expected - (aliased + deferred)} of {expected} donated "
                "buffers are not aliased to any output — the executable "
                f"copies instead of reusing them{detail}",
            )
            return
        if deferred:
            # The compiler will alias a deferred donor only if some
            # output matches its shape+dtype — check that statically.
            if closed is not None:
                out_leaves = [
                    a for a in closed.out_avals if hasattr(a, "shape")
                ]
            else:
                out_tree = jax.eval_shape(target.call, *target.args)
                out_leaves = jax.tree_util.tree_leaves(out_tree)
            pool = [
                (tuple(a.shape), np.dtype(a.dtype)) for a in out_leaves
            ]
            unmatched = []
            for leaf in donated:
                key = (tuple(leaf.shape), np.dtype(leaf.dtype))
                if key in pool:
                    pool.remove(key)
                else:
                    unmatched.append(key)
            for shape, dtype in unmatched:
                yield self.finding(
                    target, 0,
                    f"donated buffer {shape} {dtype.name} has no shape/"
                    "dtype-matching output to alias — the donation buys "
                    "nothing",
                )


class CollectiveAudit(IRRule):
    id = "LUX105"
    title = "collective-audit"
    doc = ("collectives (psum/all_gather/...) must not appear in single-"
           "shard traces and must appear in sharded exchange traces")

    @staticmethod
    def _is_collective(name: str) -> bool:
        return any(
            name == c or name.startswith(c + "_") for c in COLLECTIVE_PRIMS
        )

    def check(self, closed, target: TraceTarget) -> Iterable[Finding]:
        seen: List[Tuple[int, str]] = []
        for k, eqn in enumerate(iter_eqns(closed.jaxpr), start=1):
            if self._is_collective(eqn.primitive.name):
                seen.append((k, eqn.primitive.name))
        if target.sharded and not seen:
            yield self.finding(
                target, 0,
                "sharded exchange trace contains no collective — shards "
                "never communicate, so every shard computes on stale "
                "neighbor values",
            )
        if not target.sharded:
            for k, name in seen:
                yield self.finding(
                    target, k,
                    f"collective `{name}` in a single-shard trace — "
                    "either dead cross-device traffic or a program "
                    "traced with the wrong executor",
                )


def all_ir_rules() -> List[IRRule]:
    return [
        DtypeDrift(),
        HostCallback(),
        FootprintBlowup(),
        DonationAudit(),
        CollectiveAudit(),
    ]


# -- runner -------------------------------------------------------------

def run_targets(targets: Sequence[TraceTarget],
                rules: Optional[Sequence[IRRule]] = None) -> LintReport:
    """Trace every target and run the IR rules over the jaxprs."""
    t0 = time.perf_counter()
    if rules is None:
        rules = all_ir_rules()
    results: List[FileResult] = []
    for t in targets:
        try:
            closed = trace_target(t)
        except Exception as e:   # traced user code: anything can raise
            results.append(FileResult(
                t.name, [], [], error=f"{t.name}: trace failed: {e!r}"))
            continue
        findings: List[Finding] = []
        errors: List[str] = []
        for rule in rules:
            try:
                findings.extend(rule.check(closed, t))
            except Exception as e:
                errors.append(f"{t.name}: {rule.id} crashed: {e!r}")
        findings.sort(key=lambda f: (f.line, f.rule))
        results.append(FileResult(
            t.name, findings, [], error="; ".join(errors) or None))
    return LintReport(results, time.perf_counter() - t0, schema=IR_SCHEMA)


# -- the registry trace matrix ------------------------------------------

def _tiny_graph(weighted: bool, seed: int):
    """Small synthetic graph: big enough to exercise every code path's
    shapes, small enough that building executors stays milliseconds."""
    from lux_tpu.graph.generate import gnp

    return gnp(96, 400, seed=seed, weighted=weighted)


def build_executor(kind: str, graph, program):
    """One executor of the given kind over (graph, program) — the same
    constructions cli.py / serve use, defaults throughout."""
    if kind == "pull":
        from lux_tpu.engine.pull import PullExecutor
        return PullExecutor(graph, program)
    if kind == "tiled":
        from lux_tpu.engine.tiled import TiledPullExecutor
        return TiledPullExecutor(graph, program)
    if kind == "push":
        from lux_tpu.engine.push import PushExecutor
        return PushExecutor(graph, program)
    if kind == "push_multi":
        from lux_tpu.engine.push import MultiSourcePushExecutor
        return MultiSourcePushExecutor(graph, program, k=4)
    if kind == "push_incremental":
        from lux_tpu.engine.incremental import IncrementalExecutor
        return IncrementalExecutor(graph, program)
    if kind == "pull_sharded":
        from lux_tpu.engine.pull_sharded import ShardedPullExecutor
        return ShardedPullExecutor(graph, program)
    if kind == "tiled_sharded":
        from lux_tpu.engine.tiled_sharded import ShardedTiledExecutor
        return ShardedTiledExecutor(graph, program)
    if kind == "push_sharded":
        from lux_tpu.engine.push import ShardedPushExecutor
        return ShardedPushExecutor(graph, program)
    if kind == "push_multi_sharded":
        from lux_tpu.engine.push import ShardedMultiSourcePushExecutor
        return ShardedMultiSourcePushExecutor(graph, program, k=4)
    if kind == "gas":
        from lux_tpu.engine.gas import AdaptiveExecutor, as_gas
        return AdaptiveExecutor(graph, as_gas(program))
    if kind == "gas_multi":
        from lux_tpu.engine.gas import MultiSourceGasExecutor
        return MultiSourceGasExecutor(graph, program, k=4)
    raise ValueError(f"unknown executor kind {kind!r}")


def _compact_graph(kind: str, weighted: bool, seed: int):
    """Graph whose partition actually engages the compact exchange: the
    row-granular engines need read locality (small_world's ring plus a
    contiguous edge-balanced partition leaves only boundary reads), the
    tiled engine needs hub concentration (rmat's Kronecker skew keeps
    strip reads on the few hub blocks). The tiny gnp used for the plain
    targets is all-remote at this size, which would fall back to full
    and silently shrink audit coverage of the compact collectives."""
    from lux_tpu.graph.generate import rmat, small_world
    from lux_tpu.graph.graph import Graph

    if kind == "tiled_sharded":
        return rmat(12, 8, seed=seed, weighted=weighted)
    g = small_world(1024, k=4, p_rewire=0.05, seed=seed)
    if weighted:
        rng = np.random.default_rng(seed)
        g = Graph(nv=g.nv, ne=g.ne, row_ptr=g.row_ptr, col_src=g.col_src,
                  weights=rng.integers(1, 101, g.ne, dtype=np.int32))
    return g


def registry_targets(include_sharded: bool = True) -> List[TraceTarget]:
    """Trace targets for every registered program x capable executor.
    Sharded kinds are traced twice: once with the default full exchange
    and once under ``LUX_EXCHANGE=compact`` (``{name}@{kind}+compact``),
    so LUX104/LUX105 audit the packed all_to_all path too."""
    import os

    from lux_tpu.models import PROGRAMS, ROOTED_APPS, engine_kinds
    from lux_tpu.utils.logging import get_logger

    targets: List[TraceTarget] = []
    for i, name in enumerate(sorted(PROGRAMS)):
        program = PROGRAMS[name]()
        weighted = bool(getattr(program, "needs_weights", False))
        graph = _tiny_graph(weighted=weighted, seed=7 + i)
        init_kw = {"start": 0} if name in ROOTED_APPS else {}
        for kind in engine_kinds(name):
            if not include_sharded and kind.endswith("sharded"):
                continue
            ex = build_executor(kind, graph, program)
            spec = ex.trace_step(**init_kw)
            targets.append(target_from_spec(f"{name}@{kind}", spec))
            if not kind.endswith("sharded"):
                continue
            # luxlint: disable=LUX005 -- save/restore needs the raw set-vs-unset env entry, which the typed accessors erase
            prev = os.environ.get("LUX_EXCHANGE")
            os.environ["LUX_EXCHANGE"] = "compact"
            try:
                exc = build_executor(
                    kind, _compact_graph(kind, weighted, 7 + i), program)
            finally:
                if prev is None:
                    os.environ.pop("LUX_EXCHANGE", None)
                else:
                    os.environ["LUX_EXCHANGE"] = prev
            if getattr(exc, "exchange_mode", "full") != "compact":
                # Coverage loss must be visible, not silent.
                get_logger("luxlint").warning(
                    "%s@%s+compact fell back to the full exchange; "
                    "compact collectives untraced for this target",
                    name, kind)
                continue
            targets.append(target_from_spec(
                f"{name}@{kind}+compact", exc.trace_step(**init_kw)))
    return targets


def load_fixture_targets(path: str) -> List[TraceTarget]:
    """Targets from a fixture module exposing ``TRACES`` (a list of
    trace dicts with a ``name`` key) — the seeded-violation harness."""
    import importlib.util
    import os

    modname = "_luxlint_ir_fixture_" + \
        os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(modname, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load fixture module {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    traces = getattr(mod, "TRACES", None)
    if not traces:
        raise ValueError(f"fixture {path} exposes no TRACES")
    return [
        target_from_spec(t.get("name", f"{path}#{i}"), t)
        for i, t in enumerate(traces)
    ]


def audit_engine(engine, name: str, **init_kw) -> List[Finding]:
    """Build-time donation audit of one executor (serve/pool.py hook):
    LUX104 only — one abstract lowering, no trace walk, no execution.
    Engines without ``trace_step`` are silently fine."""
    ts = getattr(engine, "trace_step", None)
    if ts is None:
        return []
    target = target_from_spec(name, ts(**init_kw))
    rule = DonationAudit()
    try:
        # check() needs no jaxpr for LUX104; pass None explicitly.
        return list(rule.check(None, target))
    except Exception as e:
        return [Finding(rule.id, name, 0, 0,
                        f"donation audit crashed: {e!r}")]
