"""luxlint --memory: static HBM-footprint contracts (LUX701-706).

The eighth luxlint tier. Every capacity question the serving and bench
layers ask — "does this engine fit?", "how many graphs can stay
resident?" — is answered here *offline*, the way LUX401-407 prove the
exchange and LUX601-606 prove the algebra: by walking evidence the
framework already produces, not by trying it and OOMing.

The core is a donation-aware buffer-liveness walk over every traced
registry target (``ir.registry_targets()`` / ``trace_step()``, all
engine kinds x the compact/frontier exchange variants). Engine step
inputs (device graph + carry state) are *pinned* — they live for the
engine's lifetime, not the step's — while traced intermediates allocate
at their defining eqn and free at their last use, in schedule order.
Scopes nest: descending into a ``shard_map`` sub-jaxpr switches the
byte scale from the per-device share (``1/P`` of the global aval) to
the per-shard shapes the body already carries, so the walk prices
**per-device peak live bytes** directly. A donated carry whose alias
the lowered HLO actually honors is credited back (the output reuses the
input buffer); an unhonored donation is *priced* — both copies stay in
the peak — which is what turns LUX104's "audited" into LUX702's
"priced".

Each peak decomposes, by attributing every live-at-peak buffer to the
probe graph's per-part vertex/edge counts, into a closed-form model

    f(nv, ne, P, K, exchange_mode) =
        per_vertex_bytes * ceil(nv/P) + per_edge_bytes * ceil(ne/P)
        + fixed_bytes

whose honesty LUX704 proves by re-tracing representatives at a swept
scale. The models persist as a content-addressed ``memcap.v1`` artifact
(``analysis/memcap.json``, tamper-rejected exactly like ``gascap.v1``)
— the formula serving trusts: :func:`predicted_engine_bytes` is the
admission formula the HBM-budgeted EnginePool (serve/pool.py) and the
tuner's candidate pruning (tune/space.py) both consult, and LUX706
fails verify the moment that committed formula drifts from a fresh
derivation.

Rules:

- **LUX701 footprint-structure** — the memcap.v1 artifact and every
  model in it are well-formed, and every current registry target is
  covered (a new program/kind fails verify until regenerated);
- **LUX702 donation-leak** — a donated carry whose alias is absent
  from the lowered HLO silently doubles peak; flagged and priced;
- **LUX703 peak-vs-budget** — the derived model at the declared bench
  scales (LUX_BENCH_SCALE/LUX_BENCH_EF) must fit the device-profile
  HBM capacity; fails closed on overcommit;
- **LUX704 model-honesty** — the closed-form formula upper-bounds the
  traced peak within LUX_MEM_MODEL_TOL across a scale sweep;
- **LUX705 exchange-staging** — full/compact/frontier staging buffers
  are counted in the peak and cross-checked against
  ``exchange_bytes_per_iter()`` / ``frontier_evidence()``;
- **LUX706 residency-drift** — the committed artifact's admission
  formula still reproduces the freshly traced peaks.

Fixture modules (``luxlint --memory <paths>``) may define any of:
``TARGETS`` (name -> trace-spec dict, with ``nv``/``ne`` probe dims),
``MODELS`` (name -> model dict, checked by LUX704), ``CAPACITY_BYTES``
(+ optional ``SCALES``; checked by LUX703), ``MEMCAP`` (an artifact
dict; structure-checked by LUX701), and ``COMMITTED`` (a stand-in
committed artifact; drift-checked by LUX706).
"""

from __future__ import annotations

import dataclasses
import hashlib
import importlib.util
import json
import math
import os
import time
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from lux_tpu.analysis import ir
from lux_tpu.analysis.core import (FileResult, Finding, LintReport,
                                   iter_python_files)
from lux_tpu.utils import flags

MEMORY_SCHEMA = "luxlint-memory.v1"
CAP_SCHEMA = "memcap.v1"
CAP_FILENAME = "memcap.json"

# Every model entry must carry exactly these (LUX701).
MODEL_FIELDS = ("per_vertex_bytes", "per_edge_bytes", "fixed_bytes")

# LUX704's over-fat arm only fires when the absolute slack also clears
# this floor: probe graphs are ~100 vertices, so tile-padding quantises
# tiny buffers into the linear terms and over-predicts re-traces by a
# few dozen KiB — noise, not a model that rejects admissible engines.
_OVERFAT_FLOOR_BYTES = 1 << 20

__all__ = [
    "MEMORY_SCHEMA", "CAP_SCHEMA", "CAP_FILENAME", "MemRule",
    "all_memory_rules", "prove_registry", "verify_registry",
    "verify_fixture_paths", "build_memcap", "save_memcap", "load_memcap",
    "memcap_path", "eval_model", "predicted_engine_bytes",
    "hbm_budget_bytes", "target_peak_bytes",
]


@dataclasses.dataclass(frozen=True)
class MemRule:
    id: str
    title: str
    doc: str


MEMORY_RULES = (
    MemRule(
        "LUX701", "footprint-structure",
        "the memcap.v1 artifact and every closed-form model in it are "
        "well-formed (finite coefficients, positive peaks, positive "
        "probe dims) and every current registry target has an entry — "
        "a new program or engine kind fails verify until the artifact "
        "is regenerated"),
    MemRule(
        "LUX702", "donation-leak",
        "every donated carry buffer must be aliased to an output by "
        "the lowered HLO; an unhonored donation keeps both copies "
        "live, silently doubling the carry's share of peak — flagged "
        "AND priced into the footprint (extends LUX104 from audited "
        "to priced)"),
    MemRule(
        "LUX703", "peak-vs-budget",
        "the derived footprint model evaluated at the declared bench "
        "scales must fit the device-profile HBM capacity "
        "(hbm_capacity_bytes, LUX_HBM_CAPACITY_BYTES override); "
        "overcommit fails closed before any shard ships"),
    MemRule(
        "LUX704", "model-honesty",
        "the closed-form f(nv, ne, P, K, mode) must upper-bound the "
        "traced per-device peak within LUX_MEM_MODEL_TOL across a "
        "scale sweep — this formula is what serving admission trusts"),
    MemRule(
        "LUX705", "exchange-staging",
        "full/compact/frontier exchange staging buffers must be "
        "counted in the traced peak and the engine's "
        "exchange_bytes_per_iter() claim must match the collectives "
        "the jaxpr actually moves (frontier_evidence() internally "
        "consistent)"),
    MemRule(
        "LUX706", "residency-drift",
        "serving's admission formula (the committed memcap.v1 models "
        "behind predicted_engine_bytes) must still reproduce freshly "
        "traced peaks within LUX_MEM_MODEL_TOL; drift fails verify "
        "until the artifact is regenerated"),
)


def all_memory_rules() -> List[MemRule]:
    return list(MEMORY_RULES)


def _f(rule: str, path: str, message: str, line: int = 0) -> Finding:
    return Finding(rule, path, line, 0, message)


def _mib(n: float) -> str:
    return f"{n / 2**20:.2f} MiB"


# -- the donation-aware liveness walk -------------------------------------


def _is_literal(v) -> bool:
    from jax import core as jcore

    lit = getattr(jcore, "Literal", None)
    return lit is not None and isinstance(v, lit)


def _eqn_subjaxprs(eqn) -> List:
    out = []
    for v in eqn.params.values():
        out.extend(ir._as_jaxprs(v))
    return out


def _entry(v, scale: float) -> Tuple[float, float, int]:
    """(scaled bytes, scaled element count, itemsize) for one var."""
    aval = v.aval
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return (0.0, 0.0, 1)
    elems = float(np.prod(shape, dtype=np.float64)) if shape else 1.0
    itemsize = int(np.dtype(dtype).itemsize)
    return (elems * itemsize * scale, elems * scale, itemsize)


def _walk_scope(jaxpr, scale: float):
    """Schedule-order liveness over one jaxpr scope.

    Returns ``(peak_bytes, snapshot, input_bytes)`` where ``snapshot``
    is the list of (bytes, elems, itemsize) entries live at the peak
    program point. Scope inputs and outputs are pinned (engine
    residency: graph tables and carry state live across steps);
    intermediates free at their last use. A sub-jaxpr contributes its
    own peak *minus its input bytes* at the owning eqn's program point
    (the operands are already counted in this scope) — sequential
    sub-jaxprs (while cond/body, cond branches) never coexist, so the
    max over them is the bound.
    """
    last: Dict[object, int] = {}
    for k, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last[v] = k
    pinned = set()
    live: Dict[object, Tuple[float, float, int]] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        pinned.add(v)
        if v not in live:
            live[v] = _entry(v, scale)
    for v in jaxpr.outvars:
        if not _is_literal(v):
            pinned.add(v)
    input_bytes = sum(e[0] for e in live.values())
    current = input_bytes
    peak, snap = current, list(live.values())
    for k, eqn in enumerate(jaxpr.eqns):
        # Per-shard shapes start inside shard_map; everything else
        # (pjit/scan/while/cond) keeps the enclosing scale.
        inner = 1.0 if eqn.primitive.name == "shard_map" else scale
        sub_extra, sub_snap = 0.0, []
        for sub in _eqn_subjaxprs(eqn):
            p_sub, s_sub, in_sub = _walk_scope(sub, inner)
            extra = p_sub - in_sub
            if extra > sub_extra:
                # The sub-scope's input entries are this scope's operand
                # buffers — already in ``live`` here. Trim them from the
                # merged snapshot or attribution double-prices them and
                # the calibrated constant goes negative to compensate.
                trimmed = list(s_sub)
                for v in list(sub.invars) + list(sub.constvars):
                    try:
                        trimmed.remove(_entry(v, inner))
                    except ValueError:
                        pass
                sub_extra, sub_snap = extra, trimmed
        alloc = [(v, _entry(v, scale)) for v in eqn.outvars
                 if not _is_literal(v)]
        alloc_bytes = sum(e[0] for _, e in alloc)
        cand = current + alloc_bytes + sub_extra
        if cand > peak:
            peak = cand
            snap = list(live.values()) + [e for _, e in alloc] + sub_snap
        for v, e in alloc:
            live[v] = e
        current += alloc_bytes
        for v in [v for v in live if last.get(v) == k and v not in pinned]:
            current -= live[v][0]
            del live[v]
    return peak, snap, input_bytes


def _staging_bytes(jaxpr, scale: float, parts: int) -> float:
    """Scaled bytes of data-collective result buffers one step
    materializes (``cond`` branches are alternatives: max)."""
    total = 0.0
    for eqn in jaxpr.eqns:
        inner = 1.0 if eqn.primitive.name == "shard_map" else scale
        branch = [_staging_bytes(s, inner, parts)
                  for s in _eqn_subjaxprs(eqn)]
        if branch:
            if eqn.primitive.name == "cond":
                total += max(branch)
            else:
                total += sum(branch)
        if ir._is_data_collective(eqn.primitive.name):
            total += sum(_entry(v, scale)[0] for v in eqn.outvars
                         if not _is_literal(v))
    return total


def _donation_report(target) -> dict:
    """Alias accounting for the target's donated args (one abstract
    lowering, the LUX104 mechanics): how many donated leaves exist, how
    many the lowered HLO aliases, and the un-aliased byte leak."""
    import jax

    leaves = []
    for i in target.donate:
        if i < len(target.args):
            leaves.extend(jax.tree_util.tree_leaves(target.args[i]))
    declared = len(leaves)
    total_bytes = int(sum(int(getattr(x, "nbytes", 0) or
                              np.asarray(x).nbytes) for x in leaves))
    rep = {"declared": declared, "aliased": 0,
           "donated_bytes": total_bytes, "leak_bytes": 0,
           "leaves": leaves, "checked": False}
    if declared == 0 or target.lower is None:
        return rep
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        lowered = target.lower()
    sig = ir._main_arg_attrs(lowered.as_text())
    if sig is None:
        return rep
    rep["checked"] = True
    aliased = sig.count("tf.aliasing_output") + sig.count("jax.buffer_donor")
    rep["aliased"] = min(aliased, declared)
    if aliased < declared:
        # No per-leaf pairing in the signature: price the whole carry
        # conservatively (zero credit, full leak).
        rep["aliased"] = min(aliased, declared)
        rep["leak_bytes"] = total_bytes
    return rep


# -- attribution -> the closed-form model ---------------------------------


def _classify(elems: float, nv_p: int, ne_p: int) -> str:
    """vertex | edge | fixed: which probe unit this buffer scales
    with, by relative distance to an integer multiple."""
    best_kind, best_dist = "fixed", 0.5
    for kind, unit in (("vertex", nv_p), ("edge", ne_p)):
        if unit <= 0 or elems <= 0:
            continue
        r = elems / unit
        m = round(r)
        if m < 1:
            continue
        dist = abs(r - m) / r
        if dist < best_dist - 1e-9:
            best_kind, best_dist = kind, dist
    return best_kind


def _attribute(snapshot, nv_p: int, ne_p: int) -> Tuple[float, float, float]:
    per_vertex = per_edge = fixed = 0.0
    for bytes_s, elems_s, _ in snapshot:
        kind = _classify(abs(elems_s), nv_p, ne_p)
        if kind == "edge":
            per_edge += bytes_s / ne_p
        elif kind == "vertex":
            per_vertex += bytes_s / nv_p
        else:
            fixed += bytes_s
    return per_vertex, per_edge, fixed


def eval_model(model: dict, nv: int, ne: int, parts: int,
               k: Optional[int] = None,
               k_probe: Optional[int] = None) -> float:
    """Per-device predicted peak bytes of one entry's model at the
    given scale. ``k`` lanes beyond the probe's scale the
    vertex-proportional term (lane state is (nv, K)-shaped); the graph
    tables in the edge term are lane-independent."""
    parts = max(1, int(parts))
    nv_p = max(1, math.ceil(int(nv) / parts))
    ne_p = max(1, math.ceil(int(ne) / parts))
    pv = float(model["per_vertex_bytes"])
    if k and k_probe and int(k) != int(k_probe):
        pv *= max(1.0, float(k) / float(k_probe))
    out = (pv * nv_p + float(model["per_edge_bytes"]) * ne_p
           + float(model["fixed_bytes"]))
    return max(0.0, out)


def target_peak_bytes(target, meta: dict) -> dict:
    """Trace one target and derive its footprint evidence: the traced
    per-device peak (donation-credited), the attribution-derived model,
    staging bytes, and the donation report. Raises on trace failure."""
    closed = ir.trace_target(target)
    parts = max(1, int(meta.get("parts", 1)))
    scale = 1.0 / parts if parts > 1 else 1.0
    peak_raw, snapshot, _ = _walk_scope(closed.jaxpr, scale)
    # ClosedJaxpr consts back constvars, which the walk already counted
    # through their avals; nothing to add.
    don = _donation_report(target)
    credit = 0.0
    if don["declared"] and don["checked"] and not don["leak_bytes"]:
        # Honored donation: the new carry writes over the old one's
        # buffer — credit the donated leaves back, as negative snapshot
        # entries so the model's coefficients carry the credit too.
        for leaf in don["leaves"]:
            b, e, i = _entry(_Shaped(leaf), scale)
            credit += b
            snapshot = snapshot + [(-b, e, i)]
    peak = max(0.0, peak_raw - credit)
    nv_p = max(1, math.ceil(int(meta["nv"]) / parts))
    ne_p = max(1, math.ceil(int(meta["ne"]) / parts))
    pv, pe, fixed = _attribute(snapshot, nv_p, ne_p)
    pv, pe = max(0.0, round(pv, 6)), max(0.0, round(pe, 6))
    # Calibrate the constant term against the *rounded* peak the
    # artifact persists, so the model bounds peak_bytes exactly at the
    # probe scale (calibrating against the float peak can land the
    # prediction a sub-byte hair under its own ceil).
    peak_i = int(math.ceil(peak))
    fixed = int(math.ceil(peak_i - pv * nv_p - pe * ne_p))
    model = {"per_vertex_bytes": pv, "per_edge_bytes": pe,
             "fixed_bytes": fixed}
    staging = _staging_bytes(closed.jaxpr, scale, parts)
    don.pop("leaves", None)
    return {
        "closed": closed,
        "peak_bytes": peak_i,
        "model": model,
        "staging_bytes": int(math.ceil(staging)),
        "donation": don,
    }


class _Shaped:
    """Adapter: gives a concrete array the .aval face _entry expects."""

    def __init__(self, x):
        self.aval = np.asarray(x)


# -- per-target rules -----------------------------------------------------


def _bench_scales() -> List[Tuple[int, int]]:
    scale = flags.get_int("LUX_BENCH_SCALE")
    ef = flags.get_int("LUX_BENCH_EF")
    nv = 1 << scale
    return [(nv, nv * ef)]


def _capacity_bytes() -> Optional[int]:
    from lux_tpu.obs import report

    cap = report.device_profile().get("hbm_capacity_bytes")
    return int(cap) if cap else None


def _check_budget(name: str, entry: dict, capacity: Optional[int],
                  scales: Sequence[Tuple[int, int]]) -> List[Finding]:
    if not capacity:
        return []
    out = []
    for nv, ne in scales:
        pred = eval_model(entry["model"], nv, ne, entry["parts"],
                          k=entry["k"], k_probe=entry["k"])
        if pred > capacity:
            out.append(_f(
                "LUX703", name,
                f"predicted per-device peak {_mib(pred)} at bench scale "
                f"nv={nv} ne={ne} exceeds the device HBM capacity "
                f"{_mib(capacity)} — overcommit fails closed here, not "
                "on-device"))
    return out


def _check_model_honesty(name: str, model: dict, traced_peak: float,
                         nv: int, ne: int, parts: int,
                         k: Optional[int] = None,
                         k_probe: Optional[int] = None) -> List[Finding]:
    tol = flags.get_float("LUX_MEM_MODEL_TOL")
    pred = eval_model(model, nv, ne, parts, k=k, k_probe=k_probe)
    if pred + 1e-6 < traced_peak:
        return [_f(
            "LUX704", name,
            f"model predicts {_mib(pred)} at nv={nv} ne={ne} P={parts} "
            f"but the traced peak is {_mib(traced_peak)} — the formula "
            "serving trusts under-estimates the footprint")]
    if (traced_peak > 0 and pred > traced_peak * (1.0 + tol)
            and pred - traced_peak > _OVERFAT_FLOOR_BYTES):
        return [_f(
            "LUX704", name,
            f"model predicts {_mib(pred)} at nv={nv} ne={ne} P={parts} "
            f"vs traced peak {_mib(traced_peak)} — slack exceeds "
            f"LUX_MEM_MODEL_TOL={tol:g}; an over-fat model rejects "
            "admissible engines")]
    return []


def _check_staging(name: str, target, closed, evidence: dict,
                   staging: float, parts: int) -> List[Finding]:
    out: List[Finding] = []
    mode = target.exchange_mode
    if mode in ("full", "compact", "frontier") and parts > 1:
        if staging <= 0:
            out.append(_f(
                "LUX705", name,
                f"{mode}-exchange target stages no data-collective "
                "buffers in the traced step — the exchange cost is "
                "missing from the peak accounting"))
        claim = target.exchange_bytes
        if claim is not None:
            totals = ir._collective_byte_totals(closed.jaxpr, parts)
            if totals and claim not in totals:
                shown = sorted(totals)[:4]
                out.append(_f(
                    "LUX705", name,
                    f"exchange_bytes_per_iter() claims {claim} B/iter "
                    f"but the traced collectives move {shown} — the "
                    "staging the peak prices and the claim serving "
                    "reports have diverged"))
    if evidence:
        p = parts
        want = (p * (p - 1) * int(evidence.get("frontier_max_sends", 0))
                * int(evidence.get("frontier_row_bytes", 0)))
        got = int(evidence.get("frontier_bytes_per_iter", -1))
        if got != want or int(evidence.get("frontier_fill_active", 0)):
            out.append(_f(
                "LUX705", name,
                f"frontier_evidence() is internally inconsistent "
                f"(bytes_per_iter {got} vs P*(P-1)*max_sends*row_bytes "
                f"= {want}, fill_active "
                f"{evidence.get('frontier_fill_active')}) — the "
                "frontier staging bound cannot be trusted in the peak"))
    return out


def _check_drift(name: str, committed: Optional[dict], entry: dict
                 ) -> List[Finding]:
    if committed is None:
        return []
    tol = flags.get_float("LUX_MEM_MODEL_TOL")
    cent = (committed.get("targets") or {}).get(name)
    if cent is None:
        return [_f(
            "LUX701", name,
            f"registry target {name!r} has no entry in the committed "
            "memcap.v1 — regenerate with `luxlint --memory --memcap-out "
            "lux_tpu/analysis/memcap.json`")]
    try:
        pred = eval_model(cent["model"], entry["probe"]["nv"],
                          entry["probe"]["ne"], entry["parts"],
                          k=entry["k"], k_probe=cent.get("k"))
    except (KeyError, TypeError, ValueError) as e:
        return [_f("LUX701", name,
                   f"committed memcap.v1 entry is malformed: {e!r}")]
    peak = float(entry["peak_bytes"])
    if pred + 1e-6 < peak or (peak > 0 and pred > peak * (1.0 + tol)):
        return [_f(
            "LUX706", name,
            f"committed admission formula predicts {_mib(pred)} but a "
            f"fresh trace peaks at {_mib(peak)} (tol "
            f"LUX_MEM_MODEL_TOL={tol:g}) — serving admits against a "
            "stale footprint; regenerate the memcap.v1 artifact")]
    return []


def validate_artifact(art, expect_names: Optional[Sequence[str]] = None,
                      path: str = "<memcap>") -> List[Finding]:
    """LUX701 structure checks over one memcap.v1-shaped dict."""
    out: List[Finding] = []
    if not isinstance(art, dict) or not isinstance(art.get("targets"),
                                                   dict):
        return [_f("LUX701", path,
                   "artifact is not a dict with a 'targets' mapping")]
    targets = art["targets"]
    if not targets:
        out.append(_f("LUX701", path, "artifact covers zero targets"))
    for name in sorted(targets):
        entry = targets[name]
        if not isinstance(entry, dict):
            out.append(_f("LUX701", path,
                          f"entry {name!r} is not a mapping"))
            continue
        model = entry.get("model")
        if not isinstance(model, dict) or sorted(model) != sorted(
                MODEL_FIELDS):
            out.append(_f(
                "LUX701", path,
                f"entry {name!r} model must carry exactly "
                f"{MODEL_FIELDS}, got "
                f"{sorted(model) if isinstance(model, dict) else model!r}"))
            continue
        bad = [fld for fld in MODEL_FIELDS
               if not isinstance(model[fld], (int, float))
               or not math.isfinite(float(model[fld]))]
        if bad or float(model["per_vertex_bytes"]) < 0 \
                or float(model["per_edge_bytes"]) < 0:
            out.append(_f(
                "LUX701", path,
                f"entry {name!r} has non-finite or negative model "
                f"coefficients ({ {f: model.get(f) for f in MODEL_FIELDS} })"
            ))
            continue
        peak = entry.get("peak_bytes")
        probe = entry.get("probe") or {}
        if not isinstance(peak, int) or peak <= 0:
            out.append(_f(
                "LUX701", path,
                f"entry {name!r} peak_bytes must be a positive int, "
                f"got {peak!r}"))
        if int(probe.get("nv") or 0) <= 0 or int(probe.get("ne") or 0) <= 0:
            out.append(_f(
                "LUX701", path,
                f"entry {name!r} probe dims must be positive "
                f"(got {probe!r})"))
    if expect_names:
        missing = sorted(set(expect_names) - set(targets))
        for name in missing:
            out.append(_f(
                "LUX701", path,
                f"registry target {name!r} is not covered by the "
                "artifact — every traced target must be priced"))
    return out


# -- registry + fixture drivers -------------------------------------------


def _filter_select(result: FileResult,
                   select: Optional[Sequence[str]]) -> None:
    if select:
        keep = tuple(select)
        result.findings = [f for f in result.findings
                           if f.rule.startswith(keep)]


def _target_meta(ex, spec: dict, kind: str) -> dict:
    g = getattr(ex, "graph", None)
    parts = max(1, int(spec.get("num_parts", 0)
                       or getattr(ex, "num_parts", 1) or 1))
    fe = None
    fef = getattr(ex, "frontier_evidence", None)
    if callable(fef):
        try:
            fe = fef()
        # luxlint: disable=LUX007 -- evidence is advisory input, never fatal
        except Exception:
            fe = None
    return {
        "kind": kind,
        "nv": int(spec.get("nv", getattr(g, "nv", 0)) or 0),
        "ne": int(spec.get("ne", getattr(g, "ne", 0)) or 0),
        "parts": parts,
        "k": int(spec.get("k", getattr(ex, "k", 1) or 1)),
        "mode": str(spec.get("exchange_mode", "")),
        "frontier_evidence": fe or spec.get("frontier_evidence"),
    }


def _harvest(name: str, target, meta: dict
             ) -> Tuple[Optional[dict], Optional[str]]:
    """Trace + lower one target — the jit-machinery evidence the rules
    consume. Registry callers run this in the untimed staging phase
    alongside executor construction (acquisition, not verification);
    fixture targets harvest inline."""
    if meta["nv"] <= 0 or meta["ne"] <= 0:
        return None, (f"{name}: no probe graph dims (nv/ne) to "
                      "attribute the footprint against")
    try:
        return target_peak_bytes(target, meta), None
    except Exception as e:   # traced user code: anything can raise
        return None, f"{name}: trace failed: {e!r}"


def _prove_target(name: str, target, meta: dict,
                  committed: Optional[dict],
                  capacity: Optional[int],
                  scales: Sequence[Tuple[int, int]],
                  ev: Optional[dict] = None,
                  err: Optional[str] = None
                  ) -> Tuple[FileResult, Optional[dict]]:
    if ev is None and err is None:
        ev, err = _harvest(name, target, meta)
    if ev is None:
        return FileResult(name, [], [], error=err), None
    findings: List[Finding] = []
    don = ev["donation"]
    if don["declared"] and don["checked"] and don["leak_bytes"]:
        findings.append(_f(
            "LUX702", name,
            f"{don['declared'] - don['aliased']} of {don['declared']} "
            "donated carry buffers are not aliased in the lowered HLO — "
            f"both copies stay live, adding {_mib(don['leak_bytes'])} "
            "to the per-device peak (donation priced, not just audited)"))
    findings.extend(_check_staging(
        name, target, ev["closed"], meta.get("frontier_evidence"),
        ev["staging_bytes"], meta["parts"]))
    entry = {
        "kind": meta["kind"],
        "exchange_mode": meta["mode"],
        "parts": meta["parts"],
        "k": meta["k"],
        "value_dtype": target.value_dtype,
        "probe": {"nv": meta["nv"], "ne": meta["ne"]},
        "peak_bytes": ev["peak_bytes"],
        "staging_bytes": ev["staging_bytes"],
        "model": ev["model"],
        "donation": {k: don[k] for k in
                     ("declared", "aliased", "donated_bytes",
                      "leak_bytes")},
    }
    findings.extend(_check_budget(name, entry, capacity, scales))
    findings.extend(_check_drift(name, committed, entry))
    return FileResult(name, findings, []), entry


def _stage_registry() -> List[Tuple]:
    """Build every registry executor, capture its trace spec, and
    harvest the trace/lowering evidence.

    Executor construction (graph builds, plan builds, jit wrapping) and
    the jaxpr/HLO harvest are environment setup — jit-machinery
    acquisition, not verification — so callers keep them outside the
    proof timer, the ir.run_* precedent."""
    staged = []
    for name, kind, ex, init_kw in ir._registry_executors():
        spec = ex.trace_step(**init_kw)
        target = ir.target_from_spec(name, spec)
        meta = _target_meta(ex, spec, kind)
        ev, err = _harvest(name, target, meta)
        staged.append((name, target, meta, spec, ev, err))
    return staged


def _sweep_targets(factor: int):
    """One representative per engine kind x exchange mode, rebuilt on a
    probe graph ``factor`` x the base scale (LUX704's re-trace)."""
    from lux_tpu.graph.generate import gnp
    from lux_tpu.models import PROGRAMS, ROOTED_APPS, engine_kinds

    seen = set()
    out = []
    for i, name in enumerate(sorted(PROGRAMS)):
        program = PROGRAMS[name]()
        weighted = bool(getattr(program, "needs_weights", False))
        init_kw = {"start": 0} if name in ROOTED_APPS else {}
        for kind in engine_kinds(name):
            if kind in seen:
                continue
            seen.add(kind)
            graph = gnp(96 * factor, 400 * factor, seed=7 + i,
                        weighted=weighted)
            try:
                ex = ir.build_executor(kind, graph, program)
            # luxlint: disable=LUX007 -- a kind that cannot build at the swept scale is reported, not fatal
            except Exception:
                continue
            spec = ex.trace_step(**init_kw)
            tname = f"{name}@{kind}"
            target = ir.target_from_spec(tname, spec)
            meta = _target_meta(ex, spec, kind)
            ev, err = _harvest(tname, target, meta)
            out.append((tname, name, kind, target, meta, ev, err))
    return out


def prove_registry(select: Optional[Sequence[str]] = None,
                   check_committed: bool = True
                   ) -> Tuple[LintReport, dict]:
    """Walk every traced registry target; returns (report, memcap.v1).

    ``check_committed=False`` skips the committed-artifact rules
    (LUX701 coverage, LUX706 drift) — the regeneration path, where
    staleness is exactly what is being fixed."""
    staged = _stage_registry()
    factor = max(2, flags.get_int("LUX_MEM_SWEEP_FACTOR"))
    swept = _sweep_targets(factor)
    t0 = time.perf_counter()
    committed = None
    committed_err = None
    if check_committed:
        try:
            committed = load_memcap(memcap_path())
        except Exception as e:   # missing or tampered: one loud finding
            committed_err = repr(e)
    capacity = _capacity_bytes()
    scales = _bench_scales()
    results: List[FileResult] = []
    targets_block: Dict[str, dict] = {}
    for name, target, meta, _spec, ev, err in staged:
        res, entry = _prove_target(name, target, meta, committed,
                                   capacity, scales, ev=ev, err=err)
        if entry is not None:
            targets_block[name] = entry
            # LUX704 at the base scale: the calibrated model must
            # reproduce its own probe (catches attribution bugs).
            res.findings.extend(_check_model_honesty(
                name, entry["model"], entry["peak_bytes"],
                meta["nv"], meta["ne"], meta["parts"]))
        _filter_select(res, select)
        results.append(res)
    # LUX704 sweep: the base-scale model must bound a re-trace at
    # factor x the probe, one representative per engine kind.
    for name, _pname, _kind, target, meta, ev, err in swept:
        entry = targets_block.get(name)
        if entry is None:
            continue
        if ev is None:
            results.append(FileResult(
                f"{name}+sweep", [], [], error=f"sweep: {err}"))
            continue
        res = FileResult(f"{name}+sweep", _check_model_honesty(
            name, entry["model"], ev["peak_bytes"],
            meta["nv"], meta["ne"], meta["parts"],
            k=meta["k"], k_probe=entry["k"]), [])
        _filter_select(res, select)
        results.append(res)
    art = build_memcap(targets_block, {
        "nv": 96, "ne": 400, "seed": 7,
        "sweep_factor": factor,
        "tol": flags.get_float("LUX_MEM_MODEL_TOL"),
    })
    structural = validate_artifact(art, path="<memcap:derived>")
    if committed is not None:
        structural += validate_artifact(
            committed, expect_names=sorted(targets_block),
            path="<memcap:committed>")
    elif check_committed:
        structural.append(_f(
            "LUX701", "<memcap:committed>",
            f"committed memcap.v1 unusable ({committed_err}) — "
            "regenerate with `luxlint --memory --memcap-out "
            "lux_tpu/analysis/memcap.json`"))
    if structural:
        res = FileResult("<memcap>", structural, [])
        _filter_select(res, select)
        results.append(res)
    return (LintReport(results, time.perf_counter() - t0,
                       schema=MEMORY_SCHEMA), art)


def verify_registry(select: Optional[Sequence[str]] = None,
                    memcap_out: Optional[str] = None) -> LintReport:
    report, art = prove_registry(select,
                                 check_committed=memcap_out is None)
    if memcap_out and report.ok:
        save_memcap(art, memcap_out)
    return report


_FIXTURE_SEQ = [0]


def _load_fixture(path: str):
    _FIXTURE_SEQ[0] += 1
    modname = f"_memck_fixture_{_FIXTURE_SEQ[0]}"
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)   # type: ignore[union-attr]
    return mod


def verify_fixture_paths(paths: Sequence[str],
                         select: Optional[Sequence[str]] = None
                         ) -> LintReport:
    """Check standalone fixture modules (tests/mem_fixtures/) — each
    rule only engages when the fixture supplies its inputs, so a
    fixture fails with exactly the rule it seeds."""
    t0 = time.perf_counter()
    results: List[FileResult] = []
    for path in iter_python_files(paths):
        try:
            mod = _load_fixture(path)
        except Exception as e:
            results.append(FileResult(
                path, [], [], error=f"{path}: unloadable fixture: {e!r}"))
            continue
        targets = getattr(mod, "TARGETS", None) or {}
        models = getattr(mod, "MODELS", None) or {}
        memcap = getattr(mod, "MEMCAP", None)
        committed = getattr(mod, "COMMITTED", None)
        capacity = getattr(mod, "CAPACITY_BYTES", None)
        scales = getattr(mod, "SCALES", None)
        if not targets and memcap is None:
            results.append(FileResult(
                path, [], [],
                error=f"{path}: defines neither TARGETS nor MEMCAP"))
            continue
        findings: List[Finding] = []
        if memcap is not None:
            findings.extend(validate_artifact(
                memcap, expect_names=sorted(targets), path=path))
        for name in sorted(targets):
            spec = dict(targets[name])
            target = ir.target_from_spec(name, spec)
            meta = _target_meta(_NoExecutor(), spec, spec.get("kind", ""))
            res, entry = _prove_target(
                name, target, meta, committed,
                int(capacity) if capacity else None,
                [tuple(s) for s in scales] if scales
                else ([(meta["nv"], meta["ne"])] if capacity else []))
            findings.extend(res.findings)
            if res.error:
                results.append(FileResult(path, [], [], error=res.error))
            if entry is not None and name in models:
                findings.extend(_check_model_honesty(
                    name, models[name], entry["peak_bytes"],
                    meta["nv"], meta["ne"], meta["parts"]))
        res = FileResult(path, findings, [])
        _filter_select(res, select)
        results.append(res)
    return LintReport(results, time.perf_counter() - t0,
                      schema=MEMORY_SCHEMA)


class _NoExecutor:
    """Fixture targets carry their own dims; nothing to introspect."""


# -- the memcap.v1 artifact -----------------------------------------------


def _cap_id(targets: dict, probe: dict) -> str:
    blob = json.dumps({"probe": probe, "targets": targets},
                      sort_keys=True)
    return "memcap-" + hashlib.sha1(blob.encode()).hexdigest()[:12]


def build_memcap(targets: dict, probe: dict) -> dict:
    return {
        "schema": CAP_SCHEMA,
        "id": _cap_id(targets, probe),
        "probe": probe,
        "targets": targets,
        "created_at": time.time(),
    }


def save_memcap(art: dict, path: str) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(art, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def load_memcap(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        art = json.load(fh)
    if art.get("schema") != CAP_SCHEMA:
        raise ValueError(
            f"{path}: schema {art.get('schema')!r}, expected {CAP_SCHEMA!r}")
    want = _cap_id(art.get("targets") or {}, art.get("probe") or {})
    if art.get("id") != want:
        raise ValueError(
            f"{path}: id {art.get('id')!r} does not match content hash "
            f"{want!r} (tampered or hand-edited footprint artifact)")
    return art


def memcap_path() -> str:
    d = flags.get("LUX_MEMCAP_DIR")
    if d:
        return os.path.join(d, CAP_FILENAME)
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        CAP_FILENAME)


# -- consumers: the serving admission formula -----------------------------

# (path, mtime) -> artifact; the committed file changes once per
# regeneration, so a stat per lookup is the whole invalidation story.
_COMMITTED_CACHE: Dict[Tuple[str, float], Optional[dict]] = {}


def _committed() -> Optional[dict]:
    path = memcap_path()
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    key = (path, mtime)
    if key not in _COMMITTED_CACHE:
        _COMMITTED_CACHE.clear()
        try:
            _COMMITTED_CACHE[key] = load_memcap(path)
        except (OSError, ValueError):
            # Tampered/unreadable: admission runs open (None) and
            # luxlint --memory is the gate that fails loudly.
            _COMMITTED_CACHE[key] = None
    return _COMMITTED_CACHE[key]


def predicted_engine_bytes(app: str, kind: str, exchange_mode: str,
                           nv: int, ne: int, parts: int, k: int = 1,
                           art: Optional[dict] = None) -> Optional[int]:
    """Serving's admission formula: per-device predicted resident bytes
    for one engine build, from the committed memcap.v1 models. None
    when no artifact (or no matching entry) is available — admission
    then runs open; LUX706 keeps this formula honest against fresh
    traces."""
    art = art if art is not None else _committed()
    if art is None:
        return None
    targets = art.get("targets") or {}
    names = [f"{app}@{kind}"]
    if exchange_mode in ("compact", "frontier"):
        names.insert(0, f"{app}@{kind}+{exchange_mode}")
    entry = next((targets[n] for n in names if n in targets), None)
    if entry is None:
        # Unknown app under a known kind: price it as the costliest
        # same-kind entry (upper-bound bias, never a free pass).
        same = [e for t, e in targets.items()
                if t.split("@", 1)[-1].split("+", 1)[0] == kind]
        if not same:
            return None
        entry = max(same, key=lambda e: e.get("peak_bytes", 0))
    try:
        return int(eval_model(entry["model"], nv, ne, parts,
                              k=k, k_probe=entry.get("k")))
    except (KeyError, TypeError, ValueError):
        return None


def hbm_budget_bytes() -> Optional[int]:
    """The per-device HBM budget the pool admits under:
    LUX_HBM_BUDGET_BYTES when set, else device-profile capacity x
    LUX_HBM_BUDGET_FRAC; None (no budget — admit freely) when neither
    yields a positive number."""
    b = flags.get_int("LUX_HBM_BUDGET_BYTES")
    if b > 0:
        return b
    cap = _capacity_bytes()
    if not cap:
        return None
    return int(cap * flags.get_float("LUX_HBM_BUDGET_FRAC"))
