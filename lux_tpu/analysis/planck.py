"""planck: static verifier for saved GroupedTailPlan artifacts.

A grouped-tail plan (ops/merge_tail_plan.py, PR 3) is pure data — a
handful of numpy arrays a device kernel will trust blindly. A corrupted
or stale cache directory must therefore be rejected BEFORE anything
executes it, from the structural contract alone:

- LUX201 structure: ``level_ptr`` starts at 0, is monotone, covers
  exactly the row arrays; ``dst_row_ptr`` is monotone inside the root
  level's slot range; shapes/dtypes match the artifact contract.
- LUX202 conservation: every level's stream carries every real exactly
  once (per-level sum(nvalid) == n_edges) — a dropped or duplicated
  real is silent numerical corruption downstream.
- LUX203 code-plane contract: int8 codes, prefix-dense rows (lanes
  beyond nvalid are zero), side-B lanes negative / side-A non-negative
  per the row's mode, copy rows single-sided (arow == brow), level 0
  all-copy with non-negative codes.
- LUX204 alignment: every level's row count is a multiple of the Mosaic
  8-row block unit (the kernel's BlockSpecs assume it).
- LUX205 copy-window rate: per-level stream inflation (rows per level /
  ceil(n_edges/128)) stays below ``LUX_PLANCK_INFLATION`` — the bound
  that distinguishes the copy-window schedule (~1.1x measured) from the
  pre-fix 24-27x skew blowup.

numpy + stdlib only (plans are host arrays; a verifier must not drag in
jax). All checks are vectorized: a >=1M-real plan verifies in well
under a second, mmap-friendly.
"""

from __future__ import annotations

import json
import os
import time
import types
from typing import Iterable, List, Optional, Sequence

import numpy as np

from lux_tpu.analysis.core import FileResult, Finding, LintReport
from lux_tpu.utils import flags

PLAN_SCHEMA = "luxlint.plan.v1"

BLOCK = 128       # lanes per stream row (ops/merge_tail_ref.BLOCK)
ALIGN_ROWS = 8    # Mosaic sublane block unit (ops/merge_tail_plan)

# Mirror of the artifact format (ops/merge_tail_plan.PLAN_ARRAYS /
# PLAN_FORMAT). Duplicated on purpose: importing lux_tpu.ops pulls jax,
# and ``luxlint --plans`` must verify a 1M-real artifact in under two
# seconds from a cold interpreter. test_ir.py asserts the two stay
# identical.
PLAN_ARRAYS = (
    "arow", "brow", "codes", "nvalid", "mode", "level_ptr", "dst_row_ptr",
)
PLAN_FORMAT = 1


def load_plan_artifact(path: str, mmap: bool = True):
    """jax-free loader for a saved grouped-plan directory. Returns an
    object attribute-compatible with GroupedTailPlan as far as the
    LUX2xx rules read it."""
    with open(os.path.join(path, "meta.json")) as fh:
        meta = json.load(fh)
    if meta.get("format") != PLAN_FORMAT:
        raise ValueError(
            f"grouped plan {path}: unknown format {meta.get('format')}")
    arrs = {
        name: np.load(os.path.join(path, name + ".npy"),
                      mmap_mode="r" if mmap else None,
                      allow_pickle=False)
        for name in PLAN_ARRAYS
    }
    return types.SimpleNamespace(
        n_edges=int(meta["n_edges"]), n_levels=int(meta["n_levels"]),
        stats=dict(meta.get("stats", {})), **arrs,
    )


class PlanRule:
    """One artifact rule; ``line`` in findings is the level index + 1
    (0 = a plan-level finding)."""

    id = "LUX200"
    title = "base plan rule"
    doc = ""

    def check(self, plan, path: str) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, path: str, level: int, message: str) -> Finding:
        return Finding(self.id, path, level, 0, message)


def _levels(plan) -> int:
    """Number of level segments the row arrays are cut into."""
    return max(len(plan.level_ptr) - 1, 0)


class PlanStructure(PlanRule):
    id = "LUX201"
    title = "plan-structure"
    doc = ("level_ptr/dst_row_ptr monotone and in range; array shapes "
           "and dtypes match the GroupedTailPlan contract")

    def check(self, plan, path: str) -> Iterable[Finding]:
        lp = np.asarray(plan.level_ptr)
        s = int(np.asarray(plan.arow).shape[0])
        if lp.ndim != 1 or lp.shape[0] != plan.n_levels + 2:
            yield self.finding(
                path, 0,
                f"level_ptr has {lp.shape} entries, expected "
                f"n_levels+2 = {plan.n_levels + 2}",
            )
            return
        if lp[0] != 0:
            yield self.finding(path, 0, f"level_ptr[0] = {lp[0]}, not 0")
        if np.any(np.diff(lp) < 0):
            yield self.finding(path, 0, "level_ptr is not monotone")
            return
        if lp[-1] != s:
            yield self.finding(
                path, 0,
                f"level_ptr[-1] = {lp[-1]} but the row arrays hold {s} "
                "rows — the level cut does not cover the artifact",
            )
        for name in ("brow", "nvalid", "mode"):
            a = np.asarray(getattr(plan, name))
            if a.shape != (s,):
                yield self.finding(
                    path, 0,
                    f"{name} shape {a.shape} != arow shape ({s},)")
        codes = np.asarray(plan.codes)
        if codes.shape != (s, BLOCK):
            yield self.finding(
                path, 0, f"codes shape {codes.shape} != ({s}, {BLOCK})")
        if np.asarray(plan.arow).size and (
            np.asarray(plan.arow).min() < 0 or
            np.asarray(plan.brow).min() < 0
        ):
            yield self.finding(path, 0, "negative arow/brow input row")
        # Levels >= 1 read the PREVIOUS level's output stream: their
        # input rows must address inside it.
        for k in range(1, _levels(plan)):
            lo, hi = int(lp[k]), int(lp[k + 1])
            prev_rows = int(lp[k]) - int(lp[k - 1])
            if hi > lo and prev_rows > 0:
                amax = int(np.asarray(plan.arow)[lo:hi].max(initial=0))
                bmax = int(np.asarray(plan.brow)[lo:hi].max(initial=0))
                if max(amax, bmax) >= prev_rows:
                    yield self.finding(
                        path, k + 1,
                        f"level {k} reads input row "
                        f"{max(amax, bmax)} but level {k - 1} has only "
                        f"{prev_rows} rows",
                    )
        drp = np.asarray(plan.dst_row_ptr)
        if drp.size:
            if np.any(np.diff(drp) < 0):
                yield self.finding(path, 0, "dst_row_ptr is not monotone")
            nlev = _levels(plan)
            root_rows = int(lp[nlev] - lp[nlev - 1]) if nlev >= 1 else 0
            if drp.max(initial=0) > root_rows * BLOCK:
                yield self.finding(
                    path, 0,
                    f"dst_row_ptr reaches slot {int(drp.max())} beyond "
                    f"the root level's {root_rows * BLOCK} slots",
                )


class PlanConservation(PlanRule):
    id = "LUX202"
    title = "plan-conservation"
    doc = ("every real is routed exactly once per level: "
           "sum(nvalid) == n_edges in every level segment")

    def check(self, plan, path: str) -> Iterable[Finding]:
        lp = np.asarray(plan.level_ptr)
        nvalid = np.asarray(plan.nvalid, np.int64)
        if lp.ndim != 1 or lp.shape[0] < 2 or np.any(np.diff(lp) < 0) or \
                (lp.size and lp[-1] > nvalid.shape[0]):
            return   # structure is broken; LUX201 already reports it
        for k in range(_levels(plan)):
            got = int(nvalid[int(lp[k]):int(lp[k + 1])].sum())
            if got != plan.n_edges:
                yield self.finding(
                    path, k + 1,
                    f"level {k} routes {got} reals, plan claims "
                    f"{plan.n_edges} — a real was dropped or duplicated",
                )


class PlanCodePlane(PlanRule):
    id = "LUX203"
    title = "plan-code-plane"
    doc = ("int8 prefix-dense code planes; lane signs match the row "
           "mode (A >= 0, B < 0); copy rows single-sided; level 0 "
           "all-copy")

    def check(self, plan, path: str) -> Iterable[Finding]:
        codes = np.asarray(plan.codes)
        nvalid = np.asarray(plan.nvalid, np.int64)
        mode = np.asarray(plan.mode)
        arow = np.asarray(plan.arow)
        brow = np.asarray(plan.brow)
        if codes.dtype != np.int8:
            yield self.finding(
                path, 0,
                f"codes dtype {codes.dtype}, contract is int8 at rest")
        if codes.ndim != 2 or codes.shape[0] != nvalid.shape[0]:
            return   # LUX201 territory
        if nvalid.size and (nvalid.min() < 0 or nvalid.max() > BLOCK):
            yield self.finding(
                path, 0,
                f"nvalid out of [0, {BLOCK}] "
                f"(min {int(nvalid.min())}, max {int(nvalid.max())})")
            return
        if mode.size and not np.isin(mode, (0, 1, 2)).all():
            yield self.finding(
                path, 0, "mode contains values outside {0, 1, 2}")
            return
        lanes = np.arange(codes.shape[1])
        beyond = codes * (lanes[None, :] >= nvalid[:, None])
        if np.any(beyond != 0):
            rows = int(np.count_nonzero(beyond.any(axis=1)))
            yield self.finding(
                path, 0,
                f"{rows} rows carry nonzero codes beyond nvalid — rows "
                "must be prefix-dense (pads read as lane 0 on device)",
            )
        live = lanes[None, :] < nvalid[:, None]
        neg = (codes < 0) & live
        pos = (codes >= 0) & live
        bad_a = (mode == 1) & neg.any(axis=1)
        bad_b = (mode == 2) & pos.any(axis=1)
        if np.any(bad_a):
            yield self.finding(
                path, 0,
                f"{int(bad_a.sum())} copy-A rows carry negative (side-B) "
                "lane codes")
        if np.any(bad_b):
            yield self.finding(
                path, 0,
                f"{int(bad_b.sum())} copy-B rows carry non-negative "
                "(side-A) lane codes")
        mixed = (mode == 0) & (nvalid > 0)
        halfmerge = mixed & ~(neg.any(axis=1) & pos.any(axis=1))
        if np.any(halfmerge):
            yield self.finding(
                path, 0,
                f"{int(halfmerge.sum())} merge rows draw from only one "
                "side — they should be copy rows (mode 1/2)")
        single = (mode > 0) & (arow != brow)
        if np.any(single):
            yield self.finding(
                path, 0,
                f"{int(single.sum())} copy rows have arow != brow — copy "
                "windows stream exactly one input row")
        lp = np.asarray(plan.level_ptr)
        if lp.ndim == 1 and lp.shape[0] >= 2 and lp[0] == 0 and \
                not np.any(np.diff(lp) < 0) and lp[-1] <= mode.shape[0]:
            r0 = int(lp[1])
            lv0_mode = mode[:r0]
            lv0_live = nvalid[:r0] > 0
            if np.any(lv0_mode[lv0_live] != 1):
                yield self.finding(
                    path, 1,
                    "level 0 contains non-copy rows — the x2d gather "
                    "level is all copy-A by construction")
            if np.any((codes[:r0] < 0) & live[:r0]):
                yield self.finding(
                    path, 1,
                    "level 0 carries negative lane codes — source lanes "
                    "are 0..127")


class PlanAlignment(PlanRule):
    id = "LUX204"
    title = "plan-alignment"
    doc = (f"every level's row count is a multiple of {ALIGN_ROWS} "
           "(Mosaic sublane block unit the kernel BlockSpecs assume)")

    def check(self, plan, path: str) -> Iterable[Finding]:
        lp = np.asarray(plan.level_ptr)
        if lp.ndim != 1 or lp.shape[0] < 2 or np.any(np.diff(lp) < 0):
            return
        rows = np.diff(lp)
        for k in range(rows.shape[0]):
            if rows[k] % ALIGN_ROWS:
                yield self.finding(
                    path, k + 1,
                    f"level {k} has {int(rows[k])} rows — not a multiple "
                    f"of {ALIGN_ROWS}, so the kernel's 8-row blocks read "
                    "across the level boundary",
                )


class PlanCopyRate(PlanRule):
    id = "LUX205"
    title = "plan-copy-rate"
    doc = ("per-level stream inflation (rows / ceil(n_edges/128)) below "
           "LUX_PLANCK_INFLATION — the copy-window rate bound")

    def check(self, plan, path: str) -> Iterable[Finding]:
        lp = np.asarray(plan.level_ptr)
        if lp.ndim != 1 or lp.shape[0] < 2 or np.any(np.diff(lp) < 0):
            return
        bound = flags.get_float("LUX_PLANCK_INFLATION")
        ideal = max(-(-int(plan.n_edges) // BLOCK), 1)
        rows = np.diff(lp)
        for k in range(rows.shape[0]):
            inflation = rows[k] / ideal
            if inflation > bound:
                yield self.finding(
                    path, k + 1,
                    f"level {k} streams {int(rows[k])} rows = "
                    f"{inflation:.2f}x the ideal {ideal} — above the "
                    f"{bound:g}x copy-window rate bound "
                    "(LUX_PLANCK_INFLATION); this plan predates the "
                    "copy-window schedule or was built from skewed "
                    "inputs without it",
                )


def all_plan_rules() -> List[PlanRule]:
    return [
        PlanStructure(),
        PlanConservation(),
        PlanCodePlane(),
        PlanAlignment(),
        PlanCopyRate(),
    ]


def verify_plan(plan, path: str = "<plan>",
                rules: Optional[Sequence[PlanRule]] = None) -> FileResult:
    """Run the LUX2xx rules over one in-memory GroupedTailPlan."""
    if rules is None:
        rules = all_plan_rules()
    findings: List[Finding] = []
    errors: List[str] = []
    for rule in rules:
        try:
            findings.extend(rule.check(plan, path))
        except Exception as e:   # corrupted arrays can break numpy ops
            errors.append(f"{path}: {rule.id} crashed: {e!r}")
    findings.sort(key=lambda f: (f.line, f.rule))
    return FileResult(path, findings, [], error="; ".join(errors) or None)


def verify_plan_dirs(paths: Sequence[str],
                     rules: Optional[Sequence[PlanRule]] = None
                     ) -> LintReport:
    """Load (mmap) and verify saved plan directories."""
    t0 = time.perf_counter()
    results: List[FileResult] = []
    for path in paths:
        try:
            plan = load_plan_artifact(path, mmap=True)
        except Exception as e:
            results.append(FileResult(
                path, [], [], error=f"{path}: unloadable plan: {e!r}"))
            continue
        results.append(verify_plan(plan, path, rules))
    return LintReport(results, time.perf_counter() - t0, schema=PLAN_SCHEMA)
