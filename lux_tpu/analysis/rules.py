"""The luxlint rule set — this repo's real failure modes, machine-checked.

Each rule encodes an invariant the performance story depends on but the
code previously only promised in prose:

- LUX001 host-sync-in-hot-loop: Gunrock-style frontier/iteration loops
  are fast only while no hidden host round-trip sits inside them (a
  single ``.item()`` per iteration serializes the whole async dispatch
  pipeline — PERF.md measured 620 vs 316 ms/iter for dispatch-per-step
  vs fused).
- LUX002 recompile-hygiene: jitted steps must donate their buffer
  argument (else HBM holds two copies) and jitted callables must not be
  fed bare Python scalars (each distinct value retraces).
- LUX003 kernel-shape-contract: Pallas BlockSpecs must honor the plan
  layout rules from ops/merge_tail_plan.py — 128-lane blocks, rows in
  Mosaic 8-row units (or single-row scalar-prefetch form), int8 code
  planes, int32 row indices.
- LUX004 env-flag-registry: every ``LUX_*`` key read anywhere must be
  declared in lux_tpu/utils/flags.py.
- LUX005 direct-env-read: lux_tpu code reads LUX_* knobs through the
  flags module, not os.environ (writes — CLI flag plumbing,
  subprocess setup — stay legal).
- LUX006 clock-discipline: serve/engine code stamps time through
  obs.spans helpers (clock() for durations on the trace epoch,
  monotonic() for deadlines), never raw time.* — mixed clock sources
  corrupt SLO math and trace alignment.
- LUX007 swallowed-exception: serve/engine handlers that catch
  Exception/BaseException (or bare ``except``) must do more than log
  and move on — a dropped engine error is an answer somebody never
  gets, and the fault-injection harness (utils/faults.py) only proves
  anything if injected failures surface as terminal statuses.

All pure ``ast``; no jax, no numpy.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from lux_tpu.analysis.core import FileContext, Finding, Rule

# Functions that ARE the iteration hot path. Deliberately narrow: warmup
# and phase_step sync per dispatch by design.
_HOT_FN_RE = re.compile(r"(^|_)run(_|$)|fixpoint|pipelined")
# jit'd callables that carry the iteration state buffer.
_STEP_FN_RE = re.compile(r"(^|_)(step|run)")

_LANE = 128
_SUBLANE = 8


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.device_get' for Attribute chains, 'float' for Names."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root_ident(node: ast.AST) -> Optional[str]:
    """Nearest meaningful identifier of an expression: the value a call
    like ``x.codes.astype(...)`` is really about ('codes')."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _root_ident(node.value)
    if isinstance(node, ast.Call):
        if node.args:
            return _root_ident(node.args[0])
        return _root_ident(node.func)
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class HostSyncInHotLoop(Rule):
    id = "LUX001"
    title = "host-sync-in-hot-loop"
    doc = ("no host transfer/sync (.item(), float(), np.asarray, "
           "device_get, block_until_ready, hard_sync) inside engine "
           "run/fixpoint loops")

    _SYNC_CALLS = {"jax.device_get", "device_get", "hard_sync"}
    _ASARRAY = {"np.asarray", "numpy.asarray", "onp.asarray"}

    def applies_to(self, ctx: FileContext) -> bool:
        return "engine/" in ctx.posix_path

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: Dict[tuple, Finding] = {}
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _HOT_FN_RE.search(fn.name):
                continue
            host_names = self._host_tainted(fn)
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    f = self._check_call(node, fn.name, host_names, ctx)
                    if f is not None:
                        out[(f.line, f.col)] = f
        return out.values()

    def _host_tainted(self, fn: ast.AST) -> Set[str]:
        """Names holding already-fetched host values: assigned (possibly
        transitively) from a device_get result. Converting those again
        (int()/np.asarray()) is free — don't flag it."""
        assigns = sorted(
            (n for n in ast.walk(fn)
             if isinstance(n, (ast.Assign, ast.AugAssign))),
            key=lambda n: n.lineno,
        )
        tainted: Set[str] = set()
        for a in assigns:
            rhs = a.value
            from_get = any(
                isinstance(c, ast.Call)
                and _dotted(c.func) in self._SYNC_CALLS
                for c in ast.walk(rhs)
            )
            if not (from_get or (_names_in(rhs) & tainted)):
                continue
            targets = a.targets if isinstance(a, ast.Assign) else [a.target]
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                tainted.update(
                    e.id for e in elts if isinstance(e, ast.Name)
                )
        return tainted

    def _arg_is_host(self, arg: ast.AST, host_names: Set[str]) -> bool:
        if isinstance(arg, ast.Constant):
            return True
        if _names_in(arg) & host_names:
            return True
        # np.asarray(jax.device_get(x)): the inner sync is the finding.
        return any(
            isinstance(c, ast.Call) and _dotted(c.func) in self._SYNC_CALLS
            for c in ast.walk(arg)
        )

    def _check_call(self, node, fn_name, host_names, ctx):
        if not isinstance(node, ast.Call):
            return None
        name = _dotted(node.func)
        if name in self._SYNC_CALLS or (
            name is not None and name.endswith("block_until_ready")
        ):
            return self.finding(
                ctx, node,
                f"`{name}` inside hot loop of `{fn_name}` stalls the "
                "device pipeline; hoist it out of the loop or suppress "
                "with a reason",
            )
        if name in self._ASARRAY and node.args and not self._arg_is_host(
            node.args[0], host_names
        ):
            return self.finding(
                ctx, node,
                f"`{name}` on a device value inside hot loop of "
                f"`{fn_name}` forces a device->host transfer per "
                "iteration",
            )
        if name in ("float", "int") and len(node.args) == 1 and \
                not self._arg_is_host(node.args[0], host_names):
            return self.finding(
                ctx, node,
                f"`{name}()` on a device value inside hot loop of "
                f"`{fn_name}` blocks on the device per iteration",
            )
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and not node.args and \
                not self._arg_is_host(node.func.value, host_names):
            return self.finding(
                ctx, node,
                f"`.item()` inside hot loop of `{fn_name}` is a "
                "synchronous device->host scalar read per iteration",
            )
        return None


class RecompileHygiene(Rule):
    id = "LUX002"
    title = "recompile-hygiene"
    doc = ("jitted buffer-carrying steps need donate_argnums; jitted "
           "callables must not be fed bare Python scalars")

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        # binding name -> True when the jit has static_argnums/argnames
        # (scalar args are then legitimately static).
        jit_bindings: Dict[str, bool] = {}

        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ) and _dotted(node.value.func) in ("jax.jit", "jit"):
                out.extend(self._check_jit_call(node.value, ctx))
                has_static = self._has_kw(
                    node.value, "static_argnums", "static_argnames"
                )
                for t in node.targets:
                    bind = t.id if isinstance(t, ast.Name) else (
                        t.attr if isinstance(t, ast.Attribute) else None
                    )
                    if bind is not None:
                        jit_bindings[bind] = has_static
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dec_call = dec if isinstance(dec, ast.Call) else None
                    name = _dotted(dec_call.func if dec_call else dec)
                    if name in ("jax.jit", "jit") and _STEP_FN_RE.search(
                        node.name
                    ) and not (
                        dec_call is not None and self._has_kw(
                            dec_call, "donate_argnums", "donate_argnames"
                        )
                    ):
                        out.append(self.finding(
                            ctx, dec,
                            f"@jit on buffer-carrying `{node.name}` "
                            "without donate_argnums keeps the old buffer "
                            "live (2x HBM for the state)",
                        ))

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            bind = None
            if isinstance(node.func, ast.Name):
                bind = node.func.id
            elif isinstance(node.func, ast.Attribute):
                bind = node.func.attr
            if bind not in jit_bindings or jit_bindings[bind]:
                continue
            scalars = [
                a for a in list(node.args) + [k.value for k in node.keywords]
                if isinstance(a, ast.Constant)
                and type(a.value) in (int, float)
            ]
            for a in scalars:
                out.append(self.finding(
                    ctx, a,
                    f"Python scalar {a.value!r} fed to jitted `{bind}` — "
                    "every distinct value retraces and recompiles; wrap "
                    "it (jnp.asarray/jnp.int32) or mark the arg static",
                ))
        return out

    @staticmethod
    def _has_kw(call: ast.Call, *names: str) -> bool:
        return any(k.arg in names for k in call.keywords)

    def _check_jit_call(self, call: ast.Call, ctx) -> List[Finding]:
        if not call.args:
            return []
        fn_name = _dotted(call.args[0])
        if fn_name is None:
            return []
        short = fn_name.rsplit(".", 1)[-1]
        if _STEP_FN_RE.search(short) and not self._has_kw(
            call, "donate_argnums", "donate_argnames"
        ):
            return [self.finding(
                ctx, call,
                f"jax.jit of buffer-carrying `{short}` without "
                "donate_argnums keeps the old buffer live (2x HBM for "
                "the state)",
            )]
        return []


class KernelShapeContract(Rule):
    id = "LUX003"
    title = "kernel-shape-contract"
    doc = ("Pallas BlockSpecs: 128-lane blocks, rows 1 or a multiple of "
           "8; kernel dtype contract: int8 code planes, int32 row "
           "indices (ops/merge_tail_plan.py layout rules)")

    _CODE_DTYPES = {"int8", "int32"}   # codes upcast to int32 in-kernel
    _ROW_DTYPES = {"int32"}

    def applies_to(self, ctx: FileContext) -> bool:
        return "ops/" in ctx.posix_path

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        is_kernel_file = "kernel" in ctx.posix_path.rsplit("/", 1)[-1]
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            short = name.rsplit(".", 1)[-1] if name else (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else None
            )
            if short in ("BlockSpec", "ShapeDtypeStruct"):
                out.extend(self._check_shape(node, short, ctx))
            elif short == "astype" and is_kernel_file:
                out.extend(self._check_astype(node, ctx))
        return out

    def _check_shape(self, node: ast.Call, short: str, ctx) -> List[Finding]:
        if not node.args or not isinstance(node.args[0], ast.Tuple):
            return []
        elts = node.args[0].elts
        out: List[Finding] = []
        if not elts:
            return out
        last = elts[-1]
        if isinstance(last, ast.Constant) and isinstance(last.value, int) \
                and last.value % _LANE != 0:
            out.append(self.finding(
                ctx, last,
                f"{short} lane width {last.value} — the trailing block "
                f"dim must be a multiple of {_LANE} (VPU lane tile); "
                "narrower blocks scalarize",
            ))
        if short == "BlockSpec" and len(elts) >= 2:
            first = elts[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, int
            ) and first.value != 1 and first.value % _SUBLANE != 0:
                out.append(self.finding(
                    ctx, first,
                    f"BlockSpec sublane rows {first.value} — rows must "
                    f"be 1 (scalar-prefetch per-row form) or a multiple "
                    f"of {_SUBLANE} (Mosaic 8-row block units)",
                ))
        return out

    def _check_astype(self, node: ast.Call, ctx) -> List[Finding]:
        if len(node.args) != 1 or not isinstance(node.func, ast.Attribute):
            return []
        dt = node.args[0]
        dtype = dt.value if isinstance(dt, ast.Constant) else (
            (_dotted(dt) or "").rsplit(".", 1)[-1]
        )
        if not isinstance(dtype, str) or not dtype:
            return []
        ident = (_root_ident(node.func.value) or "").lower()
        if "code" in ident and dtype not in self._CODE_DTYPES:
            return [self.finding(
                ctx, node,
                f"code plane `{ident}` cast to {dtype} — the routing "
                "plane contract is int8 at rest (int32 in-kernel)",
            )]
        if "row" in ident and dtype not in self._ROW_DTYPES:
            return [self.finding(
                ctx, node,
                f"row-index `{ident}` cast to {dtype} — scalar-prefetch "
                "row offsets must be int32 on device",
            )]
        return []


def _env_key(call: ast.Call) -> Optional[str]:
    """The literal LUX_* key of an os.environ access, if any."""
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str) and \
            call.args[0].value.startswith("LUX_"):
        return call.args[0].value
    return None


class EnvFlagRegistry(Rule):
    id = "LUX004"
    title = "env-flag-registry"
    doc = ("every LUX_* env key touched anywhere must be declared in "
           "lux_tpu/utils/flags.py; flags.define() outside that file is "
           "registry drift")

    _ENV_CALLS = ("environ.get", "environ.setdefault", "environ.pop",
                  "getenv")
    _FLAG_CALLS = ("get", "get_int", "get_float", "get_bool", "tristate")

    @staticmethod
    def _define_aliases(tree: ast.Module) -> Set[str]:
        """Local names bound to lux_tpu.utils.flags.define by imports."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.endswith("utils.flags"):
                out.update(
                    a.asname or a.name for a in node.names
                    if a.name == "define"
                )
        return out

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        in_registry = ctx.posix_path.endswith("utils/flags.py")
        define_aliases = self._define_aliases(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and not in_registry:
                name = _dotted(node.func) or ""
                if name.endswith("flags.define") or name in define_aliases:
                    out.append(self.finding(
                        ctx, node,
                        "flags.define() outside lux_tpu/utils/flags.py — "
                        "the registry is the single declaration site; "
                        "LUX004's allowed-key set is generated from it",
                    ))
        for node in ast.walk(tree):
            key = None
            if isinstance(node, ast.Call):
                name = _dotted(node.func) or ""
                short = name.rsplit(".", 1)[-1]
                if any(name.endswith(c) for c in self._ENV_CALLS):
                    key = _env_key(node)
                elif short in self._FLAG_CALLS and (
                    "flags." in name or name.startswith("flags")
                ):
                    key = _env_key(node)
            elif isinstance(node, ast.Subscript):
                name = _dotted(node.value) or ""
                if name.endswith("environ") and isinstance(
                    node.slice, ast.Constant
                ) and isinstance(node.slice.value, str) and \
                        node.slice.value.startswith("LUX_"):
                    key = node.slice.value
            if key is not None and key not in ctx.declared_flags:
                out.append(self.finding(
                    ctx, node,
                    f"undeclared flag {key} — declare it in "
                    "lux_tpu/utils/flags.py so the registry stays the "
                    "single source of truth",
                ))
        return out


class DirectEnvRead(Rule):
    id = "LUX005"
    title = "direct-env-read"
    doc = ("lux_tpu code must read LUX_* knobs through "
           "lux_tpu.utils.flags, not os.environ (writes stay legal)")

    def applies_to(self, ctx: FileContext) -> bool:
        return "lux_tpu/" in ctx.posix_path and not ctx.posix_path.endswith(
            "utils/flags.py"
        )

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(tree):
            key = None
            if isinstance(node, ast.Call):
                name = _dotted(node.func) or ""
                if name.endswith("environ.get") or name.endswith("getenv"):
                    key = _env_key(node)
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                name = _dotted(node.value) or ""
                if name.endswith("environ") and isinstance(
                    node.slice, ast.Constant
                ) and isinstance(node.slice.value, str) and \
                        node.slice.value.startswith("LUX_"):
                    key = node.slice.value
            if key is not None:
                out.append(self.finding(
                    ctx, node,
                    f"direct os.environ read of {key} — use "
                    "lux_tpu.utils.flags accessors (typed, documented, "
                    "registry-checked)",
                ))
        return out


class ClockDiscipline(Rule):
    id = "LUX006"
    title = "clock-discipline"
    doc = ("serve/engine code takes timestamps through the obs helpers "
           "(spans.clock for durations, spans.monotonic for deadlines), "
           "not raw time.* — mixed clock sources make latency math and "
           "trace alignment silently wrong")

    _CLOCK_CALLS = {
        "time.time", "time.perf_counter", "time.monotonic",
        "time.perf_counter_ns", "time.monotonic_ns",
    }

    def applies_to(self, ctx: FileContext) -> bool:
        if "obs/" in ctx.posix_path:      # the helpers themselves
            return False
        return "serve/" in ctx.posix_path or "engine/" in ctx.posix_path

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name in self._CLOCK_CALLS:
                out.append(self.finding(
                    ctx, node,
                    f"direct {name}() in serve/engine code — use "
                    "lux_tpu.obs.spans.clock() (perf_counter, trace "
                    "epoch) or spans.monotonic() (deadlines) so every "
                    "latency shares one clock source",
                ))
        return out


class SwallowedException(Rule):
    id = "LUX007"
    title = "swallowed-exception"
    doc = ("serve/engine handlers catching Exception/BaseException (or "
           "bare except) must not reduce to log-and-drop — re-raise, "
           "convert to a typed ServeError, resolve the request's future, "
           "or record state the caller observes")

    # A handler whose whole body is pass/continue/bare-return plus calls
    # that only say something matches "swallow". Matching is on the
    # dotted-name parts, so self.log.warning, logging.error, print, and
    # logger.exception all count as log-only; metrics increments, future
    # resolution, and flight dumps count as real work (observable state).
    _LOG_PARTS = frozenset((
        "log", "logger", "logging", "print", "warn", "warning", "debug",
        "info", "error", "exception",
    ))

    def applies_to(self, ctx: FileContext) -> bool:
        return "serve/" in ctx.posix_path or "engine/" in ctx.posix_path

    @classmethod
    def _broad(cls, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:            # bare except
            return True
        elts = (handler.type.elts if isinstance(handler.type, ast.Tuple)
                else [handler.type])
        return any((_dotted(e) or "") in ("Exception", "BaseException")
                   for e in elts)

    @classmethod
    def _inert(cls, stmt: ast.stmt) -> bool:
        """True for statements that drop the error on the floor."""
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            return True
        if isinstance(stmt, ast.Return):
            return stmt.value is None or (
                isinstance(stmt.value, ast.Constant)
                and stmt.value.value is None
            )
        if isinstance(stmt, ast.Expr):
            if isinstance(stmt.value, ast.Constant):
                return True                 # stray docstring
            if isinstance(stmt.value, ast.Call):
                name = _dotted(stmt.value.func) or ""
                return any(p.lower() in cls._LOG_PARTS
                           for p in name.split("."))
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._broad(node):
                continue
            if all(self._inert(s) for s in node.body):
                caught = ("bare except" if node.type is None
                          else _dotted(node.type) or "broad except")
                out.append(self.finding(
                    ctx, node,
                    f"{caught} swallows the error (log-and-drop body) — "
                    "re-raise, map to a typed ServeError, or make the "
                    "failure observable (resolve the future / record "
                    "state); silent drops hide real engine faults",
                ))
        return out


class MetricNameDiscipline(Rule):
    id = "LUX008"
    title = "metric-name-discipline"
    doc = ("metric names must match lux_[a-z0-9_]+(_total|_seconds|"
           "_bytes)? and handles must not be minted per call: every "
           "counter/gauge/histogram factory call round-trips the "
           "registry lock, so creation is banned inside loops, and in "
           "obs/ code a constant-shaped handle (literal name, no or "
           "constant labels) must live at module scope")

    _NAME_RE = re.compile(r"lux_[a-z0-9_]+(_total|_seconds|_bytes)?")
    _FACTORIES = frozenset(("counter", "gauge", "histogram"))

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        bare = self._bare_factory_names(tree)
        in_obs = "obs/" in ctx.posix_path
        # One pass with explicit ancestry: (in a def, in a loop) per node.
        # At most ONE finding per creation call — bad name beats
        # loop-mint beats module-scope, so each site reads as one defect.
        stack: List[Tuple[ast.AST, bool, bool]] = [(tree, False, False)]
        while stack:
            node, in_def, in_loop = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_def = True
            elif isinstance(node, (ast.For, ast.While)):
                in_loop = True
            elif isinstance(node, ast.Call):
                f = self._check_creation(node, ctx, bare, in_obs,
                                         in_def, in_loop)
                if f is not None:
                    out.append(f)
            for child in ast.iter_child_nodes(node):
                stack.append((child, in_def, in_loop))
        return out

    def _bare_factory_names(self, tree: ast.Module) -> Set[str]:
        """Factory names bound by ``from ...metrics import counter, ...``
        anywhere in the file (engine code imports them function-locally)."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if not (node.module or "").endswith("metrics"):
                continue
            names.update(
                a.asname or a.name for a in node.names
                if a.name in self._FACTORIES)
        return names

    def _is_factory(self, node: ast.Call, bare: Set[str]) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            return func.id in bare
        name = _dotted(func)
        if name is None:
            return False
        parts = name.split(".")
        return parts[-1] in self._FACTORIES and "metrics" in parts[:-1]

    @staticmethod
    def _constant_labels(node: ast.Call) -> bool:
        """True when the labels argument is absent, None, or a literal
        dict of literal keys/values — i.e. the handle has a fixed shape
        and the creation could be hoisted verbatim."""
        labels: Optional[ast.expr] = None
        if len(node.args) > 1:
            labels = node.args[1]
        for kw in node.keywords:
            if kw.arg == "labels":
                labels = kw.value
        if labels is None:
            return True
        if isinstance(labels, ast.Constant):
            return labels.value is None
        if isinstance(labels, ast.Dict):
            return all(isinstance(k, ast.Constant) for k in labels.keys) \
                and all(isinstance(v, ast.Constant) for v in labels.values)
        return False

    def _check_creation(self, node: ast.Call, ctx: FileContext,
                        bare: Set[str], in_obs: bool,
                        in_def: bool, in_loop: bool) -> Optional[Finding]:
        if not self._is_factory(node, bare):
            return None
        name_arg = node.args[0] if node.args else None
        literal = (name_arg.value
                   if isinstance(name_arg, ast.Constant)
                   and isinstance(name_arg.value, str) else None)
        if literal is not None and not self._NAME_RE.fullmatch(literal):
            return self.finding(
                ctx, node,
                f"metric name {literal!r} breaks the naming contract — "
                "must match lux_[a-z0-9_]+(_total|_seconds|_bytes)? "
                "(lux_ prefix, lowercase snake_case, unit suffix for "
                "counters/durations/sizes)")
        hoistable = literal is not None and self._constant_labels(node)
        if in_loop and hoistable:
            return self.finding(
                ctx, node,
                f"metric handle {literal!r} minted inside a loop — each "
                "factory call takes the registry lock; create the handle "
                "once outside the loop and reuse it")
        if in_obs and in_def and hoistable:
            return self.finding(
                ctx, node,
                f"constant-shaped metric handle {literal!r} created per "
                "call — literal name with no/constant labels belongs at "
                "module scope; per-call creation churns the registry "
                "lock on every invocation")
        return None


class RegionNameDiscipline(Rule):
    id = "LUX009"
    title = "region-name-discipline"
    doc = ("profiler region names must match lux\\.[a-z0-9_.]+: a "
           "literal name passed to prof.region, jax.named_scope, or "
           "jax.profiler.TraceAnnotation that breaks the pattern never "
           "joins the profile.v1 phase accounting (the parser only "
           "classifies lux.* tags), so the time it brackets silently "
           "vanishes from exchange/compute attribution")

    _NAME_RE = re.compile(r"lux\.[a-z0-9_.]+")
    # Dotted-call tails that take a region/scope name as their first
    # argument. `region` alone is also tracked when imported bare from
    # obs.prof (mirrors LUX008's bare-factory tracking).
    _TAILS = frozenset(("named_scope", "TraceAnnotation"))

    def _bare_region_names(self, tree: ast.Module) -> Set[str]:
        """Names bound by ``from ...prof import region`` (or an asname
        of it) anywhere in the file."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if not (node.module or "").endswith("prof"):
                continue
            names.update(a.asname or a.name for a in node.names
                         if a.name == "region")
        return names

    def _is_region_call(self, node: ast.Call, bare: Set[str]) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            return func.id in bare
        name = _dotted(func)
        if name is None:
            return False
        parts = name.split(".")
        tail = parts[-1]
        if tail == "region":
            return "prof" in parts[:-1]
        if tail in self._TAILS:
            # jax.named_scope / jax.profiler.TraceAnnotation, however
            # the jax module is spelled locally.
            return True
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        bare = self._bare_region_names(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._is_region_call(node, bare):
                continue
            name_arg = node.args[0] if node.args else None
            literal = (name_arg.value
                       if isinstance(name_arg, ast.Constant)
                       and isinstance(name_arg.value, str) else None)
            if literal is None:
                continue    # dynamic names validate at runtime
            if not self._NAME_RE.fullmatch(literal):
                out.append(self.finding(
                    ctx, node,
                    f"region name {literal!r} breaks the naming contract "
                    "— must fullmatch lux.[a-z0-9_.]+ (lux. prefix, "
                    "lowercase dotted segments) or the profile.v1 parser "
                    "drops it from phase attribution"))
        return out


class LedgerDiscipline(Rule):
    id = "LUX010"
    title = "ledger-discipline"
    doc = ("run metrics (summaries, telemetry) leave the process through "
           "the run ledger (lux_tpu/obs/ledger.py record_run), not ad-hoc "
           "json.dump — an unframed dump is invisible to lux_doctor and "
           "the auto-tuner corpus, and carries no config_hash to "
           "reproduce it under")

    # Dumping an expression rooted at one of these identifiers is the
    # run-metrics shape this rule polices; artifact writes (plans,
    # reports, flight docs, bench round lines) keep their own formats.
    _METRIC_IDENTS = ("summary", "telemetry", "runrec", "run_record",
                      "metrics")

    def applies_to(self, ctx: FileContext) -> bool:
        p = ctx.posix_path
        if p.endswith("obs/ledger.py") or p.endswith("obs/report.py"):
            # The ledger's own framing, and the documented legacy
            # LUX_METRICS JSON-lines dump report.finalize still feeds.
            return False
        return ("engine/" in p or "serve/" in p or "obs/" in p
                or p.endswith("bench.py"))

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func) or ""
            if name not in ("json.dump", "json.dumps"):
                continue
            arg = node.args[0] if node.args else None
            root = (_root_ident(arg) or "").lower() if arg is not None \
                else ""
            if any(tok in root for tok in self._METRIC_IDENTS):
                out.append(self.finding(
                    ctx, node,
                    f"ad-hoc json dump of run metrics ({root!r}) — append "
                    "a runrec.v1 record via lux_tpu.obs.ledger.record_run "
                    "so the observation is durable, crc-framed, and keyed "
                    "by (graph, program, engine, mesh, config_hash)",
                ))
        return out


def all_rules() -> List[Rule]:
    return [
        HostSyncInHotLoop(),
        RecompileHygiene(),
        KernelShapeContract(),
        EnvFlagRegistry(),
        DirectEnvRead(),
        ClockDiscipline(),
        SwallowedException(),
        MetricNameDiscipline(),
        RegionNameDiscipline(),
        LedgerDiscipline(),
    ]
