"""Runtime tracing-discipline sentinels.

The static rules (rules.py) catch what an AST can see; these two catch
what only the runtime knows:

- :class:`RecompileSentinel` — counts actual XLA backend compiles per
  executor key via the jax monitoring hook
  (``/jax/core/compile/backend_compile_duration`` fires once per real
  compile, never on tracing-cache hits). serve/pool.py builds engines
  under ``expect(key)`` and serves queries under ``watch(key)``; any
  compile landing in a watch region is a recompile — the serving
  layer's "zero recompiles after warmup" claim, machine-checked.
  Counters mirror onto the obs metrics registry
  (``lux_xla_compiles_total{key,phase}``) so ``LUX_METRICS`` dumps
  carry compile counts per engine key.

- :class:`HostTransferGuard` — a context manager that fails any
  ``jax.device_get`` / ``jax.block_until_ready`` issued inside a
  guarded iteration region (and, on non-CPU backends, any implicit
  device->host transfer via jax's own transfer guard — on the CPU
  test mesh arrays are host-resident, so jax's guard never fires and
  the patched entry points are the enforcement). Tests wrap the
  region between intended sync points to prove the loop body is
  transfer-free.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional, Tuple

from lux_tpu.obs import metrics

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_SENTINELS = set()
_SENTINELS_LOCK = threading.Lock()
_LISTENER_STATE = {"installed": False, "available": False}


def _dispatch(event: str, *a, **kw):
    if event != _COMPILE_EVENT:
        return
    with _SENTINELS_LOCK:
        active = list(_SENTINELS)
    for s in active:
        s._on_compile()


def _ensure_listener() -> bool:
    """Install the process-wide compile listener once. jax's monitoring
    registry is append-only, so the listener dispatches to whatever
    sentinels are alive rather than registering per instance."""
    if _LISTENER_STATE["installed"]:
        return _LISTENER_STATE["available"]
    _LISTENER_STATE["installed"] = True
    try:
        from jax._src import monitoring
    except ImportError:
        _LISTENER_STATE["available"] = False
        return False
    monitoring.register_event_duration_secs_listener(_dispatch)
    _LISTENER_STATE["available"] = True
    return True


class RecompileError(AssertionError):
    """A compile happened in a region that promised zero recompiles."""


class RecompileSentinel:
    """Per-key XLA compile counter with warmup/serve phase attribution.

    Compiles are attributed to the innermost active region on the
    calling thread (jax compiles synchronously on the dispatching
    thread): ``expect(key)`` regions absorb warmup compiles,
    ``watch(key)`` regions count recompiles. Compiles outside any
    region are ignored — unrelated test traffic must not pollute the
    serving evidence.
    """

    def __init__(self, scope: str = "default"):
        self.scope = scope
        self.available = _ensure_listener()
        self._counts: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()
        # Registered eagerly so a clean sentinel still exports the
        # family at 0 — /metrics scrapes alert on the serve-phase count
        # going nonzero, not on its absence.
        metrics.counter(
            "lux_xla_compiles_total",
            {"scope": scope, "key": "_all", "phase": "serve"},
        )
        with _SENTINELS_LOCK:
            _SENTINELS.add(self)

    def close(self):
        with _SENTINELS_LOCK:
            _SENTINELS.discard(self)

    # -- region plumbing -------------------------------------------------

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextlib.contextmanager
    def _region(self, phase: str, key):
        st = self._stack()
        st.append((phase, str(key)))
        try:
            yield self
        finally:
            st.pop()

    def expect(self, key):
        """Region where compiles are expected (build + warmup)."""
        return self._region("warmup", key)

    def watch(self, key):
        """Region that promises zero compiles (post-warmup serving)."""
        return self._region("serve", key)

    def _on_compile(self):
        st = getattr(self._tls, "stack", None)
        if not st:
            return
        phase, key = st[-1]
        with self._lock:
            self._counts[(key, phase)] = self._counts.get((key, phase), 0) + 1
        metrics.counter(
            "lux_xla_compiles_total",
            {"scope": self.scope, "key": key, "phase": phase},
        ).inc()

    # -- readout ---------------------------------------------------------

    def compiles(self, key=None, phase: str = "warmup") -> int:
        with self._lock:
            return sum(
                c for (k, p), c in self._counts.items()
                if p == phase and (key is None or k == str(key))
            )

    def recompiles(self, key=None) -> int:
        """Compiles observed inside watch regions (should stay 0)."""
        return self.compiles(key, phase="serve")

    def stats(self) -> dict:
        with self._lock:
            per_key: Dict[str, Dict[str, int]] = {}
            for (k, p), c in self._counts.items():
                per_key.setdefault(k, {})[p] = c
        return {
            "available": self.available,
            "warmup_compiles": self.compiles(),
            "recompiles": self.recompiles(),
            "per_key": per_key,
        }

    def assert_zero_recompiles(self, key=None):
        n = self.recompiles(key)
        if n:
            raise RecompileError(
                f"{n} XLA compile(s) after warmup in scope "
                f"{self.scope!r}: {self.stats()['per_key']}"
            )


class HostTransferError(AssertionError):
    """A device->host transfer happened inside a guarded region."""


class HostTransferGuard:
    """Fail device->host transfers inside a guarded iteration region.

    Patches ``jax.device_get`` and ``jax.block_until_ready`` (the entry
    points every lux_tpu sync path funnels through — hard_sync calls
    both) and additionally arms jax's own
    ``transfer_guard_device_to_host("disallow")``, which catches
    implicit transfers (``np.asarray``, ``float()``, ``.item()``) on
    backends with a real device boundary. Single-thread test use; the
    module-level patch is process-wide while the guard is active.

    ``allow()`` opens a window for an intended sync point::

        with HostTransferGuard() as g:
            for _ in range(n):
                vals = step(vals)        # must stay on device
            with g.allow():
                jax.block_until_ready(vals)
    """

    def __init__(self, label: str = ""):
        self.label = label
        self._depth = 0          # allow() nesting
        self._saved = None
        self._stack = None

    def _blocked(self, what: str):
        raise HostTransferError(
            f"{what} inside HostTransferGuard"
            + (f" [{self.label}]" if self.label else "")
            + " — device->host transfer in a guarded iteration region"
        )

    def __enter__(self):
        import jax

        real_get, real_block = jax.device_get, jax.block_until_ready
        guard = self

        def guarded_get(x):
            if guard._depth == 0:
                guard._blocked("jax.device_get")
            return real_get(x)

        def guarded_block(x):
            if guard._depth == 0:
                guard._blocked("jax.block_until_ready")
            return real_block(x)

        self._saved = (real_get, real_block)
        jax.device_get = guarded_get
        jax.block_until_ready = guarded_block
        self._stack = contextlib.ExitStack()
        try:
            self._stack.enter_context(
                jax.transfer_guard_device_to_host("disallow")
            )
        except Exception:
            pass  # older jax without the context manager: patches only
        return self

    def __exit__(self, *exc):
        import jax

        jax.device_get, jax.block_until_ready = self._saved
        self._saved = None
        stack, self._stack = self._stack, None
        stack.close()
        return False

    @contextlib.contextmanager
    def allow(self):
        """Window for an intended sync point inside the guard."""
        import jax

        self._depth += 1
        try:
            with jax.transfer_guard_device_to_host("allow"):
                yield
        finally:
            self._depth -= 1
