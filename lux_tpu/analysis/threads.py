"""luxlint-threads: the concurrency tier (LUX301-LUX305).

The serving substrate is genuinely multi-threaded — the MicroBatcher
worker, background snapshot warms, compaction daemons, the FIFO drain
barrier, thread-per-request HTTP — and ROADMAP items 1/3/5 each add
more threads. The AST tier (rules.py) and IR tier (ir.py) say nothing
about thread-shared state; this tier machine-checks the lock discipline
the code previously only promised in comments:

- LUX301 shared-state-without-lock: in any class that hands work to
  another thread (``threading.Thread(target=...)``, a nested thread
  target, or a method registered with a batcher/worker/context
  consumer), attributes touched from both the thread side and the
  caller side must be accessed under a ``with <...lock>:`` guard.
- LUX302 lock-order-inversion: the static acquisition graph built from
  syntactically nested ``with <lock>`` blocks across the whole package
  must be acyclic — an A→B nesting in one function and B→A in another
  is a deadlock waiting for the right interleave.
- LUX303 blocking-call-under-lock: unbounded waits (``.join()`` /
  ``.result()`` / ``.wait()`` with no timeout, queue ``get()`` with no
  timeout), sleeps, device syncs, socket/HTTP I/O, and engine
  warmup/compile inside a lock-guarded region stall every other thread
  that needs the lock.
- LUX304 unjoined-thread: every spawned thread needs a drain path —
  joined directly, returned to the caller, or registered in a container
  the file drains (``SnapshotStore.drain_compactions`` is the compliant
  shape).
- LUX305 unsynchronized-publish: atomic-flip pointers (the
  ``Session._serving`` hot-swap idiom) declared with
  ``# luxlint: publish=<lock>`` must be written at most once per method,
  only under the declared lock, and read at most once per method (read
  the pointer into a local; a second raw read can observe a different
  version mid-swap).

Annotation grammar (same-line comments)::

    self._state = {}      # luxlint: publish=_swap_lock
    self._serving = snap  # luxlint: guarded-by=_swap_lock -- caller holds it

``guarded-by=<lock>`` on an ``__init__`` assignment declares the attr's
required lock class-wide; on any other access line it asserts that this
specific access runs with ``<lock>`` held by a caller (the cross-method
discipline the AST cannot see — a reviewed assertion, like a
suppression, but still checked against the declared lock name).
Findings suppress exactly like every other tier::

    ex.warmup()  # luxlint: disable=LUX303 -- first build must hold the key

Pure stdlib ``ast``; the cross-file lock graph is prebuilt by
:func:`build_lock_graph`, then every rule runs per-file through the
standard core machinery (suppressions, JSON, baselines all shared).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from lux_tpu.analysis.core import (FileContext, Finding, LintReport, Rule,
                                   iter_python_files, run_paths)
from lux_tpu.analysis.rules import _dotted

_GUARDED_BY_RE = re.compile(r"#\s*luxlint:\s*guarded-by=([A-Za-z_]\w*)")
_PUBLISH_RE = re.compile(r"#\s*luxlint:\s*publish=([A-Za-z_]\w*)")

# Constructors whose instances are synchronization/metric primitives —
# safe to touch from any thread, so LUX301 never treats them as
# unguarded shared data.
_SYNC_TYPES = {
    "Event", "Lock", "RLock", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "Thread", "local",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "make_lock", "WatchedLock",
    "counter", "gauge", "histogram", "Counter", "Gauge", "Histogram",
}

# Callees that consume a method reference and run it on another thread
# (the "registered as a worker" half of thread-entry detection).
_WORKER_CALLEE_RE = re.compile(r"batcher|worker|add_context|add_sink",
                               re.IGNORECASE)

# Container mutators that count as writes for shared-state inference.
_MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "remove", "discard", "clear", "pop", "popleft", "popitem",
    "setdefault",
}

# Dotted-name tails that block the calling thread (LUX303). Deliberately
# curated: ``.get``/``.run`` alone are too generic, so queue gets are
# matched by receiver name and engine execution by warmup/compile.
_BLOCKING_TAILS = {
    "hard_sync", "block_until_ready", "device_get", "urlopen", "sleep",
    "serve_forever", "recv", "accept", "sendall", "warmup", "compile",
}
# Unbounded waits: flagged only when called with no timeout.
_TIMEOUT_WAITS = {"join", "result", "wait"}
_QUEUEISH_RE = re.compile(r"(^_?q$)|queue", re.IGNORECASE)


def _final_ident(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lock_expr(node: ast.AST) -> bool:
    """True for with-items that acquire a lock: the final identifier of
    the (non-call) expression contains 'lock'."""
    name = _final_ident(node)
    return name is not None and "lock" in name.lower()


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or any(
        kw.arg == "timeout" for kw in call.keywords)


def _line_annotation(ctx: FileContext, lineno: int,
                     pattern: re.Pattern) -> Optional[str]:
    if 1 <= lineno <= len(ctx.lines):
        m = pattern.search(ctx.lines[lineno - 1])
        if m:
            return m.group(1)
    return None


@dataclasses.dataclass
class _Access:
    attr: str
    node: ast.AST
    method: str
    is_write: bool
    guards: Tuple[str, ...]        # final idents of locks held (syntactic)
    annotated: Optional[str]       # per-line guarded-by assertion


class _ClassAnalysis:
    """Everything LUX301/LUX305 need about one class."""

    def __init__(self, node: ast.ClassDef, ctx: FileContext):
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {
            m.name: m for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.guarded_by: Dict[str, str] = {}
        self.publish: Dict[str, str] = {}
        self.exempt: Set[str] = set()
        self.entries: Set[str] = set()
        self.accesses: Dict[str, List[_Access]] = {}   # method -> accesses
        self._nested_targets: List[ast.AST] = []
        self._scan_declarations(ctx)
        self._scan_entries()
        for name, fn in self.methods.items():
            if name == "__init__":
                continue
            out: List[_Access] = []
            _collect_accesses(fn, name, ctx, self.methods, out)
            self.accesses[name] = out

    # -- declarations (from __init__) -------------------------------------

    def _scan_declarations(self, ctx: FileContext) -> None:
        init = self.methods.get("__init__")
        if init is None:
            return
        for stmt in ast.walk(init):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            tgt = stmt.targets[0]
            if not _is_self_attr(tgt):
                continue
            attr = tgt.attr
            lock = _line_annotation(ctx, stmt.lineno, _GUARDED_BY_RE)
            if lock:
                self.guarded_by[attr] = lock
            lock = _line_annotation(ctx, stmt.lineno, _PUBLISH_RE)
            if lock:
                self.publish[attr] = lock
            if isinstance(stmt.value, ast.Call):
                ctor = _final_ident(stmt.value.func)
                if ctor in _SYNC_TYPES:
                    self.exempt.add(attr)

    # -- thread-entry detection -------------------------------------------

    def _scan_entries(self) -> None:
        for call in ast.walk(self.node):
            if not isinstance(call, ast.Call):
                continue
            callee = _dotted(call.func) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail == "Thread":
                for kw in call.keywords:
                    if kw.arg != "target":
                        continue
                    if _is_self_attr(kw.value):
                        self.entries.add(kw.value.attr)
                    elif isinstance(kw.value, ast.Name):
                        nested = self._find_nested_def(kw.value.id)
                        if nested is not None:
                            self._nested_targets.append(nested)
            elif _WORKER_CALLEE_RE.search(tail):
                for arg in list(call.args) + [k.value for k in call.keywords]:
                    if _is_self_attr(arg) and arg.attr in self.methods:
                        self.entries.add(arg.attr)

    def _find_nested_def(self, name: str) -> Optional[ast.AST]:
        for n in ast.walk(self.node):
            if isinstance(n, ast.FunctionDef) and n.name == name \
                    and name not in self.methods:
                return n
        return None

    # -- reachability ------------------------------------------------------

    def thread_methods(self) -> Set[str]:
        """Methods reachable from any thread entry via self.m() calls."""
        seeds = set(self.entries)
        for nested in self._nested_targets:
            seeds |= _self_calls(nested) & set(self.methods)
        frontier = list(seeds & set(self.methods) | (seeds & self.entries))
        reached: Set[str] = set()
        while frontier:
            m = frontier.pop()
            if m in reached or m not in self.methods:
                continue
            reached.add(m)
            frontier.extend(_self_calls(self.methods[m]))
        return reached

    def is_concurrent(self) -> bool:
        return bool(self.entries or self._nested_targets)


def _self_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == "self"):
            out.add(n.func.attr)
    return out


def _collect_accesses(fn: ast.AST, method: str, ctx: FileContext,
                      methods: Dict[str, ast.AST],
                      out: List[_Access]) -> None:
    """Record every self-attribute data access in ``fn`` with the lock
    guards syntactically active at that point. Method references
    (``self.warmup(...)``, property loads of defined methods) are code,
    not data, and are skipped. Nested defs/lambdas are walked with the
    same method attribution (closures run where the method sends them)."""
    skip: Set[int] = set()

    def record(attr_node: ast.Attribute, is_write: bool,
               guards: Tuple[str, ...]) -> None:
        if attr_node.attr in methods:
            return
        out.append(_Access(
            attr=attr_node.attr, node=attr_node, method=method,
            is_write=is_write, guards=guards,
            annotated=_line_annotation(ctx, attr_node.lineno,
                                       _GUARDED_BY_RE),
        ))

    def visit(node: ast.AST, guards: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            g = guards
            for item in node.items:
                if _is_lock_expr(item.context_expr):
                    g = g + (_final_ident(item.context_expr),)
                visit(item.context_expr, guards)
            for stmt in node.body:
                visit(stmt, g)
            return
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                    and _is_self_attr(f.value)):
                record(f.value, True, guards)
                skip.add(id(f.value))
            for child in ast.iter_child_nodes(node):
                visit(child, guards)
            return
        if isinstance(node, ast.Attribute) and _is_self_attr(node) \
                and id(node) not in skip:
            record(node, isinstance(node.ctx, (ast.Store, ast.Del)), guards)
        if isinstance(node, ast.Subscript) and _is_self_attr(node.value) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            record(node.value, True, guards)
            skip.add(id(node.value))
        for child in ast.iter_child_nodes(node):
            visit(child, guards)

    for stmt in ast.iter_child_nodes(fn):
        visit(stmt, ())


def _guard_ok(acc: _Access, required: Optional[str]) -> bool:
    if required is not None:
        return required in acc.guards or acc.annotated == required
    return bool(acc.guards) or acc.annotated is not None


class SharedStateRule(Rule):
    id = "LUX301"
    title = "thread-shared attribute accessed without its lock"
    doc = ("attributes touched from both a thread-entry path "
           "(Thread(target=...), batcher/worker callbacks) and the "
           "caller side must be accessed under `with <lock>:` or carry "
           "a same-line `# luxlint: guarded-by=<lock>` assertion")

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(_ClassAnalysis(node, ctx), ctx)

    def _check_class(self, ca: _ClassAnalysis,
                     ctx: FileContext) -> Iterable[Finding]:
        if not ca.is_concurrent():
            return
        tside = ca.thread_methods()
        t_w: Set[str] = set()
        t_any: Set[str] = set()
        o_w: Set[str] = set()
        o_any: Set[str] = set()
        for m, accs in ca.accesses.items():
            for a in accs:
                (t_any if m in tside else o_any).add(a.attr)
                if a.is_write:
                    (t_w if m in tside else o_w).add(a.attr)
        shared = ((t_w & o_any) | (o_w & t_any)) - ca.exempt \
            - set(ca.publish)
        if not shared:
            return
        entries = ",".join(sorted(ca.entries)) or "<nested thread target>"
        for m, accs in ca.accesses.items():
            for a in accs:
                if a.attr not in shared:
                    continue
                required = ca.guarded_by.get(a.attr)
                if _guard_ok(a, required):
                    continue
                want = f"self.{required}" if required else "self.<lock>"
                yield self.finding(
                    ctx, a.node,
                    f"`self.{a.attr}` is shared with the thread-entry "
                    f"path ({ca.name}.{entries}) but "
                    f"{'written' if a.is_write else 'read'} in "
                    f"`{m}` without holding a lock; wrap in "
                    f"`with {want}:` or annotate the line with "
                    f"`# luxlint: guarded-by=<lock>`",
                )


class LockOrderRule(Rule):
    id = "LUX302"
    title = "lock-order inversion in the static acquisition graph"
    doc = ("nested `with <lock>` blocks define acquisition edges across "
           "the whole package; a cycle (A before B here, B before A "
           "there) deadlocks under the right interleave")

    def __init__(self, bad_edges: Optional[Dict[str, list]] = None):
        # abs path -> [(lineno, col, held, acquired, cycle), ...]
        self.bad_edges = bad_edges or {}

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        for (lineno, col, held, acquired, cycle) in self.bad_edges.get(
                os.path.abspath(ctx.path), ()):
            yield Finding(
                self.id, ctx.path, lineno, col,
                f"acquiring `{acquired}` while holding `{held}` inverts "
                f"the lock order observed elsewhere "
                f"(cycle: {' -> '.join(cycle)}); pick one global order",
            )


class BlockingUnderLockRule(Rule):
    id = "LUX303"
    title = "blocking call while holding a lock"
    doc = ("no unbounded join/result/wait, queue get without timeout, "
           "sleep, socket/HTTP I/O, device sync, or engine "
           "warmup/compile inside a `with <lock>:` region — every other "
           "thread needing the lock stalls behind it")

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            yield from self._check_fn(fn, ctx)

    def _check_fn(self, fn: ast.AST, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []

        def visit(node: ast.AST, locks: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return   # deferred execution: not under this lock
            if isinstance(node, ast.With):
                g = locks
                for item in node.items:
                    if _is_lock_expr(item.context_expr):
                        g = g + (_final_ident(item.context_expr),)
                    visit(item.context_expr, locks)
                for stmt in node.body:
                    visit(stmt, g)
                return
            if isinstance(node, ast.Call) and locks:
                findings.extend(self._check_call(node, locks, ctx))
            for child in ast.iter_child_nodes(node):
                visit(child, locks)

        for stmt in ast.iter_child_nodes(fn):
            visit(stmt, ())
        return findings

    def _check_call(self, call: ast.Call, locks: Tuple[str, ...],
                    ctx: FileContext) -> Iterable[Finding]:
        held = ",".join(locks)
        dotted = _dotted(call.func) or ""
        tail = dotted.rsplit(".", 1)[-1]
        if tail in _BLOCKING_TAILS:
            yield self.finding(
                ctx, call,
                f"blocking call `{dotted}` while holding `{held}` — move "
                f"the slow work outside the guarded region",
            )
            return
        if not isinstance(call.func, ast.Attribute):
            return
        if tail in _TIMEOUT_WAITS and not _has_timeout(call):
            yield self.finding(
                ctx, call,
                f"unbounded `.{tail}()` while holding `{held}` — pass a "
                f"timeout or release the lock first",
            )
            return
        recv = _final_ident(call.func.value)
        if tail == "get" and recv and _QUEUEISH_RE.search(recv) \
                and not _has_timeout(call):
            yield self.finding(
                ctx, call,
                f"queue `.get()` with no timeout while holding `{held}` "
                f"— a quiet queue parks the lock forever",
            )


class UnjoinedThreadRule(Rule):
    id = "LUX304"
    title = "thread spawned without a join/drain path"
    doc = ("every threading.Thread must be joined in this file, returned "
           "to the caller, or stored in a container the file drains "
           "(SnapshotStore.drain_compactions is the compliant shape)")

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        parents: Dict[int, ast.AST] = {}
        joins_on: Set[str] = set()        # names X with X.join(...)
        attr_joins: Set[str] = set()      # attrs A with self.A.join(...)
        any_join = False
        returned: Set[str] = set()
        spawns: List[Tuple[ast.Call, ast.AST]] = []
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                any_join = True
                base = node.func.value
                if isinstance(base, ast.Name):
                    joins_on.add(base.id)
                elif _is_self_attr(base):
                    attr_joins.add(base.attr)
            if isinstance(node, ast.Return) and node.value is not None:
                returned |= {n.id for n in ast.walk(node.value)
                             if isinstance(n, ast.Name)}
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func) or ""
                if dotted.rsplit(".", 1)[-1] == "Thread":
                    spawns.append(node)
        for call in spawns:
            if not self._compliant(call, parents, joins_on, attr_joins,
                                   returned, any_join):
                yield self.finding(
                    ctx, call,
                    "thread spawned with no join/drain path in this file "
                    "— join it, return it to the caller, or register it "
                    "in a container a drain method joins",
                )

    @staticmethod
    def _compliant(call, parents, joins_on, attr_joins, returned,
                   any_join) -> bool:
        node: ast.AST = call
        in_container = False
        while True:
            parent = parents.get(id(node))
            if parent is None:
                return False
            if isinstance(parent, ast.Assign):
                for tgt in parent.targets:
                    if isinstance(tgt, ast.Name):
                        if in_container:
                            return any_join
                        return (tgt.id in joins_on or tgt.id in returned
                                or (tgt.id in _appended_somewhere(parents)
                                    and any_join))
                    if _is_self_attr(tgt):
                        return tgt.attr in attr_joins or any_join
                return False
            if isinstance(parent, (ast.ListComp, ast.SetComp, ast.List,
                                   ast.Tuple, ast.Dict, ast.GeneratorExp)):
                in_container = True
            elif isinstance(parent, ast.Call) and parent.func is not node:
                # Thread(...) passed straight into another call: the
                # consumer owns it (e.g. a drain list's append).
                return any_join
            elif isinstance(parent, (ast.Expr, ast.stmt)) \
                    and not isinstance(parent, ast.Assign):
                # bare `Thread(...).start()` chain or expression statement
                if isinstance(node, ast.Call) and node is not call:
                    return False
                return False
            node = parent


def _appended_somewhere(parents: Dict[int, ast.AST]) -> Set[str]:
    out: Set[str] = set()
    for node in parents.values():
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append":
            for a in node.args:
                if isinstance(a, ast.Name):
                    out.add(a.id)
    return out


class PublishRule(Rule):
    id = "LUX305"
    title = "atomic-publish pointer written/read outside its discipline"
    doc = ("attrs declared `# luxlint: publish=<lock>` are hot-swap flip "
           "pointers: at most one write per method, only under the "
           "declared lock (or a same-line guarded-by assertion), and at "
           "most one raw read per method — read the pointer into a "
           "local so one request can never observe two versions")

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                ca = _ClassAnalysis(node, ctx)
                if ca.publish:
                    yield from self._check_class(ca, ctx)

    def _check_class(self, ca: _ClassAnalysis,
                     ctx: FileContext) -> Iterable[Finding]:
        for m, accs in ca.accesses.items():
            writes: Dict[str, int] = {}
            raw_reads: Dict[str, int] = {}
            for a in accs:
                lock = ca.publish.get(a.attr)
                if lock is None:
                    continue
                if a.is_write:
                    writes[a.attr] = writes.get(a.attr, 0) + 1
                    if writes[a.attr] > 1:
                        yield self.finding(
                            ctx, a.node,
                            f"`self.{a.attr}` published more than once in "
                            f"`{m}` — a swap must flip the pointer "
                            f"exactly once",
                        )
                    elif not _guard_ok(a, lock):
                        yield self.finding(
                            ctx, a.node,
                            f"unsynchronized publish: `self.{a.attr}` "
                            f"written in `{m}` outside `with "
                            f"self.{lock}:` (declare the holder with "
                            f"`# luxlint: guarded-by={lock}` if a caller "
                            f"owns the lock)",
                        )
                elif not _guard_ok(a, lock):
                    raw_reads[a.attr] = raw_reads.get(a.attr, 0) + 1
                    if raw_reads[a.attr] > 1:
                        yield self.finding(
                            ctx, a.node,
                            f"torn read: `self.{a.attr}` read more than "
                            f"once in `{m}` — a swap between reads mixes "
                            f"two versions; read it once into a local",
                        )


# -- cross-file lock-order graph -------------------------------------------


def _lock_id(node: ast.AST, class_name: Optional[str],
             file_base: str) -> Optional[str]:
    d = _dotted(node)
    if d is None:
        return None
    if d.startswith("self."):
        return f"{class_name or file_base}.{d[5:]}"
    if "." in d:
        return d
    # Bare module-level name: qualify as <module>.<name> so a dotted
    # `m.lock` acquisition in another file lands on the same graph node.
    return f"{file_base}.{d}"


def _collect_edges(tree: ast.Module, path: str,
                   edges: List[tuple]) -> None:
    file_base = os.path.splitext(os.path.basename(path))[0]

    def walk_fn(fn: ast.AST, class_name: Optional[str]) -> None:
        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, ast.With):
                h = held
                for item in node.items:
                    if _is_lock_expr(item.context_expr):
                        lock = _lock_id(item.context_expr, class_name,
                                        file_base)
                        if lock is not None:
                            for prior in h:
                                if prior != lock:
                                    edges.append((
                                        prior, lock, path,
                                        item.context_expr.lineno,
                                        item.context_expr.col_offset,
                                    ))
                            h = h + (lock,)
                for stmt in node.body:
                    visit(stmt, h)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in ast.iter_child_nodes(fn):
            visit(stmt, ())

    def scan(node: ast.AST, class_name: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                scan(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_fn(child, class_name)
                scan(child, class_name)
            else:
                scan(child, class_name)

    scan(tree, None)


def build_lock_graph(paths: Sequence[str]) -> Dict[str, list]:
    """Edges from syntactically nested lock acquisitions across all
    ``paths``; returns {abs path: [(line, col, held, acquired, cycle)]}
    for every edge that participates in a cycle."""
    edges: List[tuple] = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            continue   # the per-file pass reports read/syntax errors
        _collect_edges(tree, path, edges)
    adj: Dict[str, Set[str]] = {}
    for a, b, *_ in edges:
        adj.setdefault(a, set()).add(b)
    bad: Dict[str, list] = {}
    for a, b, path, lineno, col in edges:
        cycle = _find_path(adj, b, a)
        if cycle is not None:
            bad.setdefault(os.path.abspath(path), []).append(
                (lineno, col, a, b, [a] + cycle))
    for v in bad.values():
        v.sort()
    return bad


def _find_path(adj: Dict[str, Set[str]], src: str,
               dst: str) -> Optional[List[str]]:
    seen = {src}
    frontier: List[List[str]] = [[src]]
    while frontier:
        p = frontier.pop()
        for nxt in adj.get(p[-1], ()):
            if nxt == dst:
                return p + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(p + [nxt])
    return None


# -- tier entry points ------------------------------------------------------


def all_thread_rules(graph_paths: Optional[Sequence[str]] = None
                     ) -> List[Rule]:
    """The LUX30x rule set. ``graph_paths`` feeds the cross-file lock
    graph for LUX302 (default: no prebuilt graph — use run_threads)."""
    bad = build_lock_graph(graph_paths) if graph_paths else {}
    return [SharedStateRule(), LockOrderRule(bad),
            BlockingUnderLockRule(), UnjoinedThreadRule(), PublishRule()]


def run_threads(paths: Sequence[str],
                select: Optional[Set[str]] = None,
                graph_paths: Optional[Sequence[str]] = None) -> LintReport:
    """Run the concurrency tier over ``paths``.

    The LUX302 graph is built over ``graph_paths`` (default: the lint
    paths themselves) so `--changed` runs can lint a subset of files
    against the whole tree's acquisition order.
    """
    rules = all_thread_rules(graph_paths if graph_paths is not None
                             else paths)
    if select:
        rules = [r for r in rules if r.id in select]
    report = run_paths(paths, rules)
    report.schema = "luxlint-threads.v1"
    return report
