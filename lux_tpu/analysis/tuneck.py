"""tuneck: static verifier for persisted ``tuneconf.v1`` artifacts.

A tune artifact is the configuration a serving process will trust
blindly at warmup — a corrupted, hand-edited, or stale one must be
rejected offline, from the artifact alone, before anything builds
engines under it. Like planck for grouped-tail plans:

- LUX501 structure: schema/id/key/key_string/score/score_table/tuner
  shapes match the tuneconf.v1 contract.
- LUX502 knob domains: every configured flag is declared in the
  registry AND tuner-managed (space.TUNER_MANAGED), and its value
  parses inside the flag's legal domain — an artifact must not be able
  to smuggle an arbitrary env var into a serving process.
- LUX503 selection consistency: the winner is the argmin of the final
  rung's score table (score, then candidate index — the search's own
  tie-break), scores are finite and non-negative, the default
  candidate (index 0) was probed, and ``probe_ledger_ids`` matches the
  score table's recorded probe record ids exactly.
- LUX504 staleness: ``created_at`` is sane and within
  ``LUX_TUNE_MAX_AGE_S``; the key's graph fingerprint, mesh shape, and
  graph_meta are well-formed — a config tuned for some *other* graph
  must not pass as evidence for this one.

stdlib + the flag registry only (no jax, no numpy): ``luxlint --tune``
must verify a directory of artifacts from a cold interpreter in
milliseconds. ``line`` in findings is the 1-based score-table row
(0 = an artifact-level finding).
"""

from __future__ import annotations

import math
import os
import re
import time
from typing import Iterable, List, Optional, Sequence

from lux_tpu.analysis.core import FileResult, Finding, LintReport
from lux_tpu.tune import artifact as tart
from lux_tpu.tune.space import TUNER_MANAGED
from lux_tpu.utils import flags

TUNE_SCHEMA = "luxlint-tune.v1"

_ID_RE = re.compile(r"^tune-[0-9a-f]{12}$")
_MESH_RE = re.compile(r"^\d+(x\d+)*$")
_BOOLISH = frozenset({"", "0", "1", "true", "false", "yes", "no",
                      "on", "off"})

__all__ = ["TUNE_SCHEMA", "TuneRule", "all_tune_rules", "verify_artifact",
           "verify_artifact_paths"]


class TuneRule:
    id = "LUX500"
    title = "base tune rule"
    doc = ""

    def check(self, art: dict, path: str) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, path: str, row: int, message: str) -> Finding:
        return Finding(self.id, path, row, 0, message)


class TuneStructure(TuneRule):
    id = "LUX501"
    title = "tune-structure"
    doc = ("schema, content-derived id, complete key + consistent "
           "key_string, and score-table row shapes match the "
           "tuneconf.v1 contract")

    def check(self, art: dict, path: str) -> Iterable[Finding]:
        if art.get("schema") != tart.SCHEMA:
            yield self.finding(
                path, 0,
                f"schema {art.get('schema')!r}, expected {tart.SCHEMA!r}")
        if not _ID_RE.match(str(art.get("id", ""))):
            yield self.finding(
                path, 0, f"id {art.get('id')!r} is not tune-<12 hex>")
        key = art.get("key")
        if not isinstance(key, dict) \
                or sorted(key) != sorted(tart.KEY_FIELDS):
            yield self.finding(
                path, 0,
                f"key fields {sorted(key) if isinstance(key, dict) else key!r}"
                f" != {sorted(tart.KEY_FIELDS)}")
            return
        if art.get("key_string") != tart.key_string(key):
            yield self.finding(
                path, 0,
                f"key_string {art.get('key_string')!r} does not match key "
                f"{tart.key_string(key)!r}")
        if not isinstance(art.get("config"), dict):
            yield self.finding(path, 0, "config is not an object")
        if not isinstance(art.get("score"), (int, float)):
            yield self.finding(path, 0, "score is not a number")
        tuner = art.get("tuner")
        if not isinstance(tuner, dict) or not {"seed", "rungs",
                                               "eta"} <= set(tuner):
            yield self.finding(
                path, 0, "tuner block missing seed/rungs/eta provenance")
        table = art.get("score_table")
        if not isinstance(table, list) or not table:
            yield self.finding(path, 0, "score_table missing or empty")
            return
        for i, row in enumerate(table):
            missing = {"config", "score", "iters", "rung",
                       "candidate_index"} - set(row)
            if missing:
                yield self.finding(
                    path, i + 1,
                    f"score_table row missing {sorted(missing)}")


class TuneKnobDomains(TuneRule):
    id = "LUX502"
    title = "tune-knob-domains"
    doc = ("every configured flag (winner and probed rows) is declared, "
           "tuner-managed, and valued inside its legal domain")

    def _check_config(self, path: str, row: int, config) -> Iterable[Finding]:
        if not isinstance(config, dict):
            yield self.finding(path, row, "config is not an object")
            return
        for name, value in sorted(config.items()):
            if not flags.declared(name):
                yield self.finding(
                    path, row, f"{name} is not a declared flag")
                continue
            if name not in TUNER_MANAGED:
                yield self.finding(
                    path, row,
                    f"{name} is declared but not tuner-managed "
                    "(space.TUNER_MANAGED)")
                continue
            v = str(value)
            if name == "LUX_EXCHANGE" \
                    and v not in ("full", "compact", "frontier"):
                yield self.finding(
                    path, row,
                    f"LUX_EXCHANGE={v!r} not in full/compact/frontier")
            elif name in ("LUX_EXCHANGE_FRONTIER_FRAC",
                          "LUX_GAS_DENSITY_HI", "LUX_GAS_DENSITY_LO"):
                try:
                    x = float(v)
                except ValueError:
                    yield self.finding(path, row, f"{name}={v!r} not a float")
                    continue
                if not (0.0 < x <= 1.0):
                    yield self.finding(
                        path, row, f"{name}={x} outside (0, 1]")
            elif name == "LUX_GROUPED_TAIL" \
                    and v.strip().lower() not in _BOOLISH:
                yield self.finding(
                    path, row, f"LUX_GROUPED_TAIL={v!r} not boolean")
        hi = config.get("LUX_GAS_DENSITY_HI")
        lo = config.get("LUX_GAS_DENSITY_LO")
        if hi is not None and lo is not None:
            try:
                if float(lo) >= float(hi):
                    yield self.finding(
                        path, row,
                        f"hysteresis inverted: lo {lo} >= hi {hi} "
                        "(would flap every iteration)")
            except ValueError:
                pass

    def check(self, art: dict, path: str) -> Iterable[Finding]:
        yield from self._check_config(path, 0, art.get("config"))
        for i, tbl_row in enumerate(art.get("score_table") or []):
            if isinstance(tbl_row, dict):
                yield from self._check_config(
                    path, i + 1, tbl_row.get("config"))


class TuneSelection(TuneRule):
    id = "LUX503"
    title = "tune-selection"
    doc = ("winner = argmin(score, candidate_index) of the final rung; "
           "scores finite; default candidate probed; probe_ledger_ids "
           "exactly the score table's record ids")

    def check(self, art: dict, path: str) -> Iterable[Finding]:
        table = [r for r in (art.get("score_table") or [])
                 if isinstance(r, dict)
                 and isinstance(r.get("score"), (int, float))
                 and "rung" in r and "candidate_index" in r]
        if not table:
            return   # LUX501 already rejects the shape
        for i, row in enumerate(art.get("score_table") or []):
            s = row.get("score") if isinstance(row, dict) else None
            if not isinstance(s, (int, float)) or not math.isfinite(s) \
                    or s < 0:
                yield self.finding(
                    path, i + 1, f"score {s!r} not a finite non-negative "
                    "number")
        if not any(r["candidate_index"] == 0 for r in table):
            yield self.finding(
                path, 0,
                "default candidate (index 0) never probed: the artifact "
                "carries no tuned-vs-default delta")
        last = max(r["rung"] for r in table)
        final = [r for r in table if r["rung"] == last]
        best = min(final, key=lambda r: (r["score"], r["candidate_index"]))
        if best.get("config") != art.get("config"):
            yield self.finding(
                path, 0,
                f"winner config {art.get('config')!r} is not the final "
                f"rung's argmin {best.get('config')!r}")
        if isinstance(art.get("score"), (int, float)) \
                and art["score"] != best["score"]:
            yield self.finding(
                path, 0,
                f"artifact score {art['score']!r} != winning probe score "
                f"{best['score']!r}")
        want_ids = [r.get("probe_record_id")
                    for r in (art.get("score_table") or [])
                    if isinstance(r, dict) and r.get("probe_record_id")]
        got_ids = art.get("probe_ledger_ids")
        if got_ids != want_ids:
            yield self.finding(
                path, 0,
                f"probe_ledger_ids ({len(got_ids or [])}) != score "
                f"table's recorded probe ids ({len(want_ids)})")
        if want_ids and len(set(want_ids)) != len(want_ids):
            yield self.finding(path, 0, "duplicate probe record ids")


class TuneStaleness(TuneRule):
    id = "LUX504"
    title = "tune-staleness"
    doc = ("created_at sane and within LUX_TUNE_MAX_AGE_S; fingerprint/"
           "mesh/graph_meta well-formed — a config tuned for another "
           "graph or epoch is not evidence for this one")

    def check(self, art: dict, path: str) -> Iterable[Finding]:
        now = time.time()
        at = art.get("created_at")
        if not isinstance(at, (int, float)) or not math.isfinite(at):
            yield self.finding(path, 0, f"created_at {at!r} not a timestamp")
        else:
            if at > now + 300.0:
                yield self.finding(
                    path, 0, f"created_at {at} is in the future")
            max_age = flags.get_float("LUX_TUNE_MAX_AGE_S")
            if max_age > 0 and now - at > max_age:
                yield self.finding(
                    path, 0,
                    f"artifact is {now - at:.0f}s old, past the "
                    f"LUX_TUNE_MAX_AGE_S={max_age:.0f}s staleness bound: "
                    "re-tune against the current graph")
        key = art.get("key")
        if isinstance(key, dict):
            fp = str(key.get("graph_fingerprint", ""))
            if not fp or fp == "?" or " " in fp:
                yield self.finding(
                    path, 0, f"graph_fingerprint {fp!r} is not a "
                    "checkpoint fingerprint")
            mesh = str(key.get("mesh_shape", ""))
            if not _MESH_RE.match(mesh):
                yield self.finding(
                    path, 0, f"mesh_shape {mesh!r} is not N or PxQ")
            for field in ("program", "engine_kind", "device_kind"):
                if not str(key.get(field, "")):
                    yield self.finding(path, 0, f"key.{field} is empty")
        meta = art.get("graph_meta")
        if not isinstance(meta, dict) \
                or not all(isinstance(meta.get(k), int) and meta[k] > 0
                           for k in ("nv", "ne")):
            yield self.finding(
                path, 0, f"graph_meta {meta!r} lacks positive nv/ne")


def all_tune_rules() -> List[TuneRule]:
    return [TuneStructure(), TuneKnobDomains(), TuneSelection(),
            TuneStaleness()]


def verify_artifact(art: dict, path: str = "<tuneconf>",
                    rules: Optional[Sequence[TuneRule]] = None
                    ) -> FileResult:
    """Run the LUX5xx rules over one loaded artifact dict."""
    if rules is None:
        rules = all_tune_rules()
    findings: List[Finding] = []
    errors: List[str] = []
    for rule in rules:
        try:
            findings.extend(rule.check(art, path))
        except Exception as e:   # a malformed artifact must report, not crash
            errors.append(f"{path}: {rule.id} crashed: {e!r}")
    findings.sort(key=lambda f: (f.line, f.rule))
    return FileResult(path, findings, [], error="; ".join(errors) or None)


def verify_artifact_paths(paths: Sequence[str],
                          rules: Optional[Sequence[TuneRule]] = None
                          ) -> LintReport:
    """Verify tuneconf.v1 files and/or directories of them."""
    t0 = time.perf_counter()
    files: List[str] = []
    results: List[FileResult] = []
    for p in paths:
        if os.path.isdir(p):
            found = tart.list_artifacts(p)
            if not found:
                results.append(FileResult(
                    p, [], [],
                    error=f"{p}: no tuneconf-*.json artifacts"))
            files.extend(found)
        else:
            files.append(p)
    for path in files:
        try:
            art = tart.load_path(path)
        except Exception as e:
            results.append(FileResult(
                path, [], [], error=f"{path}: unloadable artifact: {e!r}"))
            continue
        results.append(verify_artifact(art, path, rules))
    return LintReport(results, time.perf_counter() - t0,
                      schema=TUNE_SCHEMA)
