from lux_tpu.engine.program import PullProgram, EdgeCtx, VertexCtx
from lux_tpu.engine.pull import PullExecutor

_LAZY = {
    "TiledPullExecutor": "lux_tpu.engine.tiled",
    "ShardedPullExecutor": "lux_tpu.engine.pull_sharded",
    "ShardedTiledExecutor": "lux_tpu.engine.tiled_sharded",
}

__all__ = ["PullProgram", "EdgeCtx", "VertexCtx", "PullExecutor", *_LAZY]


def __getattr__(name):
    # Heavier executors are imported lazily to keep `import lux_tpu` light.
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(name)


def __dir__():
    return sorted(__all__)
