from lux_tpu.engine.program import PullProgram, EdgeCtx, VertexCtx
from lux_tpu.engine.pull import PullExecutor

__all__ = ["PullProgram", "EdgeCtx", "VertexCtx", "PullExecutor"]
