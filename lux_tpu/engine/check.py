"""Result checkers (`-check`): per-edge fixpoint invariants.

The reference validates push results with a GPU kernel counting edges that
violate the app's invariant, printing ``[PASS]``/``[FAIL]`` plus the
mistake count (sssp/sssp_gpu.cu:773-843, components/components_gpu.cu:
767-837). Same here, as one jitted reduction over all edges.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.graph.graph import Graph


def count_violations(graph: Graph, values: np.ndarray, program) -> int:
    """Number of edges violating ``program.edge_invariant``."""

    @jax.jit
    def _count(vals, col_src, seg_ids, weights):
        ok = program.edge_invariant(vals[col_src], vals[seg_ids], weights)
        # int32 count: fine unless >2^31 of the edges violate, by which
        # point the verdict is unambiguous anyway (x64 is off by default).
        return (~ok).sum(dtype=jnp.int32)

    w = None if graph.weights is None else jnp.asarray(graph.weights)
    return int(
        _count(
            jnp.asarray(values),
            jnp.asarray(graph.col_src.astype(np.int32)),
            jnp.asarray(graph.col_dst),
            w,
        )
    )


def check(graph: Graph, values: np.ndarray, program, verbose: bool = True):
    """Print the reference's check verdict; returns True on pass."""
    mistakes = count_violations(graph, values, program)
    if mistakes == 0:
        if verbose:
            print("[PASS] Check task passed!")
        return True
    if verbose:
        print(f"[FAIL] Check task failed (mistakes = {mistakes})")
    return False
