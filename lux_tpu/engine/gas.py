"""Gather-apply-scatter (GAS) subsystem: one program abstraction, an
adaptive direction-optimizing executor.

Lux's headline engine capability is per-iteration push<->pull direction
switching over an active frontier (frontier > nv/16 => pull,
sssp_gpu.cu:414); until now this repo kept separate pull and push
engines and never switched mid-run (ROADMAP item 3). Gunrock
(PAPERS.md, arXiv:1501.05387) shows that a small operator set plus
direction optimization yields a whole family of graph programs on one
engine — this module is that layer:

- :class:`GasProgram` declares the three pieces **once**:

      msg_e   = gather(val[src_e], w_e)        # per edge, per direction
      acc_v   = combine(msg_e for e into v)    # min | max | sum
      new_v   = apply(old_v, acc_v)            # per vertex
      front'_v = scatter(old_v, new_v)         # activation for the next
                                               # iteration

  The same ``gather`` runs in both directions, which is what makes
  switching safe: pull masks non-frontier messages to the combiner
  identity and segment-reduces over all CSC in-edges; push expands only
  the frontier's CSR out-edges into an identity-filled accumulator.
  Both materialize the *same* dense ``acc`` — elementwise min/max and
  integer sums are exactly associative/commutative, so ``apply`` sees
  bit-identical inputs whichever branch ran and results are **bitwise
  equal** across pull, push, and adaptive schedules. (A float32 *sum*
  combiner would reassociate; no frontier program uses one.)

- :class:`AdaptiveExecutor` picks the direction per iteration from
  frontier density with hysteresis (``LUX_GAS_DENSITY_HI`` /
  ``LUX_GAS_DENSITY_LO``; ``LUX_GAS=pull|push|adaptive`` pins it). The
  decision and both branches live inside one ``lax.cond`` under the
  chunked ``lax.while_loop`` dispatch, so a mid-run switch costs zero
  recompiles and zero extra host round-trips — the frontier count the
  decision needs is the same scalar the halt check already computes.

The legacy program models plug in through adapters (see
engine/program.py ``as_gas``): a :class:`~lux_tpu.engine.push.PushProgram`
maps ``relax`` onto ``gather`` and keeps its min/max merge; a
:class:`~lux_tpu.engine.program.PullProgram` runs as a frontier-less
fixed-iteration dense pull (``frontier = False`` below).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import EdgeCtx, PullProgram, VertexCtx
from lux_tpu.engine.pull import hard_sync
from lux_tpu.engine.push import (
    PushProgram,
    _chunk_while,
    _queue_edge_slots,
    _sparse_budgets,
)
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import (
    NULL_RECORDER,
    consume_compile_seconds,
    engobs,
    note_compile_seconds,
    recorder_for,
)
from lux_tpu.ops.segment import identity_for, segment_reduce
from lux_tpu.utils import flags
from lux_tpu.utils.timing import Timer

GAS_MODES = ("pull", "push", "adaptive")


class GasProgram:
    """One vertex program, two executable directions.

    Frontier programs (``frontier = True``, the default) implement
    ``init_values`` / ``init_frontier`` / ``gather`` and inherit the
    combiner-merge ``apply`` and changed-bitmap ``scatter``; programs
    with non-merge update rules (k-core's decrement) override those.
    ``finalize_host`` derives extra host-side outputs (BFS parents,
    label-prop community ids) from the converged values in numpy — it
    runs after the device fixpoint, so it can never add a compile to a
    served query.

    Frontier-less programs (``frontier = False``; the PullProgram
    adapter) implement ``edge_contrib`` / ``apply_ctx`` instead and run
    a fixed number of dense pull iterations — direction optimization
    needs an activation signal, which they don't have.
    """

    name: str = "gas"
    combiner: str = "min"           # 'min' | 'max' | 'sum'
    value_dtype = jnp.uint32
    needs_weights: bool = False
    rooted: bool = False            # takes a per-query `start` root
    servable: bool = True           # exposed through serve/session.py
    frontier: bool = True           # False => fixed-iteration dense pull
    # Capability declarations machine-checked by ``luxlint --programs``
    # (analysis/gasck.py, LUX601-606): frontier_ok licenses the masked
    # pull / sentinel-padded frontier exchange (the identity must
    # annihilate and the push/pull traces must agree bitwise);
    # incremental_ok licenses the warm-started refresh
    # (engine/incremental.py — requires the monotone-merge proof plus a
    # host ``relax`` hook). Declaring either without the proof is a
    # LUX604/LUX606 finding, so these are claims, not configuration.
    frontier_ok: bool = True
    incremental_ok: bool = False

    # Optional algebra/direction overrides. ``combine``/``combine_identity``
    # state the combiner's scalar semantics when they are not the declared
    # built-in (the prover cross-checks them against ``combiner`` —
    # LUX602); ``gather_push`` specializes the push-direction edge
    # function, which is only sound if LUX603 proves the traces still
    # bitwise-equal. None means "use the default".
    combine = None           # combine(a, b) -> combined value
    combine_identity = None  # combine_identity(dtype) -> identity scalar
    gather_push = None       # gather_push(src_vals, weights) -> messages

    # -- frontier-program hooks ------------------------------------------

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def gather(self, src_vals: jnp.ndarray, weights) -> jnp.ndarray:
        """Per-edge message from an active source — the ONE edge
        function both directions run."""
        raise NotImplementedError

    def apply(self, old: jnp.ndarray, acc: jnp.ndarray) -> jnp.ndarray:
        """Combine the accumulated messages into the new value; the
        default is the combiner's monotone merge."""
        if self.combiner == "min":
            return jnp.minimum(old, acc)
        if self.combiner == "max":
            return jnp.maximum(old, acc)
        raise NotImplementedError(
            f"{self.name}: sum-combiner programs must override apply()"
        )

    def scatter(self, old: jnp.ndarray, new: jnp.ndarray) -> jnp.ndarray:
        """Next iteration's frontier (the adaptive changed bitmap)."""
        return new != old

    def finalize_host(self, graph: Graph, values: np.ndarray) -> dict:
        """Extra host-side outputs derived from converged values."""
        return {}

    def edge_invariant(self, src_vals, dst_vals, weights):
        """Per-edge fixpoint invariant for `-check` (engine/check.py)."""
        raise NotImplementedError

    # -- frontier-less hooks (PullProgram adapter) -----------------------

    def edge_contrib(self, edge: EdgeCtx) -> jnp.ndarray:
        raise NotImplementedError

    def apply_ctx(self, old, acc, ctx: VertexCtx):
        raise NotImplementedError


class GasState(NamedTuple):
    values: jnp.ndarray     # (nv,) or (nv, K)
    frontier: jnp.ndarray   # bool, same leading shape
    direction: jnp.ndarray  # int32 scalar: direction the PREVIOUS
    #                         iteration took (0 pull, 1 push) — the
    #                         hysteresis memory, carried on-device


# -- adapters -------------------------------------------------------------


class PushGasAdapter(GasProgram):
    """A PushProgram as a GasProgram: ``relax`` becomes ``gather``, the
    min/max merge and changed-bitmap activation are the defaults — the
    per-iteration math is bit-identical to PushExecutor's dense branch."""

    def __init__(self, inner: PushProgram):
        self.inner = inner
        self.name = inner.name
        self.combiner = inner.combiner
        self.value_dtype = inner.value_dtype
        self.needs_weights = inner.needs_weights
        self.rooted = getattr(inner, "rooted", False)
        self.frontier_ok = getattr(inner, "frontier_ok", True)
        self.incremental_ok = getattr(inner, "incremental_ok", False)

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        return self.inner.init_values(graph, **kw)

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        return self.inner.init_frontier(graph, **kw)

    def gather(self, src_vals, weights):
        return self.inner.relax(src_vals, weights)


class PullGasAdapter(GasProgram):
    """A PullProgram as a frontier-less GasProgram: dense pull only,
    fixed iteration count, ``edge_contrib``/``apply`` forwarded (with
    the VertexCtx the pull model's update rule needs)."""

    frontier = False
    servable = False     # pagerank/colfilter keep their pull serving path
    frontier_ok = False  # no frontier machinery => no annihilation claim

    def __init__(self, inner: PullProgram):
        self.inner = inner
        self.name = inner.name
        self.combiner = inner.combiner
        self.value_dtype = inner.value_dtype
        self.needs_weights = inner.needs_weights

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        return self.inner.init_values(graph)

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        return np.ones(graph.nv, dtype=bool)

    def edge_contrib(self, edge: EdgeCtx) -> jnp.ndarray:
        return self.inner.edge_contrib(edge)

    def apply_ctx(self, old, acc, ctx: VertexCtx):
        return self.inner.apply(old, acc, ctx)


def as_gas(program) -> GasProgram:
    """Normalize any registered program model to a GasProgram."""
    if isinstance(program, GasProgram):
        return program
    if isinstance(program, PushProgram):
        return PushGasAdapter(program)
    if isinstance(program, PullProgram):
        return PullGasAdapter(program)
    raise TypeError(
        f"cannot adapt {type(program).__name__} to a GasProgram"
    )


# -- the adaptive executor ------------------------------------------------


def _resolve_mode(mode: Optional[str]) -> str:
    mode = mode if mode is not None else flags.get("LUX_GAS")
    if mode not in GAS_MODES:
        raise ValueError(
            f"LUX_GAS={mode!r}: use one of {'|'.join(GAS_MODES)}"
        )
    return mode


class AdaptiveExecutor:
    """Single-device GAS executor with per-iteration direction choice.

    Per iteration, from the frontier about to be expanded:

    - **pull**: messages from all CSC in-edges, non-frontier sources
      masked to the combiner identity, one segment reduce — O(ne) but
      fully dense/vectorized. Right when the frontier is a large
      fraction of the graph.
    - **push**: the frontier compacts into a bounded queue whose CSR
      out-edges scatter-combine into an identity-filled accumulator —
      work scales with frontier out-edges, not ne. Right for small
      frontiers (BFS start/tail, near-fixpoint label propagation).

    Adaptive hysteresis (density = frontier / nv): density >=
    ``LUX_GAS_DENSITY_HI`` forces pull, density <= ``LUX_GAS_DENSITY_LO``
    forces push, in between the previous direction sticks. A push the
    static queue/edge budgets cannot hold falls back to pull (the
    reference's sparse->dense overflow fallback) — recorded directions
    are always the branch actually taken. Either branch produces the
    identical dense ``acc``, so results are bitwise-equal across modes.
    """

    def __init__(
        self,
        graph: Graph,
        program: GasProgram,
        device=None,
        mode: Optional[str] = None,
        queue_frac: int = 16,
        edge_budget_frac: int = 8,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.device = device
        self.mode = "pull" if not program.frontier else _resolve_mode(mode)
        put = lambda x: jax.device_put(jnp.asarray(x), device)

        nv = int(graph.nv)
        hi = flags.get_float("LUX_GAS_DENSITY_HI")
        lo = flags.get_float("LUX_GAS_DENSITY_LO")
        if not 0.0 < lo <= hi <= 1.0:
            raise ValueError(
                f"need 0 < LUX_GAS_DENSITY_LO <= LUX_GAS_DENSITY_HI <= 1 "
                f"(got lo={lo}, hi={hi})"
            )
        self.hi_count = max(1, math.ceil(hi * nv))
        self.lo_count = max(0, math.ceil(lo * nv))

        dg = {
            "col_src": put(graph.col_src.astype(np.int32)),
            "seg_ids": put(graph.col_dst),
        }
        if graph.weights is not None:
            dg["weights"] = put(graph.weights)
        if not program.frontier:
            # The VertexCtx the pull model's apply consumes.
            dg["out_degrees"] = put(graph.out_degrees.astype(np.int32))
            dg["in_degrees"] = put(graph.in_degrees.astype(np.int32))
        elif self.mode != "pull":
            # Push direction: CSR expansion arrays + budgets sized so
            # every frontier the policy can route here fits (the stay-
            # push hysteresis band tops out at hi_count).
            from lux_tpu.engine.pull import _edge_index_dtype

            q_cap, e_budget = _sparse_budgets(
                nv, int(graph.ne), queue_frac, edge_budget_frac
            )
            self.queue_cap = max(q_cap, self.hi_count + 128)
            self.edge_budget = e_budget
            csr = graph.csr()
            eidx = _edge_index_dtype(graph.ne)
            dg["csr_row_ptr"] = put(csr.row_ptr.astype(eidx))
            dg["csr_col_dst"] = put(csr.col_dst)
            if csr.weights is not None:
                dg["csr_weights"] = put(csr.weights)
            dg["out_degrees"] = put(graph.out_degrees.astype(np.int32))
        self._dg = dg
        # Filled by run(): the per-run direction ledger.
        self.push_iters = 0
        self.pull_iters = 0
        self.direction_switches = 0
        self._step = jax.jit(self._step_impl, donate_argnums=0)
        self._multi_jit = jax.jit(
            self._chunk_impl, donate_argnums=0, static_argnums=2
        )

    # -- the two directions ----------------------------------------------

    def _pull_acc(self, state: GasState, dg):
        """Dense accumulator over all CSC in-edges (non-frontier
        messages masked to the combiner identity)."""
        prog = self.program
        src_vals = state.values[dg["col_src"]]
        src_front = state.frontier[dg["col_src"]]
        msg = prog.gather(src_vals, dg.get("weights"))
        ident = identity_for(prog.combiner, msg.dtype)
        msg = jnp.where(src_front, msg, ident)
        return segment_reduce(
            msg, dg["seg_ids"], num_segments=self.graph.nv,
            kind=prog.combiner,
        )

    def _push_acc(self, state: GasState, dg):
        """The same dense accumulator built the sparse way: frontier ->
        bounded queue -> static CSR edge-slot expansion -> scatter into
        an identity-filled (nv,) array. Equality with _pull_acc is
        exact: both reduce the same per-vertex message multiset with an
        exactly-associative combiner."""
        prog = self.program
        nv = self.graph.nv
        rp = dg["csr_row_ptr"]
        q = jnp.nonzero(
            state.frontier, size=self.queue_cap, fill_value=nv
        )[0].astype(jnp.int32)
        start = rp[q]
        deg = rp[jnp.minimum(q + 1, nv)] - start
        slot, edge_pos, emask = _queue_edge_slots(
            start, deg, self.edge_budget, max(self.graph.ne, 1)
        )
        dst = dg["csr_col_dst"][edge_pos]
        src_vals = state.values[jnp.clip(q[slot], 0, nv - 1)]
        w = dg["csr_weights"][edge_pos] if "csr_weights" in dg else None
        gather = getattr(prog, "gather_push", None) or prog.gather
        msg = gather(src_vals, w)
        ident = identity_for(prog.combiner, msg.dtype)
        msg = jnp.where(emask, msg, ident)
        dst = jnp.where(emask, dst, 0)
        acc = jnp.full((nv,), ident, dtype=msg.dtype)
        if prog.combiner == "min":
            return acc.at[dst].min(msg)
        if prog.combiner == "max":
            return acc.at[dst].max(msg)
        return acc.at[dst].add(msg)

    def _decide_push(self, state: GasState, dg, cnt):
        """Traced direction decision for the frontier about to expand:
        pinned modes are Python constants (only their branch traces);
        adaptive is the density hysteresis, and any push must also fit
        the static queue/edge budgets."""
        if self.mode == "pull":
            return None          # caller skips the cond entirely
        if self.mode == "push":
            want = jnp.bool_(True)
        else:
            prev_push = state.direction > 0
            want = jnp.where(
                cnt >= jnp.int32(self.hi_count), False,
                jnp.where(cnt <= jnp.int32(self.lo_count), True, prev_push),
            )
        out_edges = jnp.where(
            state.frontier, dg["out_degrees"].astype(jnp.uint32), 0
        ).sum(dtype=jnp.uint32)
        fits = (cnt <= jnp.int32(self.queue_cap)) & (
            out_edges <= jnp.uint32(self.edge_budget)
        )
        return want & fits

    def _frontier_iter(self, state: GasState, dg):
        prog = self.program
        cnt = state.frontier.sum(dtype=jnp.int32)
        take_push = self._decide_push(state, dg, cnt)
        if take_push is None:
            acc = self._pull_acc(state, dg)
            direction = jnp.int32(0)
        else:
            acc = jax.lax.cond(
                take_push,
                lambda st: self._push_acc(st, dg),
                lambda st: self._pull_acc(st, dg),
                state,
            )
            direction = take_push.astype(jnp.int32)
        new = prog.apply(state.values, acc)
        frontier = prog.scatter(state.values, new)
        ncnt = frontier.sum(dtype=jnp.int32)
        return GasState(new, frontier, direction), ncnt, direction

    def _dense_pull_iter(self, state: GasState, dg):
        """Frontier-less (PullProgram-adapted) iteration: plain dense
        pull with the vertex context; the count stays nv so the chunked
        loop never halts early — run() bounds it with max_iters."""
        prog = self.program
        edge = EdgeCtx(
            src_vals=state.values[dg["col_src"]],
            dst_vals=state.values[dg["seg_ids"]],
            weights=dg.get("weights"),
        )
        acc = segment_reduce(
            prog.edge_contrib(edge), dg["seg_ids"],
            num_segments=self.graph.nv, kind=prog.combiner,
        )
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg["out_degrees"],
            in_degrees=dg["in_degrees"],
        )
        new = prog.apply_ctx(state.values, acc, ctx)
        # Pass frontier and direction through unchanged (not fresh
        # constants) so the donated buffers alias outputs (LUX104).
        return (
            GasState(new, state.frontier, state.direction),
            jnp.int32(self.graph.nv),
            jnp.int32(0),
        )

    def _one_iter(self, state: GasState, dg):
        if self.program.frontier:
            return self._frontier_iter(state, dg)
        return self._dense_pull_iter(state, dg)

    def _step_impl(self, state: GasState, dg):
        st, cnt, _ = self._one_iter(state, dg)
        return st, cnt

    def _chunk_impl(self, state: GasState, dg, k: int, limit=None):
        return _chunk_while(
            lambda st: self._one_iter(st, dg), state, k, limit
        )

    # -- driving ----------------------------------------------------------

    def init_state(self, **kw) -> GasState:
        vals = jax.device_put(
            jnp.asarray(self.program.init_values(self.graph, **kw)),
            self.device,
        )
        fr = jax.device_put(
            jnp.asarray(self.program.init_frontier(self.graph, **kw)),
            self.device,
        )
        return GasState(vals, fr, jnp.int32(0))

    def step(self, state: GasState):
        return self._step(state, self._dg)

    def _multi(self, state: GasState, limit: int, k: int):
        return self._multi_jit(state, self._dg, k, limit=jnp.int32(limit))

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[GasState] = None,
        chunk: int = 16,
        recorder=None,
        **init_kw,
    ):
        """Iterate to fixpoint (or ``max_iters``); returns
        (final_state, iterations_run). The per-iteration directions land
        in ``self.push_iters`` / ``self.pull_iters`` /
        ``self.direction_switches`` and in the iteration records'
        ``branch`` fields."""
        if not self.program.frontier and max_iters is None:
            raise ValueError(
                f"{self.program.name} is a frontier-less pull program; "
                "run() needs max_iters"
            )
        if state is None:
            state = self.init_state(**init_kw)
        rec = recorder if recorder is not None else recorder_for(
            "gas", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne))
        state, total, pushes, switches = _run_gas_fixpoint(
            self._multi, state, max_iters, chunk, recorder=rec
        )
        self.push_iters = pushes
        self.pull_iters = total - pushes
        self.direction_switches = switches
        engobs.note(
            "gas", program=self.program.name, mode=self.mode,
            num_iters=total, direction_push=pushes,
            direction_pull=total - pushes, direction_switches=switches,
        )
        rec.finish()
        return state, total

    def warmup(self, chunk: int = 16, **init_kw):
        """Compile the chunked executable (both direction branches live
        under its one lax.cond) outside any timed/served request."""
        with Timer() as t:
            _run_gas_fixpoint(
                self._multi, self.init_state(**init_kw),
                1 if self.program.frontier else 1, chunk,
            )
        note_compile_seconds(self, t.elapsed)

    def finalize(self, state: GasState) -> dict:
        """Host-side derived outputs for the converged state (numpy —
        never compiles)."""
        vals = np.asarray(jax.device_get(state.values))
        return self.program.finalize_host(self.graph, vals)

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted single-iteration
        step with example args exactly as step() passes them."""
        return {
            "kind": "gas",
            "fn": self._step,
            "args": (self.init_state(**init_kw), self._dg),
            "donate": (0,),
            "carry": (0,),
            "sharded": False,
        }


def _run_gas_fixpoint(multi, state, max_iters, chunk, recorder=None):
    """Chunked host loop (the push fixpoint's design): one batched
    device_get per chunk; the flag lane of the chunk carries the
    per-iteration direction taken. Returns (state, total_iters,
    push_iters, direction_switches)."""
    rec = recorder if recorder is not None else NULL_RECORDER
    total = 0
    push_total = 0
    switches = 0
    prev = None
    while True:
        limit = chunk if max_iters is None else min(chunk, max_iters - total)
        if limit <= 0:
            break
        k = chunk
        state, counts, dirs, done, last = multi(state, limit, k)
        # luxlint: disable=LUX001 -- one batched fetch per chunk (not per iter) is the fixpoint design
        counts_h, dirs_h, done_h, last_h = jax.device_get(
            (counts, dirs, done, last)
        )
        done_i = int(np.asarray(done_h).reshape(-1)[0])
        last_i = int(np.asarray(last_h).reshape(-1)[0])
        dl = np.asarray(dirs_h).reshape(-1, k)[0][:done_i]
        if dl.size:
            # Host-side direction bookkeeping on the already-fetched
            # window: switches = sign changes across the chunk boundary
            # and within it.
            seq = dl if prev is None else np.concatenate(([prev], dl))
            switches += int(np.count_nonzero(np.diff(seq.astype(np.int64))))
            prev = dl[-1]
        push_total += int(dl.sum())
        total += done_i
        cnts = np.asarray(counts_h).reshape(-1, k)[0][:done_i]
        rec.flush(total, frontier_sizes=cnts, directions=dl)
        if last_i == 0 or done_i == 0:
            break
    hard_sync(state.values)
    rec.flush(total)
    return state, total, push_total, switches


class MultiSourceGasExecutor:
    """Dense GAS executor over K value columns: one O(ne) pull-direction
    sweep serves K independent root queries of any rooted GasProgram
    (the serving batcher's mechanism, generalized from
    MultiSourcePushExecutor).

    Push-direction queue compaction is single-lane-shaped, so this
    executor is pull-only; per-lane results are still bitwise-identical
    to a single-source :class:`AdaptiveExecutor` run because every
    direction builds the same dense accumulator."""

    def __init__(self, graph: Graph, program: GasProgram, k: int,
                 device=None):
        if k < 1:
            raise ValueError(f"batch width k must be >= 1 (got {k})")
        program = as_gas(program)
        if not program.frontier:
            raise ValueError(
                f"{program.name} is frontier-less; multi-source batching "
                "needs a rooted frontier program"
            )
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.k = int(k)
        self.device = device
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        dg = {
            "col_src": put(graph.col_src.astype(np.int32)),
            "seg_ids": put(graph.col_dst),
        }
        if graph.weights is not None:
            dg["weights"] = put(graph.weights)
        self._dg = dg
        self.push_iters = 0          # API parity (pull-only: always 0)
        self.pull_iters = 0
        self.direction_switches = 0
        self._multi_jit = jax.jit(
            self._chunk_impl, donate_argnums=0, static_argnums=2
        )

    def init_state(self, starts) -> GasState:
        """One value/frontier column per root; fewer than k roots are
        right-padded by repeating the last root (duplicate lanes
        converge identically, so padding changes nothing)."""
        starts = list(starts)
        if not 1 <= len(starts) <= self.k:
            raise ValueError(f"need 1..{self.k} roots, got {len(starts)}")
        starts = starts + [starts[-1]] * (self.k - len(starts))
        prog = self.program
        vals = np.stack(
            [prog.init_values(self.graph, start=s) for s in starts], axis=1
        )
        fr = np.stack(
            [prog.init_frontier(self.graph, start=s) for s in starts], axis=1
        )
        return GasState(
            jax.device_put(jnp.asarray(vals), self.device),
            jax.device_put(jnp.asarray(fr), self.device),
            jnp.int32(0),
        )

    def _one_iter(self, state: GasState, dg):
        prog = self.program
        src_vals = state.values[dg["col_src"]]        # (ne, K)
        src_front = state.frontier[dg["col_src"]]
        w = dg.get("weights")
        msg = prog.gather(src_vals, None if w is None else w[:, None])
        ident = identity_for(prog.combiner, msg.dtype)
        msg = jnp.where(src_front, msg, ident)
        acc = segment_reduce(
            msg, dg["seg_ids"], num_segments=self.graph.nv,
            kind=prog.combiner,
        )
        new = prog.apply(state.values, acc)
        frontier = prog.scatter(state.values, new)
        return (
            GasState(new, frontier, jnp.int32(0)),
            frontier.sum(dtype=jnp.int32),
            jnp.int32(0),
        )

    def _chunk_impl(self, state: GasState, dg, k: int, limit=None):
        return _chunk_while(
            lambda st: self._one_iter(st, dg), state, k, limit
        )

    def _multi(self, state: GasState, limit: int, k: int):
        return self._multi_jit(state, self._dg, k, limit=jnp.int32(limit))

    def run(
        self,
        starts,
        max_iters: Optional[int] = None,
        chunk: int = 16,
        recorder=None,
        state: Optional[GasState] = None,
    ):
        """Run all roots to the shared fixpoint; column j of
        ``state.values`` is root ``starts[j]``'s result."""
        if state is None:
            state = self.init_state(starts)
        rec = recorder if recorder is not None else recorder_for(
            "gas_multi", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne, k=self.k))
        state, total, _, _ = _run_gas_fixpoint(
            self._multi, state, max_iters, chunk, recorder=rec
        )
        self.pull_iters = total
        engobs.note(
            "gas_multi", program=self.program.name, mode="pull",
            num_iters=total, lanes=self.k,
        )
        rec.finish()
        return state, total

    def warmup(self, chunk: int = 16, start: int = 0):
        with Timer() as t:
            _run_gas_fixpoint(
                self._multi, self.init_state([start]), 1, chunk
            )
        note_compile_seconds(self, t.elapsed)

    def values_for(self, state: GasState, j: int) -> np.ndarray:
        """Host copy of lane ``j``'s value column."""
        return np.asarray(jax.device_get(state.values[:, j]))

    def finalize_for(self, state: GasState, j: int) -> dict:
        return self.program.finalize_host(
            self.graph, self.values_for(state, j)
        )

    def trace_step(self, start: int = 0, **init_kw):
        """luxlint-IR hook; the chunk executable takes a static width k
        and a dynamic limit the example args can't carry, so
        `call`/`lower` close over them (MultiSourcePushExecutor's
        pattern)."""
        state = self.init_state([start])
        fn, dg, k = self._multi_jit, self._dg, self.k
        lim = jnp.int32(1)
        return {
            "kind": "gas_multi",
            "fn": fn,
            "args": (state, dg),
            "call": lambda st, d: fn(st, d, k, limit=lim),
            "lower": lambda: fn.lower(state, dg, k, limit=lim),
            "donate": (0,),
            "carry": (0,),
            "sharded": False,
            "k": k,
        }
