"""Sharded GAS: direction-adaptive gather-apply-scatter over the mesh.

This closes the engine split (ROADMAP item 1): the single-device
:class:`~lux_tpu.engine.gas.AdaptiveExecutor` picks push vs pull per
iteration from frontier density, but every sharded executor before this
module ran one fixed direction. :class:`ShardedAdaptiveExecutor` runs
any ``GasProgram`` over the ``parts`` mesh axis with the same per-
iteration choice — hysteresis on a device-resident scalar, one
``lax.cond``, zero recompiles on switches — which is the paper's core
loop (direction-optimal traversal over an edge-balanced partition, cf.
Gunrock, PAPERS.md arXiv:1501.05387) at P > 1.

Why the same compact exchange serves both directions: either branch
materializes the identical dense per-shard accumulator (min/max and
integer sums are exactly associative/commutative), so the *exchange
surface* is direction-independent — pull moves the (values, frontier)
rows the local CSC shard reads (the static :class:`ExchangePlan`),
push moves the bounded global frontier queue. Both ride fixed-shape
collectives, so a mid-run switch never changes a traced shape.

``LUX_EXCHANGE=frontier`` is the dynamic refinement of the compact
plan: per iteration, send only the plan rows whose *source vertex is
active*, compacted into a static per-(sender, receiver) budget
(``ExchangePlan.frontier_capacity``) and sentinel-padded so shapes
never change. Rows dropped because their source is inactive would have
contributed the combiner identity anyway (the same annihilation
argument the static compact plan makes for never-read rows — the
LUX407 contract), so results stay bitwise equal. When any pair's
active rows exceed the budget the iteration *self-downgrades* to the
static compact send inside the same ``lax.cond`` — honest, logged via
the downgrade counter, and still recompile-free.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.gas import GasState, as_gas, _resolve_mode
from lux_tpu.engine.program import EdgeCtx, VertexCtx
from lux_tpu.engine.pull import hard_sync
from lux_tpu.engine.push import (
    _chunk_while,
    _queue_edge_slots,
    _sparse_budgets,
    _validated_sg,
)
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import (
    NULL_RECORDER,
    consume_compile_seconds,
    engobs,
    note_compile_seconds,
    prof,
    recorder_for,
)
from lux_tpu.ops.segment import identity_for, segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh, parts_sharding
from lux_tpu.parallel.shard import ShardedGraph, resolve_exchange
from lux_tpu.utils import compat, flags
from lux_tpu.utils.logging import get_logger
from lux_tpu.utils.timing import Timer

import math


def _value_lanes(program) -> int:
    """Trailing value lanes per vertex (1 for scalar programs; K for
    value_shape programs like colfilter, reachable through the
    PullGasAdapter's ``inner``)."""
    shape = getattr(program, "value_shape", None)
    if shape is None:
        shape = getattr(getattr(program, "inner", None), "value_shape", None)
    return int(np.prod(shape)) if shape else 1


class ShardedAdaptiveExecutor:
    """GAS executor over an N-device mesh with per-iteration direction
    choice — the sharded form of :class:`AdaptiveExecutor`:

    - **pull**: exchange the (values, frontier) rows each shard's local
      CSC in-edges read (full all-gather, static compact plan, or the
      frontier-aware dynamic plan), mask non-frontier messages to the
      combiner identity, one segment reduce per shard.
    - **push**: each shard compacts its local frontier into a bounded
      queue of (global id, value); the queues all-gather and every
      shard expands them against its global-source CSR into an
      identity-filled local accumulator — exchange and expansion scale
      with the frontier, not nv/ne.

    The decision inputs are replicated collectives (psum of frontier
    counts, pmax of local counts, psum of frontier out-edges) so every
    shard takes the same ``lax.cond`` side; hysteresis thresholds are
    fractions of the *global* nv, exactly as on one device. Both
    branches build the same dense per-shard accumulator, so results are
    bitwise equal across directions, modes, and part counts."""

    def __init__(
        self,
        graph: Graph,
        program,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
        mode: Optional[str] = None,
        queue_frac: int = 16,
        edge_budget_frac: int = 8,
        sg: Optional[ShardedGraph] = None,
    ):
        program = as_gas(program)
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.graph = graph
        self.program = program
        self.mode = "pull" if not program.frontier else _resolve_mode(mode)
        self.sg = _validated_sg(sg, graph, self.num_parts)
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)
        log = get_logger("engine")
        self.exchange_mode, self._xplan = resolve_exchange(
            self.sg, log, frontier_ok=program.frontier
        )
        if self.exchange_mode == "frontier":
            self.frontier_cap = self._xplan.frontier_capacity(
                frac=flags.get_float("LUX_EXCHANGE_FRONTIER_FRAC")
            )
        else:
            self.frontier_cap = 0

        nv = int(graph.nv)
        hi = flags.get_float("LUX_GAS_DENSITY_HI")
        lo = flags.get_float("LUX_GAS_DENSITY_LO")
        if not 0.0 < lo <= hi <= 1.0:
            raise ValueError(
                f"need 0 < LUX_GAS_DENSITY_LO <= LUX_GAS_DENSITY_HI <= 1 "
                f"(got lo={lo}, hi={hi})"
            )
        self.hi_count = max(1, math.ceil(hi * nv))
        self.lo_count = max(0, math.ceil(lo * nv))

        dg = {
            "vertex_mask": put(self.sg.vertex_mask),
            "src_pidx": put(self.sg.src_pidx),
            "dst_local": put(self.sg.dst_local),
        }
        if self.sg.weights is not None:
            dg["weights"] = put(self.sg.weights)
        if self._xplan is not None:
            dg["xch_send"] = put(self._xplan.send_units)
            dg["xch_recv"] = put(self._xplan.recv_pos)
        if not program.frontier:
            # The VertexCtx the pull model's apply consumes: each
            # owned vertex's GLOBAL degrees (vertices live in exactly
            # one shard, so per-shard rows are the global arrays
            # re-laid-out).
            dg["out_degrees"] = put(
                np.asarray(self.sg.out_degrees).astype(np.int32))
            dg["in_degrees"] = put(
                np.asarray(self.sg.in_degrees).astype(np.int32))
        elif self.mode != "pull":
            # Push direction: global-source CSR expansion arrays +
            # budgets sized so every frontier the policy can route here
            # fits. The queue is per shard, so its cap tops out at the
            # shard size even when hi_count (a global-nv fraction)
            # exceeds it.
            q_cap, e_budget = _sparse_budgets(
                self.sg.max_nv, self.sg.max_ne, queue_frac, edge_budget_frac
            )
            self.queue_cap = max(
                q_cap, min(self.hi_count, self.sg.max_nv) + 128
            )
            self.edge_budget = e_budget
            prp, pdst, pw = self.sg.build_push_csr()
            dg["push_row_ptr"] = put(prp)
            dg["push_dst_local"] = put(pdst)
            if pw is not None:
                dg["push_weights"] = put(pw)
            dg["out_degrees"] = put(
                np.asarray(self.sg.out_degrees).astype(np.int32))
            dg["row_left"] = put(self.sg.row_left.astype(np.int32)[:, None])
        self._dg = dg
        self._specs = {k: P(PARTS_AXIS) for k in dg}
        # Filled by run(): the per-run direction/exchange ledger.
        self.push_iters = 0
        self.pull_iters = 0
        self.direction_switches = 0
        self.exchange_downgrades = 0
        state_spec = GasState(P(PARTS_AXIS), P(PARTS_AXIS), P(PARTS_AXIS))
        self._state_spec = state_spec
        mapped = compat.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(state_spec, self._specs),
            out_specs=(state_spec, P(PARTS_AXIS)),
        )
        self._step = jax.jit(mapped, donate_argnums=0)
        self._chunk_cache = {}

    # -- pull-direction exchange -----------------------------------------

    def _compact_tables(self, v, f, dg):
        """Static compact exchange: fixed-capacity all_to_all of the
        rows each receiver's real edges read (values + frontier bits),
        scattered into the flat (P*max_nv,) view. Own-span rows stay
        zero — _pull_comp serves local edges from the shard itself (the
        local-first overlap branch) and unread remote rows carry
        frontier False, so their candidates collapse to the identity."""
        max_nv = self.sg.max_nv
        sel = jnp.minimum(dg["xch_send"][0], max_nv - 1)
        pv = jax.lax.all_to_all(
            v[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
        pf = jax.lax.all_to_all(
            f[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
        recv = dg["xch_recv"][0]
        flat = self.num_parts * max_nv
        all_v = jnp.zeros((flat + 1,), v.dtype).at[recv].set(pv)[:-1]
        all_f = jnp.zeros((flat + 1,), f.dtype).at[recv].set(pf)[:-1]
        return all_v, all_f

    def _frontier_active(self, f, dg):
        """(P, capacity) activity mask over this shard's static send
        table: which planned rows have an active source this iteration.
        Sentinel (pad/diagonal) entries are never active."""
        cap = self._xplan.capacity
        max_nv = self.sg.max_nv
        send = dg["xch_send"][0].reshape(self.num_parts, cap)
        act = (send < max_nv) & f[jnp.minimum(send, max_nv - 1)]
        return send, act

    def _frontier_admissible(self, f, dg):
        """Replicated bool: every (sender, receiver) pair's active rows
        fit the static frontier budget — the self-downgrade guard. pmin
        makes it mesh-agreed, so all shards take the same cond side."""
        _, act = self._frontier_active(f, dg)
        ok_loc = (
            act.sum(axis=1, dtype=jnp.int32) <= jnp.int32(self.frontier_cap)
        ).all()
        return jax.lax.pmin(ok_loc.astype(jnp.int32), PARTS_AXIS) > 0

    def _frontier_tables(self, v, f, dg):
        """Frontier-aware compact exchange: per receiver, cumsum-compact
        the active subset of the static send rows into ``frontier_cap``
        sentinel-padded slots, all_to_all the (row id, value) pairs, and
        scatter them into the flat view by ``sender*max_nv + row``.
        Rows not sent keep (0, False) — their sources are inactive, so
        the compute mask collapses their candidates to the combiner
        identity (bitwise identical to the static compact exchange; the
        LUX407 annihilator argument). Only traced under the
        admissibility cond, so no active row is ever truncated."""
        p, fcap = self.num_parts, self.frontier_cap
        max_nv = self.sg.max_nv
        send, act = self._frontier_active(f, dg)
        pos = jnp.cumsum(act.astype(jnp.int32), axis=1) - 1
        keep = act & (pos < fcap)
        tgt = jnp.where(keep, pos, fcap)            # fcap = trash column
        rows_p = jnp.full((p, fcap + 1), max_nv, jnp.int32)
        rows_p = rows_p.at[jnp.arange(p)[:, None], tgt].set(
            jnp.where(keep, send, max_nv)
        )[:, :fcap].reshape(-1)
        prow = jax.lax.all_to_all(
            rows_p, PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
        pval = jax.lax.all_to_all(
            v[jnp.clip(rows_p, 0, max_nv - 1)],
            PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
        sender = jnp.arange(p * fcap, dtype=jnp.int32) // jnp.int32(fcap)
        flat = p * max_nv
        fpos = jnp.where(prow < max_nv, sender * max_nv + prow, flat)
        all_v = jnp.zeros((flat + 1,), v.dtype).at[fpos].set(pval)[:-1]
        all_f = jnp.zeros((flat + 1,), f.dtype).at[fpos].set(True)[:-1]
        return all_v, all_f

    def _pull_load(self, state: GasState, dg):
        """Pull-direction exchange; returns (all_v, all_f, downgraded)
        where downgraded flags a frontier-mode iteration that fell back
        to the static compact send because the frontier was dense."""
        v = state.values[0]
        f = state.frontier[0]
        if self._xplan is None:
            all_v = jax.lax.all_gather(v, PARTS_AXIS).reshape(-1)
            all_f = jax.lax.all_gather(f, PARTS_AXIS).reshape(-1)
            return all_v, all_f, jnp.int32(0)
        if self.exchange_mode != "frontier":
            all_v, all_f = self._compact_tables(v, f, dg)
            return all_v, all_f, jnp.int32(0)
        ok = self._frontier_admissible(f, dg)
        all_v, all_f = jax.lax.cond(
            ok,
            lambda vf: self._frontier_tables(vf[0], vf[1], dg),
            lambda vf: self._compact_tables(vf[0], vf[1], dg),
            (v, f),
        )
        return all_v, all_f, (~ok).astype(jnp.int32)

    # -- pull-direction compute ------------------------------------------

    def _pull_comp(self, state: GasState, loaded, dg):
        """gather + identity mask + per-local-destination reduction —
        the single-device ``_pull_acc`` over this shard's CSC slice.
        Compact/frontier modes relax local-source edges against the
        shard's own values (no collective dependence — XLA overlaps it
        with the in-flight all_to_all) before the unchanged reduction,
        keeping the combine order bitwise identical."""
        prog = self.program
        max_nv = self.sg.max_nv
        all_v, all_f = loaded
        sidx = dg["src_pidx"][0]
        w = dg["weights"][0] if "weights" in dg else None
        if self._xplan is not None:
            v_loc = state.values[0]
            f_loc = state.frontier[0]
            own = jax.lax.axis_index(PARTS_AXIS)
            base = own * max_nv
            local = (sidx >= base) & (sidx < base + max_nv)
            lidx = jnp.clip(sidx - base, 0, max_nv - 1)
            # The local contribution traces COMPLETELY before anything
            # derived from the collective: jax caches the jnp.where
            # sub-jaxpr per operand signature and luxlint's dataflow
            # walk (LUX404) merges var memberships across call sites of
            # a shared jaxpr, so a program whose gather carries its own
            # same-signature where (labelprop) would smear the remote
            # side's taint onto the local mask if the remote gather
            # traced first.
            cand_l = prog.gather(v_loc[lidx], w)
            ident = identity_for(prog.combiner, cand_l.dtype)
            cand_l = jnp.where(f_loc[lidx], cand_l, ident)
            cand_r = prog.gather(all_v[sidx], w)
            cand_r = jnp.where(all_f[sidx], cand_r, ident)
            cand = jnp.where(local, cand_l, cand_r)
        else:
            cand = prog.gather(all_v[sidx], w)
            ident = identity_for(prog.combiner, cand.dtype)
            cand = jnp.where(all_f[sidx], cand, ident)
        # Pad edges carry dst_local == max_nv: the dropped trash
        # segment, so no edge mask is needed.
        return segment_reduce(
            cand, dg["dst_local"][0], num_segments=max_nv + 1,
            kind=prog.combiner,
        )[:max_nv]

    # -- push direction ----------------------------------------------------

    def _push_load(self, state: GasState, dg):
        """Local frontier -> bounded queue of (global id, value), then
        the queue all-gather — O(P*Q) bytes, not O(nv)."""
        nv, max_nv = self.graph.nv, self.sg.max_nv
        Q = self.queue_cap
        v = state.values[0]
        f = state.frontier[0]
        q_loc = jnp.nonzero(f, size=Q, fill_value=max_nv)[0].astype(jnp.int32)
        qv = v[jnp.clip(q_loc, 0, max_nv - 1)]
        base = dg["row_left"][0, 0]
        qg = jnp.where(q_loc >= max_nv, jnp.int32(nv), base + q_loc)
        all_q = jax.lax.all_gather(qg, PARTS_AXIS).reshape(-1)
        all_qv = jax.lax.all_gather(qv, PARTS_AXIS).reshape(-1)
        return all_q, all_qv

    def _push_comp(self, all_q, all_qv, dg):
        """Expand the global queue against this shard's local edges via
        the global-src CSR and scatter-combine into an identity-filled
        local accumulator — the single-device ``_push_acc`` per shard.
        (Sentinel id nv reads deg == 0: the row_ptr pad rows.)"""
        prog = self.program
        max_nv = self.sg.max_nv
        rp = dg["push_row_ptr"][0]
        start = rp[all_q]
        deg = rp[all_q + 1] - start
        slot, edge_pos, emask = _queue_edge_slots(
            start, deg, self.edge_budget, self.sg.max_ne
        )
        dstl = dg["push_dst_local"][0][edge_pos]
        w = (
            dg["push_weights"][0][edge_pos]
            if "push_weights" in dg else None
        )
        gather = getattr(prog, "gather_push", None) or prog.gather
        msg = gather(all_qv[slot], w)
        ident = identity_for(prog.combiner, msg.dtype)
        msg = jnp.where(emask, msg, ident)
        dstl = jnp.where(emask, dstl, max_nv)
        acc = jnp.full((max_nv + 1,), ident, dtype=msg.dtype)
        if prog.combiner == "min":
            acc = acc.at[dstl].min(msg)
        elif prog.combiner == "max":
            acc = acc.at[dstl].max(msg)
        else:
            acc = acc.at[dstl].add(msg)
        return acc[:max_nv]

    # -- decision + merge --------------------------------------------------

    def _decide_block(self, state: GasState, dg):
        """(local frontier count, take_push | None). Pinned pull skips
        the cond entirely (only its branch traces); otherwise the global
        hysteresis runs on psum'd counts with the single-device
        thresholds, and a push must fit the per-shard static budgets —
        all replicated collectives, so the mesh agrees."""
        f = state.frontier[0]
        cnt_loc = f.sum(dtype=jnp.int32)
        if self.mode == "pull":
            return cnt_loc, None
        cnt = jax.lax.psum(cnt_loc, PARTS_AXIS)
        if self.mode == "push":
            want = jnp.bool_(True)
        else:
            prev_push = state.direction[0] > 0
            want = jnp.where(
                cnt >= jnp.int32(self.hi_count), False,
                jnp.where(cnt <= jnp.int32(self.lo_count), True, prev_push),
            )
        oe_loc = jnp.where(
            f, dg["out_degrees"][0].astype(jnp.uint32), 0
        ).sum(dtype=jnp.uint32)
        cnt_max = jax.lax.pmax(cnt_loc, PARTS_AXIS)
        oe_tot = jax.lax.psum(oe_loc, PARTS_AXIS)
        fits = (cnt_max <= jnp.int32(self.queue_cap)) & (
            oe_tot <= jnp.uint32(self.edge_budget)
        )
        return cnt_loc, want & fits

    def _merge(self, state: GasState, acc, dirs1, dg):
        """apply + vertex-mask merge + scatter activation on this
        shard's rows; ``dirs1`` is the (1,) per-shard direction lane the
        new state carries (the hysteresis memory)."""
        prog = self.program
        v = state.values[0]
        new = prog.apply(v, acc)
        vmask = dg["vertex_mask"][0]
        new = jnp.where(vmask, new, v)
        frontier = prog.scatter(v, new) & vmask
        cnt = frontier.sum(dtype=jnp.int32)
        return GasState(new[None], frontier[None], dirs1), cnt

    # -- per-iteration blocks ---------------------------------------------

    def _frontier_iter_block(self, state: GasState, dg):
        """One adaptive iteration on this shard's blocks; returns
        (state', local count, flag) where flag packs the direction taken
        (bit 0) and a frontier-exchange downgrade (bit 1)."""
        take_push = self._decide_block(state, dg)[1]
        if take_push is None:
            with prof.region("lux.gas_sharded.exchange"):
                all_v, all_f, down = self._pull_load(state, dg)
            with prof.region("lux.gas_sharded.compute"):
                acc = self._pull_comp(state, (all_v, all_f), dg)
            direction = jnp.int32(0)
        else:
            def push_branch(st):
                with prof.region("lux.gas_sharded.exchange"):
                    all_q, all_qv = self._push_load(st, dg)
                with prof.region("lux.gas_sharded.compute"):
                    return self._push_comp(all_q, all_qv, dg), jnp.int32(0)

            def pull_branch(st):
                with prof.region("lux.gas_sharded.exchange"):
                    all_v, all_f, down = self._pull_load(st, dg)
                with prof.region("lux.gas_sharded.compute"):
                    return self._pull_comp(st, (all_v, all_f), dg), down

            acc, down = jax.lax.cond(
                take_push, push_branch, pull_branch, state
            )
            direction = take_push.astype(jnp.int32)
        new_state, ncnt = self._merge(state, acc, direction[None], dg)
        return new_state, ncnt, direction + 2 * down

    def _values_load(self, state: GasState, dg):
        """Frontier-less exchange: values only (the all-ones frontier
        never changes and is never read)."""
        v = state.values[0]
        max_nv = self.sg.max_nv
        if self._xplan is None:
            return jax.lax.all_gather(v, PARTS_AXIS).reshape(
                (-1,) + v.shape[1:])
        sel = jnp.minimum(dg["xch_send"][0], max_nv - 1)
        pv = jax.lax.all_to_all(
            v[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
        recv = dg["xch_recv"][0]
        flat = self.num_parts * max_nv
        return jnp.zeros(
            (flat + 1,) + v.shape[1:], v.dtype
        ).at[recv].set(pv)[:-1]

    def _dense_pull_step(self, state: GasState, all_v, dg):
        """Frontier-less (PullProgram-adapted) compute: edge_contrib
        over the local CSC slice with the VertexCtx apply, vertex-mask
        merged. Frontier and direction pass through unchanged (not
        fresh constants) so the donated buffers alias outputs
        (LUX104). The count is this shard's owned-vertex total, so the
        psum'd halt count stays nv — run() bounds it with max_iters."""
        prog = self.program
        max_nv = self.sg.max_nv
        v = state.values[0]
        sidx = dg["src_pidx"][0]
        dstl = dg["dst_local"][0]
        w = dg["weights"][0] if "weights" in dg else None
        if self._xplan is not None:
            own = jax.lax.axis_index(PARTS_AXIS)
            base = own * max_nv
            local = (sidx >= base) & (sidx < base + max_nv)
            lidx = jnp.clip(sidx - base, 0, max_nv - 1)
            sel = local if v.ndim == 1 else local[:, None]
            src_vals = jnp.where(sel, v[lidx], all_v[sidx])
        else:
            src_vals = all_v[sidx]
        edge = EdgeCtx(
            src_vals=src_vals,
            dst_vals=v[jnp.clip(dstl, 0, max_nv - 1)],
            weights=w,
        )
        acc = segment_reduce(
            prog.edge_contrib(edge), dstl, num_segments=max_nv + 1,
            kind=prog.combiner,
        )[:max_nv]
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg["out_degrees"][0],
            in_degrees=dg["in_degrees"][0],
        )
        new = prog.apply_ctx(v, acc, ctx)
        vmask = dg["vertex_mask"][0]
        vm = vmask if new.ndim == 1 else vmask[:, None]
        new = jnp.where(vm, new, v)
        return (
            GasState(new[None], state.frontier, state.direction),
            vmask.sum(dtype=jnp.int32),
        )

    def _dense_pull_iter_block(self, state: GasState, dg):
        with prof.region("lux.gas_sharded.exchange"):
            all_v = self._values_load(state, dg)
        with prof.region("lux.gas_sharded.compute"):
            st, cnt = self._dense_pull_step(state, all_v, dg)
        return st, cnt, jnp.int32(0)

    def _one_iter_block(self, state: GasState, dg):
        if self.program.frontier:
            return self._frontier_iter_block(state, dg)
        return self._dense_pull_iter_block(state, dg)

    def _shard_step(self, state: GasState, dg):
        new_state, cnt, _ = self._one_iter_block(state, dg)
        return new_state, cnt[None]

    def _shard_chunk(self, state: GasState, dg, limit, k: int):
        def one_iter(st):
            new_state, cnt_local, flag = self._one_iter_block(st, dg)
            return new_state, jax.lax.psum(cnt_local, PARTS_AXIS), flag

        st, counts, flags_, done, last = _chunk_while(
            one_iter, state, k, limit[0]
        )
        return st, counts[None], flags_[None], done[None], last[None]

    def _multi(self, state: GasState, limit: int, k: int):
        if k not in self._chunk_cache:
            mapped = compat.shard_map(
                lambda st, dg, lim: self._shard_chunk(st, dg, lim, k),
                mesh=self.mesh,
                in_specs=(self._state_spec, self._specs, P()),
                out_specs=(
                    self._state_spec,
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                ),
            )
            self._chunk_cache[k] = jax.jit(mapped, donate_argnums=0)
        return self._chunk_cache[k](
            state, self._dg, jnp.full((1,), limit, jnp.int32)
        )

    # -- driving ----------------------------------------------------------

    def init_state(self, **kw) -> GasState:
        sh = parts_sharding(self.mesh)
        vals = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(self.program.init_values(self.graph, **kw))
            ),
            sh,
        )
        fr = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(
                    self.program.init_frontier(self.graph, **kw))
            ),
            sh,
        )
        dirs = jax.device_put(
            jnp.zeros((self.num_parts,), jnp.int32), sh
        )
        return GasState(vals, fr, dirs)

    def step(self, state: GasState):
        return self._step(state, self._dg)

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[GasState] = None,
        chunk: int = 16,
        recorder=None,
        **init_kw,
    ):
        """Iterate to fixpoint (or ``max_iters``); returns
        (final_state, iterations_run). Directions land in
        ``self.push_iters`` / ``self.pull_iters`` /
        ``self.direction_switches``; frontier-exchange downgrades in
        ``self.exchange_downgrades``."""
        if not self.program.frontier and max_iters is None:
            raise ValueError(
                f"{self.program.name} is a frontier-less pull program; "
                "run() needs max_iters"
            )
        if state is None:
            state = self.init_state(**init_kw)
        rec = recorder if recorder is not None else recorder_for(
            "gas_sharded", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            packed = self._xplan is not None
            note = (
                "frontier_all_to_all" if self.exchange_mode == "frontier"
                else "compact_all_to_all" if packed else "dense_estimate"
            )
            rec.set_exchange_bytes(
                self.exchange_bytes_per_iter(), note=note,
                parts=self.num_parts)
            if packed:
                rec.set_overlap(True)
            useful = engobs.useful_exchange(
                self.sg, self._row_bytes(),
                exchanged_rows=(self._xplan.exchanged_units_per_iter
                                if packed else None))
            if useful is not None:
                rec.set_useful_bytes(useful["useful_bytes_per_iter"],
                                     useful["ratio"])
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne))
        if engobs.enabled():
            # Phase-fenced measurement fixpoint (LUX_ENGOBS); the off
            # path keeps the exact chunked fused executable below.
            state, total, pushes, switches, downs = engobs.run_gas_phased(
                self, state, max_iters, rec)
        else:
            state, total, pushes, switches, downs = (
                _run_sharded_gas_fixpoint(
                    self._multi, state, max_iters, chunk, recorder=rec
                )
            )
        self.push_iters = pushes
        self.pull_iters = total - pushes
        self.direction_switches = switches
        self.exchange_downgrades = downs
        engobs.note(
            "gas_sharded", program=self.program.name, mode=self.mode,
            exchange=self.exchange_mode, num_parts=self.num_parts,
            num_iters=total, direction_push=pushes,
            direction_pull=total - pushes, direction_switches=switches,
            exchange_downgrades=downs,
        )
        rec.finish()
        return state, total

    def warmup(self, chunk: int = 16, **init_kw):
        """Compile the chunked executable (both direction branches and
        both frontier-exchange sends live under its lax.conds) outside
        any timed/served request."""
        with Timer() as t:
            _run_sharded_gas_fixpoint(
                self._multi, self.init_state(**init_kw), 1, chunk
            )
        note_compile_seconds(self, t.elapsed)

    def gather_values(self, state: GasState) -> np.ndarray:
        return self.sg.from_padded(np.asarray(jax.device_get(state.values)))

    def finalize(self, state: GasState) -> dict:
        """Host-side derived outputs for the converged state (numpy —
        never compiles)."""
        return self.program.finalize_host(
            self.graph, self.gather_values(state))

    # -- `-verbose` / engobs phase split ----------------------------------

    def _sharded_phase_jits(self):
        """Separately-dispatched phase executables, each a shard_map
        jit, so engobs can fence exchange vs compute walls. SPMD phases
        run in lockstep, so the measured walls are mesh-wide."""
        if hasattr(self, "_pjits"):
            return self._pjits
        state_spec = self._state_spec
        specs = self._specs
        packed = self._xplan is not None

        def sm(fn, in_specs, out_specs):
            # check_vma off: all_gather outputs are replicated by
            # construction but the static checker cannot infer it here.
            return jax.jit(compat.shard_map(
                fn, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False,
            ))

        j = {}
        if not self.program.frontier:
            j["d_load"] = sm(
                lambda st, dg: (
                    self._values_load(st, dg)[None] if packed
                    else self._values_load(st, dg)
                ),
                (state_spec, specs),
                P(PARTS_AXIS) if packed else P(),
            )
            j["d_step"] = sm(
                lambda st, av, dg: (
                    lambda r: (r[0], r[1][None])
                )(self._dense_pull_step(
                    st, av[0] if packed else av, dg)),
                (state_spec, P(PARTS_AXIS) if packed else P(), specs),
                (state_spec, P(PARTS_AXIS)),
            )
            self._pjits = j
            return j

        def decide(st, dg):
            cnt_loc, take = self._decide_block(st, dg)
            take = jnp.int32(0) if take is None else take.astype(jnp.int32)
            return cnt_loc[None], take[None]

        j["decide"] = sm(
            decide, (state_spec, specs), (P(PARTS_AXIS), P(PARTS_AXIS)))
        # Exchanged pull tables are per-shard scatters under the packed
        # modes, replicated all_gather outputs otherwise; the downgrade
        # flag is always a per-shard scalar lane.
        tbl = P(PARTS_AXIS) if packed else P()
        j["p_load"] = sm(
            lambda st, dg: (
                lambda av, af, dn: (
                    (av[None], af[None], dn[None]) if packed
                    else (av, af, dn[None])
                )
            )(*self._pull_load(st, dg)),
            (state_spec, specs), (tbl, tbl, P(PARTS_AXIS)),
        )
        j["p_comp"] = sm(
            lambda st, av, af, dg: self._pull_comp(
                st,
                ((av[0], af[0]) if packed else (av, af)),
                dg,
            )[None],
            (state_spec, tbl, tbl, specs), P(PARTS_AXIS),
        )
        j["merge"] = sm(
            lambda st, acc, dirs, dg: (
                lambda r: (r[0], r[1][None])
            )(self._merge(st, acc[0], dirs, dg)),
            (state_spec, P(PARTS_AXIS), P(PARTS_AXIS), specs),
            (state_spec, P(PARTS_AXIS)),
        )
        if self.mode != "pull":
            j["s_load"] = sm(
                lambda st, dg: self._push_load(st, dg),
                (state_spec, specs), (P(), P()),
            )
            j["s_comp"] = sm(
                lambda q, qv, dg: self._push_comp(q, qv, dg)[None],
                (P(), P(), specs), P(PARTS_AXIS),
            )
        self._pjits = j
        return j

    def _dirs_device(self, push: bool):
        return jax.device_put(
            np.full((self.num_parts,), 1 if push else 0, np.int32),
            parts_sharding(self.mesh),
        )

    def phase_step(self, state: GasState):
        """One iteration as separately-dispatched exchange/compute/merge
        phases. Returns (new_state, total_active, info): info carries
        the phase walls, the branch taken (``push`` | ``pull`` |
        ``pull/frontier`` | ``pull/downgraded``), and the downgrade
        flag. Phase dispatch breaks fusion; use run() for timed
        fixpoints."""
        j = self._sharded_phase_jits()
        dg = self._dg
        times = {}
        if not self.program.frontier:
            with Timer() as t:
                all_v = hard_sync(j["d_load"](state, dg))
            times["loadTime"] = t.elapsed
            with Timer() as t:
                new_state, cnt = hard_sync(j["d_step"](state, all_v, dg))
            times["compTime"] = t.elapsed
            times["updateTime"] = 0.0
            times["branch"] = "pull/dense"
            times["downgraded"] = 0
            total = int(np.asarray(jax.device_get(cnt)).sum())
            return new_state, total, times
        _, take = jax.device_get(j["decide"](state, dg))
        take_i = int(np.asarray(take).reshape(-1)[0])
        down_i = 0
        if take_i:
            with Timer() as t:
                all_q, all_qv = hard_sync(j["s_load"](state, dg))
            times["loadTime"] = t.elapsed
            with Timer() as t:
                acc = hard_sync(j["s_comp"](all_q, all_qv, dg))
            times["compTime"] = t.elapsed
            times["branch"] = "push"
        else:
            with Timer() as t:
                all_v, all_f, down = hard_sync(j["p_load"](state, dg))
            times["loadTime"] = t.elapsed
            down_i = int(np.asarray(jax.device_get(down)).reshape(-1)[0])
            with Timer() as t:
                acc = hard_sync(j["p_comp"](state, all_v, all_f, dg))
            times["compTime"] = t.elapsed
            if self.exchange_mode == "frontier":
                times["branch"] = (
                    "pull/downgraded" if down_i else "pull/frontier"
                )
            else:
                times["branch"] = "pull"
        with Timer() as t:
            new_state, cnt = hard_sync(
                j["merge"](state, acc, self._dirs_device(bool(take_i)), dg)
            )
        times["updateTime"] = t.elapsed
        times["downgraded"] = down_i
        total = int(np.asarray(jax.device_get(cnt)).sum())
        return new_state, total, times

    def warmup_phases(self, state: GasState):
        """Compile every phase executable — both directions and both
        frontier-exchange sends — outside any timed region. ``state``
        is read, never donated."""
        j = self._sharded_phase_jits()
        dg = self._dg
        if not self.program.frontier:
            all_v = j["d_load"](state, dg)
            hard_sync(j["d_step"](state, all_v, dg))
            return
        jax.device_get(j["decide"](state, dg))
        all_v, all_f, _ = j["p_load"](state, dg)
        acc = j["p_comp"](state, all_v, all_f, dg)
        hard_sync(j["merge"](state, acc, self._dirs_device(False), dg))
        if self.mode != "pull":
            all_q, all_qv = j["s_load"](state, dg)
            acc = j["s_comp"](all_q, all_qv, dg)
            hard_sync(j["merge"](state, acc, self._dirs_device(True), dg))

    # -- accounting / lint hooks ------------------------------------------

    def _row_bytes(self) -> int:
        """Per-exchanged-row payload: value lanes + 1 frontier byte
        (frontier-less programs exchange values only)."""
        itemsize = np.dtype(self.program.value_dtype).itemsize
        return itemsize * _value_lanes(self.program) + (
            1 if self.program.frontier else 0
        )

    def _frontier_row_bytes(self) -> int:
        """Frontier-mode packed row: value + int32 row id (the activity
        bit rides in the id's sentinel)."""
        return np.dtype(self.program.value_dtype).itemsize + 4

    def exchange_bytes_per_iter(self) -> int:
        """Pull-branch upper bound on cross-device traffic per
        iteration. Frontier mode reports the static compact figure —
        its own downgrade branch, and the bound the dynamic send always
        beats; the measured frontier win is engobs ledger evidence, not
        this static bound."""
        p = self.num_parts
        if self._xplan is not None:
            return self._xplan.exchange_bytes_per_iter(self._row_bytes())
        return p * (p - 1) * self.sg.max_nv * self._row_bytes()

    def frontier_evidence(self) -> Optional[dict]:
        """LUX407 inputs (luxlint --exchange): the static admissibility
        contract of the dynamic plan. ``frontier_max_sends`` is the
        admission threshold — an iteration with more active rows on any
        pair downgrades instead of truncating — and
        ``frontier_fill_active`` asserts dropped rows are inactive
        (combiner-identity annihilated), never zero-filled actives."""
        if self.exchange_mode != "frontier":
            return None
        p = self.num_parts
        rb = self._frontier_row_bytes()
        return {
            "frontier_capacity": self.frontier_cap,
            "frontier_max_sends": self.frontier_cap,
            "frontier_row_bytes": rb,
            "frontier_bytes_per_iter": p * (p - 1) * self.frontier_cap * rb,
            "frontier_fill_active": 0,
        }

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted shard_map step;
        sharded=True, so LUX105 demands a collective in the trace. The
        exchange_* keys feed LUX404-407 (``luxlint --exchange``)."""
        return {
            "kind": "gas_sharded",
            "fn": self._step,
            "args": (self.init_state(**init_kw), self._dg),
            "donate": (0,),
            "carry": (0,),
            "sharded": True,
            "exchange_mode": self.exchange_mode,
            "exchange_bytes": self.exchange_bytes_per_iter(),
            "combiner": getattr(self.program, "combiner", ""),
            "value_dtype": np.dtype(
                getattr(self.program, "value_dtype", np.uint32)).name,
            "num_parts": self.num_parts,
            "plan": self._xplan,
        }


def _run_sharded_gas_fixpoint(multi, state, max_iters, chunk, recorder=None):
    """Chunked host loop: one batched device_get per chunk; the flag
    lane packs the direction taken (bit 0) and frontier-exchange
    downgrades (bit 1). Returns (state, total_iters, push_iters,
    direction_switches, exchange_downgrades)."""
    rec = recorder if recorder is not None else NULL_RECORDER
    total = 0
    push_total = 0
    switches = 0
    downgrades = 0
    prev = None
    while True:
        limit = chunk if max_iters is None else min(chunk, max_iters - total)
        if limit <= 0:
            break
        k = chunk
        state, counts, dirs, done, last = multi(state, limit, k)
        # luxlint: disable=LUX001 -- one batched fetch per chunk (not per iter) is the fixpoint design
        counts_h, dirs_h, done_h, last_h = jax.device_get(
            (counts, dirs, done, last)
        )
        done_i = int(np.asarray(done_h).reshape(-1)[0])
        last_i = int(np.asarray(last_h).reshape(-1)[0])
        fl = np.asarray(dirs_h).reshape(-1, k)[0][:done_i]
        dl = fl & 1
        downgrades += int((fl >> 1).sum())
        if dl.size:
            seq = dl if prev is None else np.concatenate(([prev], dl))
            switches += int(np.count_nonzero(np.diff(seq.astype(np.int64))))
            prev = dl[-1]
        push_total += int(dl.sum())
        total += done_i
        cnts = np.asarray(counts_h).reshape(-1, k)[0][:done_i]
        rec.flush(total, frontier_sizes=cnts, directions=dl)
        if last_i == 0 or done_i == 0:
            break
    hard_sync(state.values)
    rec.flush(total)
    return state, total, push_total, switches, downgrades


class ShardedMultiSourceGasExecutor:
    """Dense GAS over the mesh with K value lanes per vertex: one
    distributed pull-direction sweep serves K independent root queries
    of any rooted GasProgram — the sharded serving form of
    :class:`MultiSourceGasExecutor`, laid out like
    :class:`ShardedMultiSourcePushExecutor` ((P, max_nv, K) shards,
    lane axis trailing, K-lane full or compact exchange).

    Push-direction queue compaction and the frontier-aware exchange are
    single-lane-shaped, so this executor is pull-only on the static
    exchange (``LUX_EXCHANGE=frontier`` downgrades to compact here,
    logged); per-lane results are still bitwise-identical to a
    single-source sharded run because every path builds the same dense
    accumulator."""

    def __init__(
        self,
        graph: Graph,
        program,
        k: int,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
        sg: Optional[ShardedGraph] = None,
    ):
        if k < 1:
            raise ValueError(f"batch width k must be >= 1 (got {k})")
        program = as_gas(program)
        if not program.frontier:
            raise ValueError(
                f"{program.name} is frontier-less; multi-source batching "
                "needs a rooted frontier program"
            )
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.graph = graph
        self.program = program
        self.k = int(k)
        self.sg = _validated_sg(sg, graph, self.num_parts)
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)
        dg = {
            "src_pidx": put(self.sg.src_pidx),
            "dst_local": put(self.sg.dst_local),
            "vertex_mask": put(self.sg.vertex_mask),
        }
        if self.sg.weights is not None:
            dg["weights"] = put(self.sg.weights)
        self.exchange_mode, self._xplan = resolve_exchange(
            self.sg, get_logger("engine"), frontier_ok=False)
        if self._xplan is not None:
            dg["xch_send"] = put(self._xplan.send_units)
            dg["xch_recv"] = put(self._xplan.recv_pos)
        self._dg = dg
        self._specs = {key: P(PARTS_AXIS) for key in dg}
        self.push_iters = 0          # API parity (pull-only: always 0)
        self.pull_iters = 0
        self.direction_switches = 0
        self.exchange_downgrades = 0
        state_spec = GasState(P(PARTS_AXIS), P(PARTS_AXIS), P(PARTS_AXIS))
        self._state_spec = state_spec
        mapped = compat.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(state_spec, self._specs),
            out_specs=(state_spec, P(PARTS_AXIS)),
        )
        self._step = jax.jit(mapped, donate_argnums=0)
        self._chunk_cache = {}

    def _exchange_lanes_block(self, state: GasState, dg):
        """All-gather (or compact all_to_all) the (values, frontier)
        lane shards into (P*max_nv, K) global tables — own-span and
        unread rows stay zero (frontier False) under the compact plan,
        and the local-first compute branch never reads them."""
        v = state.values[0]                            # (max_nv, K)
        f = state.frontier[0]
        if self._xplan is not None:
            max_nv = self.sg.max_nv
            sel = jnp.minimum(dg["xch_send"][0], max_nv - 1)
            pv = jax.lax.all_to_all(
                v[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
            pf = jax.lax.all_to_all(
                f[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
            recv = dg["xch_recv"][0]
            flat = self.num_parts * max_nv
            all_v = jnp.zeros((flat + 1, self.k), v.dtype)
            all_f = jnp.zeros((flat + 1, self.k), f.dtype)
            return (all_v.at[recv].set(pv)[:-1], all_f.at[recv].set(pf)[:-1])
        all_v = jax.lax.all_gather(v, PARTS_AXIS).reshape(-1, self.k)
        all_f = jax.lax.all_gather(f, PARTS_AXIS).reshape(-1, self.k)
        return all_v, all_f

    def _compute_lanes_block(self, state: GasState, all_v, all_f, dg):
        """Per-lane gather + identity mask + segment reduce + GAS
        apply/scatter on this shard's rows."""
        prog = self.program
        max_nv = self.sg.max_nv
        v = state.values[0]                            # (max_nv, K)
        sidx = dg["src_pidx"][0]
        w = dg["weights"][0] if "weights" in dg else None
        wk = None if w is None else w[:, None]
        if self._xplan is not None:
            f_loc = state.frontier[0]
            own = jax.lax.axis_index(PARTS_AXIS)
            base = own * max_nv
            local = (sidx >= base) & (sidx < base + max_nv)
            lidx = jnp.clip(sidx - base, 0, max_nv - 1)
            cand_l = prog.gather(v[lidx], wk)
            cand_r = prog.gather(all_v[sidx], wk)
            ident = identity_for(prog.combiner, cand_l.dtype)
            cand_l = jnp.where(f_loc[lidx], cand_l, ident)
            cand_r = jnp.where(all_f[sidx], cand_r, ident)
            cand = jnp.where(local[:, None], cand_l, cand_r)
        else:
            cand = prog.gather(all_v[sidx], wk)
            ident = identity_for(prog.combiner, cand.dtype)
            cand = jnp.where(all_f[sidx], cand, ident)
        acc = segment_reduce(
            cand, dg["dst_local"][0], num_segments=max_nv + 1,
            kind=prog.combiner,
        )[:max_nv]
        new = prog.apply(v, acc)
        vmask = dg["vertex_mask"][0][:, None]
        new = jnp.where(vmask, new, v)
        frontier = prog.scatter(v, new) & vmask
        return (
            GasState(new[None], frontier[None], state.direction),
            frontier.sum(dtype=jnp.int32),
        )

    def _iter_block(self, state: GasState, dg):
        with prof.region("lux.gas_multi_sharded.exchange"):
            all_v, all_f = self._exchange_lanes_block(state, dg)
        with prof.region("lux.gas_multi_sharded.compute"):
            return self._compute_lanes_block(state, all_v, all_f, dg)

    def _shard_step(self, state: GasState, dg):
        new_state, cnt = self._iter_block(state, dg)
        return new_state, cnt[None]

    def _shard_chunk(self, state: GasState, dg, limit, k: int):
        def one_iter(st):
            new_state, cnt_local = self._iter_block(st, dg)
            return (
                new_state,
                jax.lax.psum(cnt_local, PARTS_AXIS),
                jnp.int32(0),
            )

        st, counts, flags_, done, last = _chunk_while(
            one_iter, state, k, limit[0]
        )
        return st, counts[None], flags_[None], done[None], last[None]

    def _multi(self, state: GasState, limit: int, k: int):
        if k not in self._chunk_cache:
            mapped = compat.shard_map(
                lambda st, dg, lim: self._shard_chunk(st, dg, lim, k),
                mesh=self.mesh,
                in_specs=(self._state_spec, self._specs, P()),
                out_specs=(
                    self._state_spec,
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                ),
            )
            self._chunk_cache[k] = jax.jit(mapped, donate_argnums=0)
        return self._chunk_cache[k](
            state, self._dg, jnp.full((1,), limit, jnp.int32)
        )

    def init_state(self, starts) -> GasState:
        """(P, max_nv, K) state with one lane per root; short batches
        are right-padded by repeating the last root (duplicate lanes
        converge identically — results, iteration counts, and the
        executable shape are all unchanged: the zero-recompile
        contract)."""
        starts = list(starts)
        if not 1 <= len(starts) <= self.k:
            raise ValueError(f"need 1..{self.k} roots, got {len(starts)}")
        starts = starts + [starts[-1]] * (self.k - len(starts))
        prog = self.program
        vals = np.stack(
            [prog.init_values(self.graph, start=s) for s in starts], axis=1
        )
        fr = np.stack(
            [prog.init_frontier(self.graph, start=s) for s in starts], axis=1
        )
        sh = parts_sharding(self.mesh)
        return GasState(
            jax.device_put(jnp.asarray(self.sg.to_padded(vals)), sh),
            jax.device_put(jnp.asarray(self.sg.to_padded(fr)), sh),
            jax.device_put(jnp.zeros((self.num_parts,), jnp.int32), sh),
        )

    def step(self, state: GasState):
        return self._step(state, self._dg)

    def run(
        self,
        starts,
        max_iters: Optional[int] = None,
        chunk: int = 16,
        recorder=None,
        state: Optional[GasState] = None,
    ):
        """Run all roots to the shared fixpoint; column j of the
        gathered values is root ``starts[j]``'s result."""
        if state is None:
            state = self.init_state(starts)
        rec = recorder if recorder is not None else recorder_for(
            "gas_multi_sharded", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            packed = self._xplan is not None
            rec.set_exchange_bytes(
                self.exchange_bytes_per_iter(),
                note="compact_all_to_all" if packed else "dense_estimate",
                parts=self.num_parts)
            if packed:
                rec.set_overlap(True)
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne, k=self.k))
        state, total, _, _, _ = _run_sharded_gas_fixpoint(
            self._multi, state, max_iters, chunk, recorder=rec
        )
        self.pull_iters = total
        engobs.note(
            "gas_multi_sharded", program=self.program.name, mode="pull",
            exchange=self.exchange_mode, num_parts=self.num_parts,
            num_iters=total, lanes=self.k,
        )
        rec.finish()
        return state, total

    def warmup(self, chunk: int = 16, start: int = 0):
        with Timer() as t:
            _run_sharded_gas_fixpoint(
                self._multi, self.init_state([start]), 1, chunk
            )
        note_compile_seconds(self, t.elapsed)

    def _row_bytes(self) -> int:
        itemsize = np.dtype(self.program.value_dtype).itemsize
        return self.k * (itemsize + 1)

    def exchange_bytes_per_iter(self) -> int:
        p = self.num_parts
        if self._xplan is not None:
            return self._xplan.exchange_bytes_per_iter(self._row_bytes())
        return p * (p - 1) * self.sg.max_nv * self._row_bytes()

    def gather_values(self, state: GasState) -> np.ndarray:
        return self.sg.from_padded(np.asarray(jax.device_get(state.values)))

    def values_for(self, state: GasState, j: int) -> np.ndarray:
        """Host copy of lane ``j``'s unpadded value column."""
        return np.ascontiguousarray(self.gather_values(state)[:, j])

    def finalize_for(self, state: GasState, j: int) -> dict:
        return self.program.finalize_host(
            self.graph, self.values_for(state, j)
        )

    def trace_step(self, start: int = 0, **init_kw):
        """luxlint-IR hook: the jitted shard_map step (sharded=True, so
        LUX105 demands a collective); exchange_* keys feed LUX404-407."""
        return {
            "kind": "gas_multi_sharded",
            "fn": self._step,
            "args": (self.init_state([start]), self._dg),
            "donate": (0,),
            "carry": (0,),
            "sharded": True,
            "exchange_mode": self.exchange_mode,
            "exchange_bytes": self.exchange_bytes_per_iter(),
            "combiner": getattr(self.program, "combiner", ""),
            "value_dtype": np.dtype(
                getattr(self.program, "value_dtype", np.uint32)).name,
            "num_parts": self.num_parts,
            "k": self.k,
            "plan": self._xplan,
        }
