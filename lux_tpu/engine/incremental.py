"""Incremental recompute: warm-start push fixpoints from a prior snapshot.

Gunrock's frontier-operator framing (arXiv:1501.05387) makes incremental
recompute a non-event: a fixpoint engine that already advances a frontier
doesn't care whether the frontier came from ``init_frontier`` or from the
set of vertices an edit batch touched. This module computes that touched
set on the host and hands the existing push executors a warm
:class:`~lux_tpu.engine.push.PushState` — same shapes, same jitted
executables, zero new compiles on a warmed pool.

Invalidation (the only subtle part) is per monotone-combiner program
(SSSP min, components max):

- *Seeds*: a removed edge ``u -> v`` invalidates ``v`` iff it supported
  v's old value — ``relax(old[u], w) == old[v]`` and ``old[v]`` is not
  v's init value (init values need no support).
- *Propagation*: a BFS over the NEW graph's out-edges resets ``b`` when a
  reset vertex ``a`` supported ``old[b]`` through a surviving edge, using
  the ORIGINAL old values for every support test.
- Reset vertices restart from their init values; everything else keeps
  its old fixpoint value.

Soundness is no longer argued here by hand — it is machine-checked.
The sketch: every non-reset vertex retains a support chain realizing
its old value, so warm values are pointwise-achievable in the new
graph, and a *monotone* push iteration from the warm frontier converges
to exactly the full-recompute fixpoint. The load-bearing premises —
idempotent monotone merge, ``apply`` == combiner merge, inflationary
and monotone ``relax`` — are exactly the LUX604 monotone-convergence
proof ``luxlint --programs`` runs offline (analysis/gasck.py), and
:class:`IncrementalExecutor` refuses construction with a typed
:class:`~lux_tpu.analysis.gasck.ProgramContractError` naming the failed
sub-check for any program that does not carry the proof.
tests/test_incremental.py still asserts the end result: bitwise parity
against from-scratch runs and host oracles.

PageRank is not a monotone push program; :func:`incremental_pagerank`
warm-starts the pull iteration from the previous ranks (re-divided by
the new out-degrees) and runs to an L-inf tolerance instead.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.push import (MultiSourcePushExecutor, PushExecutor,
                                 PushState)
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import recorder_for
from lux_tpu.utils import faults


def _relax_np(program, vals: np.ndarray, w) -> np.ndarray:
    """Host-side view of the program's relax (one tiny jnp eval)."""
    return np.asarray(program.relax(
        jnp.asarray(vals), None if w is None else jnp.asarray(w)
    ))


def _gather_slices(ptr: np.ndarray, ids: np.ndarray):
    """Flat indices of ``[ptr[i], ptr[i+1])`` for every i in ``ids``,
    plus ``np.repeat(ids, counts)`` — the vectorized adjacency expansion
    used by the host BFS (no per-vertex Python loop)."""
    starts = ptr[ids]
    counts = (ptr[ids + 1] - starts).astype(np.int64)
    total = int(counts.sum())
    if not total:
        e = np.zeros(0, dtype=np.int64)
        return e, e
    offs = np.arange(total, dtype=np.int64) - np.repeat(
        np.cumsum(counts) - counts, counts
    )
    return np.repeat(starts.astype(np.int64), counts) + offs, np.repeat(
        ids, counts
    )


def invalidate(program, graph: Graph, old_values: np.ndarray,
               init_values: np.ndarray, rem_src, rem_dst,
               rem_w) -> np.ndarray:
    """Boolean mask of vertices whose old values lose support under the
    edit batch (see module docstring for the exact rule)."""
    nv = graph.nv
    reset = np.zeros(nv, dtype=bool)
    rem_src = np.asarray(rem_src, dtype=np.int64)
    rem_dst = np.asarray(rem_dst, dtype=np.int64)
    if rem_src.size:
        cand = _relax_np(program, old_values[rem_src], rem_w)
        hit = (cand == old_values[rem_dst]) & (
            old_values[rem_dst] != init_values[rem_dst]
        )
        frontier = np.unique(rem_dst[hit])
    else:
        frontier = np.zeros(0, dtype=np.int64)
    reset[frontier] = True
    csr = graph.csr()
    while frontier.size:
        idx, a = _gather_slices(csr.row_ptr, frontier)
        if not idx.size:
            break
        b = csr.col_dst[idx].astype(np.int64)
        w = csr.weights[idx] if csr.weights is not None else None
        cand = _relax_np(program, old_values[a], w)
        hit = (cand == old_values[b]) & (
            old_values[b] != init_values[b]
        ) & ~reset[b]
        frontier = np.unique(b[hit])
        reset[frontier] = True
    return reset


def _warm_column(program, graph: Graph, old_values: np.ndarray,
                 removed, inserted, **init_kw):
    """(values, frontier, n_reset) for one root/lane, host-side."""
    old_values = np.asarray(old_values)
    init_values = np.asarray(program.init_values(graph, **init_kw))
    if old_values.shape != init_values.shape:
        raise ValueError(
            f"old values shape {old_values.shape} != graph shape "
            f"{init_values.shape}; snapshots never change nv"
        )
    rem_src, rem_dst, rem_w = removed if removed is not None else ((), (), None)
    reset = invalidate(program, graph, old_values, init_values,
                       rem_src, rem_dst, rem_w)
    vals = np.where(reset, init_values, old_values).astype(old_values.dtype)
    fr = np.zeros(graph.nv, dtype=bool)
    ridx = np.nonzero(reset)[0]
    fr[ridx] = True
    if ridx.size:
        # In-neighbors of the reset region in the NEW graph: the vertices
        # whose surviving values refill it.
        idx, _ = _gather_slices(graph.row_ptr, ridx)
        fr[graph.col_src[idx]] = True
    if inserted is not None and len(inserted[0]):
        fr[np.asarray(inserted[0], dtype=np.int64)] = True
    return vals, fr, int(ridx.size)


class IncrementalExecutor:
    """Warm-started push fixpoints over an edit batch.

    Wraps a (possibly pool-warmed) :class:`PushExecutor` and optionally a
    :class:`MultiSourcePushExecutor` for the NEW graph; ``run``/
    ``run_multi`` take the previous snapshot's fixpoint values plus the
    ``removed``/``inserted`` edge arrays and drive the wrapped engines
    from the warm state — identical shapes, so a warmed executable never
    recompiles.

    ``removed`` is ``(src, dst, w|None)`` of the base edges actually
    removed (see :func:`lux_tpu.graph.delta.removed_edges`); ``inserted``
    is ``(src, dst[, w])`` of the edges added.
    """

    def __init__(self, graph: Graph, program, push: Optional[PushExecutor] = None,
                 multi: Optional[MultiSourcePushExecutor] = None,
                 k: Optional[int] = None, device=None):
        # The warm-start argument above holds only for programs with the
        # LUX604 monotone-convergence proof; this raises
        # ProgramContractError (naming the failed sub-check) otherwise.
        from lux_tpu.analysis.gasck import require_incremental

        require_incremental(program)
        self.graph = graph
        self.program = program
        self.device = device
        self.push = push if push is not None else PushExecutor(
            graph, program, device=device
        )
        self.multi = multi
        if self.multi is None and k is not None:
            self.multi = MultiSourcePushExecutor(graph, program, k,
                                                 device=device)

    # -- single source ---------------------------------------------------

    def warm_state(self, old_values, removed=None, inserted=None, **init_kw):
        """Device-resident warm ``PushState`` + an info dict
        (``reset``/``frontier``/``touched_frac``)."""
        vals, fr, n_reset = _warm_column(
            self.program, self.graph, old_values, removed, inserted,
            **init_kw
        )
        state = PushState(
            jax.device_put(jnp.asarray(vals), self.device),
            jax.device_put(jnp.asarray(fr), self.device),
        )
        info = {
            "reset": n_reset,
            "frontier": int(fr.sum()),
            "touched_frac": float(fr.sum() / max(self.graph.nv, 1)),
        }
        return state, info

    def run(self, old_values, removed=None, inserted=None,
            max_iters: Optional[int] = None, chunk: int = 16,
            recorder=None, **init_kw):
        """Fixpoint from the warm state; returns ``(state, iters, info)``
        with ``state.values`` bitwise-equal to a from-scratch run."""
        faults.point("serve.engine.execute")
        state, info = self.warm_state(old_values, removed, inserted,
                                      **init_kw)
        if recorder is None:
            # Label the warm-started fixpoint as this engine's run, not
            # the inner push executor's (the delegate starts/finishes
            # whatever recorder it is handed).
            recorder = recorder_for("incremental", self.graph,
                                    self.program)
        state, iters = self.push.run(max_iters=max_iters, state=state,
                                     chunk=chunk, recorder=recorder)
        return state, iters, info

    # -- multi source (dense (nv, K) sweep) ------------------------------

    def run_multi(self, starts, old_columns, removed=None, inserted=None,
                  max_iters: Optional[int] = None, chunk: int = 16,
                  recorder=None):
        """Warm the K-lane sweep: lane j restarts root ``starts[j]`` from
        ``old_columns[j]``. Fewer than k roots are right-padded exactly
        like ``init_state`` so the warmed executable is reused."""
        if self.multi is None:
            raise ValueError("no MultiSourcePushExecutor attached")
        faults.point("serve.engine.execute")
        starts = list(starts)
        cols = list(old_columns)
        if len(starts) != len(cols):
            raise ValueError("one old-value column per root required")
        if not 1 <= len(starts) <= self.multi.k:
            raise ValueError(
                f"need 1..{self.multi.k} roots, got {len(starts)}"
            )
        pad = self.multi.k - len(starts)
        starts = starts + [starts[-1]] * pad
        cols = cols + [cols[-1]] * pad
        vals_cols, fr_cols, resets = [], [], 0
        for s, old in zip(starts, cols):
            v, f, r = _warm_column(self.program, self.graph, old, removed,
                                   inserted, start=s)
            vals_cols.append(v)
            fr_cols.append(f)
            resets += r
        state = PushState(
            jax.device_put(jnp.asarray(np.stack(vals_cols, axis=1)),
                           self.device),
            jax.device_put(jnp.asarray(np.stack(fr_cols, axis=1)),
                           self.device),
        )
        fsum = int(sum(int(f.sum()) for f in fr_cols))
        info = {
            "reset": resets,
            "frontier": fsum,
            "touched_frac": float(
                fsum / max(self.graph.nv * self.multi.k, 1)
            ),
        }
        if recorder is None:
            recorder = recorder_for("incremental", self.graph,
                                    self.program)
        state, iters = self.multi.run(starts, max_iters=max_iters,
                                      chunk=chunk, recorder=recorder,
                                      state=state)
        return state, iters, info

    # -- pool / luxlint-IR hooks -----------------------------------------

    def warmup(self, chunk: int = 16, **init_kw):
        self.push.warmup(chunk=chunk, **init_kw)

    def trace_step(self, **init_kw):
        """luxlint-IR hook: the wrapped push step entered through a warm
        state built from an empty edit batch — same executable signature
        the incremental path runs, audited as its own target kind."""
        init = np.asarray(self.program.init_values(self.graph, **init_kw))
        state, _ = self.warm_state(init, **init_kw)
        return {
            "kind": "push_incremental",
            "fn": self.push._step,
            "args": (state, self.push._dg),
            "donate": (0,),
            "carry": (0,),
            "sharded": False,
        }


def incremental_pagerank(executor, old_stored: np.ndarray,
                         old_out_degrees: np.ndarray, ni: int,
                         tol: float = 1e-7, chunk: int = 8):
    """Warm-start PageRank on ``executor``'s (new) graph from the
    previous snapshot's stored ranks.

    The pull engine stores ranks pre-divided by out-degree; degrees
    change under edits, so the warm vector is the previous *true* ranks
    re-divided by the NEW degrees. Iterates in ``chunk`` steps until the
    stored vector moves less than ``tol`` (L-inf) or ``ni`` iterations —
    parity with a from-scratch run is tolerance-based, matching the
    app's float semantics (the serving path keeps full ``ni``-from-init
    recomputes for its cache; see serve/session.py).

    Returns ``(stored_values, iters_run)``.
    """
    from lux_tpu.models.pagerank import true_ranks

    g = executor.graph
    true = np.asarray(true_ranks(np.asarray(old_stored),
                                 np.asarray(old_out_degrees)))
    new_deg = g.out_degrees
    warm = np.where(new_deg == 0, true,
                    true / np.maximum(new_deg, 1)).astype(np.float32)
    vals = warm
    iters = 0
    while iters < ni:
        step = min(chunk, ni - iters)
        # Compare on host copies: the pull step donates its input buffer,
        # so the device array handed to run() is dead afterwards.
        prev = np.asarray(vals)
        vals = np.asarray(executor.run(step, vals=jnp.asarray(prev)))
        iters += step
        if float(np.max(np.abs(vals - prev))) < tol:
            break
    return vals, iters
