"""Vertex-program abstraction.

The reference hardcodes each application's per-edge and per-vertex logic in
a CUDA kernel (`pr_kernel` pagerank/pagerank_gpu.cu:49-102, `cf_kernel`
col_filter/colfilter_gpu.cu:32-104, ...). Here an application is a
:class:`PullProgram` (or :class:`PushProgram`, see push.py): three pure
functions the engine traces into one fused XLA computation —

    contrib_e = edge_contrib(src_val_e, dst_val_e, weight_e)   # per edge
    acc_v     = combine(contrib_e for e into v)                # segment reduce
    new_v     = apply(old_v, acc_v, ctx)                       # per vertex

Everything is vectorized over edges/vertices (no per-element Python), so
XLA fuses gather + elementwise into the reduction and the MXU/VPU see
large dense ops.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class VertexCtx:
    """Per-vertex context available to ``apply`` (local shard slice)."""

    nv: int                      # global vertex count (static)
    out_degrees: jnp.ndarray     # (local_nv,) out-degree per vertex
    in_degrees: jnp.ndarray      # (local_nv,)


@dataclasses.dataclass(frozen=True)
class EdgeCtx:
    """Per-edge context for ``edge_contrib``; every field is (ne_local, ...)."""

    src_vals: jnp.ndarray
    dst_vals: jnp.ndarray
    weights: Optional[jnp.ndarray]


class PullProgram:
    """Base class for gather-apply (pull) vertex programs.

    Subclasses set ``combiner`` and override the three hooks. Unused
    gathers (e.g. ``dst_vals`` for PageRank) are dead-code-eliminated by
    XLA, so there is no cost to the uniform signature.
    """

    name: str = "pull"
    combiner: str = "sum"             # 'sum' | 'min' | 'max'
    value_dtype = jnp.float32
    value_shape: Tuple[int, ...] = ()  # trailing per-vertex dims, e.g. (K,)
    needs_weights: bool = False
    rooted: bool = False              # takes a per-query `start` root
    servable: bool = True             # exposed through serve/session.py
    # Machine-checked capability claims (luxlint --programs, LUX606):
    # pull programs run dense fixed-iteration sweeps, so neither the
    # frontier-annihilation license nor the incremental warm-start
    # applies by default.
    frontier_ok: bool = False
    incremental_ok: bool = False
    # True iff edge_contrib(e) == e.src_vals (an SpMV-shaped iteration);
    # unlocks the MXU tiled-hybrid executor (engine/tiled.py).
    identity_contrib: bool = False

    # -- hooks -----------------------------------------------------------

    def init_values(self, graph) -> np.ndarray:
        """Host-side initial vertex values, shape (nv, *value_shape)."""
        raise NotImplementedError

    def edge_contrib(self, edge: EdgeCtx) -> jnp.ndarray:
        """Per-edge contribution toward the destination's accumulator."""
        raise NotImplementedError

    def apply(self, old_vals: jnp.ndarray, acc: jnp.ndarray, ctx: VertexCtx):
        """Combine accumulator with the old value into the new value."""
        raise NotImplementedError


def as_gas(program):
    """Adapt any registered program model (PullProgram, PushProgram, or a
    native GasProgram) to the gather-apply-scatter abstraction the
    adaptive executor runs (engine/gas.py). The adapters subclass
    GasProgram, so they live there; this is the import-cycle-free entry
    point the registry/serving layers use."""
    from lux_tpu.engine import gas

    return gas.as_gas(program)
