"""Single-device pull executor.

Runs a :class:`PullProgram` as one jitted step over the whole CSC graph in
HBM. The reference's equivalent path is
pull_app_task_impl → load_kernel + pr_kernel + copy-back
(pagerank/pagerank_gpu.cu:104-151); on TPU there is no ZC staging or
copy-back — the values live in HBM across iterations and the step is a
single fused XLA computation. Iteration pipelining (the reference launches
all `-ni` waves and waits once, pagerank/pagerank.cc:106-114) falls out of
JAX async dispatch: `run()` enqueues every step and blocks once at the end.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import EdgeCtx, PullProgram, VertexCtx
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import (
    NULL_RECORDER,
    consume_compile_seconds,
    note_compile_seconds,
    recorder_for,
)
from lux_tpu.ops.segment import segment_reduce, segment_sum_by_rowptr
from lux_tpu.utils import flags
from lux_tpu.utils.timing import Timer


def _edge_index_dtype(ne: int):
    """Device dtype for edge offsets (row_ptr): int32 below 2^31 edges,
    int64 at the reference's E_ID=uint64 headroom (README.md:79-86).

    int64 on device requires ``jax_enable_x64``; without it JAX silently
    downcasts to int32, which would overflow — fail loudly instead."""
    if ne < 2**31:
        return jnp.int32
    import jax

    if not jax.config.jax_enable_x64:
        raise ValueError(
            f"graph has {ne} >= 2^31 edges: edge offsets need int64 on "
            "device; enable it with jax.config.update('jax_enable_x64', "
            "True) (or JAX_ENABLE_X64=1) before building the executor"
        )
    return jnp.int64


def hard_sync(x):
    """Wait until ``x`` is actually materialized on device.

    ``jax.block_until_ready`` can return early on tunneled/async backends
    (observed on the axon TPU relay: it returned in 1.6ms while the queued
    work took 28s); fetching one element forces real completion through
    the dataflow dependency."""
    jax.block_until_ready(x)
    leaf = jax.tree_util.tree_leaves(x)[0]
    jax.device_get(leaf.ravel()[:1])
    return x


def run_pipelined(step, vals, num_iters: int, flush_every: int = 8,
                  recorder=None):
    """Launch ``num_iters`` async step waves, blocking only every
    ``flush_every`` iterations. The reference pipelines all waves and waits
    once (pagerank.cc:106-114); we additionally bound in-flight depth the
    way its push model bounds SLIDING_WINDOW, so the dispatch queue — and
    on CPU meshes the collective rendezvous — can't grow unboundedly.

    ``recorder`` (an obs.IterationRecorder) is flushed only at the
    host-sync points, so disabled-mode cost is one no-op call per flush."""
    rec = recorder if recorder is not None else NULL_RECORDER
    for i in range(num_iters):
        vals = step(vals)
        if flush_every and (i + 1) % flush_every == 0:
            # Bounded-depth flush: this sync IS the point of the
            # pipelined path (caps in-flight dispatch like the
            # reference's SLIDING_WINDOW).
            jax.block_until_ready(vals)  # luxlint: disable=LUX001 -- designed flush point, one sync per flush_every iters
            rec.flush(i + 1)
    vals = hard_sync(vals)
    rec.flush(num_iters)
    return vals


def make_fused_runner(step_fn):
    """One jitted dispatch for N iterations: ``lax.fori_loop`` over the
    step with a *dynamic* trip count (no recompile per N).

    Per-call dispatch costs ~130-300 ms through the tunneled backend
    (PERF.md) and, unlike the reference's Legion futures (whose waves
    pipeline, pagerank.cc:106-114), it is NOT hidden by async dispatch —
    measured: 20 separate step calls ran at 620 ms/iter while the same
    step inside one fori_loop ran at 316 ms/iter. Executors route
    ``run(..., flush_every=0)`` ("never sync with the host") here.
    """
    def _run(vals, n, *args):
        return jax.lax.fori_loop(
            0, n, lambda i, v: step_fn(v, *args), vals
        )

    return jax.jit(_run, donate_argnums=0)


def run_maybe_fused(jrun, step, vals, num_iters: int, flush_every: int, *args,
                    recorder=None):
    """Shared run() body: ``flush_every=0`` = no host syncs at all (the
    whole loop on device in one fused dispatch, dynamic trip count);
    ``k>0`` = per-step dispatch, blocking every k iterations.

    With telemetry on, the fused path first issues a zero-trip dispatch:
    ``jrun`` has a dynamic trip count, so n=0 compiles the same
    executable as n=num_iters without running an iteration — that splits
    compile time from execute time on first call. Disabled mode skips the
    probe entirely (one predicate check, no extra dispatch)."""
    rec = recorder if recorder is not None else NULL_RECORDER
    if flush_every == 0:
        if rec.enabled:
            with Timer() as t:
                vals = hard_sync(jrun(vals, jnp.int32(0), *args))
            rec.record_compile(t.elapsed)
        vals = hard_sync(jrun(vals, jnp.int32(num_iters), *args))
        rec.flush(num_iters)
        return vals
    return run_pipelined(step, vals, num_iters, flush_every, recorder=rec)


@dataclasses.dataclass
class _DeviceGraph:
    """CSC arrays resident on one device."""

    col_src: jnp.ndarray          # (ne,) int32 — edge source ids
    seg_ids: jnp.ndarray          # (ne,) int32 — edge destination ids (sorted)
    row_ptr: jnp.ndarray          # (nv+1,) int — CSC offsets
    weights: Optional[jnp.ndarray]
    out_degrees: jnp.ndarray      # (nv,) int32
    in_degrees: jnp.ndarray       # (nv,) int32


@dataclasses.dataclass
class _ChunkedGraph:
    """CSC arrays chunked for a ``lax.scan`` over edge windows, plus the
    per-chunk row-boundary plan (host-precomputed).

    The flat engine materializes the full (ne, *value_shape) contribution
    array; at NetFlix scale (201M edges x K=20 f32 = 16 GB) that exceeds
    HBM (cf. the reference's full-nv H2D per iteration instead,
    col_filter/colfilter.cc driver). Here contributions only ever exist
    as one (C, K) chunk inside the scan; per-destination sums come from
    chunk-local cumsums gathered at the row boundaries falling in each
    chunk (``bnd_pos``, a scan input) and rebased across chunks with a
    double-single prefix over chunk totals — the K-wide generalization of
    the tiled engine's Z-stream reduction (ops/tiled_spmv.py), with
    dynamic per-chunk boundaries instead of plan-time-static ones.
    """

    col_src: jnp.ndarray          # (nchunks, C) int32, pad 0
    seg_ids: jnp.ndarray          # (nchunks, C) int32, pad 0
    weights: Optional[jnp.ndarray]   # (nchunks, C) or None
    bnd_pos: jnp.ndarray          # (nchunks, R) int32 local cumsum positions
    gather_idx: jnp.ndarray       # (nv+1,) int32 into (nchunks*R,) emits
    bnd_chunk: jnp.ndarray        # (nv+1,) int32 chunk of each boundary
    dst_lo: jnp.ndarray           # (nchunks,) int32 clamped dst-slice starts
    src_lo: jnp.ndarray           # (nchunks,) int32 clamped src-band starts
    src_banded: jnp.ndarray       # (nchunks,) bool — chunk uses the band
    out_degrees: jnp.ndarray      # (nv,) int32
    in_degrees: jnp.ndarray       # (nv,) int32


def _chunk_boundary_plan(row_ptr: np.ndarray, ne: int, chunk: int):
    """Assign each of the nv+1 row boundaries to the edge chunk it falls
    in. Returns (nchunks, bnd_pos (nchunks, R), gather_idx (nv+1,),
    bnd_chunk (nv+1,)); R is the worst-case boundaries per chunk."""
    nchunks = max(-(-ne // chunk), 1)
    rp = row_ptr.astype(np.int64)
    cidx = np.minimum(rp // chunk, nchunks - 1)
    lpos = (rp - cidx * chunk).astype(np.int32)          # ∈ [0, C]
    cnt = np.bincount(cidx, minlength=nchunks)
    starts = np.zeros(nchunks, np.int64)
    np.cumsum(cnt[:-1], out=starts[1:])
    rank = np.arange(rp.shape[0], dtype=np.int64) - starts[cidx]
    r_max = max(int(cnt.max()), 1)
    # The emit table is padded to the most boundary-dense chunk; if that
    # approaches one slot per edge, chunking no longer compresses and the
    # stacked emits would rival the flat (ne, K) array this path avoids.
    if nchunks * r_max >= 2**31 or nchunks * r_max > max(ne, 1):
        raise ValueError(
            f"edge-chunked plan does not compress: {nchunks} chunks x "
            f"{r_max} boundaries/chunk vs {ne} edges — a run of near-empty "
            "rows packs too many boundaries into one chunk; raise the edge "
            "chunk size or reorder vertices"
        )
    bnd_pos = np.zeros((nchunks, r_max), np.int32)
    bnd_pos[cidx, rank] = lpos
    gather_idx = (cidx * r_max + rank).astype(np.int32)
    return nchunks, bnd_pos, gather_idx, cidx.astype(np.int32)


# Auto edge-chunking threshold: flat contributions above this many bytes
# route through the scan path (override via the LUX_EDGE_CHUNK_BYTES
# flag; the default lives in the utils/flags.py registry).
EDGE_CHUNK_AUTO_BYTES = flags.default("LUX_EDGE_CHUNK_BYTES")
DEFAULT_EDGE_CHUNK = 1 << 20
# Ceiling for the boundary-dense degrade path (growing windows / flat
# fallback): any single contribution allocation past this is refused in
# favor of the actionable "does not compress" error (v5e HBM is 16 GB).
DEGRADE_CAP_BYTES = 4 << 30


def _dst_slice_plan(col_dst: np.ndarray, ne: int, chunk: int, nv: int):
    """Per-chunk dst-slice starts for the chunked engine's gather-cliff fix.

    Edges are dst-sorted, so each edge chunk touches a narrow contiguous
    band of destination rows. Gathering ``dst_vals`` from a per-chunk
    ``dynamic_slice`` of the value table instead of the full table keeps
    the gather under the big-table cliff (measured on the NetFlix-shaped
    CF bench: a src+dst gather+dot from the 255 MB lane-padded table runs
    at 22.2 ns/edge vs ~1.8 ns for sub-48MB tables — PERF.md "CF /
    edge-chunked engine").

    Returns ``(span, dst_lo)``: the static slice height (max band over
    chunks, sublane-rounded) and the (nchunks,) clamped slice starts.
    Starts are pre-clamped to ``nv - span`` on the host so the in-jit
    local index ``cd - dst_lo`` is always within [0, span) for real
    edges — no value-table padding needed.
    """
    nchunks = max(-(-ne // chunk), 1)
    if ne == 0:
        return 0, np.zeros(nchunks, np.int32)
    starts = np.arange(nchunks, dtype=np.int64) * chunk
    ends = np.minimum(starts + chunk, ne) - 1
    lo = col_dst[starts].astype(np.int64)
    hi = col_dst[ends].astype(np.int64)
    span = int((hi - lo).max()) + 1
    span = min(-(-span // 8) * 8, nv)
    dst_lo = np.minimum(lo, nv - span).astype(np.int32)
    return span, np.maximum(dst_lo, 0)


def _src_slice_plan(col_src: np.ndarray, ne: int, chunk: int, nv: int,
                    row_bytes: int):
    """Per-chunk SOURCE-band plan for the chunked engine.

    Unlike destinations, sources are not sorted — but structured graphs
    give many chunks a narrow source RANGE anyway: in the NetFlix-shaped
    bipartite CF graph every user-destination chunk draws sources only
    from the item id range (a ~9 MB band of the 255 MB value table —
    the PERF.md round-2 "item-side src slice" lever). Chunks whose
    source span fits under the big-table gather cliff serve ``src_vals``
    from a per-chunk ``dynamic_slice`` (selected per chunk by a traced
    ``lax.cond`` flag); wide chunks keep the full-table gather.

    Returns ``(span, src_lo, banded)``: the static slice height (max
    span over BANDED chunks; 0 = no chunk qualifies), clamped starts,
    and the per-chunk flag array.
    """
    from lux_tpu.ops.tiled_spmv import GATHER_TABLE_BYTES

    nchunks = max(-(-ne // chunk), 1)
    zero = (0, np.zeros(nchunks, np.int32), np.zeros(nchunks, bool))
    if ne == 0:
        return zero
    edges = np.arange(nchunks + 1, dtype=np.int64) * chunk
    edges[-1] = ne
    lo = np.minimum.reduceat(col_src[:ne], edges[:-1]).astype(np.int64)
    hi = np.maximum.reduceat(col_src[:ne], edges[:-1]).astype(np.int64)
    spans = hi - lo + 1
    cap = max(GATHER_TABLE_BYTES // max(row_bytes, 1), 1)
    banded = spans <= cap
    if not banded.any() or nv <= cap:
        # nv <= cap: the full table is already under the cliff.
        return zero
    span = int(spans[banded].max())
    span = min(-(-span // 8) * 8, nv)
    src_lo = np.clip(lo, 0, nv - span).astype(np.int32)
    return span, src_lo, banded


def lane_pad_width(value_shape) -> tuple:
    """(kreal, kpad) lane-padding policy for K-vector vertex values.

    Gathers of rows narrower than the 128-lane tile scalarize on TPU
    (~76.5 s/iter measured on NetFlix-shaped CF before padding); rank-1
    value shapes whose width is not a lane multiple get padded to the
    next multiple of 128. kpad == 0 means "no padding applies"."""
    vshape = tuple(value_shape or ())
    kreal = int(np.prod(vshape)) if vshape else 0
    kpad = (-(-kreal // 128)) * 128 if (
        len(vshape) == 1 and kreal % 128
    ) else 0
    return kreal, kpad


class PullExecutor:
    """Executes a pull program on a single device (CPU or one TPU chip).

    Sum-combiner programs whose flat (ne, *value_shape) contribution
    array would exceed ~2 GB run edge-chunked (``_ChunkedGraph``): a
    ``lax.scan`` over edge windows so NetFlix-scale CF (16 GB flat) fits
    in HBM. ``edge_chunk`` forces chunked with the given window;
    ``edge_chunk=0`` forces flat."""

    def __init__(
        self,
        graph: Graph,
        program: PullProgram,
        sum_strategy: str = "rowptr",   # 'rowptr' (scatter-free) | 'segment'
        device=None,
        edge_chunk: Optional[int] = None,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.sum_strategy = sum_strategy
        self.device = device
        put = lambda x: jax.device_put(jnp.asarray(x), device)

        vshape = tuple(getattr(program, "value_shape", ()) or ())
        width = int(np.prod(vshape)) if vshape else 1
        if edge_chunk is None:
            limit = flags.get_int("LUX_EDGE_CHUNK_BYTES")
            flat_bytes = graph.ne * width * np.dtype(np.float32).itemsize
            self.edge_chunk = (
                DEFAULT_EDGE_CHUNK
                if (program.combiner == "sum" and flat_bytes > limit)
                else 0
            )
        else:
            self.edge_chunk = edge_chunk
        if self.edge_chunk and program.combiner != "sum":
            raise ValueError(
                "edge-chunked execution needs a sum combiner "
                f"({program.name} has {program.combiner!r})"
            )

        # Lane padding for K-vector values on the chunked path: a gather
        # of (C, 20)-wide rows scalarizes on TPU (~765 ns/edge measured
        # on the NetFlix-shaped CF bench) because 20 < the 128-lane tile;
        # padding values to (nv, 128) makes every gather a full-bandwidth
        # 512 B row fetch and the chunk cumsum full-lane. Pad lanes are
        # re-zeroed after apply so programs whose apply adds constants
        # cannot leak garbage into the next iteration's contractions.
        self._kreal, self._kpad = lane_pad_width(vshape)

        chunk_plan = None
        if self.edge_chunk:
            # On the AUTO-selected path a boundary-dense graph (a run of
            # near-empty rows packed into one edge window) must degrade,
            # not fail: retry with growing windows (fewer chunks bounds
            # the padded emit table), then fall back to the flat engine.
            # Degrading is only legal while the resulting contribution
            # window stays under an absolute allocation cap — otherwise
            # the "fallback" would be the very HBM-scale array chunking
            # exists to avoid, traded for a silent OOM. An explicit
            # edge_chunk override keeps the hard error either way.
            C = self.edge_chunk
            w_eff = max(self._kpad or self._kreal, 1)   # chunked row width
            w_flat = max(self._kreal, 1)                # flat keeps layout
            while True:
                try:
                    chunk_plan = _chunk_boundary_plan(
                        graph.row_ptr, graph.ne, C
                    )
                    self.edge_chunk = C
                    break
                except ValueError:
                    if edge_chunk is not None:
                        raise
                    nxt = min(C * 4, max(graph.ne, 1))
                    if C < graph.ne and nxt * w_eff * 4 <= DEGRADE_CAP_BYTES:
                        C = nxt
                        continue
                    if graph.ne * w_flat * 4 <= DEGRADE_CAP_BYTES:
                        import warnings

                        warnings.warn(
                            "edge-chunked plan does not compress on this "
                            "graph — degrading to the flat engine "
                            f"({graph.ne * w_flat * 4 >> 20} MB flat "
                            "contributions)"
                        )
                        self.edge_chunk = 0
                        break
                    raise   # no safe degrade: surface the actionable error
        if not self.edge_chunk:
            self._kpad = 0   # the flat path keeps the external layout

        if self.edge_chunk:
            C = self.edge_chunk
            nchunks, bnd_pos, gidx, bchunk = chunk_plan
            pad = nchunks * C - graph.ne

            # dst-slice gather (see _dst_slice_plan): auto-on when the
            # slice traffic (nchunks x span rows/iter) is well under the
            # edge gather traffic it replaces; LUX_DST_SLICE=0/1 overrides.
            span, dst_lo = _dst_slice_plan(
                graph.col_dst, graph.ne, C, graph.nv
            )
            knob = flags.tristate("LUX_DST_SLICE", strict=False)
            auto = 0 < span < graph.nv and nchunks * span <= graph.ne // 2
            self._dst_span = span if (
                (knob is True and span < graph.nv)
                or (knob is not False and auto)
            ) else 0

            # Source-band gathers (per-chunk lax.cond — see
            # _src_slice_plan); LUX_SRC_SLICE=0/1 overrides the auto-on.
            row_b = max(self._kpad or self._kreal, 1) * 4
            span_s, src_lo, src_banded = _src_slice_plan(
                graph.col_src, graph.ne, C, graph.nv, row_b
            )
            sknob = flags.tristate("LUX_SRC_SLICE", strict=False)
            # Traffic guard (mirrors the dst path's): each banded chunk
            # pays ~2*span rows of slice copy to save ~C rows of
            # big-table gather at ~5x the sub-cliff rate — only a clear
            # win while the span stays within a couple of chunk sizes.
            s_auto = 0 < span_s <= 2 * C
            self._src_span = span_s if (
                (sknob is True and span_s)
                or (sknob is not False and s_auto)
            ) else 0

            def padded(a):
                return np.pad(a, (0, pad)).reshape(nchunks, C)

            self.dgraph = _ChunkedGraph(
                col_src=put(padded(graph.col_src.astype(np.int32))),
                seg_ids=put(padded(graph.col_dst.astype(np.int32))),
                weights=(
                    None if graph.weights is None
                    else put(padded(graph.weights))
                ),
                bnd_pos=put(bnd_pos),
                gather_idx=put(gidx),
                bnd_chunk=put(bchunk),
                dst_lo=put(dst_lo),
                src_lo=put(src_lo),
                src_banded=put(src_banded),
                out_degrees=put(graph.out_degrees.astype(np.int32)),
                in_degrees=put(graph.in_degrees.astype(np.int32)),
            )
        else:
            self._dst_span = 0
            self._src_span = 0
            eidx = _edge_index_dtype(graph.ne)
            self.dgraph = _DeviceGraph(
                col_src=put(graph.col_src.astype(np.int32)),
                seg_ids=put(graph.col_dst),
                row_ptr=put(graph.row_ptr.astype(eidx)),
                weights=None if graph.weights is None else put(graph.weights),
                out_degrees=put(graph.out_degrees.astype(np.int32)),
                in_degrees=put(graph.in_degrees.astype(np.int32)),
            )
        self._step = jax.jit(self._step_impl, donate_argnums=0)
        self._jrun = make_fused_runner(self._step_impl)

    # -- the jitted iteration -------------------------------------------

    def _step_impl(self, vals: jnp.ndarray, dg) -> jnp.ndarray:
        if self.edge_chunk:
            return self._chunked_step_impl(vals, dg)
        prog = self.program
        edge = EdgeCtx(
            src_vals=vals[dg.col_src],
            dst_vals=vals[dg.seg_ids],
            weights=dg.weights,
        )
        contrib = prog.edge_contrib(edge)
        if prog.combiner == "sum" and self.sum_strategy == "rowptr":
            acc = segment_sum_by_rowptr(contrib, dg.row_ptr)
        else:
            acc = segment_reduce(
                contrib, dg.seg_ids, num_segments=self.graph.nv,
                kind=prog.combiner,
            )
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg.out_degrees,
            in_degrees=dg.in_degrees,
        )
        return prog.apply(vals, acc, ctx)

    def _chunked_step_impl(
        self, vals: jnp.ndarray, dg: _ChunkedGraph
    ) -> jnp.ndarray:
        """Scan over edge windows; contributions never materialize beyond
        one (C, K) chunk. Per-destination sums are chunk-local cumsums
        gathered at each chunk's row boundaries, rebased with a
        double-single prefix over chunk totals (exactly the accuracy
        ladder of ops/tiled_spmv.py — boundary-diff error scales with
        chunk-local mass, not stream mass). Pad edges land after the last
        real boundary, so their garbage contributions are never gathered,
        and the polluted final chunk total is never used (the exclusive
        prefix stops before it).

        When lane padding is active (``self._kpad``), ``vals`` arrives
        and leaves (nv, kpad) — the fused runner keeps it padded across
        iterations; run()/step() convert at the boundary."""
        from lux_tpu.ops.tiled_spmv import _dd_prefix

        prog = self.program
        vshape = tuple(getattr(prog, "value_shape", ()) or ())
        kreal = int(np.prod(vshape)) if vshape else 1
        k = self._kpad or kreal

        def body(_, ch):
            cs, cd, w, bnd, dlo, slo, sbanded = ch
            if self._dst_span:
                # dst ids are sorted, so this chunk's dst rows live in a
                # narrow band: gather from a small dynamic slice instead
                # of the full value table (the big-table gather cliff —
                # PERF.md "CF / edge-chunked engine"). dlo is pre-clamped
                # on the host so cd - dlo ∈ [0, span) for real edges.
                band = jax.lax.dynamic_slice_in_dim(
                    vals, dlo, self._dst_span, axis=0
                )
                dst_vals = band[cd - dlo]
            else:
                dst_vals = vals[cd]
            if self._src_span:
                # Narrow-source chunks (e.g. the item-sourced user-dst
                # half of a bipartite ratings graph) serve src_vals from
                # a per-chunk band too; wide chunks keep the full-table
                # gather (per-chunk cond — see _src_slice_plan).
                src_vals = jax.lax.cond(
                    sbanded,
                    lambda: jax.lax.dynamic_slice_in_dim(
                        vals, slo, self._src_span, axis=0
                    )[jnp.clip(cs - slo, 0, self._src_span - 1)],
                    lambda: vals[cs],
                )
            else:
                src_vals = vals[cs]
            edge = EdgeCtx(
                src_vals=src_vals, dst_vals=dst_vals, weights=w,
            )
            contrib = prog.edge_contrib(edge)
            c2 = contrib.reshape(contrib.shape[0], k)
            z = jnp.cumsum(c2, axis=0)
            zf = jnp.concatenate([jnp.zeros((1, k), z.dtype), z])
            return 0, (zf[bnd], z[-1])

        w = dg.weights
        xs_tail = (dg.bnd_pos, dg.dst_lo, dg.src_lo, dg.src_banded)
        if w is None:
            _, (zb, totals) = jax.lax.scan(
                lambda c, ch: body(
                    c, (ch[0], ch[1], None) + tuple(ch[2:])
                ),
                0, (dg.col_src, dg.seg_ids) + xs_tail,
            )
        else:
            _, (zb, totals) = jax.lax.scan(
                body, 0, (dg.col_src, dg.seg_ids, w) + xs_tail
            )
        zg = zb.reshape(-1, k)[dg.gather_idx]           # (nv+1, k)
        ph, pl = _dd_prefix(totals)                     # (nchunks+1, k)
        ci = dg.bnd_chunk
        acc = (
            (zg[1:] - zg[:-1])
            + (ph[ci[1:]] - ph[ci[:-1]])
            + (pl[ci[1:]] - pl[ci[:-1]])
        )
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg.out_degrees,
            in_degrees=dg.in_degrees,
        )
        if not self._kpad:
            acc = acc.reshape((self.graph.nv,) + vshape)
            return prog.apply(vals, acc, ctx)
        new = prog.apply(vals, acc, ctx)
        # Re-zero pad lanes: apply may write constants into them, which
        # would otherwise pollute the next iteration's contractions.
        lane = jnp.arange(k, dtype=jnp.int32)
        return jnp.where(lane[None, :] < kreal, new, 0)

    # -- driver ----------------------------------------------------------

    def init_values(self) -> jnp.ndarray:
        return jax.device_put(
            jnp.asarray(self.program.init_values(self.graph)), self.device
        )

    def _lane_pad(self, vals: jnp.ndarray) -> jnp.ndarray:
        return jnp.pad(vals, ((0, 0), (0, self._kpad - self._kreal)))

    def step(self, vals: jnp.ndarray) -> jnp.ndarray:
        """One iteration; external (nv, *value_shape) in and out (the
        lane-padded internal layout is private to the jitted step)."""
        if self._kpad:
            padded = self._step(
                self._lane_pad(jnp.asarray(vals)), self.dgraph
            )
            return padded[:, : self._kreal]
        return self._step(vals, self.dgraph)

    def warmup(self):
        """Run one throwaway step through the run() path outside any timed
        region (the reference's kernels are compiled at build time, so its
        ELAPSED TIME never includes compilation; hard_sync also primes the
        transfer path on tunneled backends)."""
        with Timer() as t:
            hard_sync(self.step(self.init_values()))
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted step plus example
        args exactly as step() passes them — lane-padded for K-vector
        programs, so the audit sees the executable's real signature."""
        vals = self.init_values()
        if self._kpad:
            vals = self._lane_pad(jnp.asarray(vals))
        return {
            "kind": "pull",
            "fn": self._step,
            "args": (vals, self.dgraph),
            "donate": (0,),
            "carry": (0,),
            "sharded": False,
        }

    def run(
        self,
        num_iters: int,
        vals: Optional[jnp.ndarray] = None,
        flush_every: int = 8,
        recorder=None,
    ):
        if vals is None:
            vals = self.init_values()
        rec = recorder if recorder is not None else recorder_for(
            "pull", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            from lux_tpu.obs import engobs
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne, k=max(self._kreal, 1)))
        if self._kpad:
            padded = run_maybe_fused(
                self._jrun,
                lambda v: self._step(v, self.dgraph),
                self._lane_pad(jnp.asarray(vals)),
                num_iters, flush_every, self.dgraph,
                recorder=rec,
            )
            out = hard_sync(padded[:, : self._kreal])
        else:
            out = run_maybe_fused(
                self._jrun, self.step, vals, num_iters, flush_every,
                self.dgraph, recorder=rec,
            )
        rec.finish()
        return out


jax.tree_util.register_dataclass(
    _DeviceGraph,
    data_fields=["col_src", "seg_ids", "row_ptr", "weights", "out_degrees",
                 "in_degrees"],
    meta_fields=[],
)

jax.tree_util.register_dataclass(
    _ChunkedGraph,
    data_fields=["col_src", "seg_ids", "weights", "bnd_pos", "gather_idx",
                 "bnd_chunk", "dst_lo", "src_lo", "src_banded",
                 "out_degrees", "in_degrees"],
    meta_fields=[],
)
