"""Single-device pull executor.

Runs a :class:`PullProgram` as one jitted step over the whole CSC graph in
HBM. The reference's equivalent path is
pull_app_task_impl → load_kernel + pr_kernel + copy-back
(pagerank/pagerank_gpu.cu:104-151); on TPU there is no ZC staging or
copy-back — the values live in HBM across iterations and the step is a
single fused XLA computation. Iteration pipelining (the reference launches
all `-ni` waves and waits once, pagerank/pagerank.cc:106-114) falls out of
JAX async dispatch: `run()` enqueues every step and blocks once at the end.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import EdgeCtx, PullProgram, VertexCtx
from lux_tpu.graph.graph import Graph
from lux_tpu.ops.segment import segment_reduce, segment_sum_by_rowptr


def _edge_index_dtype(ne: int):
    """Device dtype for edge offsets (row_ptr): int32 below 2^31 edges,
    int64 at the reference's E_ID=uint64 headroom (README.md:79-86).

    int64 on device requires ``jax_enable_x64``; without it JAX silently
    downcasts to int32, which would overflow — fail loudly instead."""
    if ne < 2**31:
        return jnp.int32
    import jax

    if not jax.config.jax_enable_x64:
        raise ValueError(
            f"graph has {ne} >= 2^31 edges: edge offsets need int64 on "
            "device; enable it with jax.config.update('jax_enable_x64', "
            "True) (or JAX_ENABLE_X64=1) before building the executor"
        )
    return jnp.int64


def hard_sync(x):
    """Wait until ``x`` is actually materialized on device.

    ``jax.block_until_ready`` can return early on tunneled/async backends
    (observed on the axon TPU relay: it returned in 1.6ms while the queued
    work took 28s); fetching one element forces real completion through
    the dataflow dependency."""
    jax.block_until_ready(x)
    leaf = jax.tree_util.tree_leaves(x)[0]
    jax.device_get(leaf.ravel()[:1])
    return x


def run_pipelined(step, vals, num_iters: int, flush_every: int = 8):
    """Launch ``num_iters`` async step waves, blocking only every
    ``flush_every`` iterations. The reference pipelines all waves and waits
    once (pagerank.cc:106-114); we additionally bound in-flight depth the
    way its push model bounds SLIDING_WINDOW, so the dispatch queue — and
    on CPU meshes the collective rendezvous — can't grow unboundedly."""
    for i in range(num_iters):
        vals = step(vals)
        if flush_every and (i + 1) % flush_every == 0:
            jax.block_until_ready(vals)
    return hard_sync(vals)


def make_fused_runner(step_fn):
    """One jitted dispatch for N iterations: ``lax.fori_loop`` over the
    step with a *dynamic* trip count (no recompile per N).

    Per-call dispatch costs ~130-300 ms through the tunneled backend
    (PERF.md) and, unlike the reference's Legion futures (whose waves
    pipeline, pagerank.cc:106-114), it is NOT hidden by async dispatch —
    measured: 20 separate step calls ran at 620 ms/iter while the same
    step inside one fori_loop ran at 316 ms/iter. Executors route
    ``run(..., flush_every=0)`` ("never sync with the host") here.
    """
    def _run(vals, n, *args):
        return jax.lax.fori_loop(
            0, n, lambda i, v: step_fn(v, *args), vals
        )

    return jax.jit(_run, donate_argnums=0)


def run_maybe_fused(jrun, step, vals, num_iters: int, flush_every: int, *args):
    """Shared run() body: ``flush_every=0`` = no host syncs at all (the
    whole loop on device in one fused dispatch, dynamic trip count);
    ``k>0`` = per-step dispatch, blocking every k iterations."""
    if flush_every == 0:
        return hard_sync(jrun(vals, jnp.int32(num_iters), *args))
    return run_pipelined(step, vals, num_iters, flush_every)


@dataclasses.dataclass
class _DeviceGraph:
    """CSC arrays resident on one device."""

    col_src: jnp.ndarray          # (ne,) int32 — edge source ids
    seg_ids: jnp.ndarray          # (ne,) int32 — edge destination ids (sorted)
    row_ptr: jnp.ndarray          # (nv+1,) int — CSC offsets
    weights: Optional[jnp.ndarray]
    out_degrees: jnp.ndarray      # (nv,) int32
    in_degrees: jnp.ndarray       # (nv,) int32


class PullExecutor:
    """Executes a pull program on a single device (CPU or one TPU chip)."""

    def __init__(
        self,
        graph: Graph,
        program: PullProgram,
        sum_strategy: str = "rowptr",   # 'rowptr' (scatter-free) | 'segment'
        device=None,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.sum_strategy = sum_strategy
        self.device = device
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        eidx = _edge_index_dtype(graph.ne)
        self.dgraph = _DeviceGraph(
            col_src=put(graph.col_src.astype(np.int32)),
            seg_ids=put(graph.col_dst),
            row_ptr=put(graph.row_ptr.astype(eidx)),
            weights=None if graph.weights is None else put(graph.weights),
            out_degrees=put(graph.out_degrees.astype(np.int32)),
            in_degrees=put(graph.in_degrees.astype(np.int32)),
        )
        self._step = jax.jit(self._step_impl, donate_argnums=0)
        self._jrun = make_fused_runner(self._step_impl)

    # -- the jitted iteration -------------------------------------------

    def _step_impl(self, vals: jnp.ndarray, dg: _DeviceGraph) -> jnp.ndarray:
        prog = self.program
        edge = EdgeCtx(
            src_vals=vals[dg.col_src],
            dst_vals=vals[dg.seg_ids],
            weights=dg.weights,
        )
        contrib = prog.edge_contrib(edge)
        if prog.combiner == "sum" and self.sum_strategy == "rowptr":
            acc = segment_sum_by_rowptr(contrib, dg.row_ptr)
        else:
            acc = segment_reduce(
                contrib, dg.seg_ids, num_segments=self.graph.nv,
                kind=prog.combiner,
            )
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg.out_degrees,
            in_degrees=dg.in_degrees,
        )
        return prog.apply(vals, acc, ctx)

    # -- driver ----------------------------------------------------------

    def init_values(self) -> jnp.ndarray:
        return jax.device_put(
            jnp.asarray(self.program.init_values(self.graph)), self.device
        )

    def step(self, vals: jnp.ndarray) -> jnp.ndarray:
        return self._step(vals, self.dgraph)

    def warmup(self):
        """Run one throwaway step through the run() path outside any timed
        region (the reference's kernels are compiled at build time, so its
        ELAPSED TIME never includes compilation; hard_sync also primes the
        transfer path on tunneled backends)."""
        hard_sync(self.step(self.init_values()))

    def run(
        self,
        num_iters: int,
        vals: Optional[jnp.ndarray] = None,
        flush_every: int = 8,
    ):
        if vals is None:
            vals = self.init_values()
        return run_maybe_fused(
            self._jrun, self.step, vals, num_iters, flush_every, self.dgraph
        )


jax.tree_util.register_dataclass(
    _DeviceGraph,
    data_fields=["col_src", "seg_ids", "row_ptr", "weights", "out_degrees",
                 "in_degrees"],
    meta_fields=[],
)
