"""Sharded pull executor: SPMD over a device mesh via ``jax.shard_map``.

The communication pattern mirrors the reference's pull iteration
(SURVEY.md §3.1) the TPU-native way:

- reference: every GPU reads the *whole* old-value region through zero-copy
  memory and gathers only its in-neighbor values FB-side
  (pull_model.inl:454-461, pagerank_gpu.cu:34-47). Here: an ICI
  ``all_gather`` of the per-part value shards inside ``shard_map``, then a
  local gather by precomputed flat indices. XLA schedules the all-gather
  to overlap with compute where possible.
- reference: per-part new values published back to ZC (cudaMemcpy D2H,
  pagerank_gpu.cu:148-150). Here: nothing — each shard's new values stay
  resident; next iteration's all-gather *is* the exchange.
- the Legion iteration-to-iteration region dependency that acts as the
  barrier (SURVEY.md §3.1 footnote) becomes XLA's dataflow dependency
  between consecutive jitted steps.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.program import EdgeCtx, PullProgram, VertexCtx
from lux_tpu.engine.pull import hard_sync, make_fused_runner, run_maybe_fused
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import (
    consume_compile_seconds,
    engobs,
    note_compile_seconds,
    prof,
    recorder_for,
)
from lux_tpu.utils import compat
from lux_tpu.utils.timing import Timer
from lux_tpu.ops.segment import segment_reduce, segment_sum_by_rowptr
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh, parts_sharding
from lux_tpu.parallel.shard import ShardedGraph, resolve_exchange
from lux_tpu.utils.logging import get_logger


class ShardedPullExecutor:
    """Runs a :class:`PullProgram` over an N-device 1-D mesh."""

    def __init__(
        self,
        graph: Graph,
        program: PullProgram,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
        sum_strategy: str = "rowptr",
        sg: Optional[ShardedGraph] = None,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.graph = graph
        self.program = program
        self.sum_strategy = sum_strategy
        if sg is not None and sg.num_parts != self.num_parts:
            raise ValueError(
                f"prebuilt ShardedGraph has {sg.num_parts} parts, mesh has "
                f"{self.num_parts}"
            )
        if sg is not None and sg.graph is not graph:
            raise ValueError(
                "prebuilt ShardedGraph was built from a different Graph "
                "object — edge indices and partition bounds would not "
                "match this executor's graph"
            )
        self.sg = sg if sg is not None else ShardedGraph.build(
            graph, self.num_parts
        )

        # Lane padding for K-vector values: gathering (ne, K)-narrow rows
        # scalarizes on TPU (measured 76.5 s/iter on NetFlix-shaped CF in
        # the single-device engine before the same fix). Values are
        # STORED lane-padded per shard so the src/dst row gathers stream
        # full 512 B rows; the all-gather sends the UNPADDED slice (the
        # pad is re-applied locally), so ICI bytes do not inflate.
        from lux_tpu.engine.pull import lane_pad_width

        self._kreal, self._kpad = lane_pad_width(
            getattr(program, "value_shape", ())
        )

        # Exchange mode is captured here, once: the jitted step traces a
        # single program, and the serving pool keys engines by the mode
        # (flags re-read env per call, so a later flip builds NEW
        # engines rather than mutating this one).
        self.exchange_mode, self._xplan = resolve_exchange(
            self.sg, get_logger("engine"))

        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)
        sgd = {
            "src_pidx": put(self.sg.src_pidx),
            "dst_local": put(self.sg.dst_local),
            "local_row_ptr": put(self.sg.local_row_ptr),
            "out_degrees": put(self.sg.out_degrees),
            "in_degrees": put(self.sg.in_degrees),
            "vertex_mask": put(self.sg.vertex_mask),
        }
        if self.sg.weights is not None:
            sgd["weights"] = put(self.sg.weights)
        if self._xplan is not None:
            sgd["xch_send"] = put(self._xplan.send_units)
            sgd["xch_recv"] = put(self._xplan.recv_pos)
        self._device_graph = sgd

        specs = {k: P(PARTS_AXIS) for k in sgd}
        mapped = compat.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(P(PARTS_AXIS), specs),
            out_specs=P(PARTS_AXIS),
        )
        self._step = jax.jit(mapped, donate_argnums=0)
        self._jrun = make_fused_runner(mapped)

    # -- per-shard body (runs under shard_map; block shapes (1, ...)) ----

    def _exchange_block(self, vals_blk, dg):
        """Value exchange: all-gather the shards into the flat global
        table every shard gathers from (the reference's whole-region
        zero-copy read, pull_model.inl:454-461) — or, under
        ``LUX_EXCHANGE=compact``, a fixed-capacity ``all_to_all`` of the
        packed needed rows scattered into the same flat view (rows no
        remote edge reads stay zero; the comp block routes local edges
        to the shard's own values, so only genuinely remote reads touch
        this table)."""
        v = vals_blk[0]                  # (max_nv, *t); lane-padded if _kpad
        kp, kr = self._kpad, self._kreal
        if kp:
            # Exchange the real lanes only; re-pad locally for fast
            # 512 B-row gathers from the flat table.
            flat = self._flat_table(v[:, :kr], dg)
            flat = jnp.pad(flat, ((0, 0), (0, kp - kr)))
        else:
            flat = self._flat_table(v, dg)
        return flat

    def _flat_table(self, vv, dg):
        """(P*max_nv, *t) flat value table from this shard's (max_nv, *t)
        slice: whole-shard all_gather (full) or packed needed-rows
        all_to_all + receiver scatter (compact)."""
        if self._xplan is None:
            gathered = jax.lax.all_gather(vv, PARTS_AXIS)
            return gathered.reshape((-1,) + vv.shape[1:])
        max_nv = self.sg.max_nv
        packed = vv[jnp.minimum(dg["xch_send"][0], max_nv - 1)]
        got = jax.lax.all_to_all(
            packed, PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        # Scatter into a (P*max_nv + 1)-row buffer: pad entries of the
        # scatter map land on the final trash row, sliced off here.
        buf = jnp.zeros(
            (self.num_parts * max_nv + 1,) + vv.shape[1:], vv.dtype
        )
        return buf.at[dg["xch_recv"][0]].set(got)[:-1]

    def _comp_block(self, vals_blk, flat, dg):
        """Edge gather + contribution + per-destination reduction."""
        prog = self.program
        max_nv = self.sg.max_nv
        v = vals_blk[0]
        # Padded width is kept through edge_contrib and the reduction:
        # slicing here would either re-narrow the gather (XLA folds the
        # slice in, reviving the scalarized path) or materialize both
        # widths; pad lanes are zero, so contraction-style programs (CF's
        # dot/err*src) are unaffected, and narrow (ne, K) arrays pad to
        # the 128-lane tile physically anyway.
        sidx = dg["src_pidx"][0]
        dst_ids = jnp.minimum(dg["dst_local"][0], max_nv - 1)
        dst_vals = v[dst_ids]
        w = dg["weights"][0] if "weights" in dg else None

        def contrib_from(src_vals):
            return prog.edge_contrib(EdgeCtx(
                src_vals=src_vals, dst_vals=dst_vals, weights=w,
            ))

        if self._xplan is None:
            contrib = contrib_from(flat[sidx])
        else:
            # Local-first overlap: the local-edge contribution reads only
            # this shard's values — no data dependence on the collective —
            # so XLA can compute it while the packed exchange is in
            # flight; the per-edge select (before the SINGLE unchanged
            # reduction) folds the remote contribution in without
            # reordering the combine, keeping results bitwise equal to
            # the full path for every combiner, float sum included.
            own = jax.lax.axis_index(PARTS_AXIS)
            base = own * max_nv
            local = (sidx >= base) & (sidx < base + max_nv)
            c_local = contrib_from(v[jnp.clip(sidx - base, 0, max_nv - 1)])
            c_remote = contrib_from(flat[sidx])
            mask = local.reshape(local.shape + (1,) * (c_local.ndim - 1))
            contrib = jnp.where(mask, c_local, c_remote)
        if prog.combiner == "sum" and self.sum_strategy == "rowptr":
            acc = segment_sum_by_rowptr(contrib, dg["local_row_ptr"][0])
        else:
            # Pad edges carry dst_local == max_nv: an extra trash segment
            # sliced off below, so no combiner-identity masking is needed.
            acc = segment_reduce(
                contrib,
                dg["dst_local"][0],
                num_segments=max_nv + 1,
                kind=prog.combiner,
            )[:max_nv]
        return acc

    def _update_block(self, vals_blk, acc, dg):
        """Vertex apply + pad-lane/pad-vertex re-masking."""
        prog = self.program
        max_nv = self.sg.max_nv
        v = vals_blk[0]
        kp, kr = self._kpad, self._kreal
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg["out_degrees"][0],
            in_degrees=dg["in_degrees"][0],
        )
        new = prog.apply(v, acc, ctx)
        if kp:
            # Re-zero pad lanes: apply may write constants into them,
            # which would pollute the next iteration's contractions.
            lanes = jnp.arange(kp, dtype=jnp.int32)
            new = jnp.where(lanes[None, :] < kr, new, 0)
        vmask = dg["vertex_mask"][0].reshape(
            (max_nv,) + (1,) * (new.ndim - 1)
        )
        new = jnp.where(vmask, new, v)  # freeze pad vertices
        return new[None]

    def _shard_step(self, vals_blk, dg):
        # prof regions tag the lowered ops per phase (static names, so
        # executable cache keys — and hence recompiles — are unchanged);
        # the scopes do not fence XLA's schedule, so the compact path's
        # exchange/local-compute overlap still happens and shows up as
        # intersecting intervals in a device profile.
        with prof.region("lux.pull_sharded.exchange"):
            flat = self._exchange_block(vals_blk, dg)
        with prof.region("lux.pull_sharded.compute"):
            acc = self._comp_block(vals_blk, flat, dg)
            return self._update_block(vals_blk, acc, dg)

    # -- driver ----------------------------------------------------------

    def init_values(self):
        return self.host_to_device(self.program.init_values(self.graph))

    def host_to_device(self, host_vals: np.ndarray):
        """Global (nv, *t) host array → this executor's device layout
        (padded shard stack, lane-padded for K-vector programs)."""
        padded = self.sg.to_padded(np.asarray(host_vals))
        if self._kpad:
            padded = np.pad(
                padded, ((0, 0), (0, 0), (0, self._kpad - self._kreal))
            )
        return jax.device_put(jnp.asarray(padded), parts_sharding(self.mesh))

    def step(self, vals):
        return self._step(vals, self._device_graph)

    def phase_step(self, vals):
        """One iteration as separately-dispatched exchange/comp/update
        phases for `-verbose` attribution (the pull-side analogue of the
        reference's per-iteration breakdown, sssp/sssp_gpu.cu:516-518 —
        phase names follow this engine's pipeline). SPMD phases are
        mesh-lockstep, so the walls are mesh-wide. Returns (new vals,
        {phase: seconds}). Phase dispatch breaks fusion; use run() for
        timed loops."""
        if not hasattr(self, "_pjits"):
            specs = {k: P(PARTS_AXIS) for k in self._device_graph}
            compact = self._xplan is not None
            # Full mode: the all-gathered flat table is replicated, so
            # the exchange phase hands one copy across. Compact mode:
            # every shard scatters its OWN flat view (rows differ per
            # receiver), so the table stays per-shard.
            flat_spec = P(PARTS_AXIS) if compact else P()

            def sm(fn, in_specs, out_specs):
                # check_vma off: the all-gathered flat table is
                # replicated by construction, but the static checker
                # cannot infer it here.
                return jax.jit(compat.shard_map(
                    fn, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False,
                ))

            self._pjits = {
                "exchange": sm(
                    lambda v, dg: (
                        self._exchange_block(v, dg)[None] if compact
                        else self._exchange_block(v, dg)
                    ),
                    (P(PARTS_AXIS), specs), flat_spec,
                ),
                "comp": sm(
                    lambda v, flat, dg: self._comp_block(
                        v, flat[0] if compact else flat, dg
                    )[None],
                    (P(PARTS_AXIS), flat_spec, specs), P(PARTS_AXIS),
                ),
                "update": sm(
                    lambda v, acc, dg: self._update_block(v, acc[0], dg),
                    (P(PARTS_AXIS), P(PARTS_AXIS), specs), P(PARTS_AXIS),
                ),
            }
        j, dg, times = self._pjits, self._device_graph, {}
        with Timer() as t:
            flat = hard_sync(j["exchange"](vals, dg))
        times["exchange"] = t.elapsed
        with Timer() as t:
            acc = hard_sync(j["comp"](vals, flat, dg))
        times["comp"] = t.elapsed
        with Timer() as t:
            new = hard_sync(j["update"](vals, acc, dg))
        times["update"] = t.elapsed
        return new, times

    def warmup(self):
        with Timer() as t:
            hard_sync(self.step(self.init_values()))
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted shard_map step;
        sharded=True, so LUX105 demands the exchange all-gather shows
        up in the trace. The exchange_* keys feed the LUX404-406
        collective-dataflow rules (``luxlint --exchange``)."""
        return {
            "kind": "pull_sharded",
            "fn": self._step,
            "args": (self.init_values(), self._device_graph),
            "donate": (0,),
            "carry": (0,),
            "sharded": True,
            "exchange_mode": self.exchange_mode,
            "exchange_bytes": self.exchange_bytes_per_iter(),
            "combiner": getattr(self.program, "combiner", ""),
            "value_dtype": np.dtype(
                getattr(self.program, "value_dtype", np.float32)).name,
            "num_parts": self.num_parts,
            "plan": self._xplan,
        }

    def _row_bytes(self) -> int:
        try:
            itemsize = np.dtype(self.program.value_dtype).itemsize
        except (AttributeError, TypeError):
            itemsize = 4
        return max(self._kreal, 1) * itemsize

    def _exchange_bytes_per_iter(self) -> int:
        """ICI bytes moved by one iteration's exchange. Full: each of
        the P shards sends its (max_nv, kreal-or-scalar) slice to the
        P-1 others (``_exchange_block`` gathers only real lanes when
        lane-padded). Compact: the packed-capacity figure — what the
        fixed-capacity all_to_all actually moves."""
        row = self._row_bytes()
        if self._xplan is not None:
            return self._xplan.exchange_bytes_per_iter(row)
        p = self.num_parts
        return p * (p - 1) * self.sg.max_nv * row

    def exchange_bytes_per_iter(self) -> int:
        """Public form of the per-iteration exchange estimate (the
        serving layer reports it in serve_bench.v1 mesh evidence)."""
        return self._exchange_bytes_per_iter()

    def run(self, num_iters: int, vals=None, flush_every: int = 8,
            recorder=None):
        if vals is None:
            vals = self.init_values()
        rec = recorder if recorder is not None else recorder_for(
            "pull_sharded", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            compact = self._xplan is not None
            rec.set_exchange_bytes(
                self._exchange_bytes_per_iter(),
                note="compact_all_to_all" if compact else "all_gather",
                parts=self.num_parts)
            if compact:
                rec.set_overlap(True)
            self._note_ledger(rec)
        if engobs.enabled():
            # Phase-fenced measurement run: exchange/compute split per
            # iteration. Off (the default) never reaches here, so the
            # fused program below stays the exact pre-observatory one.
            out = engobs.run_pull_phased(self, vals, num_iters, rec)
        else:
            out = run_maybe_fused(
                self._jrun, self.step, vals, num_iters, flush_every,
                self._device_graph, recorder=rec,
            )
        rec.finish()
        return out

    def _note_ledger(self, rec):
        """Exchange-ledger and roofline inputs: useful-bytes from the
        plan's remote-read index, HBM traffic from the byte model."""
        try:
            itemsize = np.dtype(self.program.value_dtype).itemsize
        except (AttributeError, TypeError):
            itemsize = 4
        width = max(self._kreal, 1)
        xrows = (self._xplan.exchanged_units_per_iter
                 if self._xplan is not None else None)
        useful = engobs.useful_exchange(self.sg, width * itemsize,
                                        exchanged_rows=xrows)
        if useful is not None:
            rec.set_useful_bytes(useful["useful_bytes_per_iter"],
                                 useful["ratio"])
        rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
            self.graph.nv, self.graph.ne, itemsize, width))

    def gather_values(self, vals) -> np.ndarray:
        """Padded device layout → global (nv, *t) host array."""
        host = np.asarray(jax.device_get(vals))
        if self._kpad:
            host = host[:, :, : self._kreal]
        return self.sg.from_padded(host)
