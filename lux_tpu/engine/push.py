"""Push-model engine: frontier-driven fixpoint iteration.

The reference push engine (core/push_model.inl + sssp/sssp_gpu.cu:335-522)
keeps an *active frontier*, expands each frontier vertex's out-edges with
atomic relaxations, adaptively switches between a sparse queue and a dense
bitmap, and between push and pull directions (frontier > nv/16 ⇒ pull,
sssp_gpu.cu:414).

TPU-native formulation: the frontier is a dense boolean mask (XLA needs
static shapes; the reference's own dense-bitmap mode, sssp_gpu.cu:248-281,
is the shape-stable representation). Each iteration is executed in the
*pull direction* over the CSC in-edges with non-frontier contributions
masked to the combiner identity:

    cand_e = relax(val[src_e], w_e)        if frontier[src_e] else identity
    acc_v  = min/max over in-edges of v
    new_v  = combine(old_v, acc_v)
    frontier'_v = (new_v != old_v)         — the adaptive "changed" bitmap
                                             diff, cf. bitmap_kernel
                                             sssp_gpu.cu:248-281
    active = Σ frontier'                   — the halt signal the reference
                                             returns per point task
                                             (sssp_gpu.cu:521)

This is work-suboptimal for tiny frontiers (O(ne) per iteration instead of
O(frontier edges)) but every op is a large dense VPU-friendly computation;
a Pallas sparse path is layered on later.

Halt detection: the reference hides the per-iteration host round-trip for
the active count behind a 4-deep speculative window (SLIDING_WINDOW,
sssp/sssp.cc:111-129) — valid because the fixpoint is monotone, so extra
iterations are harmless. The TPU-native form goes further: up to ``chunk``
iterations run under one ``lax.while_loop`` dispatch with on-device early
exit, and the host reads one count batch per chunk. Same monotonicity
argument, ~chunk× fewer synchronizations (this round-trip is SURVEY.md
§7 hard-part (c)).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.pull import hard_sync
from lux_tpu.graph.graph import Graph
from lux_tpu.ops.segment import identity_for, segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh, parts_sharding
from lux_tpu.parallel.shard import ShardedGraph

class PushProgram:
    """Frontier-driven vertex program (SSSP, CC, ...)."""

    name: str = "push"
    combiner: str = "min"          # 'min' | 'max'
    value_dtype = jnp.uint32
    needs_weights: bool = False

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def relax(self, src_vals: jnp.ndarray, weights) -> jnp.ndarray:
        """Candidate value pushed along an edge from an active source."""
        raise NotImplementedError

    def edge_invariant(self, src_vals, dst_vals, weights) -> jnp.ndarray:
        """Per-edge fixpoint invariant for `-check` (True = ok). The
        reference's GPU checkers: sssp_gpu.cu:773-798,
        components_gpu.cu:769-792."""
        raise NotImplementedError


class PushState(NamedTuple):
    values: jnp.ndarray     # (nv,) or (P, max_nv)
    frontier: jnp.ndarray   # bool, same shape


def _chunk_while(one_iter, state: PushState, k: int, limit):
    """Run up to ``min(k, limit)`` fixpoint iterations on-device with
    early exit.

    The reference pays one host round-trip per iteration past its 4-deep
    window to read the halt count (sssp.cc:116-124); on TPU (especially a
    tunneled one) that round-trip dominates tiny iterations, so the whole
    loop runs under ``lax.while_loop`` and the host syncs once per chunk.
    ``k`` is static (compiled once); ``limit`` is a traced bound so partial
    final chunks reuse the same executable instead of recompiling.
    Returns (state, counts[k], iters_done, last_count).
    """

    def cond(carry):
        _, i, last, _ = carry
        return (i < jnp.minimum(k, limit)) & (last > 0)

    def body(carry):
        st, i, _, counts = carry
        st, cnt = one_iter(st)
        counts = jax.lax.dynamic_update_index_in_dim(
            counts, cnt, i, axis=0
        )
        return st, i + 1, cnt, counts

    init = (state, jnp.int32(0), jnp.int32(1), jnp.zeros(k, jnp.int32))
    st, done, last, counts = jax.lax.while_loop(cond, body, init)
    return st, counts, done, last


class PushExecutor:
    """Single-device push executor with adaptive direction switching.

    Two per-iteration strategies, chosen on-device by ``lax.cond`` the way
    the reference switches per iteration (sssp_gpu.cu:414-421):

    - **dense (pull direction)**: masked relax over all CSC in-edges —
      O(ne) but fully vectorized. Used for large frontiers.
    - **sparse (push direction)**: compact the frontier into a bounded
      queue (the FrontierHeader/queue design, push_model.inl:390-412,
      made static-shape), expand exactly the queued vertices' out-edges
      through the CSR, scatter-combine the candidates. Work scales with
      the *edge budget*, not ne — the win when frontiers are small, since
      on TPU gathers/scatters cost per element.

    Sparse is taken when the previous frontier fits the queue AND its
    out-edge total fits the edge budget; otherwise dense (the reference's
    sparse→dense overflow fallback, sssp_gpu.cu:462-491).
    """

    def __init__(
        self,
        graph: Graph,
        program: PushProgram,
        device=None,
        sparse: bool = True,
        queue_frac: int = 16,     # queue capacity = nv/queue_frac + slack
        edge_budget_frac: int = 8,  # edge budget = ne/edge_budget_frac
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.device = device
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        dg = {
            "col_src": put(graph.col_src.astype(np.int32)),
            "seg_ids": put(graph.col_dst),
        }
        if graph.weights is not None:
            dg["weights"] = put(graph.weights)
        self.sparse = sparse and graph.ne >= 1024
        if self.sparse:
            # Queue capacity mirrors the reference's per-part sparse queue
            # sizing (nv/SPARSE_THRESHOLD + slack, push_model.inl:390-412).
            self.queue_cap = int(graph.nv) // queue_frac + 128
            self.edge_budget = max(int(graph.ne) // edge_budget_frac, 1024)
            from lux_tpu.engine.pull import _edge_index_dtype

            csr = graph.csr()
            eidx = _edge_index_dtype(graph.ne)
            dg["csr_row_ptr"] = put(csr.row_ptr.astype(eidx))
            dg["csr_col_dst"] = put(csr.col_dst)
            if csr.weights is not None:
                dg["csr_weights"] = put(csr.weights)
            dg["out_degrees"] = put(graph.out_degrees.astype(np.int32))
        self._dg = dg
        self._step = jax.jit(self._step_impl, donate_argnums=0)
        self._multi_jit = jax.jit(
            self._chunk_impl, donate_argnums=0, static_argnums=2
        )

    # -- dense (pull-direction) iteration --------------------------------

    def _dense_iter(self, state: PushState, dg):
        prog = self.program
        src_vals = state.values[dg["col_src"]]
        cand = prog.relax(src_vals, dg.get("weights"))
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(state.frontier[dg["col_src"]], cand, ident)
        acc = segment_reduce(
            cand, dg["seg_ids"], num_segments=self.graph.nv,
            kind=prog.combiner,
        )
        if prog.combiner == "min":
            new = jnp.minimum(state.values, acc)
        else:
            new = jnp.maximum(state.values, acc)
        frontier = new != state.values
        return PushState(new, frontier), frontier.sum(dtype=jnp.int32)

    # -- sparse (push-direction) iteration -------------------------------

    def _sparse_iter(self, state: PushState, dg):
        prog = self.program
        nv, Q, E = self.graph.nv, self.queue_cap, self.edge_budget
        values, frontier = state
        # 1. Frontier → bounded queue (ids sorted ascending; pad slot nv).
        q = jnp.nonzero(frontier, size=Q, fill_value=nv)[0].astype(jnp.int32)
        # Padded row_ptr lookup: q == nv yields start == end == ne.
        rp = dg["csr_row_ptr"]
        start = rp[q]
        deg = rp[jnp.minimum(q + 1, nv)] - start
        offs = jnp.concatenate([jnp.zeros(1, deg.dtype), jnp.cumsum(deg)])
        total = offs[-1]
        # 2. Edge slot → queue slot: mark segment starts, prefix-sum.
        marks = jnp.zeros(E + 1, jnp.int32).at[
            jnp.clip(offs[:-1], 0, E)
        ].add(1, mode="drop")
        slot = jnp.cumsum(marks[:E]) - 1                      # (E,)
        e_idx = jnp.arange(E, dtype=offs.dtype)
        emask = e_idx < total
        slot = jnp.clip(slot, 0, Q - 1)
        edge_pos = jnp.clip(
            start[slot] + (e_idx - offs[slot]), 0, max(self.graph.ne - 1, 0)
        )
        dst = dg["csr_col_dst"][edge_pos]
        src_vals = values[jnp.clip(q[slot], 0, nv - 1)]
        w = dg["csr_weights"][edge_pos] if "csr_weights" in dg else None
        cand = prog.relax(src_vals, w)
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(emask, cand, ident)
        dst = jnp.where(emask, dst, 0)
        # 3. Scatter-combine candidates into the values (deterministic in
        # XLA, unlike the reference's atomicMin, sssp_gpu.cu:48-61).
        if prog.combiner == "min":
            new = values.at[dst].min(cand)
        else:
            new = values.at[dst].max(cand)
        new_frontier = new != values
        return PushState(new, new_frontier), new_frontier.sum(dtype=jnp.int32)

    # -- adaptive combination --------------------------------------------

    def _one_iter(self, state: PushState, dg):
        if not self.sparse:
            return self._dense_iter(state, dg)
        cnt = state.frontier.sum(dtype=jnp.int32)
        # uint32 sum is exact for any total <= 2^32 > ne, so the sparse
        # branch (only correct when total fits the edge budget) can never
        # be selected by rounding error.
        out_edges = jnp.where(
            state.frontier, dg["out_degrees"].astype(jnp.uint32), 0
        ).sum(dtype=jnp.uint32)
        use_sparse = (cnt <= self.queue_cap) & (
            out_edges <= jnp.uint32(self.edge_budget)
        )
        return jax.lax.cond(
            use_sparse,
            lambda st: self._sparse_iter(st, dg),
            lambda st: self._dense_iter(st, dg),
            state,
        )

    def _step_impl(self, state: PushState, dg):
        return self._one_iter(state, dg)

    def _chunk_impl(self, state: PushState, dg, k: int, limit=None):
        one_iter = lambda st: self._one_iter(st, dg)
        return _chunk_while(one_iter, state, k, limit)

    def init_state(self, **kw) -> PushState:
        vals = jax.device_put(
            jnp.asarray(self.program.init_values(self.graph, **kw)),
            self.device,
        )
        fr = jax.device_put(
            jnp.asarray(self.program.init_frontier(self.graph, **kw)),
            self.device,
        )
        return PushState(vals, fr)

    def step(self, state: PushState):
        return self._step(state, self._dg)

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[PushState] = None,
        verbose: bool = False,
        chunk: int = 16,
        **init_kw,
    ):
        """Iterate to fixpoint; returns (final_state, iterations_run).

        Runs ``chunk`` iterations per device dispatch with on-device early
        exit; the host reads back one count batch per chunk."""
        if state is None:
            state = self.init_state(**init_kw)
        return _run_to_fixpoint(self._multi, state, max_iters, chunk, verbose)

    def _multi(self, state: PushState, limit: int, k: int):
        return self._multi_jit(state, self._dg, k, limit=jnp.int32(limit))

    def warmup(self, chunk: int = 16, **init_kw):
        """Run one throwaway iteration through the exact run() path so
        ELAPSED TIME excludes XLA compilation AND first-transfer setup
        (both disproportionately slow on tunneled backends)."""
        _run_to_fixpoint(
            self._multi, self.init_state(**init_kw), 1, chunk, False
        )


def _run_to_fixpoint(multi, state, max_iters, chunk, verbose):
    total = 0
    while True:
        limit = chunk if max_iters is None else min(chunk, max_iters - total)
        if limit <= 0:
            break
        k = chunk
        state, counts, done, last = multi(state, limit, k)
        # One batched transfer: on a tunneled TPU every device_get is a
        # full round-trip (~tens of ms), so fetch all three together.
        counts_h, done_h, last_h = jax.device_get((counts, done, last))
        done_i = int(np.asarray(done_h).reshape(-1)[0])
        last_i = int(np.asarray(last_h).reshape(-1)[0])
        if verbose:
            ch = np.asarray(counts_h).reshape(-1, k)[0][:done_i]
            for j, c in enumerate(ch):
                print(f"iter {total + j}: active {int(c)}")
        total += done_i
        if last_i == 0 or done_i == 0:
            break
    hard_sync(state.values)
    return state, total


class ShardedPushExecutor:
    """Push executor over an N-device mesh: the ghost/frontier exchange is
    one fused all-gather of (values, frontier) shards — the analogue of the
    reference's whole-region old-value + old-frontier ZC reads
    (push_model.inl:234-241, 250-257)."""

    def __init__(
        self,
        graph: Graph,
        program: PushProgram,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.graph = graph
        self.program = program
        self.sg = ShardedGraph.build(graph, self.num_parts)
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)
        self._dg = {
            "src_pidx": put(self.sg.src_pidx),
            "dst_local": put(self.sg.dst_local),
            "vertex_mask": put(self.sg.vertex_mask),
        }
        if self.sg.weights is not None:
            self._dg["weights"] = put(self.sg.weights)
        self._specs = {k: P(PARTS_AXIS) for k in self._dg}
        state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))
        mapped = jax.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(state_spec, self._specs),
            out_specs=(state_spec, P(PARTS_AXIS)),
        )
        self._step = jax.jit(mapped, donate_argnums=0)
        self._chunk_cache = {}

    def _iter_block(self, state: PushState, dg):
        """One iteration on this shard's (1, ...) blocks; returns the new
        blocks and the *local* new-frontier count."""
        prog = self.program
        max_nv = self.sg.max_nv
        v = state.values[0]
        f = state.frontier[0]
        all_v = jax.lax.all_gather(v, PARTS_AXIS).reshape(-1)
        all_f = jax.lax.all_gather(f, PARTS_AXIS).reshape(-1)
        sidx = dg["src_pidx"][0]
        src_vals = all_v[sidx]
        src_front = all_f[sidx]
        w = dg["weights"][0] if "weights" in dg else None
        cand = prog.relax(src_vals, w)
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(src_front, cand, ident)
        acc = segment_reduce(
            cand, dg["dst_local"][0], num_segments=max_nv + 1,
            kind=prog.combiner,
        )[:max_nv]
        if prog.combiner == "min":
            new = jnp.minimum(v, acc)
        else:
            new = jnp.maximum(v, acc)
        vmask = dg["vertex_mask"][0]
        new = jnp.where(vmask, new, v)
        frontier = (new != v) & vmask
        cnt = frontier.sum(dtype=jnp.int32)
        return PushState(new[None], frontier[None]), cnt

    def _shard_step(self, state: PushState, dg):
        new_state, cnt = self._iter_block(state, dg)
        return new_state, cnt[None]

    def _shard_chunk(self, state: PushState, dg, limit, k: int):
        def one_iter(st):
            new_state, cnt_local = self._iter_block(st, dg)
            return new_state, jax.lax.psum(cnt_local, PARTS_AXIS)

        st, counts, done, last = _chunk_while(one_iter, state, k, limit[0])
        return st, counts[None], done[None], last[None]

    def _multi(self, state: PushState, limit: int, k: int):
        if k not in self._chunk_cache:
            state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))
            mapped = jax.shard_map(
                lambda st, dg, lim: self._shard_chunk(st, dg, lim, k),
                mesh=self.mesh,
                in_specs=(state_spec, self._specs, P()),
                out_specs=(
                    state_spec,
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                ),
            )
            self._chunk_cache[k] = jax.jit(mapped, donate_argnums=0)
        return self._chunk_cache[k](
            state, self._dg, jnp.full((1,), limit, jnp.int32)
        )

    def init_state(self, **kw) -> PushState:
        sh = parts_sharding(self.mesh)
        vals = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(self.program.init_values(self.graph, **kw))
            ),
            sh,
        )
        fr = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(self.program.init_frontier(self.graph, **kw))
            ),
            sh,
        )
        return PushState(vals, fr)

    def step(self, state: PushState):
        return self._step(state, self._dg)

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[PushState] = None,
        verbose: bool = False,
        chunk: int = 16,
        **init_kw,
    ):
        if state is None:
            state = self.init_state(**init_kw)
        return _run_to_fixpoint(self._multi, state, max_iters, chunk, verbose)

    def warmup(self, chunk: int = 16, **init_kw):
        _run_to_fixpoint(
            self._multi, self.init_state(**init_kw), 1, chunk, False
        )

    def gather_values(self, state: PushState) -> np.ndarray:
        return self.sg.from_padded(np.asarray(jax.device_get(state.values)))
