"""Push-model engine: frontier-driven fixpoint iteration.

The reference push engine (core/push_model.inl + sssp/sssp_gpu.cu:335-522)
keeps an *active frontier*, expands each frontier vertex's out-edges with
atomic relaxations, adaptively switches between a sparse queue and a dense
bitmap, and between push and pull directions (frontier > nv/16 ⇒ pull,
sssp_gpu.cu:414).

TPU-native formulation: the frontier is a dense boolean mask (XLA needs
static shapes; the reference's own dense-bitmap mode, sssp_gpu.cu:248-281,
is the shape-stable representation). Each iteration is executed in the
*pull direction* over the CSC in-edges with non-frontier contributions
masked to the combiner identity:

    cand_e = relax(val[src_e], w_e)        if frontier[src_e] else identity
    acc_v  = min/max over in-edges of v
    new_v  = combine(old_v, acc_v)
    frontier'_v = (new_v != old_v)         — the adaptive "changed" bitmap
                                             diff, cf. bitmap_kernel
                                             sssp_gpu.cu:248-281
    active = Σ frontier'                   — the halt signal the reference
                                             returns per point task
                                             (sssp_gpu.cu:521)

This is work-suboptimal for tiny frontiers (O(ne) per iteration instead of
O(frontier edges)) but every op is a large dense VPU-friendly computation;
a Pallas sparse path is layered on later. Because the fixpoint is monotone,
speculative extra iterations are harmless — which is exactly what makes the
reference's SLIDING_WINDOW=4 pipelining valid (sssp/sssp.cc:111-129), and
we reuse the same trick: the host blocks on the active-count of iteration
i-4 while iterations i-3..i are already enqueued.
"""

from __future__ import annotations

import collections
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.graph.graph import Graph
from lux_tpu.ops.segment import identity_for, segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh, parts_sharding
from lux_tpu.parallel.shard import ShardedGraph

SLIDING_WINDOW = 4  # speculative in-flight iterations (sssp/app.h:20)


class PushProgram:
    """Frontier-driven vertex program (SSSP, CC, ...)."""

    name: str = "push"
    combiner: str = "min"          # 'min' | 'max'
    value_dtype = jnp.uint32
    needs_weights: bool = False

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def relax(self, src_vals: jnp.ndarray, weights) -> jnp.ndarray:
        """Candidate value pushed along an edge from an active source."""
        raise NotImplementedError

    def edge_invariant(self, src_vals, dst_vals, weights) -> jnp.ndarray:
        """Per-edge fixpoint invariant for `-check` (True = ok). The
        reference's GPU checkers: sssp_gpu.cu:773-798,
        components_gpu.cu:769-792."""
        raise NotImplementedError


class PushState(NamedTuple):
    values: jnp.ndarray     # (nv,) or (P, max_nv)
    frontier: jnp.ndarray   # bool, same shape


class PushExecutor:
    """Single-device push executor."""

    def __init__(self, graph: Graph, program: PushProgram, device=None):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.device = device
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        self._col_src = put(graph.col_src.astype(np.int32))
        self._seg_ids = put(graph.col_dst)
        self._weights = (
            None if graph.weights is None else put(graph.weights)
        )
        self._step = jax.jit(self._step_impl, donate_argnums=0)

    def _step_impl(self, state: PushState, col_src, seg_ids, weights):
        prog = self.program
        src_vals = state.values[col_src]
        cand = prog.relax(src_vals, weights)
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(state.frontier[col_src], cand, ident)
        acc = segment_reduce(
            cand, seg_ids, num_segments=self.graph.nv, kind=prog.combiner
        )
        if prog.combiner == "min":
            new = jnp.minimum(state.values, acc)
        else:
            new = jnp.maximum(state.values, acc)
        frontier = new != state.values
        return PushState(new, frontier), frontier.sum(dtype=jnp.int32)

    def init_state(self, **kw) -> PushState:
        vals = jax.device_put(
            jnp.asarray(self.program.init_values(self.graph, **kw)),
            self.device,
        )
        fr = jax.device_put(
            jnp.asarray(self.program.init_frontier(self.graph, **kw)),
            self.device,
        )
        return PushState(vals, fr)

    def step(self, state: PushState):
        return self._step(state, self._col_src, self._seg_ids, self._weights)

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[PushState] = None,
        verbose: bool = False,
        **init_kw,
    ):
        """Iterate to fixpoint with SLIDING_WINDOW-deep speculative
        pipelining; returns (final_state, iterations_run)."""
        if state is None:
            state = self.init_state(**init_kw)
        window = collections.deque()
        it = 0
        while max_iters is None or it < max_iters:
            state, cnt = self.step(state)
            window.append(cnt)
            it += 1
            if len(window) >= SLIDING_WINDOW:
                done = int(window.popleft())  # blocks on iteration it-4
                if verbose:
                    print(f"iter {it - SLIDING_WINDOW}: active {done}")
                if done == 0:
                    break
        jax.block_until_ready(state.values)
        return state, it


class ShardedPushExecutor:
    """Push executor over an N-device mesh: the ghost/frontier exchange is
    one fused all-gather of (values, frontier) shards — the analogue of the
    reference's whole-region old-value + old-frontier ZC reads
    (push_model.inl:234-241, 250-257)."""

    def __init__(
        self,
        graph: Graph,
        program: PushProgram,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.graph = graph
        self.program = program
        self.sg = ShardedGraph.build(graph, self.num_parts)
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)
        self._dg = {
            "src_pidx": put(self.sg.src_pidx),
            "dst_local": put(self.sg.dst_local),
            "vertex_mask": put(self.sg.vertex_mask),
        }
        if self.sg.weights is not None:
            self._dg["weights"] = put(self.sg.weights)
        specs = {k: P(PARTS_AXIS) for k in self._dg}
        mapped = jax.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(PushState(P(PARTS_AXIS), P(PARTS_AXIS)), specs),
            out_specs=(PushState(P(PARTS_AXIS), P(PARTS_AXIS)), P(PARTS_AXIS)),
        )
        self._step = jax.jit(mapped, donate_argnums=0)

    def _shard_step(self, state: PushState, dg):
        prog = self.program
        max_nv = self.sg.max_nv
        v = state.values[0]
        f = state.frontier[0]
        all_v = jax.lax.all_gather(v, PARTS_AXIS).reshape(-1)
        all_f = jax.lax.all_gather(f, PARTS_AXIS).reshape(-1)
        sidx = dg["src_pidx"][0]
        src_vals = all_v[sidx]
        src_front = all_f[sidx]
        w = dg["weights"][0] if "weights" in dg else None
        cand = prog.relax(src_vals, w)
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(src_front, cand, ident)
        acc = segment_reduce(
            cand, dg["dst_local"][0], num_segments=max_nv + 1,
            kind=prog.combiner,
        )[:max_nv]
        if prog.combiner == "min":
            new = jnp.minimum(v, acc)
        else:
            new = jnp.maximum(v, acc)
        vmask = dg["vertex_mask"][0]
        new = jnp.where(vmask, new, v)
        frontier = (new != v) & vmask
        cnt = frontier.sum(dtype=jnp.int32)
        return PushState(new[None], frontier[None]), cnt[None]

    def init_state(self, **kw) -> PushState:
        sh = parts_sharding(self.mesh)
        vals = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(self.program.init_values(self.graph, **kw))
            ),
            sh,
        )
        fr = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(self.program.init_frontier(self.graph, **kw))
            ),
            sh,
        )
        return PushState(vals, fr)

    def step(self, state: PushState):
        return self._step(state, self._dg)

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[PushState] = None,
        verbose: bool = False,
        **init_kw,
    ):
        if state is None:
            state = self.init_state(**init_kw)
        window = collections.deque()
        it = 0
        while max_iters is None or it < max_iters:
            state, cnts = self.step(state)
            window.append(cnts)
            it += 1
            if len(window) >= SLIDING_WINDOW:
                done = int(np.asarray(window.popleft()).sum())
                if verbose:
                    print(f"iter {it - SLIDING_WINDOW}: active {done}")
                if done == 0:
                    break
        jax.block_until_ready(state.values)
        return state, it

    def gather_values(self, state: PushState) -> np.ndarray:
        return self.sg.from_padded(np.asarray(jax.device_get(state.values)))
