"""Push-model engine: frontier-driven fixpoint iteration.

The reference push engine (core/push_model.inl + sssp/sssp_gpu.cu:335-522)
keeps an *active frontier*, expands each frontier vertex's out-edges with
atomic relaxations, adaptively switches between a sparse queue and a dense
bitmap, and between push and pull directions (frontier > nv/16 ⇒ pull,
sssp_gpu.cu:414).

TPU-native formulation: the frontier is a dense boolean mask (XLA needs
static shapes; the reference's own dense-bitmap mode, sssp_gpu.cu:248-281,
is the shape-stable representation). Each iteration is executed in the
*pull direction* over the CSC in-edges with non-frontier contributions
masked to the combiner identity:

    cand_e = relax(val[src_e], w_e)        if frontier[src_e] else identity
    acc_v  = min/max over in-edges of v
    new_v  = combine(old_v, acc_v)
    frontier'_v = (new_v != old_v)         — the adaptive "changed" bitmap
                                             diff, cf. bitmap_kernel
                                             sssp_gpu.cu:248-281
    active = Σ frontier'                   — the halt signal the reference
                                             returns per point task
                                             (sssp_gpu.cu:521)

This is work-suboptimal for tiny frontiers (O(ne) per iteration instead of
O(frontier edges)) but every op is a large dense VPU-friendly computation;
a Pallas sparse path is layered on later.

Halt detection: the reference hides the per-iteration host round-trip for
the active count behind a 4-deep speculative window (SLIDING_WINDOW,
sssp/sssp.cc:111-129) — valid because the fixpoint is monotone, so extra
iterations are harmless. The TPU-native form goes further: up to ``chunk``
iterations run under one ``lax.while_loop`` dispatch with on-device early
exit, and the host reads one count batch per chunk. Same monotonicity
argument, ~chunk× fewer synchronizations (this round-trip is SURVEY.md
§7 hard-part (c)).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.pull import hard_sync
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import (
    NULL_RECORDER,
    consume_compile_seconds,
    engobs,
    note_compile_seconds,
    prof,
    recorder_for,
)
from lux_tpu.ops.segment import identity_for, segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh, parts_sharding
from lux_tpu.parallel.shard import ShardedGraph, resolve_exchange
from lux_tpu.utils import compat
from lux_tpu.utils.logging import get_logger
from lux_tpu.utils.timing import Timer

class PushProgram:
    """Frontier-driven vertex program (SSSP, CC, ...)."""

    name: str = "push"
    combiner: str = "min"          # 'min' | 'max'
    value_dtype = jnp.uint32
    needs_weights: bool = False
    rooted: bool = False           # takes a per-query `start` root
    servable: bool = True          # exposed through serve/session.py
    # Machine-checked capability claims (luxlint --programs, LUX604/606):
    # frontier_ok licenses the masked-identity frontier machinery above;
    # incremental_ok additionally claims the monotone-merge proof that
    # engine/incremental.py's warm-start depends on.
    frontier_ok: bool = True
    incremental_ok: bool = False
    # Declare True iff every value the program can ever hold fits in 31
    # bits (e.g. SSSP distances and CC labels, both <= nv < 2^31). The
    # blocked dense path packs the frontier bit into the value's top bit
    # and silently corrupts programs that use it — it only enables when
    # this is declared.
    packable_values: bool = False

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        raise NotImplementedError

    def relax(self, src_vals: jnp.ndarray, weights) -> jnp.ndarray:
        """Candidate value pushed along an edge from an active source."""
        raise NotImplementedError

    def edge_invariant(self, src_vals, dst_vals, weights) -> jnp.ndarray:
        """Per-edge fixpoint invariant for `-check` (True = ok). The
        reference's GPU checkers: sssp_gpu.cu:773-798,
        components_gpu.cu:769-792."""
        raise NotImplementedError


class PushState(NamedTuple):
    values: jnp.ndarray     # (nv,) or (P, max_nv)
    frontier: jnp.ndarray   # bool, same shape


def _sparse_budgets(nv: int, ne: int, queue_frac: int, edge_budget_frac: int):
    """(queue capacity, edge budget) for the bounded sparse frontier.

    Shared by the single-device and sharded executors so both pick the
    sparse branch under identical conditions. Mirrors the reference's
    per-part sparse queue sizing (nv/SPARSE_THRESHOLD + slack,
    push_model.inl:390-412)."""
    return nv // queue_frac + 128, max(ne // edge_budget_frac, 1024)


def _make_tiers(queue_cap: int, edge_budget: int):
    """Ascending (queue, edge budget) size tiers derived from the full
    budgets. Shared by both executors (like _sparse_budgets) so a policy
    tweak cannot silently diverge them: per iteration the smallest
    adequate tier serves, so a near-fixpoint frontier of a few vertices
    does not pay the full ne/8 expansion + scatter (~1 s/iter measured
    at RMAT22)."""
    tiers = []
    for div in (64, 8, 1):
        t = (max(queue_cap // div, 256), max(edge_budget // div, 1024))
        if t not in tiers:
            tiers.append(t)
    return tiers


def _tier_index(cnt, out_edges, tiers):
    """lax.switch branch index: 0 = dense, i >= 1 = tiers[i-1] (the
    smallest adequate tier; adequacy is monotone in tier size, so the
    suffix count identifies it)."""
    nadeq = jnp.int32(0)
    for (Q, E) in tiers:
        ok = (cnt <= Q) & (out_edges <= jnp.uint32(E))
        nadeq = nadeq + ok.astype(jnp.int32)
    T = len(tiers)
    return jnp.where(nadeq == 0, 0, T - nadeq + 1)


def _tier_label(tiers, tier):
    return f"sparse/{tiers[tier - 1][1]}" if tier > 0 else "dense"


def _blocked_candidates(x2d, relax, combiner, chunks, weighted: bool,
                        ne_real=None):
    """Shared scan body of the blocked dense path: per edge, one 128-lane
    row gather from the packed (value | frontier<<31) uint32 table
    ``x2d``, lane select, unpack, relax, identity-mask. ``chunks`` is
    (sb, lane[, emask][, w]) with leading scan axes; returns the flat
    candidate stream (padded length). ``ne_real`` masks positions past
    the real edge count to the identity without a per-edge mask array
    (needed by block-granular consumers, which see pad positions —
    end-pos extraction never did)."""
    iota = jnp.arange(128, dtype=jnp.int32)
    ident = identity_for(combiner, jnp.uint32)
    C = chunks[0].shape[1]

    def body(base, ch):
        ch = list(ch)
        sb, lane = ch[0], ch[1]
        w = ch.pop() if weighted else None
        em = ch[2] if len(ch) > 2 else None
        rows = x2d[sb]
        pk = jnp.where(
            lane.astype(jnp.int32)[:, None] == iota[None, :], rows, 0
        ).sum(axis=1, dtype=jnp.uint32)
        sv = pk & jnp.uint32(0x7FFFFFFF)
        active = (pk >> 31).astype(bool)
        if em is not None:
            active = active & em
        if ne_real is not None:
            # int32 is safe: blocked_dense is gated on ne < 2^31.
            active = active & (
                base + jnp.arange(C, dtype=jnp.int32) < ne_real
            )
        cand = relax(sv, w)
        return base + C, jnp.where(active, cand, ident)

    _, cands = jax.lax.scan(body, jnp.int32(0), tuple(chunks))
    return cands.reshape(-1)


def _queue_edge_slots(start, deg, E: int, ne_cap: int):
    """Static-shape expansion of a bounded queue's edge ranges.

    Given per-queue-slot CSR ``start`` offsets and ``deg`` degrees, lay
    the queued vertices' edges head-to-head into ``E`` static edge slots:
    returns (slot, edge_pos, emask) where ``slot[e]`` is the queue slot
    owning edge slot e, ``edge_pos[e]`` its position in the edge arrays
    (clipped into [0, ne_cap)), and ``emask`` marks live slots. The
    caller must mask candidates/destinations with ``emask``."""
    offs = jnp.concatenate([jnp.zeros(1, deg.dtype), jnp.cumsum(deg)])
    total = offs[-1]
    marks = jnp.zeros(E + 1, jnp.int32).at[
        jnp.clip(offs[:-1], 0, E)
    ].add(1, mode="drop")
    slot = jnp.clip(jnp.cumsum(marks[:E]) - 1, 0, start.shape[0] - 1)
    e_idx = jnp.arange(E, dtype=offs.dtype)
    emask = e_idx < total
    edge_pos = jnp.clip(start[slot] + (e_idx - offs[slot]), 0, ne_cap - 1)
    return slot, edge_pos, emask


def _chunk_while(one_iter, state: PushState, k: int, limit):
    """Run up to ``min(k, limit)`` fixpoint iterations on-device with
    early exit.

    The reference pays one host round-trip per iteration past its 4-deep
    window to read the halt count (sssp.cc:116-124); on TPU (especially a
    tunneled one) that round-trip dominates tiny iterations, so the whole
    loop runs under ``lax.while_loop`` and the host syncs once per chunk.
    ``k`` is static (compiled once); ``limit`` is a traced bound so partial
    final chunks reuse the same executable instead of recompiling.
    ``one_iter`` returns (state, count, took_sparse); returns
    (state, counts[k], sparse_flags[k], iters_done, last_count).
    """

    def cond(carry):
        _, i, last, _, _ = carry
        return (i < jnp.minimum(k, limit)) & (last > 0)

    def body(carry):
        st, i, _, counts, flags = carry
        st, cnt, sp = one_iter(st)
        counts = jax.lax.dynamic_update_index_in_dim(
            counts, cnt, i, axis=0
        )
        flags = jax.lax.dynamic_update_index_in_dim(
            flags, sp, i, axis=0
        )
        return st, i + 1, cnt, counts, flags

    init = (
        state, jnp.int32(0), jnp.int32(1),
        jnp.zeros(k, jnp.int32), jnp.zeros(k, jnp.int32),
    )
    st, done, last, counts, flags = jax.lax.while_loop(cond, body, init)
    return st, counts, flags, done, last


class PushExecutor:
    """Single-device push executor with adaptive direction switching.

    Two per-iteration strategies, chosen on-device by ``lax.cond`` the way
    the reference switches per iteration (sssp_gpu.cu:414-421):

    - **dense (pull direction)**: masked relax over all CSC in-edges —
      O(ne) but fully vectorized. Used for large frontiers.
    - **sparse (push direction)**: compact the frontier into a bounded
      queue (the FrontierHeader/queue design, push_model.inl:390-412,
      made static-shape), expand exactly the queued vertices' out-edges
      through the CSR, scatter-combine the candidates. Work scales with
      the *edge budget*, not ne — the win when frontiers are small, since
      on TPU gathers/scatters cost per element.

    Sparse is taken when the previous frontier fits the queue AND its
    out-edge total fits the edge budget; otherwise dense (the reference's
    sparse→dense overflow fallback, sssp_gpu.cu:462-491).
    """

    # Edge count below which the blocked dense path's fixed passes cost
    # more than they save over the plain gather/scatter formulation.
    BLOCKED_DENSE_MIN_NE = 1 << 16

    def __init__(
        self,
        graph: Graph,
        program: PushProgram,
        device=None,
        sparse: bool = True,
        queue_frac: int = 16,     # queue capacity = nv/queue_frac + slack
        edge_budget_frac: int = 8,  # edge budget = ne/edge_budget_frac
        blocked_dense: Optional[bool] = None,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.device = device
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        if blocked_dense is None:
            blocked_dense = (
                graph.ne >= self.BLOCKED_DENSE_MIN_NE
                and getattr(program, "packable_values", False)
                and program.value_dtype == jnp.uint32
                and graph.nv < 2**31
                and graph.ne < 2**31   # end positions are int32
            )
        elif blocked_dense:
            # An explicit request must not silently corrupt: the packed
            # table carries the frontier in the value's top bit and the
            # scan layout uses int32 positions.
            if program.value_dtype != jnp.uint32 or not getattr(
                program, "packable_values", False
            ):
                raise ValueError(
                    "blocked_dense needs a program declaring "
                    "packable_values (uint32 values < 2^31); "
                    f"{program.name} does not"
                )
            if graph.nv >= 2**31 or graph.ne >= 2**31:
                raise ValueError(
                    "blocked_dense needs nv and ne < 2^31 "
                    f"(got nv={graph.nv}, ne={graph.ne})"
                )
        self.blocked_dense = bool(blocked_dense)
        dg = {}
        if not self.blocked_dense:
            # The plain dense stages' arrays; the blocked path replaces
            # them with blk_* (skipping ~8 B/edge of dead HBM).
            dg["col_src"] = put(graph.col_src.astype(np.int32))
            dg["seg_ids"] = put(graph.col_dst)
            if graph.weights is not None:
                dg["weights"] = put(graph.weights)
        self.sparse = sparse and graph.ne >= 1024
        if self.sparse:
            self.queue_cap, self.edge_budget = _sparse_budgets(
                int(graph.nv), int(graph.ne), queue_frac, edge_budget_frac
            )
            self.tiers = _make_tiers(self.queue_cap, self.edge_budget)
            from lux_tpu.engine.pull import _edge_index_dtype

            csr = graph.csr()
            eidx = _edge_index_dtype(graph.ne)
            dg["csr_row_ptr"] = put(csr.row_ptr.astype(eidx))
            dg["csr_col_dst"] = put(csr.col_dst)
            if csr.weights is not None:
                dg["csr_weights"] = put(csr.weights)
            dg["out_degrees"] = put(graph.out_degrees.astype(np.int32))

        # Blocked dense path: serve per-edge (value, frontier-bit) via
        # 128-lane row gathers + lane select (the tail trick) from ONE
        # packed uint32 table, and reduce with a segmented min/max scan —
        # both ends of the plain dense iteration run at TPU scalar rate
        # (~8.5 ns/gather elem, ~45 ns/scatter row; phase-measured 1.45 s
        # load + 0.93 s comp per RMAT22 iteration, vs 0.39 + 0.51
        # blocked — 2.4x on the fused fixpoint). Needs values < 2^31
        # (the top bit carries the frontier), true for SSSP distances and
        # CC labels (both < nv).
        if self.blocked_dense:
            from lux_tpu.ops.segment import BlockMinLayout
            from lux_tpu.ops.tiled_spmv import GATHER_TABLE_BYTES

            C = 1 << 17
            ne = graph.ne
            pad = (-ne) % C
            sb = np.pad(graph.col_src >> 7, (0, pad)).astype(np.int32)
            lane = np.pad(graph.col_src & 127, (0, pad)).astype(np.int8)
            dg["blk_sb"] = put(sb.reshape(-1, C))
            dg["blk_lane"] = put(lane.reshape(-1, C))
            if graph.weights is not None:
                dg["blk_w"] = put(
                    np.pad(graph.weights, (0, pad)).reshape(-1, C)
                )
            # Block-min reduction layout (one dense 128-block reduce +
            # a 128x-smaller block-level segmented scan + masked
            # head/tail row extraction from sub-cliff table slices) —
            # replaces the edge-level associative min-scan whose
            # log-depth passes dominated compTime (~4 ns/edge measured).
            layout = BlockMinLayout(
                graph.row_ptr, ne + pad,
                seg_rows=GATHER_TABLE_BYTES // 512,
            )
            self._bm_segs = (layout.head_segs, layout.tail_segs)
            for k, v in layout.device_arrays().items():
                dg[k] = put(v)
        self._dg = dg
        self.sparse_iters = 0       # sparse-branch count of the last run()
        self._step = jax.jit(self._step_impl, donate_argnums=0)
        self._multi_jit = jax.jit(
            self._chunk_impl, donate_argnums=0, static_argnums=2
        )

    # -- dense (pull-direction) stages ------------------------------------
    # Each strategy is three stages (load / comp / update) so the fused
    # iteration and the `-verbose` phase_step share one implementation
    # (the reference's phase split, sssp_gpu.cu:389-513).

    def _d_load(self, state: PushState, dg):
        return state.values[dg["col_src"]], state.frontier[dg["col_src"]]

    def _d_comp(self, src_vals, src_front, dg):
        prog = self.program
        cand = prog.relax(src_vals, dg.get("weights"))
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(src_front, cand, ident)
        return segment_reduce(
            cand, dg["seg_ids"], num_segments=self.graph.nv,
            kind=prog.combiner,
        )

    def _merge_update(self, state: PushState, acc):
        if self.program.combiner == "min":
            new = jnp.minimum(state.values, acc)
        else:
            new = jnp.maximum(state.values, acc)
        frontier = new != state.values
        return PushState(new, frontier), frontier.sum(dtype=jnp.int32)

    def _bd_load(self, state: PushState, dg):
        """Per-edge candidates via the packed-table row-gather + lane
        select: values and frontier bits travel in ONE uint32 table
        (top bit = frontier), so each edge costs one 512 B row fetch
        instead of two scalar gathers. Returns (ne_padded,) candidates
        already masked to the combiner identity."""
        prog = self.program
        packed = (
            state.values.astype(jnp.uint32)
            | (state.frontier.astype(jnp.uint32) << 31)
        )
        nvb = -(-self.graph.nv // 128)
        x2d = jnp.pad(packed, (0, nvb * 128 - self.graph.nv)).reshape(
            nvb, 128
        )
        has_w = "blk_w" in dg
        chunks = (dg["blk_sb"], dg["blk_lane"])
        if has_w:
            chunks = chunks + (dg["blk_w"],)
        return _blocked_candidates(
            x2d, prog.relax, prog.combiner, chunks, has_w,
            ne_real=self.graph.ne,
        )

    def _bd_comp(self, cands, dg):
        from lux_tpu.ops.segment import segment_minmax_blockmin

        head_segs, tail_segs = self._bm_segs
        return segment_minmax_blockmin(
            cands, dg, head_segs, tail_segs, self.program.combiner,
        )

    def _dense_iter(self, state: PushState, dg):
        if self.blocked_dense:
            acc = self._bd_comp(self._bd_load(state, dg), dg)
            return self._merge_update(state, acc)
        src_vals, src_front = self._d_load(state, dg)
        return self._merge_update(state, self._d_comp(src_vals, src_front, dg))

    # -- sparse (push-direction) stages -----------------------------------

    def _s_load(self, state: PushState, dg, Q=None):
        """Frontier → bounded queue (ids sorted ascending; pad slot nv)
        plus per-slot CSR ranges (padded row_ptr: q == nv → deg 0)."""
        nv = self.graph.nv
        Q = self.queue_cap if Q is None else Q
        q = jnp.nonzero(
            state.frontier, size=Q, fill_value=nv
        )[0].astype(jnp.int32)
        rp = dg["csr_row_ptr"]
        start = rp[q]
        deg = rp[jnp.minimum(q + 1, nv)] - start
        return q, start, deg

    def _s_comp(self, state: PushState, q, start, deg, dg, E=None):
        prog = self.program
        nv = self.graph.nv
        E = self.edge_budget if E is None else E
        slot, edge_pos, emask = _queue_edge_slots(
            start, deg, E, max(self.graph.ne, 1)
        )
        dst = dg["csr_col_dst"][edge_pos]
        src_vals = state.values[jnp.clip(q[slot], 0, nv - 1)]
        w = dg["csr_weights"][edge_pos] if "csr_weights" in dg else None
        cand = prog.relax(src_vals, w)
        ident = identity_for(prog.combiner, cand.dtype)
        return jnp.where(emask, cand, ident), jnp.where(emask, dst, 0)

    def _s_update(self, state: PushState, cand, dst):
        """Deterministic scatter-combine into the values (unlike the
        reference's atomicMin, sssp_gpu.cu:48-61)."""
        if self.program.combiner == "min":
            new = state.values.at[dst].min(cand)
        else:
            new = state.values.at[dst].max(cand)
        frontier = new != state.values
        return PushState(new, frontier), frontier.sum(dtype=jnp.int32)

    def _sparse_iter(self, state: PushState, dg, Q=None, E=None):
        q, start, deg = self._s_load(state, dg, Q)
        cand, dst = self._s_comp(state, q, start, deg, dg, E)
        return self._s_update(state, cand, dst)

    # -- adaptive combination --------------------------------------------

    def _decide_tier(self, state: PushState, dg):
        """Branch index for lax.switch — the static-shape analogue of
        the reference's frontier-proportional kernel sizes
        (sssp_gpu.cu:424-458); uint32 out-edge sums are exact for any
        total <= 2^32 > ne, so a tier can never be selected past its
        edge budget by rounding error."""
        cnt = state.frontier.sum(dtype=jnp.int32)
        out_edges = jnp.where(
            state.frontier, dg["out_degrees"].astype(jnp.uint32), 0
        ).sum(dtype=jnp.uint32)
        return _tier_index(cnt, out_edges, self.tiers)

    def _one_iter(self, state: PushState, dg):
        if not self.sparse:
            st, cnt = self._dense_iter(state, dg)
            return st, cnt, jnp.int32(0)
        tier = self._decide_tier(state, dg)
        branches = [lambda st: self._dense_iter(st, dg)]
        for (Q, E) in self.tiers:
            branches.append(
                lambda st, Q=Q, E=E: self._sparse_iter(st, dg, Q, E)
            )
        st, ncnt = jax.lax.switch(tier, branches, state)
        return st, ncnt, (tier > 0).astype(jnp.int32)

    def _step_impl(self, state: PushState, dg):
        st, cnt, _ = self._one_iter(state, dg)
        return st, cnt

    def _chunk_impl(self, state: PushState, dg, k: int, limit=None):
        one_iter = lambda st: self._one_iter(st, dg)
        return _chunk_while(one_iter, state, k, limit)

    def _phase_jits(self):
        """Jitted wrappers of the shared load/comp/update stage methods
        (one implementation for the fused iteration and the `-verbose`
        phases — they cannot drift)."""
        if not hasattr(self, "_jphase"):
            # Both dense strategies normalize to load -> tuple of
            # intermediates, comp(*intermediates, dg) -> acc, so the
            # timing scaffolding below is strategy-agnostic.
            if self.blocked_dense:
                load_fn = lambda st, dg: (self._bd_load(st, dg),)
                comp_fn = lambda cands, dg: self._bd_comp(cands, dg)
            else:
                load_fn = self._d_load
                comp_fn = self._d_comp
            self._jphase = {
                "d_load": jax.jit(load_fn),
                "d_comp": jax.jit(comp_fn),
                "update": jax.jit(self._merge_update),
            }
            if self.sparse:
                # One (s_load, s_comp) pair per size tier, so the phase
                # breakdown measures the SAME executables run() selects
                # (the "they cannot drift" contract).
                self._jphase["decide"] = jax.jit(self._decide_tier)
                for i, (Q, E) in enumerate(self.tiers):
                    self._jphase[f"s_load{i}"] = jax.jit(
                        lambda st, dg, Q=Q: self._s_load(st, dg, Q)
                    )
                    self._jphase[f"s_comp{i}"] = jax.jit(
                        lambda st, q, s, d, dg, E=E: self._s_comp(
                            st, q, s, d, dg, E
                        )
                    )
                self._jphase["s_update"] = jax.jit(self._s_update)
        return self._jphase

    def warmup_phases(self, state: PushState):
        """Compile every phase jit (all branches and tiers) outside any
        timed region — mirrors warmup()'s contract that ELAPSED TIME
        excludes compilation. ``state`` is read, never donated."""
        j = self._phase_jits()
        dg = self._dg
        acc = j["d_comp"](*j["d_load"](state, dg), dg)
        hard_sync(j["update"](state, acc))
        if self.sparse:
            jax.device_get(j["decide"](state, dg))
            for i in range(len(self.tiers)):
                q, start, deg = j[f"s_load{i}"](state, dg)
                cand, dst = j[f"s_comp{i}"](state, q, start, deg, dg)
                hard_sync(j["s_update"](state, cand, dst))

    def phase_step(self, state: PushState):
        """One iteration as separately-timed load/comp/update dispatches —
        the reference's per-iteration `-verbose` breakdown
        (sssp/sssp_gpu.cu:516-518: activeNodes, loadTime, compTime,
        updateTime). load = frontier staging (queue build or frontier
        gather), comp = relax + reduce, update = value merge + new
        frontier. Returns (new_state, active, info dict). Phase dispatch
        breaks fusion; use run() for timed fixpoints."""
        from lux_tpu.utils.timing import Timer

        j = self._phase_jits()
        dg = self._dg
        tier = int(
            jax.device_get(j["decide"](state, dg))
        ) if self.sparse else 0
        times = {}
        if tier > 0:
            i = tier - 1
            with Timer() as t:
                q, start, deg = hard_sync(j[f"s_load{i}"](state, dg))
            times["loadTime"] = t.elapsed
            with Timer() as t:
                cand, dst = hard_sync(
                    j[f"s_comp{i}"](state, q, start, deg, dg)
                )
            times["compTime"] = t.elapsed
            with Timer() as t:
                new_state, cnt = hard_sync(j["s_update"](state, cand, dst))
            times["updateTime"] = t.elapsed
        else:
            with Timer() as t:
                loaded = hard_sync(j["d_load"](state, dg))
            times["loadTime"] = t.elapsed
            with Timer() as t:
                acc = hard_sync(j["d_comp"](*loaded, dg))
            times["compTime"] = t.elapsed
            with Timer() as t:
                new_state, cnt = hard_sync(j["update"](state, acc))
            times["updateTime"] = t.elapsed
        times["branch"] = _tier_label(self.tiers, tier)
        return new_state, int(jax.device_get(cnt)), times

    def init_state(self, **kw) -> PushState:
        vals = jax.device_put(
            jnp.asarray(self.program.init_values(self.graph, **kw)),
            self.device,
        )
        fr = jax.device_put(
            jnp.asarray(self.program.init_frontier(self.graph, **kw)),
            self.device,
        )
        return PushState(vals, fr)

    def step(self, state: PushState):
        return self._step(state, self._dg)

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[PushState] = None,
        chunk: int = 16,
        recorder=None,
        **init_kw,
    ):
        """Iterate to fixpoint; returns (final_state, iterations_run).

        Runs ``chunk`` iterations per device dispatch with on-device early
        exit; the host reads back one count batch per chunk. The number of
        iterations served by the sparse (push-direction) branch is left in
        ``self.sparse_iters`` after each run."""
        if state is None:
            state = self.init_state(**init_kw)
        rec = recorder if recorder is not None else recorder_for(
            "push", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne))
        state, total, self.sparse_iters = _run_to_fixpoint(
            self._multi, state, max_iters, chunk, recorder=rec
        )
        rec.finish()
        return state, total

    def _multi(self, state: PushState, limit: int, k: int):
        return self._multi_jit(state, self._dg, k, limit=jnp.int32(limit))

    def warmup(self, chunk: int = 16, **init_kw):
        """Run one throwaway iteration through the exact run() path so
        ELAPSED TIME excludes XLA compilation AND first-transfer setup
        (both disproportionately slow on tunneled backends)."""
        with Timer() as t:
            _run_to_fixpoint(self._multi, self.init_state(**init_kw), 1, chunk)
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted single-iteration
        step with example args exactly as step() passes them."""
        return {
            "kind": "push",
            "fn": self._step,
            "args": (self.init_state(**init_kw), self._dg),
            "donate": (0,),
            "carry": (0,),
            "sharded": False,
        }


def _run_to_fixpoint(multi, state, max_iters, chunk, recorder=None):
    rec = recorder if recorder is not None else NULL_RECORDER
    total = 0
    sparse_total = 0
    while True:
        limit = chunk if max_iters is None else min(chunk, max_iters - total)
        if limit <= 0:
            break
        k = chunk
        state, counts, flags, done, last = multi(state, limit, k)
        # One batched transfer: on a tunneled TPU every device_get is a
        # full round-trip (~tens of ms), so fetch everything together.
        # luxlint: disable=LUX001 -- one batched fetch per chunk (not per iter) is the fixpoint design
        counts_h, flags_h, done_h, last_h = jax.device_get(
            (counts, flags, done, last)
        )
        done_i = int(np.asarray(done_h).reshape(-1)[0])
        last_i = int(np.asarray(last_h).reshape(-1)[0])
        fl = np.asarray(flags_h).reshape(-1, k)[0][:done_i]
        sparse_total += int(fl.sum())
        total += done_i
        # counts is (k,) single-device or psum-replicated (P, k) sharded;
        # row 0 is the global post-step active count either way.
        cnts = np.asarray(counts_h).reshape(-1, k)[0][:done_i]
        rec.flush(total, frontier_sizes=cnts, sparse_flags=fl)
        if last_i == 0 or done_i == 0:
            break
    hard_sync(state.values)
    rec.flush(total)
    return state, total, sparse_total


class MultiSourcePushExecutor:
    """Dense push executor over K value columns: one O(ne) sweep serves K
    independent root queries (multi-source micro-batching, the serving
    layer's headline mechanism — serve/batcher.py).

    State arrays are ``(nv, K)``; the pull-direction dense iteration
    vectorizes untouched — the per-edge gather ``values[col_src]`` becomes
    a ``(ne, K)`` row gather and the segment reduction keeps its trailing
    lane axis, so the marginal cost of lane k+1 is one more VPU lane, not
    another sweep. Per-lane fixpoints are monotone, so running every lane
    until ALL are quiet (one shared halt count) only repeats no-op
    iterations on early finishers — the same argument that justifies the
    chunked speculative window.

    Sparse/blocked strategies are single-lane-shaped (queue compaction and
    bit-packing assume scalar values), so this executor is dense-only; the
    serving layer routes single queries to the adaptive ``PushExecutor``
    and batches here.
    """

    def __init__(self, graph: Graph, program: PushProgram, k: int,
                 device=None):
        if k < 1:
            raise ValueError(f"batch width k must be >= 1 (got {k})")
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.graph = graph
        self.program = program
        self.k = int(k)
        self.device = device
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        dg = {
            "col_src": put(graph.col_src.astype(np.int32)),
            "seg_ids": put(graph.col_dst),
        }
        if graph.weights is not None:
            dg["weights"] = put(graph.weights)
        self._dg = dg
        self.sparse_iters = 0   # API parity with PushExecutor (always 0)
        self._multi_jit = jax.jit(
            self._chunk_impl, donate_argnums=0, static_argnums=2
        )

    def init_state(self, starts) -> PushState:
        """State with one value/frontier column per root in ``starts``.
        Fewer than k roots are right-padded by repeating the last root —
        duplicate lanes converge identically, so padding never changes
        results or iteration counts, and the executable stays one shape."""
        starts = list(starts)
        if not 1 <= len(starts) <= self.k:
            raise ValueError(
                f"need 1..{self.k} roots, got {len(starts)}"
            )
        starts = starts + [starts[-1]] * (self.k - len(starts))
        prog = self.program
        vals = np.stack(
            [prog.init_values(self.graph, start=s) for s in starts], axis=1
        )
        fr = np.stack(
            [prog.init_frontier(self.graph, start=s) for s in starts], axis=1
        )
        return PushState(
            jax.device_put(jnp.asarray(vals), self.device),
            jax.device_put(jnp.asarray(fr), self.device),
        )

    def _one_iter(self, state: PushState, dg):
        prog = self.program
        src_vals = state.values[dg["col_src"]]        # (ne, K)
        src_front = state.frontier[dg["col_src"]]
        w = dg.get("weights")
        cand = prog.relax(src_vals, None if w is None else w[:, None])
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(src_front, cand, ident)
        acc = segment_reduce(
            cand, dg["seg_ids"], num_segments=self.graph.nv,
            kind=prog.combiner,
        )
        if prog.combiner == "min":
            new = jnp.minimum(state.values, acc)
        else:
            new = jnp.maximum(state.values, acc)
        frontier = new != state.values
        return (
            PushState(new, frontier),
            frontier.sum(dtype=jnp.int32),
            jnp.int32(0),
        )

    def _chunk_impl(self, state: PushState, dg, k: int, limit=None):
        return _chunk_while(
            lambda st: self._one_iter(st, dg), state, k, limit
        )

    def _multi(self, state: PushState, limit: int, k: int):
        return self._multi_jit(state, self._dg, k, limit=jnp.int32(limit))

    def run(
        self,
        starts,
        max_iters: Optional[int] = None,
        chunk: int = 16,
        recorder=None,
        state: Optional[PushState] = None,
    ):
        """Run all roots in ``starts`` to their shared fixpoint; returns
        (final_state, iterations_run). Column j of ``state.values`` is
        root ``starts[j]``'s result — bit-identical to a single-source
        ``PushExecutor`` run from that root (tests/test_serve.py).

        ``state`` warm-starts the sweep from a caller-built (nv, k)
        state instead of ``init_state(starts)`` — the incremental
        executor seeds per-lane values/frontiers from a previous
        snapshot's fixpoint. Shapes must match ``init_state``'s so the
        warmed executable is reused."""
        if state is None:
            state = self.init_state(starts)
        rec = recorder if recorder is not None else recorder_for(
            "push_multi", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne, k=self.k))
        state, total, _ = _run_to_fixpoint(
            self._multi, state, max_iters, chunk, recorder=rec
        )
        rec.finish()
        return state, total

    def warmup(self, chunk: int = 16, start: int = 0):
        """Compile the chunked executable outside any timed/served
        request (the serving pool calls this once per keyed engine)."""
        with Timer() as t:
            _run_to_fixpoint(
                self._multi, self.init_state([start]), 1, chunk
            )
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, start: int = 0, **init_kw):
        """luxlint-IR hook (analysis/ir.py). The chunk executable takes
        a static width k and a dynamic iteration limit the example args
        can't carry, so `call`/`lower` close over them explicitly."""
        state = self.init_state([start])
        fn, dg, k = self._multi_jit, self._dg, self.k
        lim = jnp.int32(1)
        return {
            "kind": "push_multi",
            "fn": fn,
            "args": (state, dg),
            "call": lambda st, d: fn(st, d, k, limit=lim),
            "lower": lambda: fn.lower(state, dg, k, limit=lim),
            "donate": (0,),
            "carry": (0,),
            "sharded": False,
            "k": k,
        }

    def values_for(self, state: PushState, j: int) -> np.ndarray:
        """Host copy of lane ``j``'s value column."""
        return np.asarray(jax.device_get(state.values[:, j]))


def _validated_sg(sg: Optional[ShardedGraph], graph: Graph,
                  num_parts: int) -> ShardedGraph:
    """Accept a prebuilt partition plan (the serving layer caches one
    per (fingerprint, parts) — serve/mesh.py) after checking it really
    describes this executor's graph and mesh; build fresh otherwise."""
    if sg is None:
        return ShardedGraph.build(graph, num_parts)
    if sg.num_parts != num_parts:
        raise ValueError(
            f"prebuilt ShardedGraph has {sg.num_parts} parts, mesh has "
            f"{num_parts}"
        )
    if sg.graph is not graph:
        raise ValueError(
            "prebuilt ShardedGraph was built from a different Graph "
            "object — edge indices and partition bounds would not "
            "match this executor's graph"
        )
    return sg


class ShardedPushExecutor:
    """Push executor over an N-device mesh with the same two per-iteration
    strategies as the single-device engine, chosen on-device each
    iteration (the reference's push engine is identical single- vs
    multi-GPU for the same reason, core/push_model.inl):

    - **dense**: all-gather full (values, frontier) shards and run the
      masked pull-direction relax over local CSC in-edges — the analogue
      of the whole-region old-value + old-frontier ZC reads
      (push_model.inl:234-241, 250-257).
    - **sparse**: each shard compacts its local frontier into a bounded
      queue, the queues (+ queued values) are all-gathered — the analogue
      of streaming every part's frontier chunk H2D (sssp_gpu.cu:424-458)
      — and each shard expands the global queue against its local edges
      via a per-shard CSR keyed by *global* source id (the replicated
      push row-ptr, push_model.inl:321-324,449-465). Exchange and
      expansion cost scale with the frontier, not nv/ne.

    The branch is picked by replicated collectives (pmax of local
    frontier counts, psum of frontier out-edges) so every shard takes the
    same ``lax.cond`` side."""

    BLOCKED_DENSE_MIN_NE = PushExecutor.BLOCKED_DENSE_MIN_NE

    def __init__(
        self,
        graph: Graph,
        program: PushProgram,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
        sparse: bool = True,
        queue_frac: int = 16,       # per-shard queue = max_nv/queue_frac + slack
        edge_budget_frac: int = 8,  # per-shard edge budget = max_ne/frac
        blocked_dense: Optional[bool] = None,
        sg: Optional[ShardedGraph] = None,
    ):
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.graph = graph
        self.program = program
        self.sg = _validated_sg(sg, graph, self.num_parts)
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)

        # Blocked dense path, distributed: same single-vs-multi-identical
        # contract as the reference (core/push_model.inl) — each shard
        # serves its edges from the all-gathered packed (value,
        # frontier-bit) table via row gathers + lane select and reduces
        # with the segmented min/max scan over its local CSC.
        log = get_logger("engine")
        self.exchange_mode, self._xplan = resolve_exchange(self.sg, log)
        flat_nv = self.num_parts * self.sg.max_nv
        if blocked_dense is None:
            # The packed blocked path gathers the whole (value | frontier
            # bit) table; it has no needed-rows form, so the compact
            # exchange takes precedence when both are viable.
            blocked_dense = (
                self._xplan is None
                and graph.ne >= self.BLOCKED_DENSE_MIN_NE
                and getattr(program, "packable_values", False)
                and program.value_dtype == jnp.uint32
                and flat_nv < 2**31
                and self.sg.max_ne < 2**31
            )
        elif blocked_dense:
            if self._xplan is not None:
                log.info(
                    "LUX_EXCHANGE=compact has no packed blocked form; "
                    "explicit blocked_dense=True keeps the full exchange"
                )
                self.exchange_mode, self._xplan = "full", None
            if program.value_dtype != jnp.uint32 or not getattr(
                program, "packable_values", False
            ):
                raise ValueError(
                    "blocked_dense needs a program declaring "
                    "packable_values (uint32 values < 2^31); "
                    f"{program.name} does not"
                )
            if flat_nv >= 2**31 or self.sg.max_ne >= 2**31:
                raise ValueError(
                    "blocked_dense needs P*max_nv and max_ne < 2^31 "
                    f"(got {flat_nv}, {self.sg.max_ne})"
                )
        self.blocked_dense = bool(blocked_dense)

        self._dg = {
            "vertex_mask": put(self.sg.vertex_mask),
        }
        if self.blocked_dense:
            P_, max_ne = self.num_parts, self.sg.max_ne
            C = 1 << 17
            pad = (-max_ne) % C
            k = (max_ne + pad) // C

            def chunked(a, fill=0):
                return np.pad(
                    a, ((0, 0), (0, pad)), constant_values=fill
                ).reshape(P_, k, C)

            self._dg["blk_sb"] = put(
                chunked(self.sg.src_pidx >> 7).astype(np.int32)
            )
            self._dg["blk_lane"] = put(
                chunked(self.sg.src_pidx & 127).astype(np.int8)
            )
            self._dg["blk_emask"] = put(chunked(self.sg.edge_mask))
            if self.sg.weights is not None:
                self._dg["blk_w"] = put(chunked(self.sg.weights))
            # Per-shard block-min layouts, stacked. The head/tail gather
            # tables stay unsegmented (seg_rows=0): per-part row splits
            # are data under shard_map's one-trace model, so static
            # segmentation is not available — same tradeoff as the
            # sharded Z-stream; warn when a shard's table would cross
            # the gather cliff.
            from lux_tpu.ops.segment import BlockMinLayout
            from lux_tpu.ops.tiled_spmv import _warn_big_table

            stacked = {}
            for p in range(P_):
                layout = BlockMinLayout(
                    self.sg.local_row_ptr[p], max_ne + pad, seg_rows=0
                )
                for k_, v in layout.device_arrays().items():
                    stacked.setdefault(k_, []).append(v)
            # seg_rows=0 ⇒ one unsegmented table; derive the bounds from
            # the (identical-across-parts) padded shapes rather than the
            # last loop iteration's layout.
            one = ((0, self.sg.max_nv, 0, (max_ne + pad) // 128),)
            self._bm_segs = (one, one)
            _warn_big_table(
                (max_ne + pad) // 128, "sharded push block-min",
                advice="; use more parts",
            )
            for k_, vs in stacked.items():
                self._dg[k_] = put(np.stack(vs))
        else:
            self._dg["src_pidx"] = put(self.sg.src_pidx)
            self._dg["dst_local"] = put(self.sg.dst_local)
            if self.sg.weights is not None:
                self._dg["weights"] = put(self.sg.weights)
        if self._xplan is not None:
            self._dg["xch_send"] = put(self._xplan.send_units)
            self._dg["xch_recv"] = put(self._xplan.recv_pos)
        self.sparse = sparse and graph.ne >= 1024
        if self.sparse:
            self.queue_cap, self.edge_budget = _sparse_budgets(
                self.sg.max_nv, self.sg.max_ne, queue_frac, edge_budget_frac
            )
            self.tiers = _make_tiers(self.queue_cap, self.edge_budget)
            prp, pdst, pw = self.sg.build_push_csr()
            self._dg["push_row_ptr"] = put(prp)
            self._dg["push_dst_local"] = put(pdst)
            if pw is not None:
                self._dg["push_weights"] = put(pw)
            self._dg["out_degrees"] = put(self.sg.out_degrees)
            self._dg["row_left"] = put(
                self.sg.row_left.astype(np.int32)[:, None]
            )
        self._specs = {k: P(PARTS_AXIS) for k in self._dg}
        self.sparse_iters = 0       # sparse-branch count of the last run()
        state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))
        mapped = compat.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(state_spec, self._specs),
            out_specs=(state_spec, P(PARTS_AXIS)),
        )
        self._step = jax.jit(mapped, donate_argnums=0)
        self._chunk_cache = {}

    # Dense-iteration phases (load/comp/update split so phase_step can
    # dispatch them separately for `-verbose`; _iter_block composes them
    # into the fused step).

    def _dense_load(self, state: PushState, dg):
        """Exchange: all-gather the value+frontier shards (the whole-
        region ZC reads, push_model.inl:234-241,250-257)."""
        v = state.values[0]
        f = state.frontier[0]
        if self.blocked_dense:
            packed = v.astype(jnp.uint32) | (f.astype(jnp.uint32) << 31)
            allp = jax.lax.all_gather(packed, PARTS_AXIS).reshape(-1)
            x2d = jnp.pad(allp, (0, (-allp.shape[0]) % 128)).reshape(-1, 128)
            return (x2d,)
        if self._xplan is not None:
            # Compact exchange: fixed-capacity all_to_all of the rows
            # each receiver's real edges read (values + frontier bits),
            # scattered into the flat view at the positions src_pidx
            # indexes. Own-span rows stay zero — _dense_comp serves
            # local edges straight from the shard (the local-first
            # overlap branch), and unread remote rows carry frontier
            # False, so their candidates collapse to the identity.
            max_nv = self.sg.max_nv
            sel = jnp.minimum(dg["xch_send"][0], max_nv - 1)
            pv = jax.lax.all_to_all(
                v[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
            pf = jax.lax.all_to_all(
                f[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
            recv = dg["xch_recv"][0]
            flat = self.num_parts * max_nv
            all_v = jnp.zeros((flat + 1,), v.dtype).at[recv].set(pv)[:-1]
            all_f = jnp.zeros((flat + 1,), f.dtype).at[recv].set(pf)[:-1]
            return all_v, all_f
        all_v = jax.lax.all_gather(v, PARTS_AXIS).reshape(-1)
        all_f = jax.lax.all_gather(f, PARTS_AXIS).reshape(-1)
        return all_v, all_f

    def _dense_comp(self, loaded, dg, state: Optional[PushState] = None):
        """Relax + per-local-destination reduction; returns (acc, edges)
        where edges counts this shard's frontier-sourced edges. Compact
        exchange passes ``state`` so local-source edges relax against the
        shard's own values — a branch with no collective dependence that
        XLA overlaps with the in-flight all_to_all — selected per edge
        against the remote branch before the unchanged reduction, which
        keeps the combine order (and hence results) bitwise identical."""
        prog = self.program
        max_nv = self.sg.max_nv
        if self.blocked_dense:
            from lux_tpu.ops.segment import segment_minmax_blockmin

            (x2d,) = loaded
            has_w = "blk_w" in dg
            chunks = (dg["blk_sb"][0], dg["blk_lane"][0], dg["blk_emask"][0])
            if has_w:
                chunks = chunks + (dg["blk_w"][0],)
            cands = _blocked_candidates(
                x2d, prog.relax, prog.combiner, chunks, has_w
            )
            head_segs, tail_segs = self._bm_segs
            la = {k: v[0] for k, v in dg.items() if k.startswith("bm_")}
            acc = segment_minmax_blockmin(
                cands, la, head_segs, tail_segs, prog.combiner,
            )
            return acc, jnp.int32(-1)   # frontier bits ride inside cands
        all_v, all_f = loaded
        sidx = dg["src_pidx"][0]
        w = dg["weights"][0] if "weights" in dg else None
        if self._xplan is not None:
            v_loc = state.values[0]
            f_loc = state.frontier[0]
            own = jax.lax.axis_index(PARTS_AXIS)
            base = own * max_nv
            local = (sidx >= base) & (sidx < base + max_nv)
            lidx = jnp.clip(sidx - base, 0, max_nv - 1)
            cand_l = prog.relax(v_loc[lidx], w)
            cand_r = prog.relax(all_v[sidx], w)
            ident = identity_for(prog.combiner, cand_l.dtype)
            cand_l = jnp.where(f_loc[lidx], cand_l, ident)
            cand_r = jnp.where(all_f[sidx], cand_r, ident)
            cand = jnp.where(local, cand_l, cand_r)
            src_front = jnp.where(local, f_loc[lidx], all_f[sidx])
        else:
            src_vals = all_v[sidx]
            src_front = all_f[sidx]
            cand = prog.relax(src_vals, w)
            ident = identity_for(prog.combiner, cand.dtype)
            cand = jnp.where(src_front, cand, ident)
        acc = segment_reduce(
            cand, dg["dst_local"][0], num_segments=max_nv + 1,
            kind=prog.combiner,
        )[:max_nv]
        # Edge counter excludes pad slots (their src_pidx is 0, so a
        # frontier-active vertex 0 would count every pad edge).
        real = dg["dst_local"][0] != max_nv
        return acc, (src_front & real).sum(dtype=jnp.int32)

    def _merge_update(self, state: PushState, acc, dg):
        """Value merge + new-frontier detection (shared by both dense
        variants)."""
        prog = self.program
        v = state.values[0]
        if prog.combiner == "min":
            new = jnp.minimum(v, acc)
        else:
            new = jnp.maximum(v, acc)
        vmask = dg["vertex_mask"][0]
        new = jnp.where(vmask, new, v)
        frontier = (new != v) & vmask
        cnt = frontier.sum(dtype=jnp.int32)
        return PushState(new[None], frontier[None]), cnt

    def _iter_block(self, state: PushState, dg):
        """One dense iteration on this shard's (1, ...) blocks; returns the
        new blocks and the *local* new-frontier count. prof regions tag
        the lowered ops per phase (static names — no cache-key change);
        the scopes do not fence the schedule, so compact-mode overlap
        still happens and a device profile can measure it."""
        with prof.region("lux.push_sharded.exchange"):
            loaded = self._dense_load(state, dg)
        with prof.region("lux.push_sharded.compute"):
            acc, _ = self._dense_comp(loaded, dg, state=state)
            return self._merge_update(state, acc, dg)

    # Sparse-iteration phases (same load/comp/update split).

    def _sparse_load(self, state: PushState, dg, Q=None):
        """Local frontier → bounded queue of global ids + values, then the
        queue all-gather — the analogue of per-part frontier-chunk
        streaming (sssp_gpu.cu:424-458); O(P*Q) bytes, not O(nv)."""
        nv, max_nv = self.graph.nv, self.sg.max_nv
        Q = self.queue_cap if Q is None else Q
        v = state.values[0]
        f = state.frontier[0]
        q_loc = jnp.nonzero(f, size=Q, fill_value=max_nv)[0].astype(jnp.int32)
        qv = v[jnp.clip(q_loc, 0, max_nv - 1)]
        base = dg["row_left"][0, 0]
        qg = jnp.where(q_loc >= max_nv, jnp.int32(nv), base + q_loc)
        all_q = jax.lax.all_gather(qg, PARTS_AXIS).reshape(-1)    # (P*Q,)
        all_qv = jax.lax.all_gather(qv, PARTS_AXIS).reshape(-1)
        return all_q, all_qv

    def _sparse_comp(self, all_q, all_qv, dg, E=None):
        """Expand the global queue against this shard's local edges via
        the global-src CSR (sentinel id nv reads deg == 0 — row_ptr is
        padded with two n_e entries). Returns (cand, dstl, edges)."""
        prog = self.program
        max_nv = self.sg.max_nv
        E = self.edge_budget if E is None else E
        rp = dg["push_row_ptr"][0]
        start = rp[all_q]
        deg = rp[all_q + 1] - start
        slot, edge_pos, emask = _queue_edge_slots(
            start, deg, E, self.sg.max_ne
        )
        dstl = dg["push_dst_local"][0][edge_pos]
        w = (
            dg["push_weights"][0][edge_pos]
            if "push_weights" in dg else None
        )
        cand = prog.relax(all_qv[slot], w)
        ident = identity_for(prog.combiner, cand.dtype)
        cand = jnp.where(emask, cand, ident)
        dstl = jnp.where(emask, dstl, max_nv)
        return cand, dstl, emask.sum(dtype=jnp.int32)

    def _sparse_update(self, state: PushState, cand, dstl, dg):
        """Deterministic scatter-combine into local values (pad slot
        max_nv swallows masked edges) + new-frontier detection."""
        prog = self.program
        max_nv = self.sg.max_nv
        v = state.values[0]
        ident = identity_for(prog.combiner, cand.dtype)
        vv = jnp.concatenate([v, jnp.full((1,), ident, v.dtype)])
        if prog.combiner == "min":
            new = vv.at[dstl].min(cand)[:max_nv]
        else:
            new = vv.at[dstl].max(cand)[:max_nv]
        vmask = dg["vertex_mask"][0]
        new = jnp.where(vmask, new, v)
        frontier = (new != v) & vmask
        cnt = frontier.sum(dtype=jnp.int32)
        return PushState(new[None], frontier[None]), cnt

    def _sparse_block(self, state: PushState, dg, Q=None, E=None):
        """One sparse iteration (fused composition of the three phases)."""
        with prof.region("lux.push_sharded.exchange"):
            all_q, all_qv = self._sparse_load(state, dg, Q)
        with prof.region("lux.push_sharded.compute"):
            cand, dstl, _ = self._sparse_comp(all_q, all_qv, dg, E)
            return self._sparse_update(state, cand, dstl, dg)

    def _decide_block(self, state: PushState, dg):
        """Per-shard active count + the replicated tier index (0 = dense,
        i >= 1 = self.tiers[i-1], smallest adequate tier). The decision
        inputs are pmax/psum collectives, so every shard agrees: each
        shard's expansion is bounded by the GLOBAL frontier out-edge
        total (its local degrees sum to the global ones), so one
        conservative test keeps all shards inside the static budgets."""
        f = state.frontier[0]
        cnt_loc = f.sum(dtype=jnp.int32)
        if not self.sparse:
            return cnt_loc, jnp.int32(0)
        oe_loc = jnp.where(
            f, dg["out_degrees"][0].astype(jnp.uint32), 0
        ).sum(dtype=jnp.uint32)
        cnt_max = jax.lax.pmax(cnt_loc, PARTS_AXIS)
        oe_tot = jax.lax.psum(oe_loc, PARTS_AXIS)
        return cnt_loc, _tier_index(cnt_max, oe_tot, self.tiers)

    def _one_iter_block(self, state: PushState, dg):
        """Adaptive per-iteration branch; returns (state, local count,
        took_sparse)."""
        _, tier = self._decide_block(state, dg)
        if not self.sparse:
            st, cnt = self._iter_block(state, dg)
            return st, cnt, jnp.int32(0)
        branches = [lambda s: self._iter_block(s, dg)]
        for (Q, E) in self.tiers:
            branches.append(
                lambda s, Q=Q, E=E: self._sparse_block(s, dg, Q, E)
            )
        st, ncnt = jax.lax.switch(tier, branches, state)
        return st, ncnt, (tier > 0).astype(jnp.int32)

    def _shard_step(self, state: PushState, dg):
        new_state, cnt, _ = self._one_iter_block(state, dg)
        return new_state, cnt[None]

    def _shard_chunk(self, state: PushState, dg, limit, k: int):
        def one_iter(st):
            new_state, cnt_local, sp = self._one_iter_block(st, dg)
            return new_state, jax.lax.psum(cnt_local, PARTS_AXIS), sp

        st, counts, flags, done, last = _chunk_while(
            one_iter, state, k, limit[0]
        )
        return st, counts[None], flags[None], done[None], last[None]

    def _multi(self, state: PushState, limit: int, k: int):
        if k not in self._chunk_cache:
            state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))
            mapped = compat.shard_map(
                lambda st, dg, lim: self._shard_chunk(st, dg, lim, k),
                mesh=self.mesh,
                in_specs=(state_spec, self._specs, P()),
                out_specs=(
                    state_spec,
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                ),
            )
            self._chunk_cache[k] = jax.jit(mapped, donate_argnums=0)
        return self._chunk_cache[k](
            state, self._dg, jnp.full((1,), limit, jnp.int32)
        )

    def init_state(self, **kw) -> PushState:
        sh = parts_sharding(self.mesh)
        vals = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(self.program.init_values(self.graph, **kw))
            ),
            sh,
        )
        fr = jax.device_put(
            jnp.asarray(
                self.sg.to_padded(self.program.init_frontier(self.graph, **kw))
            ),
            sh,
        )
        return PushState(vals, fr)

    def step(self, state: PushState):
        return self._step(state, self._dg)

    # -- per-shard `-verbose` phases -------------------------------------

    def _sharded_phase_jits(self):
        """Separately-dispatched load/comp/update phase executables, each
        a shard_map jit. SPMD phases run in lockstep across the mesh, so
        the measured walls are mesh-wide; per-shard variation shows up in
        the activeNodes/edges counters (which ARE per shard)."""
        if hasattr(self, "_pjits"):
            return self._pjits
        state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))
        specs = self._specs

        def sm(fn, in_specs, out_specs):
            # check_vma off: all_gather outputs are replicated by
            # construction but the static checker cannot infer it here.
            mapped = compat.shard_map(
                fn, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False,
            )
            return jax.jit(mapped)

        n_loaded = 1 if self.blocked_dense else 2
        compact = self._xplan is not None
        if compact:
            # Compact flat tables are per-shard scatters, not the full
            # path's replicated all_gather output; and comp needs the
            # state for the local-first branch.
            d_load = sm(
                lambda st, dg: tuple(
                    a[None] for a in self._dense_load(st, dg)
                ),
                (state_spec, specs),
                tuple(P(PARTS_AXIS) for _ in range(n_loaded)),
            )
            d_comp = sm(
                lambda st, loaded, dg: tuple(
                    a[None] for a in self._dense_comp(
                        tuple(x[0] for x in loaded), dg, state=st
                    )
                ),
                (state_spec,
                 tuple(P(PARTS_AXIS) for _ in range(n_loaded)), specs),
                (P(PARTS_AXIS), P(PARTS_AXIS)),
            )
        else:
            d_load = sm(
                lambda st, dg: self._dense_load(st, dg),
                (state_spec, specs),
                tuple(P() for _ in range(n_loaded)),
            )
            d_comp = sm(
                lambda loaded, dg: tuple(
                    a[None] for a in self._dense_comp(loaded, dg)
                ),
                (tuple(P() for _ in range(n_loaded)), specs),
                (P(PARTS_AXIS), P(PARTS_AXIS)),
            )
        j = {
            "decide": sm(
                lambda st, dg: tuple(
                    a[None] for a in self._decide_block(st, dg)
                ),
                (state_spec, specs), (P(PARTS_AXIS), P(PARTS_AXIS)),
            ),
            "d_load": d_load,
            "d_comp": d_comp,
            "update": sm(
                lambda st, acc, dg: (
                    lambda r: (r[0], r[1][None])
                )(self._merge_update(st, acc[0], dg)),
                (state_spec, P(PARTS_AXIS), specs),
                (state_spec, P(PARTS_AXIS)),
            ),
        }
        if self.sparse:
            # One (s_load, s_comp) pair per size tier, so the phase
            # breakdown measures the SAME executables run() selects.
            for i, (Q, E) in enumerate(self.tiers):
                j[f"s_load{i}"] = sm(
                    lambda st, dg, Q=Q: self._sparse_load(st, dg, Q),
                    (state_spec, specs), (P(), P()),
                )
                j[f"s_comp{i}"] = sm(
                    lambda q, qv, dg, E=E: tuple(
                        a[None] for a in self._sparse_comp(q, qv, dg, E)
                    ),
                    (P(), P(), specs),
                    (P(PARTS_AXIS), P(PARTS_AXIS), P(PARTS_AXIS)),
                )
            j["s_update"] = sm(
                lambda st, cand, dstl, dg: (
                    lambda r: (r[0], r[1][None])
                )(self._sparse_update(st, cand[0], dstl[0], dg)),
                (state_spec, P(PARTS_AXIS), P(PARTS_AXIS), specs),
                (state_spec, P(PARTS_AXIS)),
            )
        self._pjits = j
        return j

    def phase_step(self, state: PushState):
        """One iteration as separately-dispatched load/comp/update phases
        — the reference's per-GPU `-verbose` breakdown
        (sssp/sssp_gpu.cu:516-518). Returns (new_state, total_active,
        info): info carries the (mesh-lockstep) phase walls, the branch
        taken, and a per-shard list with each shard's BEFORE-step
        activeNodes and frontier-sourced edge count (-1 where the packed
        blocked path folds frontier bits into the candidates). Phase
        dispatch breaks fusion; use run() for timed fixpoints."""
        from lux_tpu.utils.timing import Timer

        j = self._sharded_phase_jits()
        dg = self._dg
        cnt_before, tier = jax.device_get(j["decide"](state, dg))
        cnt_before = np.asarray(cnt_before).reshape(-1)
        tier = int(np.asarray(tier).reshape(-1)[0])
        times = {}
        if tier > 0:
            i = tier - 1
            with Timer() as t:
                all_q, all_qv = hard_sync(j[f"s_load{i}"](state, dg))
            times["loadTime"] = t.elapsed
            with Timer() as t:
                cand, dstl, edges = hard_sync(
                    j[f"s_comp{i}"](all_q, all_qv, dg)
                )
            times["compTime"] = t.elapsed
            with Timer() as t:
                new_state, cnt = hard_sync(
                    j["s_update"](state, cand, dstl, dg)
                )
            times["updateTime"] = t.elapsed
        else:
            with Timer() as t:
                loaded = hard_sync(j["d_load"](state, dg))
            times["loadTime"] = t.elapsed
            with Timer() as t:
                if self._xplan is not None:
                    acc, edges = hard_sync(j["d_comp"](state, loaded, dg))
                else:
                    acc, edges = hard_sync(j["d_comp"](loaded, dg))
            times["compTime"] = t.elapsed
            with Timer() as t:
                new_state, cnt = hard_sync(j["update"](state, acc, dg))
            times["updateTime"] = t.elapsed
        times["branch"] = _tier_label(self.tiers, tier)
        edges_h = np.asarray(jax.device_get(edges)).reshape(-1)
        times["shards"] = [
            {"part": p, "activeNodes": int(cnt_before[p]),
             "edges": int(edges_h[p])}
            for p in range(self.num_parts)
        ]
        total = int(np.asarray(jax.device_get(cnt)).sum())
        return new_state, total, times

    def warmup_phases(self, state: PushState):
        """Compile every phase executable — the dense branch plus every
        size tier, not just the branch the given state would take —
        outside any timed region
        (mirrors the single-device warmup_phases contract; otherwise the
        first iteration on the other branch would report seconds of XLA
        compile as its phase walls). ``state`` is read, never donated."""
        j = self._sharded_phase_jits()
        dg = self._dg
        jax.device_get(j["decide"](state, dg))
        loaded = j["d_load"](state, dg)
        if self._xplan is not None:
            acc, _ = j["d_comp"](state, loaded, dg)
        else:
            acc, _ = j["d_comp"](loaded, dg)
        hard_sync(j["update"](state, acc, dg))
        if self.sparse:
            for i in range(len(self.tiers)):
                all_q, all_qv = j[f"s_load{i}"](state, dg)
                cand, dstl, _ = j[f"s_comp{i}"](all_q, all_qv, dg)
                hard_sync(j["s_update"](state, cand, dstl, dg))

    def run(
        self,
        max_iters: Optional[int] = None,
        state: Optional[PushState] = None,
        chunk: int = 16,
        recorder=None,
        **init_kw,
    ):
        if state is None:
            state = self.init_state(**init_kw)
        rec = recorder if recorder is not None else recorder_for(
            "push_sharded", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            compact = self._xplan is not None
            rec.set_exchange_bytes(
                self.exchange_bytes_per_iter(),
                note="compact_all_to_all" if compact else "dense_estimate",
                parts=self.num_parts)
            if compact:
                rec.set_overlap(True)
            useful = engobs.useful_exchange(
                self.sg, 5,
                exchanged_rows=(self._xplan.exchanged_units_per_iter
                                if compact else None))
            if useful is not None:
                rec.set_useful_bytes(useful["useful_bytes_per_iter"],
                                     useful["ratio"])
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne))
        if engobs.enabled():
            # Phase-fenced measurement fixpoint (LUX_ENGOBS); the off
            # path keeps the exact chunked fused executable below.
            state, total, self.sparse_iters = engobs.run_push_phased(
                self, state, max_iters, rec)
        else:
            state, total, self.sparse_iters = _run_to_fixpoint(
                self._multi, state, max_iters, chunk, recorder=rec
            )
        rec.finish()
        return state, total

    def warmup(self, chunk: int = 16, **init_kw):
        with Timer() as t:
            _run_to_fixpoint(self._multi, self.init_state(**init_kw), 1, chunk)
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted shard_map step;
        sharded=True, so LUX105 demands a collective in the trace. The
        exchange_* keys feed LUX404-406 (``luxlint --exchange``)."""
        return {
            "kind": "push_sharded",
            "fn": self._step,
            "args": (self.init_state(**init_kw), self._dg),
            "donate": (0,),
            "carry": (0,),
            "sharded": True,
            "exchange_mode": self.exchange_mode,
            "exchange_bytes": self.exchange_bytes_per_iter(),
            "combiner": getattr(self.program, "combiner", ""),
            "value_dtype": np.dtype(
                getattr(self.program, "value_dtype", np.uint32)).name,
            "num_parts": self.num_parts,
            "plan": self._xplan,
        }

    def exchange_bytes_per_iter(self) -> int:
        """Dense-branch upper bound on cross-device traffic: each part
        broadcasts its candidate table (max_nv values @4B + 1B flag) to
        the P-1 others. The sparse branch moves less; per-branch
        accounting would need device readbacks the fixpoint loop doesn't
        do. This is the number PERF.md's serve_bench.v1 evidence policy
        reports per device. Compact mode reports the packed figure — the
        fixed-capacity all_to_all payload that actually crosses the
        interconnect (still a dense-branch bound; sparse moves less)."""
        p = self.num_parts
        if self._xplan is not None:
            return self._xplan.exchange_bytes_per_iter(5)
        return p * (p - 1) * self.sg.max_nv * 5

    def gather_values(self, state: PushState) -> np.ndarray:
        return self.sg.from_padded(np.asarray(jax.device_get(state.values)))


class ShardedMultiSourcePushExecutor:
    """Multi-source push over an N-device mesh: K value lanes per vertex,
    dense pull-direction sweeps, one shared halt count — the sharded
    serving form of :class:`MultiSourcePushExecutor`, so K batched SSSP
    roots cost one distributed sweep instead of K.

    Layout composes the two parents directly: state arrays are
    ``(P, max_nv, K)`` (the partition plan's padded shards, lane axis
    trailing); each iteration all-gathers the (values, frontier) shards
    into a ``(P*max_nv, K)`` global table — the same whole-region
    exchange as :class:`ShardedPushExecutor`'s dense branch, K lanes
    wide — then relaxes over the local CSC shard with the per-lane
    identity mask and segment-reduces into local destinations. Per-lane
    fixpoints are monotone, so the shared halt count only repeats no-op
    iterations on early-finishing lanes (the single-device argument,
    unchanged by sharding).

    Dense-only, like the single-device multi-source engine: queue
    compaction and bit-packing are single-lane-shaped. The serving layer
    routes batch-of-one queries to the adaptive ``ShardedPushExecutor``
    and lands K-lane batches here.
    """

    def __init__(
        self,
        graph: Graph,
        program: PushProgram,
        k: int,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
        sg: Optional[ShardedGraph] = None,
    ):
        if k < 1:
            raise ValueError(f"batch width k must be >= 1 (got {k})")
        if program.needs_weights and graph.weights is None:
            raise ValueError(f"{program.name} requires an edge-weighted graph")
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.graph = graph
        self.program = program
        self.k = int(k)
        self.sg = _validated_sg(sg, graph, self.num_parts)
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)
        dg = {
            "src_pidx": put(self.sg.src_pidx),
            "dst_local": put(self.sg.dst_local),
            "vertex_mask": put(self.sg.vertex_mask),
        }
        if self.sg.weights is not None:
            dg["weights"] = put(self.sg.weights)
        self.exchange_mode, self._xplan = resolve_exchange(
            self.sg, get_logger("engine"))
        if self._xplan is not None:
            dg["xch_send"] = put(self._xplan.send_units)
            dg["xch_recv"] = put(self._xplan.recv_pos)
        self._dg = dg
        self._specs = {key: P(PARTS_AXIS) for key in dg}
        self.sparse_iters = 0   # API parity with the sharded push engine
        state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))
        mapped = compat.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(state_spec, self._specs),
            out_specs=(state_spec, P(PARTS_AXIS)),
        )
        self._step = jax.jit(mapped, donate_argnums=0)
        self._chunk_cache = {}

    def _exchange_lanes_block(self, state: PushState, dg):
        """Exchange bracket: all-gather the (values, frontier) shards
        into (P*max_nv, K) global tables. Split from the compute bracket
        so ``phase_step`` can fence the collective separately; the fused
        ``_iter_block`` composes both, so the traced ops are identical.
        Compact mode moves only the needed rows — two fixed-capacity
        all_to_alls of packed (capacity, K) slabs scattered into the flat
        view; own-span and unread rows stay zero (frontier False), and
        the compute bracket's local-first select never reads them."""
        v = state.values[0]                            # (max_nv, K)
        f = state.frontier[0]
        if self._xplan is not None:
            max_nv = self.sg.max_nv
            sel = jnp.minimum(dg["xch_send"][0], max_nv - 1)
            pv = jax.lax.all_to_all(
                v[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
            pf = jax.lax.all_to_all(
                f[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
            recv = dg["xch_recv"][0]
            flat = self.num_parts * max_nv
            all_v = jnp.zeros((flat + 1, self.k), v.dtype)
            all_f = jnp.zeros((flat + 1, self.k), f.dtype)
            return (all_v.at[recv].set(pv)[:-1], all_f.at[recv].set(pf)[:-1])
        all_v = jax.lax.all_gather(v, PARTS_AXIS).reshape(-1, self.k)
        all_f = jax.lax.all_gather(f, PARTS_AXIS).reshape(-1, self.k)
        return all_v, all_f

    def _compute_lanes_block(self, state: PushState, all_v, all_f, dg):
        """Local-compute bracket: relax this shard's edges against the
        gathered tables, segment-reduce into local destinations, apply."""
        prog = self.program
        v = state.values[0]                            # (max_nv, K)
        sidx = dg["src_pidx"][0]
        w = dg["weights"][0] if "weights" in dg else None
        wk = None if w is None else w[:, None]
        if self._xplan is not None:
            # Local-first overlap: the local branch relaxes against the
            # shard's own lanes (no collective dependence), the remote
            # branch against the scattered table; the per-edge select
            # runs before the unchanged reduction, so the combine order
            # — and the results — stay bitwise identical to full.
            f_loc = state.frontier[0]
            own = jax.lax.axis_index(PARTS_AXIS)
            base = own * self.sg.max_nv
            local = (sidx >= base) & (sidx < base + self.sg.max_nv)
            lidx = jnp.clip(sidx - base, 0, self.sg.max_nv - 1)
            cand_l = prog.relax(v[lidx], wk)
            cand_r = prog.relax(all_v[sidx], wk)
            ident = identity_for(prog.combiner, cand_l.dtype)
            cand_l = jnp.where(f_loc[lidx], cand_l, ident)
            cand_r = jnp.where(all_f[sidx], cand_r, ident)
            cand = jnp.where(local[:, None], cand_l, cand_r)
        else:
            src_vals = all_v[sidx]                     # (max_ne, K)
            src_front = all_f[sidx]
            cand = prog.relax(src_vals, wk)
            ident = identity_for(prog.combiner, cand.dtype)
            cand = jnp.where(src_front, cand, ident)
        # Pad edges carry dst_local == max_nv: they land in the dropped
        # trash segment for every lane, so no edge mask is needed here
        # (same trick as the sharded single-source dense branch).
        acc = segment_reduce(
            cand, dg["dst_local"][0], num_segments=self.sg.max_nv + 1,
            kind=prog.combiner,
        )[: self.sg.max_nv]
        if prog.combiner == "min":
            new = jnp.minimum(v, acc)
        else:
            new = jnp.maximum(v, acc)
        vmask = dg["vertex_mask"][0][:, None]
        new = jnp.where(vmask, new, v)
        frontier = (new != v) & vmask
        return (
            PushState(new[None], frontier[None]),
            frontier.sum(dtype=jnp.int32),
        )

    def _iter_block(self, state: PushState, dg):
        """One dense K-lane iteration on this shard's (1, max_nv, K)
        blocks; returns the new blocks and the local new-frontier count
        (summed over lanes)."""
        with prof.region("lux.push_multi_sharded.exchange"):
            all_v, all_f = self._exchange_lanes_block(state, dg)
        with prof.region("lux.push_multi_sharded.compute"):
            return self._compute_lanes_block(state, all_v, all_f, dg)

    def _shard_step(self, state: PushState, dg):
        new_state, cnt = self._iter_block(state, dg)
        return new_state, cnt[None]

    def _shard_chunk(self, state: PushState, dg, limit, k: int):
        def one_iter(st):
            new_state, cnt_local = self._iter_block(st, dg)
            return (
                new_state,
                jax.lax.psum(cnt_local, PARTS_AXIS),
                jnp.int32(0),
            )

        st, counts, flags, done, last = _chunk_while(
            one_iter, state, k, limit[0]
        )
        return st, counts[None], flags[None], done[None], last[None]

    def _multi(self, state: PushState, limit: int, k: int):
        if k not in self._chunk_cache:
            state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))
            mapped = compat.shard_map(
                lambda st, dg, lim: self._shard_chunk(st, dg, lim, k),
                mesh=self.mesh,
                in_specs=(state_spec, self._specs, P()),
                out_specs=(
                    state_spec,
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                    P(PARTS_AXIS),
                ),
            )
            self._chunk_cache[k] = jax.jit(mapped, donate_argnums=0)
        return self._chunk_cache[k](
            state, self._dg, jnp.full((1,), limit, jnp.int32)
        )

    def init_state(self, starts) -> PushState:
        """(P, max_nv, K) state with one lane per root; short batches are
        right-padded by repeating the last root (duplicate lanes converge
        identically — results, iteration counts, and the executable shape
        are all unchanged: the zero-recompile contract)."""
        starts = list(starts)
        if not 1 <= len(starts) <= self.k:
            raise ValueError(
                f"need 1..{self.k} roots, got {len(starts)}"
            )
        starts = starts + [starts[-1]] * (self.k - len(starts))
        prog = self.program
        vals = np.stack(
            [prog.init_values(self.graph, start=s) for s in starts], axis=1
        )
        fr = np.stack(
            [prog.init_frontier(self.graph, start=s) for s in starts], axis=1
        )
        sh = parts_sharding(self.mesh)
        return PushState(
            jax.device_put(jnp.asarray(self.sg.to_padded(vals)), sh),
            jax.device_put(jnp.asarray(self.sg.to_padded(fr)), sh),
        )

    def step(self, state: PushState):
        return self._step(state, self._dg)

    def _phase_jits(self):
        if hasattr(self, "_pjits"):
            return self._pjits
        state_spec = PushState(P(PARTS_AXIS), P(PARTS_AXIS))

        def sm(fn, in_specs, out_specs):
            # check_vma off: the gathered lane tables are replicated by
            # construction but the static checker cannot infer it.
            return jax.jit(compat.shard_map(
                fn, mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False,
            ))

        if self._xplan is not None:
            # Per-shard scattered tables, not the replicated all_gather
            # output: carry them shard-major between the two jits.
            self._pjits = {
                "exchange": sm(
                    lambda st, dg: tuple(
                        a[None] for a in self._exchange_lanes_block(st, dg)
                    ),
                    (state_spec, self._specs),
                    (P(PARTS_AXIS), P(PARTS_AXIS)),
                ),
                "compute": sm(
                    lambda st, av, af, dg: (
                        lambda ns, cnt: (ns, cnt[None])
                    )(*self._compute_lanes_block(st, av[0], af[0], dg)),
                    (state_spec, P(PARTS_AXIS), P(PARTS_AXIS), self._specs),
                    (state_spec, P(PARTS_AXIS)),
                ),
            }
            return self._pjits
        self._pjits = {
            "exchange": sm(
                lambda st, dg: self._exchange_lanes_block(st, dg),
                (state_spec, self._specs), (P(), P()),
            ),
            "compute": sm(
                lambda st, av, af, dg: (
                    lambda ns, cnt: (ns, cnt[None])
                )(*self._compute_lanes_block(st, av, af, dg)),
                (state_spec, P(), P(), self._specs),
                (state_spec, P(PARTS_AXIS)),
            ),
        }
        return self._pjits

    def phase_step(self, state: PushState):
        """One K-lane iteration as separately-dispatched exchange and
        compute brackets; returns (new_state, total_active, times) with
        the mesh-lockstep phase walls. Dense-only engine, so the branch
        is always "dense". Fencing breaks fusion — measurement mode."""
        j = self._phase_jits()
        times = {}
        with Timer() as t:
            all_v, all_f = hard_sync(j["exchange"](state, self._dg))
        times["loadTime"] = t.elapsed
        with Timer() as t:
            new_state, cnt = hard_sync(
                j["compute"](state, all_v, all_f, self._dg)
            )
        times["compTime"] = t.elapsed
        times["branch"] = "dense"
        total = int(np.asarray(jax.device_get(cnt)).sum())
        return new_state, total, times

    def warmup_phases(self, state: PushState):
        """Compile both phase executables outside any timed region
        (``state`` is read, never donated)."""
        j = self._phase_jits()
        all_v, all_f = j["exchange"](state, self._dg)
        hard_sync(j["compute"](state, all_v, all_f, self._dg))

    def run(
        self,
        starts,
        max_iters: Optional[int] = None,
        chunk: int = 16,
        recorder=None,
        state: Optional[PushState] = None,
    ):
        """Run all roots in ``starts`` to their shared fixpoint; returns
        (final_state, iterations_run). ``gather_values(state)[:, j]`` is
        root ``starts[j]``'s result — bit-identical to a single-source
        run from that root (integer min/max combiners commute with the
        partitioned reduction order)."""
        if state is None:
            state = self.init_state(starts)
        rec = recorder if recorder is not None else recorder_for(
            "push_multi_sharded", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            compact = self._xplan is not None
            rec.set_exchange_bytes(
                self.exchange_bytes_per_iter(),
                note="compact_all_to_all" if compact else "dense_estimate",
                parts=self.num_parts)
            if compact:
                rec.set_overlap(True)
            useful = engobs.useful_exchange(
                self.sg, 5 * self.k,
                exchanged_rows=(self._xplan.exchanged_units_per_iter
                                if compact else None))
            if useful is not None:
                rec.set_useful_bytes(useful["useful_bytes_per_iter"],
                                     useful["ratio"])
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne, k=self.k))
        if engobs.enabled():
            # Phase-fenced measurement fixpoint (LUX_ENGOBS); off keeps
            # the exact chunked fused executable below.
            state, total, _ = engobs.run_push_phased(
                self, state, max_iters, rec)
        else:
            state, total, _ = _run_to_fixpoint(
                self._multi, state, max_iters, chunk, recorder=rec
            )
        rec.finish()
        return state, total

    def warmup(self, chunk: int = 16, start: int = 0):
        """Compile the chunked executable outside any timed/served
        request (the serving pool calls this once per keyed engine)."""
        with Timer() as t:
            _run_to_fixpoint(
                self._multi, self.init_state([start]), 1, chunk
            )
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, start: int = 0, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted shard_map step;
        sharded=True, so LUX105 demands a collective in the trace. The
        exchange_* keys feed LUX404-406 (``luxlint --exchange``)."""
        return {
            "kind": "push_multi_sharded",
            "fn": self._step,
            "args": (self.init_state([start]), self._dg),
            "donate": (0,),
            "carry": (0,),
            "sharded": True,
            "exchange_mode": self.exchange_mode,
            "exchange_bytes": self.exchange_bytes_per_iter(),
            "combiner": getattr(self.program, "combiner", ""),
            "value_dtype": np.dtype(
                getattr(self.program, "value_dtype", np.uint32)).name,
            "num_parts": self.num_parts,
            "k": self.k,
            "plan": self._xplan,
        }

    def exchange_bytes_per_iter(self) -> int:
        """Per-iteration exchange figure. Full: the K-lane candidate
        table broadcast — (max_nv values @4B + 1B flag) x K lanes from
        each part to the P-1 others (a dense estimate). Compact: the
        measured packed payload the fixed-capacity all_to_alls move,
        K lanes x 5 bytes per exchanged row."""
        p = self.num_parts
        if self._xplan is not None:
            return self._xplan.exchange_bytes_per_iter(5 * self.k)
        return p * (p - 1) * self.sg.max_nv * self.k * 5

    def gather_values(self, state: PushState) -> np.ndarray:
        """(nv, K) host array: every lane's values in one device fetch
        (the serving layer slices columns out of this rather than paying
        one device round-trip per lane)."""
        return self.sg.from_padded(np.asarray(jax.device_get(state.values)))

    def values_for(self, state: PushState, j: int) -> np.ndarray:
        """Host copy of lane ``j``'s value column."""
        return self.sg.from_padded(
            np.asarray(jax.device_get(state.values[:, :, j]))
        )
