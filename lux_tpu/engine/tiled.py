"""Hybrid pull executor: MXU strips + lane-select tail, no scalar gathers.

Drop-in alternative to :class:`lux_tpu.engine.pull.PullExecutor` for pull
programs whose edge contribution is the source value itself
(``program.identity_contrib``) with a ``sum`` combiner — i.e. SpMV-shaped
iterations like PageRank (the reference stores rank pre-divided by
out-degree precisely so its gather side is an identity sum,
pagerank/pagerank_gpu.cu:90-99).

Internally the executor runs in degree-sorted vertex order (the plan's
"internal" space) and converts at the public API boundary, so callers
see external vertex ids exactly like the plain executor. See
:mod:`lux_tpu.ops.tiled_spmv` for the layout design and measured rates.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import PullProgram, VertexCtx
from lux_tpu.engine.pull import (
    hard_sync,
    make_fused_runner,
    run_maybe_fused,
)
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import (
    NULL_RECORDER,
    consume_compile_seconds,
    note_compile_seconds,
    recorder_for,
)
from lux_tpu.utils.timing import Timer
from lux_tpu.ops.merge_tail_kernel import (
    DeviceGroupedTail,
    grouped_tail_enabled,
)
from lux_tpu.ops.tiled_spmv import (
    DEFAULT_CHUNK_STRIPS,
    DEFAULT_CHUNK_TAIL,
    DeviceHybrid,
    HybridPlan,
    hybrid_spmv,
    plan_hybrid,
)


def spmv_capable(program: PullProgram) -> bool:
    """True if the strip/lane-select hybrid can run this program
    (sum combiner, edge contribution == source value)."""
    return (
        program.combiner == "sum"
        and getattr(program, "identity_contrib", False)
        and not getattr(program, "value_shape", ())  # scalar values only
    )


def get_cached_plan(
    graph: Graph,
    path: str,
    levels: Sequence[Tuple[int, int]] = ((8, 2),),
    budget_bytes: int = 8 << 30,
    log=None,
    cap: int = 15,
    pack: Optional[bool] = None,
) -> HybridPlan:
    """Load the hybrid plan cached at ``path`` (validating it against the
    graph), else plan and save. Planning costs minutes of host time at
    RMAT22+ scale and is graph-deterministic, so every entry point (CLI,
    bench) should come through here. A failed save (read-only graph dir)
    degrades to planning without a cache. ``pack`` is the caller's
    nibble-packing intent (None = the LUX_PACK_STRIPS env default): a
    cap-127 legacy cache is perfectly servable unless packing will
    actually be used."""
    import os

    from lux_tpu.ops.tiled_spmv import load_plan, resolve_pack, save_plan

    say = log if log is not None else (lambda *_: None)
    load_path = path
    if not os.path.exists(path) and path.endswith(".luxplan"):
        # Round-1 caches used a single .npz at the same key; serve them
        # rather than replanning (load_plan keeps the legacy reader). A
        # replan still saves to the .luxplan path, not the legacy name.
        legacy = path[: -len(".luxplan")] + ".npz"
        if os.path.exists(legacy):
            say(f"serving legacy plan cache {legacy}")
            load_path = legacy
    if os.path.exists(load_path):
        plan = None
        try:
            plan = load_plan(load_path)
        except Exception as e:
            say(f"cached plan {load_path} unreadable ({e!r}) — replanning")
        if plan is not None and (
            plan.nv != graph.nv or plan.total_edges != graph.ne
        ):
            say(
                f"cached plan {load_path} does not match graph "
                f"(nv {plan.nv} vs {graph.nv}, edges {plan.total_edges} "
                f"vs {graph.ne}) — replanning"
            )
            plan = None
        # Config check. The cascade's r-sequence is recoverable from any
        # plan; thresholds/budget are recorded by current saves
        # (levels_spec/budget_bytes) and validated when present — legacy
        # caches predating those fields pass on the r-sequence alone.
        want_rs = tuple(r for r, _ in levels)
        if plan is not None and tuple(l.r for l in plan.levels) != want_rs:
            say(
                f"cached plan {load_path} has cascade r-levels "
                f"{tuple(l.r for l in plan.levels)}, requested {want_rs} "
                "— replanning"
            )
            plan = None
        want_spec = tuple((int(r), int(t)) for r, t in levels)
        if (
            plan is not None
            and plan.levels_spec is not None
            and (
                plan.levels_spec != want_spec
                or plan.budget_bytes != int(budget_bytes)
            )
        ):
            say(
                f"cached plan {load_path} was planned with "
                f"levels={plan.levels_spec} budget={plan.budget_bytes}, "
                f"requested levels={want_spec} budget={int(budget_bytes)} "
                "— replanning"
            )
            plan = None
        # A plan capped tighter than requested is servable (it just
        # spilled a few more overflow edges to the tail). A looser cap
        # only matters when nibble packing will actually be used — an
        # unpacked run (the default and the measured-better config)
        # serves cap-127 legacy plans as-is.
        if plan is not None and plan.cap > cap and resolve_pack(pack, cap):
            say(
                f"cached plan {load_path} has count cap {plan.cap}, "
                f"requested <= {cap} (nibble packing needs <= 15) "
                "— replanning"
            )
            plan = None
        if plan is not None:
            return plan
    plan = plan_hybrid(graph, levels=levels, budget_bytes=budget_bytes, cap=cap)
    try:
        save_plan(path, plan)
    except OSError as e:
        say(f"could not cache plan at {path}: {e}")
    return plan


def require_spmv_program(program: PullProgram, cls: str, fallback: str):
    """Tiled executors only run sum-combiner programs whose edge
    contribution is the source value (SpMV shape)."""
    if program.combiner != "sum" or not getattr(
        program, "identity_contrib", False
    ):
        raise ValueError(
            f"{cls} requires a sum-combiner program whose "
            f"edge contribution is the source value; {program.name} "
            f"is not (use {fallback})"
        )


class TiledPullExecutor:
    """Executes an identity-contribution sum-combiner pull program via the
    strip/lane-select hybrid SpMV on a single device."""

    def __init__(
        self,
        graph: Graph,
        program: PullProgram,
        levels: Sequence[Tuple[int, int]] = ((8, 2),),
        budget_bytes: int = 8 << 30,
        chunk_strips: int = DEFAULT_CHUNK_STRIPS,
        chunk_tail: int = DEFAULT_CHUNK_TAIL,
        plan: Optional[HybridPlan] = None,
        device=None,
        pack: Optional[bool] = None,
    ):
        require_spmv_program(program, "TiledPullExecutor", "PullExecutor")
        self.graph = graph
        self.program = program
        self.device = device
        self.plan = plan if plan is not None else plan_hybrid(
            graph, levels=levels, budget_bytes=budget_bytes
        )
        p = self.plan
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        self.dhybrid = DeviceHybrid.build(
            p, chunk_strips=chunk_strips, chunk_tail=chunk_tail,
            device=device, pack=pack,
        )
        self.gtail = None
        self.gtail_stats = None
        if grouped_tail_enabled():
            from lux_tpu.obs.metrics import counter, gauge
            from lux_tpu.ops.merge_tail_plan import plan_grouped_tail

            gplan = plan_grouped_tail(
                p.tail_sb, p.tail_lane, p.tail_row_ptr)
            self.gtail = DeviceGroupedTail.build(gplan, device=device)
            self.gtail_stats = gplan.stats
            gauge("lux_grouped_tail_inflation").set(
                gplan.stats["mean_inflation"])
            counter("lux_grouped_tail_copy_rows").inc(
                gplan.stats["copy_rows"])
            counter("lux_grouped_tail_merge_rows").inc(
                gplan.stats["merge_rows"])
        self.out_degrees = put(p.out_degrees.astype(np.int32))
        self.in_degrees = put(p.in_degrees.astype(np.int32))
        self.order = put(p.order)   # external id at internal position
        self.rank = put(p.rank)     # internal position of external id
        # Device data goes through jit ARGUMENTS, never closures: a
        # closed-over array is a baked-in constant, re-uploaded with every
        # compile request (multi-GB of strips would break remote compile).
        self._step_args = (
            self.dhybrid,
            self.out_degrees,
            self.in_degrees,
            self.gtail,
        )
        self._jstep = jax.jit(self._step_impl, donate_argnums=0)
        self._step = lambda vals: self._jstep(vals, *self._step_args)
        self._jrun = make_fused_runner(self._step_impl)
        self._to_internal = jax.jit(lambda v, order: v[order])
        self._to_external = jax.jit(lambda v, rank: v[rank])

    # -- the jitted iteration (internal vertex order) --------------------

    def _apply_acc(self, vals, acc, out_degrees, in_degrees):
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=out_degrees,
            in_degrees=in_degrees,
        )
        return self.program.apply(vals, acc, ctx)

    def _step_impl(
        self, vals, dhybrid, out_degrees, in_degrees, gtail=None
    ) -> jnp.ndarray:
        acc = hybrid_spmv(vals, dhybrid, gtail)
        return self._apply_acc(vals, acc, out_degrees, in_degrees)

    # -- driver ----------------------------------------------------------
    # Every public entry point speaks EXTERNAL vertex ids, exactly like
    # PullExecutor (cli.py drives executors through init_values/step);
    # only the private _step/_init_internal work in degree-sorted order.

    def _init_internal(self) -> jnp.ndarray:
        ext = np.asarray(self.program.init_values(self.graph))
        return jax.device_put(jnp.asarray(ext[self.plan.order]), self.device)

    def init_values(self) -> jnp.ndarray:
        return jax.device_put(
            jnp.asarray(self.program.init_values(self.graph)), self.device
        )

    def step(self, vals: jnp.ndarray) -> jnp.ndarray:
        """One iteration, external order in and out (boundary converts cost
        two nv-row gathers — use run() for timed multi-iteration loops,
        which converts once per call, not per step)."""
        internal = self._to_internal(jnp.asarray(vals), self.order)
        return self._to_external(self._step(internal), self.rank)

    def phase_step(self, vals: jnp.ndarray):
        """One iteration dispatched as separately-timed phases for
        ``-verbose`` attribution (the analogue of the reference's
        per-iteration loadTime/compTime/updateTime breakdown,
        sssp/sssp_gpu.cu:516-518 — phase names follow this engine's
        actual pipeline instead of the CUDA one). Returns
        (new external vals, {phase: seconds}). Phase dispatch breaks
        XLA's cross-phase fusion, so the sum runs slower than step().

        With the grouped tail active the tail phase is dispatched one
        network level at a time; the per-level seconds land in
        ``times["tail_level<k>"]`` and in the
        ``lux_grouped_tail_level_seconds`` histograms (level 0 is the
        x2d gather level), with ``times["tail"]`` still the total."""
        from lux_tpu.ops.tiled_spmv import strips_sum, tail_sum, vals_to_x2d

        if not hasattr(self, "_jphase"):
            nv = self.graph.nv

            # The same strips/tail/apply building blocks the fused step
            # composes (hybrid_spmv) — phase timing cannot drift from it.
            def strips_fn(v, dh):
                return strips_sum(vals_to_x2d(v, dh), dh, nv)

            def tail_fn(v, dh):
                return tail_sum(vals_to_x2d(v, dh), dh)

            def apply_fn(v, acc_s, acc_t, od, idg):
                return self._apply_acc(v, acc_s + acc_t, od, idg)

            self._jphase = (
                jax.jit(strips_fn), jax.jit(tail_fn), jax.jit(apply_fn),
            )

        strips_fn, tail_fn, apply_fn = self._jphase
        times = {}
        internal = hard_sync(self._to_internal(jnp.asarray(vals), self.order))
        with Timer() as t:
            acc_s = hard_sync(strips_fn(internal, self.dhybrid))
        times["strips"] = t.elapsed
        if self.gtail is not None:
            acc_t = self._grouped_tail_phases(internal, times)
        else:
            with Timer() as t:
                acc_t = hard_sync(tail_fn(internal, self.dhybrid))
            times["tail"] = t.elapsed
        with Timer() as t:
            new = hard_sync(apply_fn(
                internal, acc_s, acc_t, self.out_degrees, self.in_degrees
            ))
        times["apply"] = t.elapsed
        return self._to_external(new, self.rank), times

    def _grouped_tail_phases(self, internal, times):
        """Tail accumulator via the merge network, one hard-synced and
        timed dispatch per level (plus the final masked per-dst
        reduction). Composes the exact building blocks grouped
        hybrid_spmv fuses, so attribution cannot drift from the real
        step."""
        from lux_tpu.obs.metrics import histogram
        from lux_tpu.ops.merge_tail_kernel import level_apply, root_reduce
        from lux_tpu.ops.tiled_spmv import vals_to_x2d

        if not hasattr(self, "_jgphase"):
            self._jgphase = (
                jax.jit(vals_to_x2d), jax.jit(level_apply),
                jax.jit(root_reduce),
            )
        x2d_fn, level_fn, finish_fn = self._jgphase
        gt = self.gtail
        total = 0.0
        with Timer() as t:
            x = hard_sync(x2d_fn(internal, self.dhybrid))
        total += t.elapsed
        for k in range(gt.n_levels + 1):
            with Timer() as t:
                x = hard_sync(level_fn(
                    x, gt.arow[k], gt.brow[k], gt.codes[k]))
            times[f"tail_level{k}"] = t.elapsed
            histogram("lux_grouped_tail_level_seconds",
                      {"level": str(k)}).observe(t.elapsed)
            total += t.elapsed
        with Timer() as t:
            acc_t = hard_sync(finish_fn(
                x, gt.nvalid_root, gt.dst_row_ptr))
        total += t.elapsed
        times["tail"] = total
        return acc_t

    def warmup(self):
        """Compile the step and both permutation converters (run(1) with
        explicit vals exercises every jitted path run() can take)."""
        with Timer() as t:
            # NULL_RECORDER: the throwaway iteration must not write a
            # telemetry report of its own.
            hard_sync(self.run(1, vals=self.init_values(),
                               recorder=NULL_RECORDER))
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted step with its
        real argument tuple (device data travels as jit ARGS here, see
        _step_args above — the audit must see that same signature)."""
        return {
            "kind": "tiled",
            "fn": self._jstep,
            "args": (self._init_internal(), *self._step_args),
            "donate": (0,),
            "carry": (0,),
            "sharded": False,
        }

    def run(
        self,
        num_iters: int,
        vals: Optional[jnp.ndarray] = None,
        flush_every: int = 8,
        recorder=None,
    ):
        if vals is None:
            internal = self._init_internal()
        else:
            internal = self._to_internal(jnp.asarray(vals), self.order)
        rec = recorder if recorder is not None else recorder_for(
            "tiled", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            from lux_tpu.obs import engobs
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne))
        internal = run_maybe_fused(
            self._jrun, self._step, internal, num_iters, flush_every,
            *self._step_args, recorder=rec,
        )
        out = hard_sync(self._to_external(internal, self.rank))
        rec.finish()
        return out
