"""Sharded hybrid pull executor: MXU strips + lane-select tail over a mesh.

Distribution design — the two layouts are two independent resources and
are balanced separately:

- **Tail edges** are owner-computes over a contiguous dst partition (the
  reference's edge-balanced contiguous vertex partitioning,
  pull_model.inl:108-131, in the plan's degree-sorted internal order at
  128-block granularity), balanced by tail-edge count with a span term so
  no shard's padded vertex span blows up.
- **Strips** are sharded by strip index in equal counts (degree sort
  concentrates strips onto hub destinations, so a dst partition would
  hand one shard nearly all strip bytes — and SPMD padding would then
  charge every shard the worst shard's allocation). Each device computes
  a *partial global* accumulator over its strips; one ``psum`` merges
  them (an nv-sized f32 all-reduce, trivial next to the strip stream).
- The per-iteration value exchange is one ``all_gather`` of the value
  shards over ICI (the reference's whole-region zero-copy read,
  pull_model.inl:454-461, as a collective), after which every shard
  serves its row gathers from the full operand locally.
- New values are written only for owned destinations; the next
  iteration's all-gather is the publish step (no explicit scatter).

Per-shard arrays are stacked on a leading ``parts`` axis and the step runs
under ``jax.shard_map``, so the same code drives a real v5e-8 ICI ring or
the CPU-simulated mesh used in tests.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.program import PullProgram, VertexCtx
from lux_tpu.engine.pull import (
    hard_sync,
    make_fused_runner,
    run_maybe_fused,
)
from lux_tpu.engine.tiled import require_spmv_program
from lux_tpu.graph.graph import Graph
from lux_tpu.ops.tiled_spmv import (
    BLOCK,
    REBASE_STRIP,
    REBASE_TAIL,
    DeviceLevel,
    HybridPlan,
    boundary_gather_data,
    lane_select_tail_sums,
    plan_hybrid,
    rebase_granularity,
    strip_boundaries,
    strip_level_spmv,
)
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh, parts_sharding


# ---------------------------------------------------------------------------
# Host-side partitioning of a HybridPlan
# ---------------------------------------------------------------------------

# Streamed-bytes cost of serving one tail edge: a 512 B row gather of the
# source block, amortized ~4x by destination locality in CSC order. The
# exact constant only shifts the balance point between strip-heavy and
# tail-heavy shards; 512 B keeps hub blocks (strip-dense) and leaf blocks
# (tail-dense) comparably weighted.
TAIL_EDGE_COST = 512


@dataclasses.dataclass(eq=False)
class PlanPartition:
    """P contiguous 128-block runs over a plan's internal dst space."""

    blk_lo: np.ndarray   # (P,) int64, inclusive
    blk_hi: np.ndarray   # (P,) int64, exclusive
    max_nvb: int         # max blocks owned by any part

    @property
    def num_parts(self) -> int:
        return self.blk_lo.shape[0]


def partition_plan(plan: HybridPlan, num_parts: int) -> PlanPartition:
    """Contiguous sweep over dst 128-blocks, balanced by tail-edge bytes
    (the reference's edge-balanced contiguous partitioning,
    pull_model.inl:108-131, under the TPU cost model), via quantile cuts
    of the cumulative cost so no shard's block SPAN can blow up either.

    Strips are NOT in this cost: they are sharded separately by strip
    index (see module docstring), so the dst partition only has to
    balance the tail."""
    nvb = plan.nvb
    tail_per_v = np.diff(plan.tail_row_ptr).astype(np.int64)
    tail_per_blk = np.pad(
        tail_per_v, (0, nvb * BLOCK - plan.nv)
    ).reshape(nvb, BLOCK).sum(axis=1)
    cost = tail_per_blk * TAIL_EDGE_COST

    # Per-block span term: degree-sorted order concentrates strip bytes in
    # the first blocks, so pure byte balance would give the leaf-heavy last
    # shard a span of most of the graph — and every shard's padded arrays
    # (and the per-iteration all-gather) are sized by the WORST span. One
    # average block-cost per block makes every block cost >= alpha, so a
    # shard's per-part quota (2*total0/P) bounds its span at 2*nvb/P + 1
    # for at most 2x byte skew.
    cost = cost + max(int(cost.sum()) // nvb, 1)

    # Quantile cuts of the cumulative cost: block b belongs to the part its
    # exclusive prefix falls into. Monotone by construction; unlike a
    # cap-greedy sweep, leftovers can't pile onto the last part.
    prefix = np.concatenate([[0], np.cumsum(cost[:-1])])
    owner = np.minimum(
        prefix * num_parts // int(cost.sum()), num_parts - 1
    ).astype(np.int64)
    parts = np.arange(num_parts, dtype=np.int64)
    blk_lo = np.searchsorted(owner, parts, side="left").astype(np.int64)
    blk_hi = np.searchsorted(owner, parts, side="right").astype(np.int64)
    assert blk_hi[-1] == nvb and (blk_hi >= blk_lo).all()
    spans = blk_hi - blk_lo
    return PlanPartition(
        blk_lo=blk_lo, blk_hi=blk_hi, max_nvb=int(max(spans.max(), 1))
    )


def _chunk2(a: np.ndarray, c: int, fill) -> np.ndarray:
    """(P, N, ...) -> (P, nchunks, C, ...) with trailing fill padding."""
    p, n = a.shape[0], a.shape[1]
    c = min(c, n) if n else 1
    pad = (-n) % c
    if pad:
        padding = np.full((p, pad) + a.shape[2:], fill, a.dtype)
        a = np.concatenate([a, padding], axis=1)
    return a.reshape((p, -1, c) + a.shape[2:])


@dataclasses.dataclass
class ShardedLevel:
    """One strip level, stacked per part: arrays lead with (P, nchunks, C).

    Strips are split across parts in equal contiguous runs of the plan's
    (row-major sorted) strip order — NOT by destination — so boundaries
    stay against GLOBAL strip rows and each part's accumulator is a
    partial sum over the whole vertex space, merged by psum in the step
    (a part's boundary ranges clip to its local strip run; rows it
    doesn't touch collapse to empty ranges and contribute zero)."""

    r: int
    cs: int                 # rebase granularity (boundary data's chunk)
    strips: jnp.ndarray     # (P, K, C, r, 128) int8
    cols: jnp.ndarray       # (P, K, C) int32  GLOBAL src 128-block ids
    bnd_blk: jnp.ndarray    # (P, nrb+1) int32 per-part boundary blocks
    bnd_off: jnp.ndarray    # (P, nrb+1) int32 per-part boundary offsets


@dataclasses.dataclass
class ShardedHybrid:
    levels: Tuple[ShardedLevel, ...]
    tail_sb: jnp.ndarray     # (P, K, C) int32 GLOBAL src block
    tail_lane: jnp.ndarray   # (P, K, C) int8
    tail_cs: int             # tail rebase granularity
    max_nvb: int             # blocks per shard (padded)


for _cls, _data, _meta in (
    (ShardedLevel, ["strips", "cols", "bnd_blk", "bnd_off"], ["r", "cs"]),
    (ShardedHybrid, ["levels", "tail_sb", "tail_lane"],
     ["tail_cs", "max_nvb"]),
):
    jax.tree_util.register_dataclass(_cls, data_fields=_data, meta_fields=_meta)


class ShardedTiledExecutor:
    """Strip/lane-select hybrid SpMV over an N-device 1-D mesh.

    Same program contract as :class:`TiledPullExecutor` (sum combiner,
    identity contribution), but the value-array contract is the sharded
    one (like :class:`ShardedPullExecutor`): ``init_values``/``step``/
    ``run`` speak the (P, max_nv) padded degree-sorted device layout, and
    ``gather_values`` converts back to a global (nv,) EXTERNAL-order host
    array.
    """

    def __init__(
        self,
        graph: Graph,
        program: PullProgram,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
        levels: Sequence[Tuple[int, int]] = ((8, 4),),
        budget_bytes: int = 6 << 30,
        chunk_strips: int = 16384,
        chunk_tail: int = 1 << 19,
        plan: Optional[HybridPlan] = None,
    ):
        require_spmv_program(
            program, "ShardedTiledExecutor", "ShardedPullExecutor"
        )
        self.graph = graph
        self.program = program
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.plan = plan if plan is not None else plan_hybrid(
            graph, levels=levels, budget_bytes=budget_bytes
        )
        self.part = partition_plan(self.plan, self.num_parts)
        self._build_device_data(chunk_strips, chunk_tail)

        specs = {k: P(PARTS_AXIS) for k in self._shard_args}
        # check_vma off: the scan carries inside strip_level_spmv /
        # lane_select_tail_sums are freshly-zeroed per-shard accumulators, which
        # the varying-manual-axes checker would otherwise insist on seeing
        # pvary-annotated at every scan site.
        mapped = jax.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(P(PARTS_AXIS), specs, P()),
            out_specs=P(PARTS_AXIS),
            check_vma=False,
        )
        jstep = jax.jit(mapped, donate_argnums=0)
        self._step = lambda vals: jstep(vals, self._shard_args, self._replicated)
        self._jrun = make_fused_runner(mapped)

    # -- host-side shard construction ------------------------------------

    def _build_device_data(self, chunk_strips: int, chunk_tail: int):
        plan, part = self.plan, self.part
        pcount, max_nvb = self.num_parts, part.max_nvb
        self.max_nv = max_nvb * BLOCK
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)

        slevels = []
        for lev in plan.levels:
            rpb = BLOCK // lev.r
            nrb_global = plan.nvb * rpb
            n = lev.rows.shape[0]
            cmax = -(-n // pcount) if n else 0
            if cmax == 0:
                blk0, off0 = strip_boundaries(lev.rows, 1, nrb_global, lev.r)
                slevels.append(ShardedLevel(
                    r=lev.r,
                    cs=1,
                    strips=put(np.zeros((pcount, 0, 1, lev.r, BLOCK), np.int8)),
                    cols=put(np.zeros((pcount, 0, 1), np.int32)),
                    bnd_blk=put(np.tile(blk0, (pcount, 1))),
                    bnd_off=put(np.tile(off0, (pcount, 1))),
                ))
                continue
            # Equal contiguous runs of the sorted strip list; pad strips
            # are zero counts (contribute nothing). Boundaries are
            # computed per part against its LOCAL run (searchsorted on the
            # slice), so uncovered global rows collapse to empty ranges.
            st = np.zeros((pcount, cmax, lev.r, BLOCK), np.int8)
            co = np.zeros((pcount, cmax), np.int32)
            c = min(chunk_strips, cmax)
            cs = rebase_granularity(c, REBASE_STRIP) if lev.r < BLOCK else c
            blk = np.zeros((pcount, nrb_global + 1), np.int32)
            off = np.zeros((pcount, nrb_global + 1), np.int32)
            for p in range(pcount):
                i0, i1 = p * cmax, min((p + 1) * cmax, n)
                k = max(i1 - i0, 0)
                st[p, :k] = lev.strips[i0:i1]
                co[p, :k] = lev.cols[i0:i1]
                blk[p], off[p] = strip_boundaries(
                    lev.rows[i0:i1], cs, nrb_global, lev.r
                )
            slevels.append(ShardedLevel(
                r=lev.r,
                cs=cs,
                strips=put(_chunk2(st, chunk_strips, 0)),
                cols=put(_chunk2(co, chunk_strips, 0)),
                bnd_blk=put(blk),
                bnd_off=put(off),
            ))

        # Tail slices (CSC by dst => contiguous per part) + per-part
        # static boundary gather data over the LOCAL row ptrs.
        v_lo = np.minimum(part.blk_lo * BLOCK, plan.nv)
        v_hi = np.minimum(part.blk_hi * BLOCK, plan.nv)
        e_lo = plan.tail_row_ptr[v_lo]
        e_hi = plan.tail_row_ptr[v_hi]
        mmax = max(int((e_hi - e_lo).max()), 0)
        c_tail = min(chunk_tail, mmax) if mmax else 1
        cs_tail = rebase_granularity(c_tail, REBASE_TAIL)
        sb = np.zeros((pcount, mmax), np.int32)
        lane = np.zeros((pcount, mmax), np.int8)
        tblk = np.zeros((pcount, self.max_nv + 1), np.int32)
        toff = np.zeros((pcount, self.max_nv + 1), np.int32)
        deg_out = np.ones((pcount, self.max_nv), np.int64)
        deg_in = np.zeros((pcount, self.max_nv), np.int64)
        vmask = np.zeros((pcount, self.max_nv), bool)
        for p in range(pcount):
            m = e_hi[p] - e_lo[p]
            nvloc = v_hi[p] - v_lo[p]
            sb[p, :m] = plan.tail_sb[e_lo[p]:e_hi[p]]
            lane[p, :m] = plan.tail_lane[e_lo[p]:e_hi[p]]
            rp = np.full(self.max_nv + 1, m, np.int64)
            rp[: nvloc + 1] = plan.tail_row_ptr[v_lo[p]: v_hi[p] + 1] - e_lo[p]
            tblk[p], toff[p] = boundary_gather_data(rp, cs_tail, 1)
            deg_out[p, :nvloc] = plan.out_degrees[v_lo[p]:v_hi[p]]
            deg_in[p, :nvloc] = plan.in_degrees[v_lo[p]:v_hi[p]]
            vmask[p, :nvloc] = True

        self.shybrid = ShardedHybrid(
            levels=tuple(slevels),
            tail_sb=put(_chunk2(sb, chunk_tail, 0)),
            tail_lane=put(_chunk2(lane, chunk_tail, 0)),
            tail_cs=cs_tail,
            max_nvb=max_nvb,
        )
        self._shard_args = {
            "tail_bnd_blk": put(tblk),
            "tail_bnd_off": put(toff),
            "out_degrees": put(deg_out.astype(np.int32)),
            "in_degrees": put(deg_in.astype(np.int32)),
            "vertex_mask": put(vmask),
        }
        # shybrid rides in the same dict so shard_map specs cover it.
        self._shard_args["hybrid"] = self.shybrid

        # Replicated helpers: block_map turns the gathered (P, max_nv)
        # shards into the global (nvb, 128) operand with one row gather
        # (block b of part p lives at flat row p*max_nvb + b - blk_lo[p]);
        # blk_lo lets each shard slice its own span out of the psum-merged
        # global strip accumulator.
        owner = np.searchsorted(part.blk_hi, np.arange(plan.nvb), side="right")
        owner = np.minimum(owner, pcount - 1)
        repl = jax.sharding.NamedSharding(self.mesh, P())
        self._replicated = {
            "block_map": jax.device_put(
                jnp.asarray(
                    (owner * max_nvb + np.arange(plan.nvb)
                     - part.blk_lo[owner]).astype(np.int32)
                ),
                repl,
            ),
            "blk_lo": jax.device_put(
                jnp.asarray(part.blk_lo.astype(np.int32)), repl
            ),
        }
        self._v_lo, self._v_hi = v_lo, v_hi

    # -- per-shard step (runs under shard_map) ---------------------------

    def _shard_step(self, vals_blk, dg, repl):
        hy: ShardedHybrid = dg["hybrid"]
        v = vals_blk[0]                                   # (max_nv,) f32
        gathered = jax.lax.all_gather(v, PARTS_AXIS)      # (P, max_nv)
        x2d = gathered.reshape(-1, BLOCK)[repl["block_map"]]  # (nvb, 128)

        # Strips: each shard sums ITS strips into a full-height partial
        # accumulator; psum merges, then the shard keeps its dst span.
        nv_g = self.plan.nvb * BLOCK
        acc_g = jnp.zeros(nv_g, jnp.float32)
        for lev in hy.levels:
            dl = DeviceLevel(
                r=lev.r, cs=lev.cs, strips=lev.strips[0], cols=lev.cols[0],
                bnd_blk=lev.bnd_blk[0], bnd_off=lev.bnd_off[0],
            )
            acc_g = acc_g + strip_level_spmv(
                x2d, dl, self.plan.nvb * (BLOCK // lev.r)
            )
        acc_g = jax.lax.psum(acc_g, PARTS_AXIS)
        start = repl["blk_lo"][jax.lax.axis_index(PARTS_AXIS)] * BLOCK
        acc = jax.lax.dynamic_slice(
            jnp.pad(acc_g, (0, self.max_nv)), (start,), (self.max_nv,)
        )
        acc = acc + lane_select_tail_sums(
            x2d, hy.tail_sb[0], hy.tail_lane[0],
            dg["tail_bnd_blk"][0], dg["tail_bnd_off"][0], hy.tail_cs,
        )

        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg["out_degrees"][0],
            in_degrees=dg["in_degrees"][0],
        )
        new = self.program.apply(v, acc, ctx)
        new = jnp.where(dg["vertex_mask"][0], new, v)
        return new[None]

    # -- driver (external vertex order at the API boundary) --------------

    def _to_padded_internal(self, ext_vals: np.ndarray) -> jnp.ndarray:
        internal = np.asarray(ext_vals)[self.plan.order]
        out = np.zeros((self.num_parts, self.max_nv), internal.dtype)
        for p in range(self.num_parts):
            n = self._v_hi[p] - self._v_lo[p]
            out[p, :n] = internal[self._v_lo[p]: self._v_hi[p]]
        return jax.device_put(jnp.asarray(out), parts_sharding(self.mesh))

    def init_values(self) -> jnp.ndarray:
        return self._to_padded_internal(
            np.asarray(self.program.init_values(self.graph))
        )

    def step(self, vals):
        return self._step(vals)

    def warmup(self):
        hard_sync(self.step(self.init_values()))

    def run(self, num_iters: int, vals=None, flush_every: int = 8):
        if vals is None:
            vals = self.init_values()
        return run_maybe_fused(
            self._jrun, self._step, vals, num_iters, flush_every,
            self._shard_args, self._replicated,
        )

    def gather_values(self, vals) -> np.ndarray:
        """Sharded padded internal layout -> global EXTERNAL (nv,) array."""
        host = np.asarray(jax.device_get(vals))
        internal = np.concatenate(
            [
                host[p, : self._v_hi[p] - self._v_lo[p]]
                for p in range(self.num_parts)
            ]
        )
        return internal[self.plan.rank]
