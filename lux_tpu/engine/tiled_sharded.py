"""Sharded hybrid pull executor: MXU strips + lane-select tail over a mesh.

Distribution design — the two layouts are two independent resources and
are balanced separately:

- **Tail edges** are owner-computes over a NON-contiguous dst partition:
  128-blocks are snake-dealt to parts by descending tail cost (see
  PlanPartition), balancing both the per-part block counts (which size
  every padded array and the per-iteration collectives) and the tail
  bytes to ~1x — a contiguous cut on the degree-sorted order (the
  reference's scheme, pull_model.inl:108-131, which partitions natural
  order) could only trade ~2x padding against ~2x tail skew. Each
  part's tail edges are the gathered concatenation of its owned blocks'
  CSC ranges, dst-sorted within the part.
- **Strips** are sharded by strip index in equal counts (degree sort
  concentrates strips onto hub destinations, so a dst partition would
  hand one shard nearly all strip bytes — and SPMD padding would then
  charge every shard the worst shard's allocation). Each device computes
  a *partial global* accumulator over its strips; one ``psum`` merges
  them (an nv-sized f32 all-reduce, trivial next to the strip stream).
- The per-iteration value exchange is one ``all_gather`` of the value
  shards over ICI (the reference's whole-region zero-copy read,
  pull_model.inl:454-461, as a collective), after which every shard
  serves its row gathers from the full operand locally.
- New values are written only for owned destinations; the next
  iteration's all-gather is the publish step (no explicit scatter).

Per-shard arrays are stacked on a leading ``parts`` axis and the step runs
under ``jax.shard_map``, so the same code drives a real v5e-8 ICI ring or
the CPU-simulated mesh used in tests.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lux_tpu.engine.program import PullProgram, VertexCtx
from lux_tpu.engine.pull import (
    hard_sync,
    make_fused_runner,
    run_maybe_fused,
)
from lux_tpu.engine.tiled import require_spmv_program
from lux_tpu.graph.graph import Graph
from lux_tpu.graph.partition import ExchangePlan
from lux_tpu.obs import (
    consume_compile_seconds,
    engobs,
    note_compile_seconds,
    prof,
    recorder_for,
)
from lux_tpu.utils import compat
from lux_tpu.utils.timing import Timer
from lux_tpu.ops.tiled_spmv import (
    BLOCK,
    DEFAULT_CHUNK_STRIPS,
    DEFAULT_CHUNK_TAIL,
    GATHER_TABLE_BYTES,
    DeviceLevel,
    HybridPlan,
    _warn_big_table as _warn_big_table_impl,
    block_level_boundaries,
    crossing_correction,
    lane_select_tail_sums,
    plan_hybrid,
    round_chunk,
    pack_strips,
    resolve_pack,
    strip_level_spmv,
    zstream_boundaries,
)
from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh, parts_sharding
from lux_tpu.parallel.shard import exchange_mode
from lux_tpu.utils.logging import get_logger


# ---------------------------------------------------------------------------
# Host-side partitioning of a HybridPlan
# ---------------------------------------------------------------------------

# Streamed-bytes cost of serving one tail edge: a 512 B row gather of the
# source block, amortized ~4x by destination locality in CSC order. The
# exact constant only shifts the balance point between strip-heavy and
# tail-heavy shards; 512 B keeps hub blocks (strip-dense) and leaf blocks
# (tail-dense) comparably weighted.
TAIL_EDGE_COST = 512


@dataclasses.dataclass(eq=False)
class PlanPartition:
    """Ownership of the plan's dst 128-blocks across P parts.

    Ownership is NON-contiguous: on the degree-sorted internal order the
    tail concentrates in the leaf (late) blocks, so any contiguous cut
    must trade padded-span blowup against tail imbalance (measured on
    RMAT24: the best contiguous balance is ~2x padding AND ~2x tail
    skew, and the padding directly inflates every per-iteration
    all-gather/reduce-scatter). Snake-dealing blocks by descending tail
    cost balances both to ~1x. The reference partitions the NATURAL
    vertex order where contiguous edge-balanced cuts suffice
    (pull_model.inl:108-131); degree sorting is what forces the
    generalization here."""

    owner: np.ndarray     # (nvb,) int32 owning part per block
    blocks: tuple         # P arrays: owned block ids, ascending
    max_nvb: int          # max blocks owned by any part (= ceil(nvb/P))

    @property
    def num_parts(self) -> int:
        return len(self.blocks)


def partition_plan(plan: HybridPlan, num_parts: int) -> PlanPartition:
    """Snake-deal dst 128-blocks to parts by descending tail-edge cost:
    part counts balance exactly (each part takes every P-th block of the
    cost-sorted order) and tail bytes balance to ~1x because adjacent
    cost ranks alternate direction each round.

    Strips are NOT in this cost: they are sharded separately by strip
    index (see module docstring), so the dst partition only has to
    balance the tail."""
    nvb = plan.nvb
    tail_per_v = np.diff(plan.tail_row_ptr).astype(np.int64)
    tail_per_blk = np.pad(
        tail_per_v, (0, nvb * BLOCK - plan.nv)
    ).reshape(nvb, BLOCK).sum(axis=1)

    order = np.argsort(-tail_per_blk, kind="stable")
    owner = np.empty(nvb, np.int32)
    ranks = np.arange(nvb, dtype=np.int64)
    rounds, pos = divmod(ranks, num_parts)
    snake = np.where(rounds % 2 == 0, pos, num_parts - 1 - pos)
    owner[order] = snake.astype(np.int32)
    blocks = tuple(
        np.flatnonzero(owner == p).astype(np.int64)
        for p in range(num_parts)
    )
    max_nvb = max(max(b.shape[0] for b in blocks), 1)
    return PlanPartition(owner=owner, blocks=blocks, max_nvb=int(max_nvb))


@dataclasses.dataclass
class ShardedLevel:
    """One strip level, stacked per part: arrays lead with (P, nchunks, C).

    Strips are split across parts in equal contiguous runs of the plan's
    (row-major sorted) strip order — NOT by destination — so boundaries
    stay against GLOBAL strip rows and each part's accumulator is a
    partial sum over the whole vertex space, merged by psum in the step
    (a part's boundary ranges clip to its local strip run; rows it
    doesn't touch collapse to empty ranges and contribute zero).
    Per-part crossing sets are padded to a common length with
    (idx=0, s0=0, s1=0) no-op entries. The Z-stream is one unsegmented
    gather table per shard (holding 1/P of the stream): P >= 4 keeps it
    under the big-table gather cliff at RMAT22+ scale; smaller part
    counts on huge graphs get a warning (see _warn_big_table)."""

    r: int
    segs: tuple
    strips: jnp.ndarray     # (P, K, C, r, 128) int8
    cols: jnp.ndarray       # (P, K, C) int32  GLOBAL src 128-block ids
    bnd_row: jnp.ndarray    # (P, nrb+1) int32
    bnd_grp: jnp.ndarray    # (P, nrb+1) int32
    xing_idx: jnp.ndarray   # (P, Xmax*r) int32
    xing_s0: jnp.ndarray    # (P, Xmax) int32
    xing_s1: jnp.ndarray    # (P, Xmax) int32
    packed: bool = False    # nibble-packed strips (see pack_strips)


@dataclasses.dataclass
class ShardedHybrid:
    levels: Tuple[ShardedLevel, ...]
    tail_sb: jnp.ndarray        # (P, K, C) int32 GLOBAL src block
    tail_lane: jnp.ndarray      # (P, K, C) int8
    tail_bnd_row: jnp.ndarray   # (P, max_nv+1) int32
    tail_bnd_grp: jnp.ndarray   # (P, max_nv+1) int32
    tail_xing_idx: jnp.ndarray  # (P, Xmax) int32
    tail_xing_s0: jnp.ndarray   # (P, Xmax) int32
    tail_xing_s1: jnp.ndarray   # (P, Xmax) int32
    tail_segs: tuple
    max_nvb: int             # blocks per shard (padded)


for _cls, _data, _meta in (
    (ShardedLevel,
     ["strips", "cols", "bnd_row", "bnd_grp",
      "xing_idx", "xing_s0", "xing_s1"],
     ["r", "segs", "packed"]),
    (ShardedHybrid,
     ["levels", "tail_sb", "tail_lane", "tail_bnd_row", "tail_bnd_grp",
      "tail_xing_idx", "tail_xing_s0", "tail_xing_s1"],
     ["tail_segs", "max_nvb"]),
):
    jax.tree_util.register_dataclass(_cls, data_fields=_data, meta_fields=_meta)


def _ranges_to_indices(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Concatenate [starts[i], starts[i]+lens[i]) ranges into one index
    array (vectorized; the tail-edge gather list of a part's owned
    blocks)."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    offs = np.concatenate(([0], np.cumsum(lens)[:-1]))
    return (
        np.arange(total, dtype=np.int64)
        + np.repeat(starts - offs, lens)
    )


def _pad_stack(arrs, width: int, dtype=np.int32) -> np.ndarray:
    """Stack variable-length 1-D arrays into (P, width), zero-padded."""
    out = np.zeros((len(arrs), width), dtype)
    for p, a in enumerate(arrs):
        out[p, : a.shape[0]] = a
    return out


def _warn_big_table(nrows: int, what: str):
    """Sharded wrapper: per-shard Z-streams are single unsegmented gather
    tables (see ops.tiled_spmv._warn_big_table) — only small part counts
    (P <= 2) on huge graphs trip this."""
    _warn_big_table_impl(
        nrows, f"sharded {what} (per-shard)",
        advice="; use more parts or the single-device executor",
    )


class ShardedTiledExecutor:
    """Strip/lane-select hybrid SpMV over an N-device 1-D mesh.

    Same program contract as :class:`TiledPullExecutor` (sum combiner,
    identity contribution), but the value-array contract is the sharded
    one (like :class:`ShardedPullExecutor`): ``init_values``/``step``/
    ``run`` speak the (P, max_nv) padded degree-sorted device layout, and
    ``gather_values`` converts back to a global (nv,) EXTERNAL-order host
    array.
    """

    def __init__(
        self,
        graph: Graph,
        program: PullProgram,
        mesh: Optional[Mesh] = None,
        num_parts: Optional[int] = None,
        levels: Sequence[Tuple[int, int]] = ((8, 2),),
        budget_bytes: int = 8 << 30,
        chunk_strips: int = DEFAULT_CHUNK_STRIPS,
        chunk_tail: int = DEFAULT_CHUNK_TAIL,
        plan: Optional[HybridPlan] = None,
        pack=None,
    ):
        require_spmv_program(
            program, "ShardedTiledExecutor", "ShardedPullExecutor"
        )
        self.graph = graph
        self.program = program
        self.mesh = mesh if mesh is not None else make_mesh(num_parts)
        self.num_parts = self.mesh.devices.size
        self.plan = plan if plan is not None else plan_hybrid(
            graph, levels=levels, budget_bytes=budget_bytes
        )
        self.part = partition_plan(self.plan, self.num_parts)
        self._pack = pack
        self._build_device_data(chunk_strips, chunk_tail)

        specs = {k: P(PARTS_AXIS) for k in self._shard_args}
        # check_vma off: the scan carries inside strip_level_spmv /
        # lane_select_tail_sums are freshly-zeroed per-shard accumulators, which
        # the varying-manual-axes checker would otherwise insist on seeing
        # pvary-annotated at every scan site.
        mapped = compat.shard_map(
            self._shard_step,
            mesh=self.mesh,
            in_specs=(P(PARTS_AXIS), specs, P()),
            out_specs=P(PARTS_AXIS),
            check_vma=False,
        )
        jstep = jax.jit(mapped, donate_argnums=0)
        self._jstep = jstep   # bare jit, for trace_step / luxlint-IR
        self._step = lambda vals: jstep(vals, self._shard_args, self._replicated)
        self._jrun = make_fused_runner(mapped)

    # -- host-side shard construction ------------------------------------

    def _build_device_data(self, chunk_strips: int, chunk_tail: int):
        plan, part = self.plan, self.part
        pcount, max_nvb = self.num_parts, part.max_nvb
        self.max_nv = max_nvb * BLOCK
        sh = parts_sharding(self.mesh)
        put = lambda x: jax.device_put(jnp.asarray(x), sh)

        # Remote-read index (exchange ledger): which global src 128-blocks
        # each part's strips/tail actually gather, collected while the
        # host-side plan arrays are alive. Block granularity — the value
        # exchange is row-wise, but a block is the finest unit the tiled
        # gather addresses.
        read_blocks = [set() for _ in range(pcount)]

        slevels = []
        for lev in plan.levels:
            rpb = BLOCK // lev.r
            nrb_global = plan.nvb * rpb
            n = lev.rows.shape[0]
            cmax = -(-n // pcount) if n else 0
            # Equal contiguous runs of the sorted strip list; pad strips
            # are zero counts (contribute nothing). Boundaries are
            # computed per part against its LOCAL run (searchsorted on the
            # slice), so uncovered global rows collapse to empty ranges.
            c = round_chunk(chunk_strips, cmax, lev.r)
            cpad = -(-max(cmax, 1) // c) * c
            kch = cpad // c
            # One unsegmented Z-stream table per shard (segs is static
            # under shard_map, while per-part boundary splits are not).
            if lev.r < BLOCK:
                nrows = kch * (c // (BLOCK // lev.r) + 1) + 1
                segs = ((0, nrb_global + 1, 0, nrows),)
                _warn_big_table(nrows, f"strip level r={lev.r}")
            else:
                segs = ()
            st = np.zeros((pcount, cpad, lev.r, BLOCK), np.int8)
            co = np.zeros((pcount, cpad), np.int32)
            row = np.zeros((pcount, nrb_global + 1), np.int32)
            grp = np.zeros((pcount, nrb_global + 1), np.int32)
            xis, s0s, s1s = [], [], []
            for p in range(pcount):
                i0, i1 = p * cmax, min((p + 1) * cmax, n)
                k = max(i1 - i0, 0)
                st[p, :k] = lev.strips[i0:i1]
                co[p, :k] = lev.cols[i0:i1]
                if k:
                    read_blocks[p].update(
                        np.unique(lev.cols[i0:i1]).tolist())
                b = np.searchsorted(
                    lev.rows[i0:i1], np.arange(nrb_global + 1, dtype=np.int64)
                )
                if lev.r == BLOCK:
                    row[p], grp[p] = block_level_boundaries(b, c)
                    xi = s0 = s1 = np.zeros(0, np.int32)
                else:
                    row[p], grp[p], sub = zstream_boundaries(b, c, lev.r)
                    xi, s0, s1 = crossing_correction(sub, lev.r)
                xis.append(xi); s0s.append(s0); s1s.append(s1)
            xmax = max((a.shape[0] for a in s0s), default=0)
            lev_packed = (
                resolve_pack(self._pack, self.plan.cap) and lev.r % 2 == 0
            )
            rr = lev.r // 2 if lev_packed else lev.r
            if lev_packed:
                st = pack_strips(st)
            slevels.append(ShardedLevel(
                r=lev.r,
                segs=segs,
                packed=lev_packed,
                strips=put(st.reshape(pcount, kch, c, rr, BLOCK)),
                cols=put(co.reshape(pcount, kch, c)),
                bnd_row=put(row),
                bnd_grp=put(grp),
                xing_idx=put(_pad_stack(xis, xmax * lev.r)),
                xing_s0=put(_pad_stack(s0s, xmax)),
                xing_s1=put(_pad_stack(s1s, xmax)),
            ))

        # Tail slices + per-part static boundary gather data over the
        # LOCAL row ptrs. Ownership is non-contiguous (snake-dealt
        # blocks), so each part's local vertex space is the ascending
        # concatenation of its owned blocks' vertex ranges and its tail
        # edges the matching gather of per-block edge ranges — the
        # Z-stream machinery only needs the LOCAL stream and row ptrs,
        # which stay dst-sorted within the part by construction.
        tail_per_v = np.diff(plan.tail_row_ptr).astype(np.int64)
        self._vidx = []
        part_ne = []
        for p in range(pcount):
            B = part.blocks[p]
            vs = B * BLOCK
            vidx = (vs[:, None] + np.arange(BLOCK, dtype=np.int64)).ravel()
            vidx = vidx[vidx < plan.nv]
            # int32 suffices (nv < 2^31) and these persist per executor.
            self._vidx.append(vidx.astype(np.int32))
            part_ne.append(int(tail_per_v[vidx].sum()))
        mmax = max(part_ne) if part_ne else 0
        c_tail = round_chunk(chunk_tail, mmax, 1)
        mpad = -(-max(mmax, 1) // c_tail) * c_tail
        k2 = mpad // c_tail
        sb = np.zeros((pcount, mpad), np.int32)
        lane = np.zeros((pcount, mpad), np.int8)
        trow = np.zeros((pcount, self.max_nv + 1), np.int32)
        tgrp = np.zeros((pcount, self.max_nv + 1), np.int32)
        xis, s0s, s1s = [], [], []
        deg_out = np.ones((pcount, self.max_nv), np.int64)
        deg_in = np.zeros((pcount, self.max_nv), np.int64)
        vmask = np.zeros((pcount, self.max_nv), bool)
        for p in range(pcount):
            vidx = self._vidx[p]
            nvloc = vidx.shape[0]
            m = part_ne[p]
            starts = plan.tail_row_ptr[vidx]
            lens = tail_per_v[vidx]
            eidx = _ranges_to_indices(starts, lens)
            sb[p, :m] = plan.tail_sb[eidx]
            lane[p, :m] = plan.tail_lane[eidx]
            if m:
                read_blocks[p].update(np.unique(sb[p, :m]).tolist())
            rp = np.full(self.max_nv + 1, m, np.int64)
            np.cumsum(lens, out=rp[1 : nvloc + 1])
            rp[0] = 0
            trow[p], tgrp[p], sub = zstream_boundaries(rp, c_tail, 1)
            xi, s0, s1 = crossing_correction(sub, 1)
            xis.append(xi); s0s.append(s0); s1s.append(s1)
            deg_out[p, :nvloc] = plan.out_degrees[vidx]
            deg_in[p, :nvloc] = plan.in_degrees[vidx]
            vmask[p, :nvloc] = True
        xmax = max((a.shape[0] for a in s0s), default=0)
        cs_t = c_tail // BLOCK
        _warn_big_table(k2 * (cs_t + 1) + 1, "tail")

        counts = np.zeros((pcount, pcount), np.int64)
        for p, blocks in enumerate(read_blocks):
            if blocks:
                owners = part.owner[np.fromiter(
                    blocks, np.int64, len(blocks))]
                counts[p] += np.bincount(
                    owners, minlength=pcount).astype(np.int64) * BLOCK
        # (P, P) rows-read matrix in value rows, same shape/meaning as
        # ShardedGraph.remote_read_counts (engobs exchange ledger).
        self._remote_read_counts = counts

        self.shybrid = ShardedHybrid(
            levels=tuple(slevels),
            tail_sb=put(sb.reshape(pcount, k2, c_tail)),
            tail_lane=put(lane.reshape(pcount, k2, c_tail)),
            tail_bnd_row=put(trow),
            tail_bnd_grp=put(tgrp),
            tail_xing_idx=put(_pad_stack(xis, xmax)),
            tail_xing_s0=put(_pad_stack(s0s, xmax)),
            tail_xing_s1=put(_pad_stack(s1s, xmax)),
            tail_segs=((0, self.max_nv + 1, 0, k2 * (cs_t + 1) + 1),),
            max_nvb=max_nvb,
        )
        self._shard_args = {
            "out_degrees": put(deg_out.astype(np.int32)),
            "in_degrees": put(deg_in.astype(np.int32)),
            "vertex_mask": put(vmask),
        }
        # shybrid rides in the same dict so shard_map specs cover it.
        self._shard_args["hybrid"] = self.shybrid

        # Replicated helpers: block_map turns the gathered (P, max_nv)
        # shards into the global (nvb, 128) operand with one row gather
        # (block b lives at flat row owner[b]*max_nvb + its rank within
        # the owner's ascending block list); stack_map inverts it —
        # stacked slot p*max_nvb + i → the p-th part's i-th owned block
        # (or the sentinel zero row nvb for pad slots) — so the strip
        # accumulator can be rearranged into owner-stacked layout and
        # merged with a reduce-scatter instead of a full psum.
        rank_in_owner = np.zeros(plan.nvb, np.int64)
        stack = np.full(pcount * max_nvb, plan.nvb, np.int32)
        for p in range(pcount):
            B = part.blocks[p]
            rank_in_owner[B] = np.arange(B.shape[0], dtype=np.int64)
            stack[p * max_nvb : p * max_nvb + B.shape[0]] = B

        # Compact-exchange plan (LUX_EXCHANGE=compact): block-granular —
        # a 128-row block is the finest unit the tiled gather addresses,
        # so the needed-units lists are the ranks (within each owner's
        # stacked layout) of the blocks each part's strips/tail read.
        self._xplan = None
        if exchange_mode() == "compact" and pcount > 1:
            needs = [[np.zeros(0, np.int64)] * pcount for _ in range(pcount)]
            for q in range(pcount):
                blocks = np.fromiter(
                    read_blocks[q], np.int64, len(read_blocks[q]))
                owners_b = part.owner[blocks]
                ranks = rank_in_owner[blocks]
                for p in range(pcount):
                    needs[q][p] = np.sort(ranks[owners_b == p])
            # multiple=1: a unit is already a 128-row block, so there is
            # no lane-alignment reason to round the capacity up (the
            # default 8-unit rounding would sink profitability on small
            # meshes where max_nvb is itself single digits).
            xplan = ExchangePlan.from_needs(
                needs, max_nvb, pcount, unit_rows=BLOCK, multiple=1)
            if xplan.profitable:
                self._xplan = xplan
                self._shard_args["xch_send"] = put(xplan.send_units)
                self._shard_args["xch_recv"] = put(xplan.recv_pos)
            else:
                get_logger("engine").info(
                    "LUX_EXCHANGE=compact unprofitable for this tiled "
                    "plan (capacity %d >= %d blocks/part); "
                    "using the full exchange", xplan.capacity, max_nvb)
        self.exchange_mode = "compact" if self._xplan is not None else "full"
        repl = jax.sharding.NamedSharding(self.mesh, P())
        self._replicated = {
            "block_map": jax.device_put(
                jnp.asarray(
                    (part.owner.astype(np.int64) * max_nvb
                     + rank_in_owner).astype(np.int32)
                ),
                repl,
            ),
            "stack_map": jax.device_put(jnp.asarray(stack), repl),
        }

    # -- per-shard step (runs under shard_map) ---------------------------

    def _exchange_block(self, vals_blk, dg, repl):
        """Value exchange into the global (nvb, 128) gather operand.
        Full: all-gather the shards and rearrange via block_map. Compact:
        fixed-capacity all_to_all of the packed needed blocks, scattered
        into the owner-stacked view, own span written from the local
        shard. Blocks this part neither owns nor reads stay zero — the
        strips and tail never gather their columns (their block ids
        appear in no cols/tail_sb entry), and pad strip slots multiply
        them by all-zero coefficients, so the zeros never reach a sum."""
        v = vals_blk[0]                                   # (max_nv,) f32
        if self._xplan is None:
            gathered = jax.lax.all_gather(v, PARTS_AXIS)  # (P, max_nv)
            return gathered.reshape(-1, BLOCK)[repl["block_map"]]
        max_nvb = self.part.max_nvb
        v2d = v.reshape(max_nvb, BLOCK)
        sel = jnp.minimum(dg["xch_send"][0], max_nvb - 1)
        got = jax.lax.all_to_all(
            v2d[sel], PARTS_AXIS, split_axis=0, concat_axis=0, tiled=True)
        buf = jnp.zeros((self.num_parts * max_nvb + 1, BLOCK), v.dtype)
        buf = buf.at[dg["xch_recv"][0]].set(got)
        own = jax.lax.axis_index(PARTS_AXIS)
        buf = jax.lax.dynamic_update_slice(buf, v2d, (own * max_nvb, 0))
        return buf[:-1][repl["block_map"]]                # (nvb, 128)

    def _strips_block(self, x2d, dg, repl):
        """Strips: each shard sums ITS strips into a full-height partial
        accumulator, rearranges it into owner-stacked block layout (one
        cheap row gather; pad slots read the sentinel zero row), and a
        tiled reduce-scatter hands every shard just its reduced span —
        (P-1)*max_nv*4 ring bytes per device instead of the full-height
        psum's 2(P-1)/P*nv_g*4 that capped large-P scaling (the
        reference's per-part ZC publish never ships a full-nv array per
        GPU either, core/pull_model.inl:454-461)."""
        hy: ShardedHybrid = dg["hybrid"]
        nv_g = self.plan.nvb * BLOCK
        acc_g = jnp.zeros(nv_g, jnp.float32)
        for lev in hy.levels:
            dl = DeviceLevel(
                r=lev.r, segs=lev.segs, strips=lev.strips[0],
                cols=lev.cols[0], bnd_row=lev.bnd_row[0],
                bnd_grp=lev.bnd_grp[0], xing_idx=lev.xing_idx[0],
                xing_s0=lev.xing_s0[0], xing_s1=lev.xing_s1[0],
                packed=lev.packed,
            )
            acc_g = acc_g + strip_level_spmv(
                x2d, dl, self.plan.nvb * (BLOCK // lev.r)
            )
        acc2d = jnp.pad(acc_g.reshape(-1, BLOCK), ((0, 1), (0, 0)))
        stacked = acc2d[repl["stack_map"]]     # (P*max_nvb, 128)
        return jax.lax.psum_scatter(
            stacked, PARTS_AXIS, scatter_dimension=0, tiled=True
        ).reshape(-1)                          # (max_nv,) own span, reduced

    def _tail_block(self, x2d, dg):
        """Lane-select tail sums over this shard's owned dst span."""
        hy: ShardedHybrid = dg["hybrid"]
        return lane_select_tail_sums(
            x2d, hy.tail_sb[0], hy.tail_lane[0],
            hy.tail_bnd_row[0], hy.tail_bnd_grp[0],
            hy.tail_xing_idx[0], hy.tail_xing_s0[0], hy.tail_xing_s1[0],
            hy.tail_segs,
        )

    def _apply_block(self, vals_blk, acc, dg):
        v = vals_blk[0]
        ctx = VertexCtx(
            nv=self.graph.nv,
            out_degrees=dg["out_degrees"][0],
            in_degrees=dg["in_degrees"][0],
        )
        new = self.program.apply(v, acc, ctx)
        new = jnp.where(dg["vertex_mask"][0], new, v)
        return new[None]

    def _shard_step(self, vals_blk, dg, repl):
        # prof regions: the value exchange vs the strip/tail/apply local
        # work (the strips' psum_scatter rides the compute tag — it is
        # the reduction's own collective, not the value exchange).
        # Static names keep executable cache keys unchanged.
        with prof.region("lux.tiled_sharded.exchange"):
            x2d = self._exchange_block(vals_blk, dg, repl)
        with prof.region("lux.tiled_sharded.compute"):
            acc = self._strips_block(x2d, dg, repl)
            acc = acc + self._tail_block(x2d, dg)
            return self._apply_block(vals_blk, acc, dg)

    # -- driver (external vertex order at the API boundary) --------------

    def _to_padded_internal(self, ext_vals: np.ndarray) -> jnp.ndarray:
        internal = np.asarray(ext_vals)[self.plan.order]
        out = np.zeros((self.num_parts, self.max_nv), internal.dtype)
        for p in range(self.num_parts):
            vidx = self._vidx[p]
            out[p, : vidx.shape[0]] = internal[vidx]
        return jax.device_put(jnp.asarray(out), parts_sharding(self.mesh))

    # The CLI's host→device protocol (cli._host_to_device).
    host_to_device = _to_padded_internal

    def init_values(self) -> jnp.ndarray:
        return self._to_padded_internal(
            np.asarray(self.program.init_values(self.graph))
        )

    def step(self, vals):
        return self._step(vals)

    def phase_step(self, vals):
        """One iteration as separately-dispatched exchange/strips/tail/
        apply phases for `-verbose` attribution (phase names follow this
        engine's pipeline, the analogue of the reference's per-iteration
        breakdown, sssp/sssp_gpu.cu:516-518). SPMD phases are
        mesh-lockstep, so the walls are mesh-wide. Returns (new vals,
        {phase: seconds})."""
        if not hasattr(self, "_pjits"):
            specs = {k: P(PARTS_AXIS) for k in self._shard_args}

            def sm(fn, in_specs, out_specs):
                return jax.jit(compat.shard_map(
                    fn, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False,
                ))

            if self._xplan is not None:
                # Compact operands are per-shard scatters (each part's
                # unread blocks differ), not the replicated all_gather
                # output: carry them shard-major between phase jits.
                exchange = sm(
                    lambda v, dg, repl: self._exchange_block(
                        v, dg, repl)[None],
                    (P(PARTS_AXIS), specs, P()), P(PARTS_AXIS),
                )
                strips = sm(
                    lambda x, dg, repl: self._strips_block(
                        x[0], dg, repl)[None],
                    (P(PARTS_AXIS), specs, P()), P(PARTS_AXIS),
                )
                tail = sm(
                    lambda x, dg: self._tail_block(x[0], dg)[None],
                    (P(PARTS_AXIS), specs), P(PARTS_AXIS),
                )
            else:
                exchange = sm(
                    lambda v, dg, repl: self._exchange_block(v, dg, repl),
                    (P(PARTS_AXIS), specs, P()), P(),
                )
                strips = sm(
                    lambda x, dg, repl: self._strips_block(x, dg, repl)[None],
                    (P(), specs, P()), P(PARTS_AXIS),
                )
                tail = sm(
                    lambda x, dg: self._tail_block(x, dg)[None],
                    (P(), specs), P(PARTS_AXIS),
                )
            self._pjits = {
                "exchange": exchange,
                "strips": strips,
                "tail": tail,
                "apply": sm(
                    lambda v, a, b, dg: self._apply_block(
                        v, a[0] + b[0], dg
                    ),
                    (P(PARTS_AXIS), P(PARTS_AXIS), P(PARTS_AXIS), specs),
                    P(PARTS_AXIS),
                ),
            }
        j, times = self._pjits, {}
        dg, repl = self._shard_args, self._replicated
        with Timer() as t:
            x2d = hard_sync(j["exchange"](vals, dg, repl))
        times["exchange"] = t.elapsed
        with Timer() as t:
            acc_s = hard_sync(j["strips"](x2d, dg, repl))
        times["strips"] = t.elapsed
        with Timer() as t:
            acc_t = hard_sync(j["tail"](x2d, dg))
        times["tail"] = t.elapsed
        with Timer() as t:
            new = hard_sync(j["apply"](vals, acc_s, acc_t, dg))
        times["apply"] = t.elapsed
        return new, times

    def warmup(self):
        with Timer() as t:
            hard_sync(self.step(self.init_values()))
        note_compile_seconds(self, t.elapsed)

    def trace_step(self, **init_kw):
        """luxlint-IR hook (analysis/ir.py): the jitted shard_map step
        with its real argument tuple; sharded=True, so LUX105 demands
        the strip psum / exchange all-gather in the trace. The
        exchange_* keys feed LUX404-406 (``luxlint --exchange``)."""
        vals = self.init_values()
        return {
            "kind": "tiled_sharded",
            "fn": self._jstep,
            "args": (vals, self._shard_args, self._replicated),
            "donate": (0,),
            "carry": (0,),
            "sharded": True,
            "exchange_mode": self.exchange_mode,
            "exchange_bytes": self._exchange_bytes_per_iter(vals),
            "combiner": getattr(self.program, "combiner", "sum"),
            "value_dtype": np.dtype(vals.dtype).name,
            "num_parts": self.num_parts,
            "plan": self._xplan,
        }

    def _exchange_bytes_per_iter(self, vals) -> int:
        """ICI bytes for one iteration's exchange. Full: all-gather of
        the (P, max_nv) value stack — each part sends its shard to the
        P-1 others. Compact: the packed block all_to_all payload."""
        if self._xplan is not None:
            return self._xplan.exchange_bytes_per_iter(vals.dtype.itemsize)
        shard_elems = int(np.prod(vals.shape[1:])) if vals.ndim > 1 else 1
        p = self.num_parts
        return p * (p - 1) * shard_elems * vals.dtype.itemsize

    def run(self, num_iters: int, vals=None, flush_every: int = 8,
            recorder=None):
        if vals is None:
            vals = self.init_values()
        rec = recorder if recorder is not None else recorder_for(
            "tiled_sharded", self.graph, self.program)
        rec.start()
        if rec.enabled:
            rec.record_compile(consume_compile_seconds(self))
            compact = self._xplan is not None
            rec.set_exchange_bytes(
                self._exchange_bytes_per_iter(vals),
                note="compact_all_to_all" if compact else "all_gather",
                parts=self.num_parts)
            counts = getattr(self, "_remote_read_counts", None)
            if counts is not None:
                p = self.num_parts
                if compact:
                    exchanged = (self._xplan.exchanged_units_per_iter
                                 * self._xplan.unit_rows)
                else:
                    exchanged = p * (p - 1) * self.max_nv
                useful_rows = int(counts.sum() - np.trace(counts))
                if exchanged:
                    rec.set_useful_bytes(
                        useful_rows * int(vals.dtype.itemsize),
                        useful_rows / exchanged)
            rec.set_hbm_bytes(engobs.hbm_bytes_per_iter(
                self.graph.nv, self.graph.ne, int(vals.dtype.itemsize)))
        if engobs.enabled():
            # Phase-fenced measurement run (LUX_ENGOBS); the off path
            # keeps the exact fused program below.
            out = engobs.run_pull_phased(self, vals, num_iters, rec)
        else:
            out = run_maybe_fused(
                self._jrun, self._step, vals, num_iters, flush_every,
                self._shard_args, self._replicated, recorder=rec,
            )
        rec.finish()
        return out

    def gather_values(self, vals) -> np.ndarray:
        """Sharded padded internal layout -> global EXTERNAL (nv,) array."""
        host = np.asarray(jax.device_get(vals))
        internal = np.empty(self.plan.nv, host.dtype)
        for p in range(self.num_parts):
            vidx = self._vidx[p]
            internal[vidx] = host[p, : vidx.shape[0]]
        return internal[self.plan.rank]
