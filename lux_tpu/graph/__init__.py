from lux_tpu.graph.graph import Graph
from lux_tpu.graph.format import (detect_layout, read_lux, read_lux_mmap, write_lux)
from lux_tpu.graph.partition import edge_balanced_bounds, PartitionInfo
from lux_tpu.graph.delta import DeltaGraph, EdgeEdits
from lux_tpu.graph.snapshot import Snapshot, SnapshotStore
from lux_tpu.graph.wal import (RecoveryResult, Wal, WalCorruptError, replay)
from lux_tpu.graph import generate

__all__ = [
    "Graph",
    "DeltaGraph",
    "EdgeEdits",
    "Snapshot",
    "SnapshotStore",
    "Wal",
    "WalCorruptError",
    "RecoveryResult",
    "replay",
    "read_lux",
    "read_lux_mmap",
    "write_lux",
    "detect_layout",
    "edge_balanced_bounds",
    "PartitionInfo",
    "generate",
]
