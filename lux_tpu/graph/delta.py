"""Delta graphs: an immutable base CSC plus sorted edit runs.

lux_tpu graphs have been frozen-at-load since PR 0; the GPU-accelerator
survey (arXiv:1902.10130) calls streaming/mutable graphs the open
frontier for graph accelerators, and the serving stack (fingerprint-keyed
engines and caches, PR 2/6) was shaped so a snapshot layer could sit on
top without touching the engines. The representation here is the classic
LSM-flavored one: the base CSC never mutates; inserts accumulate as a
``(dst, src)``-sorted run, deletes as a sorted key set over the base.
``merged()`` materializes a fresh CSC with one counting-sort pass
(:func:`lux_tpu.ops.segment.csc_counting_merge`) — O(ne + ni + nv), no
comparison sort — and is bitwise-identical to ``Graph.from_edges`` over
the surviving edge list, so every downstream engine, fingerprint, and
plan sees an ordinary frozen graph.

Semantics (documented, tested in test_delta.py):

- The vertex set is fixed: edits are edge-only. Growing ``nv`` means a
  new base graph, not a delta.
- A delete removes *all* parallel copies of a ``(src, dst)`` pair.
- Within one ``EdgeEdits`` batch, deletes apply before inserts, so
  delete-then-reinsert in a single batch leaves the edge present (as a
  fresh insert).
- Edge keys are ``dst * nv + src`` in int64 — unique for nv < 2**31.5,
  far beyond an in-RAM CSC.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from lux_tpu.graph.graph import Graph, W_DTYPE
from lux_tpu.utils.locks import make_lock


def _edge_keys(src: np.ndarray, dst: np.ndarray, nv: int) -> np.ndarray:
    return dst.astype(np.int64) * np.int64(nv) + src.astype(np.int64)


@dataclasses.dataclass(frozen=True)
class EdgeEdits:
    """One batch of edge edits: arrays of inserts and deletes.

    ``ins_src``/``ins_dst`` (and optional ``ins_w``) are the edges to add;
    ``del_src``/``del_dst`` the pairs to remove. No ordering requirement —
    :meth:`DeltaGraph.stack` sorts.
    """

    ins_src: np.ndarray
    ins_dst: np.ndarray
    ins_w: Optional[np.ndarray]
    del_src: np.ndarray
    del_dst: np.ndarray

    @staticmethod
    def from_lists(insert=(), delete=()) -> "EdgeEdits":
        """Build from ``[(u, v)]`` / ``[(u, v, w)]`` insert and ``[(u, v)]``
        delete pairs (``u -> v``: u is the source)."""
        ins = list(insert)
        dels = list(delete)
        weighted = bool(ins) and len(ins[0]) == 3
        if any((len(t) == 3) != weighted for t in ins):
            raise ValueError("mixed weighted/unweighted inserts")
        return EdgeEdits(
            ins_src=np.asarray([t[0] for t in ins], dtype=np.int64),
            ins_dst=np.asarray([t[1] for t in ins], dtype=np.int64),
            ins_w=(np.asarray([t[2] for t in ins], dtype=W_DTYPE)
                   if weighted else None),
            del_src=np.asarray([t[0] for t in dels], dtype=np.int64),
            del_dst=np.asarray([t[1] for t in dels], dtype=np.int64),
        )

    @property
    def n_ins(self) -> int:
        return int(self.ins_src.shape[0])

    @property
    def n_del(self) -> int:
        return int(self.del_src.shape[0])

    def validate(self, nv: int) -> None:
        for name, arr in (("ins_src", self.ins_src), ("ins_dst", self.ins_dst),
                          ("del_src", self.del_src), ("del_dst", self.del_dst)):
            if arr.size and (arr.min() < 0 or arr.max() >= nv):
                raise ValueError(
                    f"{name} has vertex ids outside [0, {nv}); edits are "
                    "edge-only — the vertex set is fixed per base graph"
                )


def removed_edges(graph: Graph, del_src: np.ndarray, del_dst: np.ndarray):
    """The ``(src, dst, w|None)`` arrays of edges of ``graph`` that a
    delete batch actually removes (all parallel copies of each pair)."""
    if not len(del_src):
        e = np.zeros(0, dtype=np.int64)
        return e, e, (np.zeros(0, dtype=graph.weights.dtype)
                      if graph.weighted else None)
    keys = _edge_keys(graph.col_src, graph.col_dst, graph.nv)
    hit = np.isin(keys, np.unique(_edge_keys(
        np.asarray(del_src), np.asarray(del_dst), graph.nv)))
    idx = np.nonzero(hit)[0]
    return (
        graph.col_src[idx].astype(np.int64),
        graph.col_dst[idx].astype(np.int64),
        graph.weights[idx] if graph.weighted else None,
    )


@dataclasses.dataclass(eq=False)
class DeltaGraph:
    """Immutable base CSC + sorted insert run + sorted delete key set.

    ``stack(edits)`` returns a *new* DeltaGraph (value semantics — a
    snapshot holding this delta never changes under it). ``merged()`` is
    lazy, cached, and thread-safe; with no pending edits it returns the
    base graph object itself so identity (and hence the snapshot
    fingerprint) is preserved across no-op stacks and compactions.
    """

    base: Graph
    ins_src: np.ndarray               # int64, sorted by (dst, src)
    ins_dst: np.ndarray               # int64, sorted by (dst, src)
    ins_w: Optional[np.ndarray]
    del_keys: np.ndarray              # int64, sorted unique, base-relative

    def __post_init__(self):
        self._merge_lock = make_lock("delta.merge")
        self._merged: Optional[Graph] = None

    @staticmethod
    def fresh(base: Graph) -> "DeltaGraph":
        e = np.zeros(0, dtype=np.int64)
        w = np.zeros(0, dtype=base.weights.dtype) if base.weighted else None
        return DeltaGraph(base=base, ins_src=e, ins_dst=e, ins_w=w, del_keys=e)

    # -- sizes -----------------------------------------------------------

    @property
    def n_ins(self) -> int:
        return int(self.ins_src.shape[0])

    @property
    def n_del(self) -> int:
        return int(self.del_keys.shape[0])

    @property
    def delta_edges(self) -> int:
        return self.n_ins + self.n_del

    @property
    def ratio(self) -> float:
        """Pending-edit volume relative to the base edge count — the
        compaction trigger compared against LUX_DELTA_COMPACT_RATIO."""
        return self.delta_edges / max(self.base.ne, 1)

    # -- stacking --------------------------------------------------------

    def stack(self, edits: EdgeEdits) -> "DeltaGraph":
        """Apply one edit batch on top of this delta, returning a new one.

        Deletes land first: they drop matching *pending inserts* and join
        the base delete-key set (kept as stated keys — ``merged()`` masks
        with ``isin``, so keys absent from the base are harmless). Inserts
        are then merge-appended, so a delete-then-reinsert pair inside one
        batch leaves the edge present.
        """
        nv = self.base.nv
        edits.validate(nv)
        if self.base.weighted and edits.n_ins and edits.ins_w is None:
            raise ValueError("weighted base graph requires insert weights")
        if not self.base.weighted and edits.ins_w is not None:
            raise ValueError("insert weights given for an unweighted base")

        ins_src, ins_dst, ins_w = self.ins_src, self.ins_dst, self.ins_w
        del_keys = self.del_keys
        if edits.n_del:
            nk = np.unique(_edge_keys(edits.del_src, edits.del_dst, nv))
            if self.n_ins:
                keep = ~np.isin(_edge_keys(ins_src, ins_dst, nv), nk)
                ins_src, ins_dst = ins_src[keep], ins_dst[keep]
                if ins_w is not None:
                    ins_w = ins_w[keep]
            del_keys = np.union1d(del_keys, nk)
        if edits.n_ins:
            new_keys = _edge_keys(edits.ins_src, edits.ins_dst, nv)
            order = np.argsort(new_keys, kind="stable")
            all_src = np.concatenate([ins_src, edits.ins_src[order]])
            all_dst = np.concatenate([ins_dst, edits.ins_dst[order]])
            all_w = (np.concatenate([ins_w, edits.ins_w[order]])
                     if ins_w is not None else None)
            merged_order = np.argsort(
                _edge_keys(all_src, all_dst, nv), kind="stable")
            ins_src = all_src[merged_order]
            ins_dst = all_dst[merged_order]
            if all_w is not None:
                ins_w = all_w[merged_order]
            # Inserts re-deleted by a *later* batch were filtered above;
            # keys they shared with base deletes stay in del_keys, and the
            # fresh inserts still land (inserts are appended post-mask).
        return DeltaGraph(base=self.base, ins_src=ins_src, ins_dst=ins_dst,
                          ins_w=ins_w, del_keys=del_keys)

    # -- materialization -------------------------------------------------

    def merged(self) -> Graph:
        """The delta applied to the base as a fresh frozen CSC (cached)."""
        if self._merged is not None:
            return self._merged
        with self._merge_lock:
            if self._merged is None:
                self._merged = self._materialize()
        return self._merged

    def _materialize(self) -> Graph:
        # Deferred so `import lux_tpu.graph` stays jax-free (ops.segment
        # pulls in jax); only materializing a non-empty delta pays it.
        from lux_tpu.ops.segment import csc_counting_merge

        base = self.base
        if not self.delta_edges:
            return base
        if self.n_del:
            keys = _edge_keys(base.col_src, base.col_dst, base.nv)
            keep = ~np.isin(keys, self.del_keys)
        else:
            keep = np.ones(base.ne, dtype=bool)
        rp, src, w = csc_counting_merge(
            base.row_ptr, base.col_src, base.weights, keep,
            self.ins_dst, self.ins_src, self.ins_w, base.nv,
        )
        return Graph(nv=base.nv, ne=int(rp[-1]), row_ptr=rp,
                     col_src=src.astype(base.col_src.dtype), weights=w)
