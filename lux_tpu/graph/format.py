"""Bit-compatible reader/writer for the ``.lux`` binary CSC format.

Layout (reference: README.md "Graph Format"; writer: tools/converter.cc:108-124;
reader offsets: core/pull_model.inl:296-320):

    nv        uint32  (1)
    ne        uint64  (1)
    row_ptrs  uint64  (nv)    -- *end* offsets; row_ptrs[nv-1] == ne
    col_srcs  uint32  (ne)    -- in-edge sources, edges sorted by dst
    [weights  int32   (ne)]   -- only for weighted graphs (EDGE_WEIGHT apps;
                                 core/pull_model.inl:309-318)
    [degrees  uint32  (nv)]   -- trailing out-degree array written by the
                                 converter but never read back by any app
                                 (converter.cc:123; apps recompute degrees
                                 via the scan task, pull_model.inl:322-345)

All fields little-endian.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from lux_tpu.graph.graph import Graph

FILE_HEADER_SIZE = 12  # sizeof(u32 nv) + sizeof(u64 ne), matches core/graph.h


def detect_layout(path: str) -> Tuple[int, int, bool, bool]:
    """Infer (nv, ne, has_weights, has_degrees) from the header + file size."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        nv = int(np.fromfile(f, dtype="<u4", count=1)[0])
        ne = int(np.fromfile(f, dtype="<u8", count=1)[0])
    base = FILE_HEADER_SIZE + 8 * nv + 4 * ne
    for has_w in (False, True):
        for has_d in (False, True):
            if size == base + (4 * ne if has_w else 0) + (4 * nv if has_d else 0):
                return nv, ne, has_w, has_d
    raise ValueError(
        f"{path}: size {size} inconsistent with header nv={nv} ne={ne}"
    )


def read_lux(path: str, weighted: Optional[bool] = None) -> Graph:
    """Read a ``.lux`` file into a host :class:`Graph`.

    ``weighted=None`` auto-detects from the file size; pass an explicit
    bool to disambiguate the (rare) case where 4*ne == 4*nv and both
    layouts match.
    """
    nv, ne, has_w, has_d = detect_layout(path)
    if weighted is not None and weighted != has_w:
        # The caller overrides auto-detection; the override must still be
        # consistent with the file size.
        size = os.path.getsize(path)
        want = FILE_HEADER_SIZE + 8 * nv + 4 * ne + (4 * ne if weighted else 0)
        if size != want and size != want + 4 * nv:
            raise ValueError(
                f"{path}: weighted={weighted} inconsistent with size {size}"
            )
        has_w = weighted
    with open(path, "rb") as f:
        f.seek(FILE_HEADER_SIZE)
        ends = np.fromfile(f, dtype="<u8", count=nv).astype(np.int64)
        col_src = np.fromfile(f, dtype="<u4", count=ne).astype(np.int32)
        weights = (
            np.fromfile(f, dtype="<i4", count=ne) if has_w else None
        )
    if len(ends) != nv or len(col_src) != ne or (has_w and len(weights) != ne):
        raise ValueError(f"{path}: truncated file")
    row_ptr = np.zeros(nv + 1, dtype=np.int64)
    row_ptr[1:] = ends
    validate_row_ptr(ends, ne, path)
    return Graph(nv=nv, ne=ne, row_ptr=row_ptr, col_src=col_src, weights=weights)


def read_lux_mmap(path: str) -> Graph:
    """Read a ``.lux`` file with the edge array memory-mapped.

    At the reference's headline scale (RMAT27, 2^31 edges = 8.6 GB of
    col_src) a materializing read costs two full copies of host RAM;
    here ``col_src`` stays a read-only ``np.memmap`` view (uint32 —
    consumers slice and convert per partition) and only the (nv+1)
    row_ptr array (1.07 GB at RMAT27) is materialized. Weights, if
    present, are mapped the same way. Out-degrees stay lazy —
    ``Graph.out_degrees`` bincounts in chunks, so a first touch streams
    the mmap once instead of materializing it.
    """
    nv, ne, has_w, _ = detect_layout(path)
    with open(path, "rb") as f:
        f.seek(FILE_HEADER_SIZE)
        ends = np.fromfile(f, dtype="<u8", count=nv).astype(np.int64)
    validate_row_ptr(ends, ne, path)
    row_ptr = np.zeros(nv + 1, dtype=np.int64)
    row_ptr[1:] = ends
    edge_off = FILE_HEADER_SIZE + 8 * nv
    col_src = np.memmap(path, dtype="<u4", mode="r", offset=edge_off,
                        shape=(ne,))
    weights = (
        np.memmap(path, dtype="<i4", mode="r",
                  offset=edge_off + 4 * ne, shape=(ne,))
        if has_w else None
    )
    return Graph(nv=nv, ne=ne, row_ptr=row_ptr, col_src=col_src,
                 weights=weights)


def validate_row_ptr(ends: np.ndarray, ne: int, path: str) -> None:
    """Reject non-monotone end-offsets / wrong edge totals (the reference
    asserts the same on load, pull_model.inl:100-102)."""
    if len(ends) > 0 and (not np.all(np.diff(ends) >= 0) or ends[-1] != ne):
        raise ValueError(f"{path}: non-monotone row_ptrs or bad edge count")


def write_lux(path: str, g: Graph, include_degrees: bool = True) -> None:
    """Write a :class:`Graph` in the reference binary layout."""
    with open(path, "wb") as f:
        np.asarray([g.nv], dtype="<u4").tofile(f)
        np.asarray([g.ne], dtype="<u8").tofile(f)
        g.row_ptr[1:].astype("<u8").tofile(f)
        g.col_src.astype("<u4").tofile(f)
        if g.weights is not None:
            g.weights.astype("<i4").tofile(f)
        if include_degrees:
            g.out_degrees.astype("<u4").tofile(f)


def convert_edge_list(
    input_path: str,
    output_path: str,
    nv: int,
    ne: int,
    weighted: bool = False,
    include_degrees: bool = True,
) -> Graph:
    """Text edge list (``src dst [weight]`` per line) → ``.lux``.

    Python equivalent of the reference converter CLI (tools/converter.cc:72-130);
    a native C++ fast path lives in :mod:`lux_tpu.native`.
    """
    ncols = 3 if weighted else 2
    data = np.loadtxt(input_path, dtype=np.int64, max_rows=ne, ndmin=2)
    if data.shape[0] != ne:
        raise ValueError(f"expected {ne} edges, got {data.shape[0]}")
    if data.shape[1] < ncols:
        raise ValueError(
            f"expected {ncols} columns (weighted={weighted}), "
            f"got {data.shape[1]}"
        )
    src, dst = data[:, 0], data[:, 1]
    for name, ids in (("src", src), ("dst", dst)):
        if len(ids) and (ids.min() < 0 or ids.max() >= nv):
            raise ValueError(
                f"{name} ids out of range [0, {nv}): "
                f"[{ids.min()}, {ids.max()}]"
            )
    w = data[:, 2].astype(np.int32) if weighted else None
    g = Graph.from_edges(src, dst, nv=nv, weights=w)
    write_lux(output_path, g, include_degrees=include_degrees)
    return g
