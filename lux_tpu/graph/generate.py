"""Synthetic graph generators (for tests and benchmarks).

The reference ships no generator — its benchmark graphs (Hollywood, Twitter,
RMAT27, ... README.md:79-86) are downloaded. We generate R-MAT graphs of the
same family locally for benchmarking, plus tiny deterministic graphs for
unit tests.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from lux_tpu.graph.graph import Graph


def rmat_edges(
    scale: int,
    ne: int,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    batch: int = 1 << 24,
):
    """Yield (src, dst) int64 batches of an R-MAT graph with 2**scale
    vertices. Vectorized one bit-level at a time; streamed in batches so
    RMAT27-sized generation stays within memory."""
    rng = np.random.default_rng(seed)
    remaining = ne
    while remaining > 0:
        n = min(batch, remaining)
        src = np.zeros(n, dtype=np.int64)
        dst = np.zeros(n, dtype=np.int64)
        for _ in range(scale):
            u = rng.random(n)
            # Quadrant probs: (0,0)=a, (0,1)=b, (1,0)=c, (1,1)=d.
            src_bit = u >= a + b
            dst_bit = ((u >= a) & (u < a + b)) | (u >= a + b + c)
            src = (src << 1) | src_bit
            dst = (dst << 1) | dst_bit
        yield src, dst
        remaining -= n


def rmat(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    weighted: bool = False,
    max_weight: int = 100,
    batch: int = 1 << 24,
) -> Graph:
    """R-MAT graph with ``nv = 2**scale`` vertices and ``nv * edge_factor``
    edges (Graph500 parameters by default; RMAT27 ⇒ scale=27, ef=16).

    Builds the CSC out-of-core-style: two generation passes over identical
    batches (first: in-degree histogram → row_ptr; second: counting-sort
    placement), so peak memory is the output arrays plus one batch — never
    the full int64 edge list. This is the "out-of-core graph build for
    RMAT27" requirement of SURVEY.md §7(e).
    """
    nv = 1 << scale
    ne = nv * edge_factor

    # Pass 1: in-degree histogram.
    in_deg = np.zeros(nv, dtype=np.int64)
    for s, d in rmat_edges(scale, ne, a=a, b=b, c=c, seed=seed, batch=batch):
        in_deg += np.bincount(d, minlength=nv)
    row_ptr = np.zeros(nv + 1, dtype=np.int64)
    np.cumsum(in_deg, out=row_ptr[1:])

    # Pass 2: regenerate the same batches and counting-sort into place.
    col_src = np.empty(ne, dtype=np.int32)
    w_out = np.empty(ne, dtype=np.int32) if weighted else None
    wrng = np.random.default_rng(seed + 1) if weighted else None
    cursor = row_ptr[:-1].copy()  # next free slot per destination
    for s, d in rmat_edges(scale, ne, a=a, b=b, c=c, seed=seed, batch=batch):
        order = np.argsort(d, kind="stable")
        d_sorted = d[order]
        s_sorted = s[order]
        # rank of each edge within its (batch-local) destination group
        counts = np.bincount(d_sorted, minlength=nv)
        local_rank = np.arange(len(d_sorted)) - np.searchsorted(
            d_sorted, d_sorted
        )
        pos = cursor[d_sorted] + local_rank
        col_src[pos] = s_sorted.astype(np.int32)
        if weighted:
            batch_w = wrng.integers(
                1, max_weight + 1, size=len(order), dtype=np.int32
            )
            w_out[pos] = batch_w[order]
        cursor += counts
    return Graph(nv=nv, ne=ne, row_ptr=row_ptr, col_src=col_src, weights=w_out)


def gnp(nv: int, ne: int, seed: int = 0, weighted: bool = False) -> Graph:
    """Uniform random multigraph with exactly ``ne`` directed edges."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, nv, size=ne, dtype=np.int64)
    dst = rng.integers(0, nv, size=ne, dtype=np.int64)
    w = rng.integers(1, 101, size=ne, dtype=np.int32) if weighted else None
    return Graph.from_edges(src, dst, nv=nv, weights=w)


def undirected(g: Graph) -> Graph:
    """Symmetrize: add the reverse of every edge (needed for CC, whose label
    propagation follows directed edges only — reference components use
    symmetric inputs)."""
    dst = g.col_dst
    src = g.col_src
    both_src = np.concatenate([src, dst]).astype(np.int64)
    both_dst = np.concatenate([dst, src]).astype(np.int64)
    w = None
    if g.weights is not None:
        w = np.concatenate([g.weights, g.weights])
    return Graph.from_edges(both_src, both_dst, nv=g.nv, weights=w)


def small_world(
    nv: int,
    k: int = 16,
    p_rewire: float = 0.05,
    seed: int = 0,
) -> Graph:
    """Watts-Strogatz-style ring lattice: vertex v points at its next
    ``k`` ring neighbors, with a ``p_rewire`` fraction of source
    endpoints rewired uniformly at random (destinations keep their ring
    position so the graph stays dst-major).

    The locality-rich synthetic stand-in for the reference's web/social
    benchmark graphs (Hollywood-2009, Indochina-2004 — README.md:79-86),
    whose strong community structure is what GPU L2 caches (and this
    framework's strip tiles) exploit; R-MAT's Kronecker tail has no such
    structure, making it the adversarial case instead. Generated
    dst-major, so building the CSC needs no sort."""
    rng = np.random.default_rng(seed)
    ne = nv * k
    # dst-major enumeration: dst v receives from v-1 ... v-k (mod nv).
    dst = np.repeat(np.arange(nv, dtype=np.int64), k)
    src = dst - np.tile(np.arange(1, k + 1, dtype=np.int64), nv)
    src %= nv
    m = rng.random(ne) < p_rewire
    src[m] = rng.integers(0, nv, size=int(m.sum()), dtype=np.int64)
    row_ptr = np.arange(nv + 1, dtype=np.int64) * k
    return Graph(
        nv=nv, ne=ne, row_ptr=row_ptr, col_src=src.astype(np.int32),
        weights=None,
    )


def halo(
    blocks: int,
    span: int,
    hubs: int = 16,
    seed: int = 0,
    weighted: bool = False,
) -> Graph:
    """Halo-exchange locality graph: ``blocks`` contiguous ranges of
    ``span`` vertices, a forward chain inside each range, and exactly
    ``hubs`` cross-range source rows read by every other range — the
    stencil/halo communication pattern where each partition's remote
    reads are a small fixed set of boundary rows.

    Per-range edge totals are identical, so an edge-balanced contiguous
    P-way partition with ``P == blocks`` recovers the ranges to within a
    few boundary rows, and every part reads the same ``hubs`` mid-range
    rows from every other part (mid-range placement keeps hub ownership
    immune to the small boundary drift of the strictly-exceeds split
    rule): the best case for the compacted exchange — per-pair needs are
    uniform, so the fixed all_to_all capacity carries no padding
    waste."""
    if span // 2 + (blocks - 1) * hubs > span:
        raise ValueError(
            f"span {span} too small for {(blocks - 1) * hubs} distinct "
            "mid-range cross destinations"
        )
    mid = span // 2
    src = []
    dst = []
    for b in range(blocks):
        base = b * span
        # Forward chain keeps every range internally connected with
        # purely local edges (the compute the overlap path hides).
        chain = np.arange(span - 1, dtype=np.int64) + base
        src.append(chain)
        dst.append(chain + 1)
    for q in range(blocks):
        for p in range(blocks):
            if p == q:
                continue
            # Sender p's ``hubs`` mid-range rows land on distinct
            # receiver rows (one slot group per sender), so in-degrees
            # stay even and the per-pair needed-rows count is exactly
            # ``hubs`` plus the adjacent chain-boundary row.
            t = (p - q - 1) % blocks
            j = np.arange(hubs, dtype=np.int64)
            src.append(p * span + mid + j)
            dst.append(q * span + mid + t * hubs + j)
    src = np.concatenate(src)
    dst = np.concatenate(dst)
    w = None
    if weighted:
        rng = np.random.default_rng(seed)
        w = rng.integers(1, 101, size=src.size, dtype=np.int32)
    return Graph.from_edges(src, dst, nv=blocks * span, weights=w)


def bipartite_ratings(
    n_users: int,
    n_items: int,
    n_ratings: int,
    seed: int = 0,
    max_weight: int = 5,
) -> Graph:
    """Weighted bipartite ratings graph with edges in both directions
    (users 0..n_users-1, items n_users..n_users+n_items-1) — the
    NetFlix-shaped CF workload (480K users x 17.8K movies x 100M
    ratings, README.md:85). Item popularity is quadratically skewed
    (a bounded inverse-transform — popular items get ~sqrt-density
    weight, a milder skew than a true Zipf tail) so hub items exist
    without the distribution degenerating; total directed edges =
    2 * n_ratings."""
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, size=n_ratings, dtype=np.int64)
    # Quadratic inverse-transform of uniforms → denser low item ids.
    z = rng.random(n_ratings)
    items = (n_items * z ** 2.0).astype(np.int64).clip(0, n_items - 1)
    i = items + n_users
    w = rng.integers(1, max_weight + 1, size=n_ratings, dtype=np.int32)
    src = np.concatenate([u, i])
    dst = np.concatenate([i, u])
    ww = np.concatenate([w, w])
    return Graph.from_edges(src, dst, nv=n_users + n_items, weights=ww)


def path_graph(n: int) -> Graph:
    """0 → 1 → ... → n-1 (directed path, both directions NOT added)."""
    src = np.arange(n - 1, dtype=np.int64)
    dst = src + 1
    return Graph.from_edges(src, dst, nv=n)


def star_graph(n: int) -> Graph:
    """Center 0 with out-edges to 1..n-1."""
    src = np.zeros(n - 1, dtype=np.int64)
    dst = np.arange(1, n, dtype=np.int64)
    return Graph.from_edges(src, dst, nv=n)


def cycle_graph(n: int) -> Graph:
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    return Graph.from_edges(src, dst, nv=n)
