"""Synthetic graph generators (for tests and benchmarks).

The reference ships no generator — its benchmark graphs (Hollywood, Twitter,
RMAT27, ... README.md:79-86) are downloaded. We generate R-MAT graphs of the
same family locally for benchmarking, plus tiny deterministic graphs for
unit tests.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from lux_tpu.graph.graph import Graph


def rmat_edges(
    scale: int,
    ne: int,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    batch: int = 1 << 24,
):
    """Yield (src, dst) int64 batches of an R-MAT graph with 2**scale
    vertices. Vectorized one bit-level at a time; streamed in batches so
    RMAT27-sized generation stays within memory."""
    rng = np.random.default_rng(seed)
    remaining = ne
    while remaining > 0:
        n = min(batch, remaining)
        src = np.zeros(n, dtype=np.int64)
        dst = np.zeros(n, dtype=np.int64)
        for _ in range(scale):
            u = rng.random(n)
            # Quadrant probs: (0,0)=a, (0,1)=b, (1,0)=c, (1,1)=d.
            src_bit = u >= a + b
            dst_bit = ((u >= a) & (u < a + b)) | (u >= a + b + c)
            src = (src << 1) | src_bit
            dst = (dst << 1) | dst_bit
        yield src, dst
        remaining -= n


def rmat(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    weighted: bool = False,
    max_weight: int = 100,
) -> Graph:
    """R-MAT graph with ``nv = 2**scale`` vertices and ``nv * edge_factor``
    edges (Graph500 parameters by default; RMAT27 ⇒ scale=27, ef=16)."""
    nv = 1 << scale
    ne = nv * edge_factor
    srcs, dsts = [], []
    for s, d in rmat_edges(scale, ne, a=a, b=b, c=c, seed=seed):
        srcs.append(s)
        dsts.append(d)
    src = np.concatenate(srcs)
    dst = np.concatenate(dsts)
    w = None
    if weighted:
        w = np.random.default_rng(seed + 1).integers(
            1, max_weight + 1, size=ne, dtype=np.int32
        )
    return Graph.from_edges(src, dst, nv=nv, weights=w)


def gnp(nv: int, ne: int, seed: int = 0, weighted: bool = False) -> Graph:
    """Uniform random multigraph with exactly ``ne`` directed edges."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, nv, size=ne, dtype=np.int64)
    dst = rng.integers(0, nv, size=ne, dtype=np.int64)
    w = rng.integers(1, 101, size=ne, dtype=np.int32) if weighted else None
    return Graph.from_edges(src, dst, nv=nv, weights=w)


def undirected(g: Graph) -> Graph:
    """Symmetrize: add the reverse of every edge (needed for CC, whose label
    propagation follows directed edges only — reference components use
    symmetric inputs)."""
    dst = g.col_dst
    src = g.col_src
    both_src = np.concatenate([src, dst]).astype(np.int64)
    both_dst = np.concatenate([dst, src]).astype(np.int64)
    w = None
    if g.weights is not None:
        w = np.concatenate([g.weights, g.weights])
    return Graph.from_edges(both_src, both_dst, nv=g.nv, weights=w)


def path_graph(n: int) -> Graph:
    """0 → 1 → ... → n-1 (directed path, both directions NOT added)."""
    src = np.arange(n - 1, dtype=np.int64)
    dst = src + 1
    return Graph.from_edges(src, dst, nv=n)


def star_graph(n: int) -> Graph:
    """Center 0 with out-edges to 1..n-1."""
    src = np.zeros(n - 1, dtype=np.int64)
    dst = np.arange(1, n, dtype=np.int64)
    return Graph.from_edges(src, dst, nv=n)


def cycle_graph(n: int) -> Graph:
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    return Graph.from_edges(src, dst, nv=n)
