"""Host-side graph data model.

The reference (Lux) stores graphs in binary CSC: edges sorted by destination
vertex, with per-vertex *end* offsets (reference: README.md "Graph Format",
tools/converter.cc:108-124). On device we want SoA numpy/JAX arrays, not the
reference's AoS ``NodeStruct``/``EdgeStruct`` (core/graph.h:26-34) — SoA is
the idiomatic TPU layout.

Conventions:
- ``row_ptr`` has length ``nv + 1`` with a leading 0 (the reference keeps
  only the ``nv`` end-offsets; we add the implicit 0 so slices are uniform).
- ``col_src[row_ptr[v]:row_ptr[v+1]]`` are the in-neighbors (sources) of
  vertex ``v``.
- ``out_degrees`` counts each vertex's appearances as a source, matching the
  reference's scan task (core/pull_model.inl:322-345) and the converter's
  trailing degree array (tools/converter.cc:84-92).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

V_DTYPE = np.uint32  # V_ID in the reference (pagerank/app.h:21)
E_DTYPE = np.uint64  # E_ID in the reference (pagerank/app.h:22)
W_DTYPE = np.int32   # WeightType in the reference (col_filter/app.h:23)


@dataclasses.dataclass(eq=False)
class Graph:
    """A host-side CSC graph (in-edges, sorted by destination).

    ``eq=False``: ndarray fields make the generated ``__eq__`` raise; compare
    fields explicitly with ``np.array_equal`` where needed.
    """

    nv: int
    ne: int
    row_ptr: np.ndarray               # int64 (nv+1,), row_ptr[0] == 0
    col_src: np.ndarray               # int32  (ne,) source vertex per in-edge
    weights: Optional[np.ndarray] = None    # int32 (ne,) or None
    _out_degrees: Optional[np.ndarray] = None  # lazily computed
    _csr: Optional["Csr"] = None               # lazily built out-edge view
    _col_dst: Optional[np.ndarray] = None      # lazily expanded CSC dsts

    def __post_init__(self):
        self.nv = int(self.nv)
        self.ne = int(self.ne)
        assert self.row_ptr.shape == (self.nv + 1,)
        assert self.row_ptr[0] == 0 and self.row_ptr[-1] == self.ne
        assert self.col_src.shape == (self.ne,)
        if self.weights is not None:
            assert self.weights.shape == (self.ne,)

    # -- degrees ---------------------------------------------------------

    @property
    def in_degrees(self) -> np.ndarray:
        return np.diff(self.row_ptr).astype(np.int64)

    @property
    def out_degrees(self) -> np.ndarray:
        if self._out_degrees is None:
            # Chunked so a memory-mapped col_src (read_lux_mmap at RMAT27
            # scale) is streamed once instead of materialized, and the
            # bincount temp stays bounded; harmless for in-RAM arrays.
            chunk = 1 << 27
            deg = np.zeros(self.nv, dtype=np.int64)
            for s in range(0, self.ne, chunk):
                deg += np.bincount(
                    self.col_src[s : s + chunk], minlength=self.nv
                )
            self._out_degrees = deg
        return self._out_degrees

    @property
    def weighted(self) -> bool:
        return self.weights is not None

    # -- derived views ---------------------------------------------------

    @property
    def col_dst(self) -> np.ndarray:
        """Destination vertex per in-edge (expansion of the CSC segments).

        Cached: executor builds hit this several times, and at RMAT27
        scale each np.repeat is a multi-GB host materialization.
        """
        if self._col_dst is None:
            self._col_dst = np.repeat(
                np.arange(self.nv, dtype=np.int32), self.in_degrees
            )
        return self._col_dst

    def csr(self) -> "Csr":
        """Out-edge (push) view: edges grouped by source.

        The reference builds this per GPU at init time via a degree
        histogram + prefix sum + scatter (sssp/sssp_gpu.cu:550-607); the
        native C++ path does the same (lux_native.cc lux_build_csr);
        the numpy fallback is a stable argsort by source.
        """
        if self._csr is None:
            self._csr = self._csr_native() or self._csr_numpy()
        return self._csr

    def _csr_native(self):
        import ctypes

        from lux_tpu.native.build import maybe_library

        lib = maybe_library()
        if lib is None:
            return None
        ptr = np.zeros(self.nv + 1, dtype=np.int64)
        dst = np.zeros(self.ne, dtype=np.int32)
        w = None if self.weights is None else np.zeros(self.ne, np.int32)
        col_src = np.ascontiguousarray(self.col_src, dtype=np.int32)
        # Keep every buffer alive in a local: c_void_p captures only the
        # raw address, so an inline temporary would be freed pre-call.
        csc_ptr = np.ascontiguousarray(self.row_ptr, np.int64)
        weights = (
            None
            if self.weights is None
            else np.ascontiguousarray(self.weights, dtype=np.int32)
        )
        rc = lib.lux_build_csr(
            ctypes.c_uint32(self.nv),
            ctypes.c_uint64(self.ne),
            ctypes.c_void_p(col_src.ctypes.data),
            ctypes.c_void_p(csc_ptr.ctypes.data),
            ctypes.c_void_p(ptr.ctypes.data),
            ctypes.c_void_p(dst.ctypes.data),
            ctypes.c_void_p(weights.ctypes.data) if weights is not None else None,
            ctypes.c_void_p(w.ctypes.data) if w is not None else None,
        )
        if rc != 0:
            if rc == -6:
                raise ValueError(
                    f"col_src contains ids outside [0, {self.nv})"
                )
            return None
        return Csr(row_ptr=ptr, col_dst=dst, weights=w)

    def _csr_numpy(self) -> "Csr":
        order = np.argsort(self.col_src, kind="stable").astype(np.int64)
        dst = self.col_dst[order].astype(np.int32)
        ptr = np.zeros(self.nv + 1, dtype=np.int64)
        np.cumsum(self.out_degrees, out=ptr[1:])
        w = None if self.weights is None else self.weights[order]
        return Csr(row_ptr=ptr, col_dst=dst, weights=w)

    # -- constructors ----------------------------------------------------

    @staticmethod
    def from_edges(
        src: np.ndarray,
        dst: np.ndarray,
        nv: int,
        weights: Optional[np.ndarray] = None,
    ) -> "Graph":
        """Build CSC from an arbitrary edge list (sorts by dst, stable —
        same ordering the reference converter produces, converter.cc:98)."""
        src = np.asarray(src)
        dst = np.asarray(dst)
        ne = src.shape[0]
        order = np.argsort(dst, kind="stable")
        src_sorted = src[order].astype(np.int32)
        dst_sorted = dst[order]
        row_ptr = np.zeros(nv + 1, dtype=np.int64)
        np.cumsum(np.bincount(dst_sorted, minlength=nv), out=row_ptr[1:])
        w = None if weights is None else np.asarray(weights)[order].astype(W_DTYPE)
        return Graph(nv=nv, ne=ne, row_ptr=row_ptr, col_src=src_sorted, weights=w)

    def __repr__(self):
        return (
            f"Graph(nv={self.nv}, ne={self.ne}, "
            f"weighted={self.weights is not None})"
        )


@dataclasses.dataclass(eq=False)
class Csr:
    """Out-edge view: ``col_dst[row_ptr[u]:row_ptr[u+1]]`` are the
    destinations of u's out-edges."""

    row_ptr: np.ndarray   # int64 (nv+1,)
    col_dst: np.ndarray   # int32 (ne,)
    weights: Optional[np.ndarray] = None
