"""Edge-balanced contiguous vertex partitioning.

Reproduces the reference's greedy sweep exactly (core/pull_model.inl:108-131,
same code in push_model.inl:378-413): walk vertices in order accumulating
in-degree; when the running count *exceeds* ``ceil(ne / num_parts)``, close
the current part at this vertex (inclusive) and reset the counter.

The sweep is implemented with ``np.searchsorted`` per part instead of a
Python loop — O(parts · log nv) — so it stays fast at RMAT27 scale
(134M vertices). The produced bounds are identical to the reference's.

Two deliberate divergences:
- the reference ``assert``s that the sweep yields exactly ``num_parts``
  parts (pull_model.inl:130) and aborts otherwise (which can happen on
  small or skewed graphs). We instead pad with empty trailing parts so any
  graph runs on any mesh size;
- the reference leaves trailing zero-in-degree vertices uncovered (its
  final part is only emitted when it holds edges, pull_model.inl:124-128).
  We always extend the last non-empty part to ``nv - 1`` so every vertex
  owns a slot in the value arrays.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Tuple

import numpy as np

# Frontier-queue sizing for the push model (push_model.inl:390-412,
# sssp/app.h:19): sparse capacity per part, plus slack for corner cases.
SPARSE_THRESHOLD = 16
FRONTIER_SLACK_SLOTS = 100


def edge_balanced_bounds(
    row_ptr: np.ndarray, num_parts: int
) -> List[Tuple[int, int]]:
    """Return ``num_parts`` inclusive (left, right) vertex ranges.

    Empty parts are encoded as (left, left-1) with zero vertices.
    """
    nv = row_ptr.shape[0] - 1
    ne = int(row_ptr[-1])
    edge_cap = (ne + num_parts - 1) // num_parts if num_parts > 0 else ne
    ends = row_ptr[1:]  # cumulative edge count through vertex v (inclusive)
    bounds: List[Tuple[int, int]] = []
    left = 0
    base = 0  # edges consumed by closed parts
    while left < nv and len(bounds) < num_parts:
        # Smallest v >= left with ends[v] - base > edge_cap  (i.e. the
        # running count strictly exceeds the cap — the reference closes the
        # part *at* that vertex, pull_model.inl:117-123).
        v = int(np.searchsorted(ends, base + edge_cap, side="right"))
        if v >= nv or len(bounds) == num_parts - 1:
            v = nv - 1  # remainder part (pull_model.inl:124-128)
        bounds.append((left, v))
        base = int(ends[v])
        left = v + 1
    while len(bounds) < num_parts:
        bounds.append((left, left - 1))  # empty padding part
    return bounds


@dataclasses.dataclass
class PartitionInfo:
    """Partition metadata mirroring the reference Graph's per-part state
    (rowLeft/rowRight/fqLeft/fqRight, core/graph.h:80-87)."""

    num_parts: int
    bounds: List[Tuple[int, int]]         # inclusive vertex ranges
    edge_bounds: List[Tuple[int, int]]    # half-open [colLeft, colRight)
    frontier_slots: List[int]             # sparse queue capacity per part

    @staticmethod
    def build(row_ptr: np.ndarray, num_parts: int) -> "PartitionInfo":
        bounds = edge_balanced_bounds(row_ptr, num_parts)
        edge_bounds = [
            (int(row_ptr[l]), int(row_ptr[r + 1])) if r >= l
            else (int(row_ptr[l]),) * 2   # empty part: l <= nv is in range
            for (l, r) in bounds
        ]
        slots = [
            (max(r - l, 0)) // SPARSE_THRESHOLD + FRONTIER_SLACK_SLOTS
            for (l, r) in bounds
        ]
        return PartitionInfo(
            num_parts=num_parts,
            bounds=bounds,
            edge_bounds=edge_bounds,
            frontier_slots=slots,
        )

    @property
    def max_part_nv(self) -> int:
        return max((r - l + 1) for (l, r) in self.bounds) if self.bounds else 0

    @property
    def max_part_ne(self) -> int:
        return max((e - s) for (s, e) in self.edge_bounds) if self.edge_bounds else 0


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclasses.dataclass(eq=False)
class ExchangePlan:
    """Precomputed needed-rows exchange tables for the sharded engines.

    The full exchange all-gathers every part's whole ``max_units``-row
    shard to every other part; the remote-read index proves most of
    those rows are never gathered by the receiver. This plan turns the
    exchange into a fixed-capacity ``all_to_all`` of packed rows: per
    (sender p → receiver q) pair, ``send_units[p]`` lists exactly the
    local row ids of p that q's real edges read, padded to one static
    ``capacity`` so shapes never change across iterations (the
    zero-recompile contract), and ``recv_pos[q]`` scatters the received
    rows into q's flat ``(P * max_units,)`` view at the positions the
    unchanged compute bodies index. ``unit_rows`` generalizes the unit:
    1 for row-granular plans (ShardedGraph), BLOCK for the tiled
    executor's 128-row block granularity.

    Sentinels: a pad entry of ``send_units`` is ``max_units`` (senders
    clip the gather; the row's payload is garbage) and the matching
    ``recv_pos`` entry is ``P * max_units`` (receivers scatter it into a
    trash row sliced off before compute), so pad traffic can never leak
    into results.
    """

    num_parts: int
    max_units: int          # per-part padded unit count (max_nv / max_nvb)
    unit_rows: int          # value rows per unit (1, or BLOCK for tiled)
    capacity: int           # static per-(sender, receiver) unit capacity
    counts: np.ndarray      # (P, P) int64: units part q reads of part p
    send_units: np.ndarray  # (P, P*capacity) int32 sender gather lists
    recv_pos: np.ndarray    # (P, P*capacity) int32 receiver scatter slots

    @property
    def exchanged_units_per_iter(self) -> int:
        """Units moved per iteration over the whole mesh (capacity
        figure — what actually crosses the interconnect)."""
        p = self.num_parts
        return p * (p - 1) * self.capacity

    def exchange_bytes_per_iter(self, row_bytes: int) -> int:
        """Interconnect bytes per iteration for ``row_bytes`` per value
        row — the packed-capacity figure the exchange ledger prices."""
        return self.exchanged_units_per_iter * self.unit_rows * int(row_bytes)

    @property
    def profitable(self) -> bool:
        """Whether the packed exchange moves strictly fewer rows per
        pair than the full all-gather; executors fall back to the full
        path (with a log note) when this is False."""
        return self.capacity < self.max_units

    def frontier_capacity(self, frac: float = 0.25, multiple: int = 8) -> int:
        """Static per-(sender, receiver) row budget for the
        frontier-aware exchange (``LUX_EXCHANGE=frontier``).

        The frontier exchange sends only the subset of a pair's static
        ``send_units`` whose source vertex is active this iteration,
        compacted into this many slots (sentinel-padded, so shapes —
        and therefore compiled executables — never depend on runtime
        frontier density). It is derived from the static ``capacity``
        rather than from any runtime measurement: ``frac`` of the
        densest pair's padded budget, rounded up to ``multiple`` and
        clamped to ``capacity`` (a frontier can never need more rows
        than the static plan already covers). Iterations whose
        per-pair active-row count exceeds this budget self-downgrade to
        the static compact send — the plan never truncates (the LUX407
        admissibility contract)."""
        if not 0.0 < frac <= 1.0:
            raise ValueError(
                f"frontier capacity fraction must be in (0, 1] (got {frac})"
            )
        cap = _round_up(
            max(1, int(np.ceil(self.capacity * float(frac)))), multiple
        )
        return min(self.capacity, cap)

    @staticmethod
    def from_needs(
        needs,
        max_units: int,
        num_parts: int,
        unit_rows: int = 1,
        multiple: int = 8,
        capacity: Optional[int] = None,
    ) -> "ExchangePlan":
        """Build from per-(receiver, sender) needed-unit lists.

        ``needs[q][p]`` is an ascending int array of the LOCAL unit ids
        of part p that part q reads (``needs[q][q]`` counts toward the
        ledger's diagonal but is never exchanged — own rows stay local).
        ``capacity`` pins the static per-pair pad width; when the needed
        rows of any pair exceed it, the build fails loudly (silent
        truncation would silently corrupt results downstream)."""
        P = num_parts
        counts = np.zeros((P, P), dtype=np.int64)
        for q in range(P):
            for p in range(P):
                counts[q, p] = len(needs[q][p])
        off_diag = counts - np.diag(np.diag(counts))
        required = int(off_diag.max()) if P > 1 else 0
        cap = _round_up(max(required, 1), multiple)
        if capacity is not None:
            capacity = int(capacity)
            if capacity < required:
                raise ValueError(
                    f"exchange capacity {capacity} cannot hold the "
                    f"{required} needed units of the densest "
                    "(sender, receiver) pair — refusing to truncate "
                    "the exchange"
                )
            cap = max(capacity, 1)
        send = np.full((P, P, cap), max_units, dtype=np.int32)
        recv = np.full((P, P, cap), P * max_units, dtype=np.int32)
        for q in range(P):
            for p in range(P):
                if p == q:
                    continue
                rows = np.asarray(needs[q][p], dtype=np.int64)
                n = rows.shape[0]
                if n:
                    send[p, q, :n] = rows.astype(np.int32)
                    recv[q, p, :n] = (p * max_units + rows).astype(np.int32)
        return ExchangePlan(
            num_parts=P,
            max_units=max_units,
            unit_rows=int(unit_rows),
            capacity=cap,
            counts=counts,
            send_units=send.reshape(P, P * cap),
            recv_pos=recv.reshape(P, P * cap),
        )

    @staticmethod
    def from_src_pidx(
        src_pidx: np.ndarray,
        edge_mask: np.ndarray,
        max_nv: int,
        num_parts: int,
        multiple: int = 8,
        capacity: Optional[int] = None,
    ) -> "ExchangePlan":
        """Row-granular plan from the stacked flat-index edge arrays —
        the same ``src_pidx``/``edge_mask`` data that feeds
        ``ShardedGraph.remote_read_counts``, so the plan's ``counts``
        matrix is identical to the ledger's remote-read index."""
        P = num_parts
        needs = [[np.zeros(0, np.int64)] * P for _ in range(P)]
        for q in range(P):
            rows = np.unique(src_pidx[q][edge_mask[q]]).astype(np.int64)
            owners = rows // max_nv
            for p in range(P):
                needs[q][p] = rows[owners == p] - p * max_nv
        return ExchangePlan.from_needs(
            needs, max_nv, P, unit_rows=1, multiple=multiple,
            capacity=capacity,
        )


# -- exchange-plan artifact (consumed by the jax-free exchange linter) -----
#
# Layout mirrors the grouped-tail plan artifact (plan.py / planck.py):
# one directory per plan, ``meta.json`` with the scalar fields plus one
# ``.npy`` per table so the checker can mmap them without jax.
# ``analysis/exchck.py`` mirrors these constants deliberately (it must
# stay importable without this module's jax-adjacent neighbors); the
# parity test in tests/test_exchck.py keeps the two in lockstep.

EXCHANGE_PLAN_FORMAT = 1
EXCHANGE_PLAN_ARRAYS = ("counts", "send_units", "recv_pos")


def save_exchange_artifact(
    plan: ExchangePlan,
    path: str,
    remote_read_counts: Optional[np.ndarray] = None,
    row_bytes: Optional[int] = None,
    ledger: Optional[dict] = None,
) -> None:
    """Write ``plan`` to ``path/`` for offline verification.

    ``remote_read_counts`` (value rows, from ShardedGraph) enables the
    LUX402 conservation proof; ``row_bytes`` and ``ledger`` (the
    ``engobs.useful_exchange`` dict) enable the LUX403 pricing checks.
    """
    os.makedirs(path, exist_ok=True)
    meta = {
        "format": EXCHANGE_PLAN_FORMAT,
        "num_parts": int(plan.num_parts),
        "max_units": int(plan.max_units),
        "unit_rows": int(plan.unit_rows),
        "capacity": int(plan.capacity),
        "profitable": bool(plan.profitable),
        "exchanged_units_per_iter": int(plan.exchanged_units_per_iter),
    }
    if row_bytes is not None:
        meta["row_bytes"] = int(row_bytes)
        meta["exchange_bytes_per_iter"] = int(
            plan.exchange_bytes_per_iter(row_bytes))
    if ledger is not None:
        meta["ledger"] = {k: (float(v) if k == "ratio" else int(v))
                          for k, v in ledger.items()}
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    for name in EXCHANGE_PLAN_ARRAYS:
        np.save(os.path.join(path, name + ".npy"),
                np.asarray(getattr(plan, name)))
    if remote_read_counts is not None:
        np.save(os.path.join(path, "remote_read_counts.npy"),
                np.asarray(remote_read_counts))
