"""Edge-balanced contiguous vertex partitioning.

Reproduces the reference's greedy sweep exactly (core/pull_model.inl:108-131,
same code in push_model.inl:378-413): walk vertices in order accumulating
in-degree; when the running count *exceeds* ``ceil(ne / num_parts)``, close
the current part at this vertex (inclusive) and reset the counter.

The sweep is implemented with ``np.searchsorted`` per part instead of a
Python loop — O(parts · log nv) — so it stays fast at RMAT27 scale
(134M vertices). The produced bounds are identical to the reference's.

Two deliberate divergences:
- the reference ``assert``s that the sweep yields exactly ``num_parts``
  parts (pull_model.inl:130) and aborts otherwise (which can happen on
  small or skewed graphs). We instead pad with empty trailing parts so any
  graph runs on any mesh size;
- the reference leaves trailing zero-in-degree vertices uncovered (its
  final part is only emitted when it holds edges, pull_model.inl:124-128).
  We always extend the last non-empty part to ``nv - 1`` so every vertex
  owns a slot in the value arrays.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np

# Frontier-queue sizing for the push model (push_model.inl:390-412,
# sssp/app.h:19): sparse capacity per part, plus slack for corner cases.
SPARSE_THRESHOLD = 16
FRONTIER_SLACK_SLOTS = 100


def edge_balanced_bounds(
    row_ptr: np.ndarray, num_parts: int
) -> List[Tuple[int, int]]:
    """Return ``num_parts`` inclusive (left, right) vertex ranges.

    Empty parts are encoded as (left, left-1) with zero vertices.
    """
    nv = row_ptr.shape[0] - 1
    ne = int(row_ptr[-1])
    edge_cap = (ne + num_parts - 1) // num_parts if num_parts > 0 else ne
    ends = row_ptr[1:]  # cumulative edge count through vertex v (inclusive)
    bounds: List[Tuple[int, int]] = []
    left = 0
    base = 0  # edges consumed by closed parts
    while left < nv and len(bounds) < num_parts:
        # Smallest v >= left with ends[v] - base > edge_cap  (i.e. the
        # running count strictly exceeds the cap — the reference closes the
        # part *at* that vertex, pull_model.inl:117-123).
        v = int(np.searchsorted(ends, base + edge_cap, side="right"))
        if v >= nv or len(bounds) == num_parts - 1:
            v = nv - 1  # remainder part (pull_model.inl:124-128)
        bounds.append((left, v))
        base = int(ends[v])
        left = v + 1
    while len(bounds) < num_parts:
        bounds.append((left, left - 1))  # empty padding part
    return bounds


@dataclasses.dataclass
class PartitionInfo:
    """Partition metadata mirroring the reference Graph's per-part state
    (rowLeft/rowRight/fqLeft/fqRight, core/graph.h:80-87)."""

    num_parts: int
    bounds: List[Tuple[int, int]]         # inclusive vertex ranges
    edge_bounds: List[Tuple[int, int]]    # half-open [colLeft, colRight)
    frontier_slots: List[int]             # sparse queue capacity per part

    @staticmethod
    def build(row_ptr: np.ndarray, num_parts: int) -> "PartitionInfo":
        bounds = edge_balanced_bounds(row_ptr, num_parts)
        edge_bounds = [
            (int(row_ptr[l]), int(row_ptr[r + 1])) if r >= l
            else (int(row_ptr[l]),) * 2   # empty part: l <= nv is in range
            for (l, r) in bounds
        ]
        slots = [
            (max(r - l, 0)) // SPARSE_THRESHOLD + FRONTIER_SLACK_SLOTS
            for (l, r) in bounds
        ]
        return PartitionInfo(
            num_parts=num_parts,
            bounds=bounds,
            edge_bounds=edge_bounds,
            frontier_slots=slots,
        )

    @property
    def max_part_nv(self) -> int:
        return max((r - l + 1) for (l, r) in self.bounds) if self.bounds else 0

    @property
    def max_part_ne(self) -> int:
        return max((e - s) for (s, e) in self.edge_bounds) if self.edge_bounds else 0
