"""Versioned graph snapshots over :class:`~lux_tpu.graph.delta.DeltaGraph`.

A :class:`SnapshotStore` holds the linear version history of one logical
graph. ``apply(edits)`` stacks an edit batch onto the current snapshot's
delta and mints version N+1; each snapshot is identified by the hardened
checkpoint fingerprint of its *materialized* graph, which is what keys
every serving engine and cache entry downstream. When a snapshot's
pending-edit ratio crosses ``LUX_DELTA_COMPACT_RATIO`` the store kicks a
background compaction thread that re-anchors the delta on the merged CSC
— the merged arrays are reused as-is, so compaction never changes the
fingerprint (tested: compaction round-trips are bitwise no-ops for
readers).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from lux_tpu.graph.delta import DeltaGraph, EdgeEdits
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import metrics, spans
from lux_tpu.utils import checkpoint, flags
from lux_tpu.utils.locks import make_lock

_compactions = metrics.counter("lux_snapshot_compactions_total")


class Snapshot:
    """One immutable version: a DeltaGraph plus lazy graph/fingerprint."""

    def __init__(self, version: int, delta: DeltaGraph):
        self.version = version
        self._delta = delta
        self._lock = make_lock("snapshot")
        self._fingerprint: Optional[str] = None
        self.compacted = delta.delta_edges == 0

    @property
    def delta(self) -> DeltaGraph:
        return self._delta

    @property
    def graph(self) -> Graph:
        return self._delta.merged()

    @property
    def fingerprint(self) -> str:
        if self._fingerprint is None:
            with self._lock:
                if self._fingerprint is None:
                    self._fingerprint = checkpoint.fingerprint_hex(self.graph)
        return self._fingerprint

    @property
    def ratio(self) -> float:
        return self._delta.ratio

    def compact(self) -> None:
        """Re-anchor the delta on its merged CSC (idempotent).

        ``merged()`` of the fresh delta returns the same Graph object the
        old delta materialized, so fingerprints and any reader holding
        ``.graph`` are unaffected — compaction only drops the edit runs
        and frees the old base for GC.
        """
        with self._lock:
            if not self.compacted:
                self._delta = DeltaGraph.fresh(self._delta.merged())
                self.compacted = True


class SnapshotStore:
    """Linear version history with threshold-triggered background compaction."""

    def __init__(self, base: Graph):
        self._lock = make_lock("snapshot.store")
        self._snaps: List[Snapshot] = [Snapshot(0, DeltaGraph.fresh(base))]
        self._compaction_threads: List[threading.Thread] = []

    # -- reads -----------------------------------------------------------

    def current(self) -> Snapshot:
        with self._lock:
            return self._snaps[-1]

    def get(self, version: int) -> Snapshot:
        with self._lock:
            if not 0 <= version < len(self._snaps):
                raise KeyError(f"unknown snapshot version {version}")
            return self._snaps[version]

    def history(self) -> List[dict]:
        with self._lock:
            snaps = list(self._snaps)
        return [
            {
                "version": s.version,
                "delta_edges": s.delta.delta_edges,
                "ratio": round(s.ratio, 6),
                "compacted": s.compacted,
            }
            for s in snaps
        ]

    # -- writes ----------------------------------------------------------

    def apply(self, edits: EdgeEdits,
              on_compact: Optional[Callable[[Snapshot], None]] = None
              ) -> Snapshot:
        """Stack ``edits`` on the current version and mint version N+1.

        Compaction past LUX_DELTA_COMPACT_RATIO runs on a background
        thread (adopting the caller's trace id so the swap's trace covers
        it); ``on_compact`` fires after it finishes.
        """
        with spans.span("snapshot.apply") as tid:
            with self._lock:
                head = self._snaps[-1]
                snap = Snapshot(head.version + 1, head.delta.stack(edits))
                self._snaps.append(snap)
            if snap.ratio > flags.get_float("LUX_DELTA_COMPACT_RATIO"):
                t = threading.Thread(
                    target=self._compact_one, args=(snap, tid, on_compact),
                    name=f"lux-compact-v{snap.version}", daemon=True,
                )
                with self._lock:
                    self._compaction_threads.append(t)
                t.start()
        return snap

    def _compact_one(self, snap: Snapshot, trace_id, on_compact) -> None:
        with spans.adopt(trace_id):
            with spans.span("snapshot.compact", version=snap.version,
                            delta_edges=snap.delta.delta_edges):
                snap.compact()
                _compactions.inc()
        if on_compact is not None:
            on_compact(snap)

    def drain_compactions(self, timeout: float = 30.0) -> None:
        """Join outstanding compaction threads (tests / Session.close)."""
        with self._lock:
            threads = list(self._compaction_threads)
        for t in threads:
            t.join(timeout)
        with self._lock:
            self._compaction_threads = [
                t for t in self._compaction_threads if t.is_alive()
            ]
