"""Versioned graph snapshots over :class:`~lux_tpu.graph.delta.DeltaGraph`.

A :class:`SnapshotStore` holds the linear version history of one logical
graph. ``apply(edits)`` stacks an edit batch onto the current snapshot's
delta and mints version N+1; each snapshot is identified by the hardened
checkpoint fingerprint of its *materialized* graph, which is what keys
every serving engine and cache entry downstream. When a snapshot's
pending-edit ratio crosses ``LUX_DELTA_COMPACT_RATIO`` the store kicks a
background compaction thread that re-anchors the delta on the merged CSC
— the merged arrays are reused as-is, so compaction never changes the
fingerprint (tested: compaction round-trips are bitwise no-ops for
readers).

Durability (PR 9): pass ``wal_dir`` (or set ``LUX_WAL_DIR``) and the
store writes every edit batch through :mod:`lux_tpu.graph.wal` *before*
any version is minted — ``enqueue`` logs + stages a batch without
swapping (ROADMAP item 3's write-ahead queue; many small batches
coalesce into one ``apply``), ``apply`` folds all staged batches, mints
version N+1, and seals it with a fingerprinted commit record.
:meth:`SnapshotStore.recover` replays the log on startup onto the base
graph, yielding a bitwise-identical current snapshot with any
uncommitted batches re-staged.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from lux_tpu.graph.delta import DeltaGraph, EdgeEdits
from lux_tpu.graph.graph import Graph
from lux_tpu.obs import metrics, spans
from lux_tpu.utils import checkpoint, flags
from lux_tpu.utils.locks import make_lock

_compactions = metrics.counter("lux_snapshot_compactions_total")


class Snapshot:
    """One immutable version: a DeltaGraph plus lazy graph/fingerprint."""

    def __init__(self, version: int, delta: DeltaGraph):
        self.version = version
        self._delta = delta
        self._lock = make_lock("snapshot")
        self._fingerprint: Optional[str] = None
        self.compacted = delta.delta_edges == 0

    @property
    def delta(self) -> DeltaGraph:
        return self._delta

    @property
    def graph(self) -> Graph:
        return self._delta.merged()

    @property
    def fingerprint(self) -> str:
        if self._fingerprint is None:
            with self._lock:
                if self._fingerprint is None:
                    self._fingerprint = checkpoint.fingerprint_hex(self.graph)
        return self._fingerprint

    @property
    def ratio(self) -> float:
        return self._delta.ratio

    def compact(self) -> None:
        """Re-anchor the delta on its merged CSC (idempotent).

        ``merged()`` of the fresh delta returns the same Graph object the
        old delta materialized, so fingerprints and any reader holding
        ``.graph`` are unaffected — compaction only drops the edit runs
        and frees the old base for GC.
        """
        with self._lock:
            if not self.compacted:
                self._delta = DeltaGraph.fresh(self._delta.merged())
                self.compacted = True


class SnapshotStore:
    """Linear version history with threshold-triggered background compaction."""

    def __init__(self, base: Graph, wal_dir: Optional[str] = None):
        self._lock = make_lock("snapshot.store")
        self._snaps: List[Snapshot] = [Snapshot(0, DeltaGraph.fresh(base))]
        self._compaction_threads: List[threading.Thread] = []
        self._pending: List[EdgeEdits] = []
        self._wal = None
        if wal_dir:
            from lux_tpu.graph.wal import Wal
            self._wal = Wal(wal_dir)

    @classmethod
    def recover(cls, base: Graph, wal_dir: str) -> "SnapshotStore":
        """Rebuild a store from ``base`` plus the WAL in ``wal_dir``.

        The recovered current snapshot is bitwise-identical to the last
        *committed* (minted) version before the crash — a torn tail
        record is truncated, never fatal — and edit batches logged but
        not yet committed are re-staged as pending, so the next
        ``apply()`` mints them exactly as the dead process would have.
        Raises :class:`~lux_tpu.graph.wal.WalCorruptError` on interior
        damage rather than serving a silently wrong graph."""
        from lux_tpu.graph import wal as walmod
        result = walmod.replay(base, wal_dir)
        store = cls(result.graph, wal_dir=wal_dir)
        # Version numbering resumes where the dead process left off: the
        # log's commit records carry versions, and downstream state
        # (metrics, serving summaries) must not watch versions run
        # backwards across a restart.
        store._snaps[-1].version = result.version
        store._pending.extend(result.pending)
        return store

    # -- reads -----------------------------------------------------------

    def current(self) -> Snapshot:
        with self._lock:
            return self._snaps[-1]

    def get(self, version: int) -> Snapshot:
        with self._lock:
            # After recover() the history starts at the replayed version,
            # not 0 — index relative to the first retained snapshot.
            idx = version - self._snaps[0].version
            if not 0 <= idx < len(self._snaps):
                raise KeyError(f"unknown snapshot version {version}")
            return self._snaps[idx]

    def history(self) -> List[dict]:
        with self._lock:
            snaps = list(self._snaps)
        return [
            {
                "version": s.version,
                "delta_edges": s.delta.delta_edges,
                "ratio": round(s.ratio, 6),
                "compacted": s.compacted,
            }
            for s in snaps
        ]

    def pending_edits(self) -> int:
        """Batches enqueued behind the WAL but not yet minted."""
        with self._lock:
            return len(self._pending)

    def pending_batches(self) -> tuple:
        """Snapshot of the enqueued batches (read-only; apply() drains)."""
        with self._lock:
            return tuple(self._pending)

    def wal_stats(self) -> Optional[dict]:
        return self._wal.stats() if self._wal is not None else None

    # -- writes ----------------------------------------------------------

    def enqueue(self, edits: EdgeEdits) -> int:
        """Durably stage one batch without minting a version.

        The batch is validated, appended (CRC-framed, fsync'd) to the WAL
        chained on the current snapshot's fingerprint, and staged; the
        next :meth:`apply` folds every staged batch into ONE new version,
        so swaps amortize over many small edits (ROADMAP item 3). With no
        ``wal_dir`` the queue still works — it just isn't durable.
        Returns the pending-batch count."""
        with self._lock:
            head = self._snaps[-1]
        edits.validate(head.delta.base.nv)
        with spans.span("snapshot.enqueue"):
            # The WAL append and the stage are one critical section under
            # the store lock: an apply() draining the queue concurrently
            # must not commit between our append and our stage, or the
            # log would chain a batch onto a fingerprint it never saw.
            with self._lock:
                if self._wal is not None:
                    self._wal.append_edits(edits, self._snaps[-1].fingerprint)
                self._pending.append(edits)
                return len(self._pending)

    def apply(self, edits: Optional[EdgeEdits] = None,
              on_compact: Optional[Callable[[Snapshot], None]] = None
              ) -> Snapshot:
        """Fold ``edits`` plus every enqueued batch into version N+1.

        WAL-before-mint: ``edits`` goes through :meth:`enqueue` first, so
        by the time a version exists its batches are already durable; the
        mint is then sealed with a fingerprinted ``commit`` record.
        ``apply(None)`` flushes the queue alone (no-op if empty).

        Compaction past LUX_DELTA_COMPACT_RATIO runs on a background
        thread (adopting the caller's trace id so the swap's trace covers
        it); ``on_compact`` fires after it finishes.
        """
        if edits is not None:
            self.enqueue(edits)
        with spans.span("snapshot.apply") as tid:
            with self._lock:
                head = self._snaps[-1]
                if not self._pending:
                    return head
                batches, self._pending = self._pending, []
                delta = head.delta
                for e in batches:
                    delta = delta.stack(e)
                snap = Snapshot(head.version + 1, delta)
                self._snaps.append(snap)
                if self._wal is not None:
                    # Fingerprint forces materialization; the store lock
                    # is held so the commit serializes against enqueue's
                    # chain read (see enqueue). Swaps already pay the
                    # merge here — the warm path needs the graph anyway.
                    self._wal.append_commit(snap.version, snap.fingerprint)
            if snap.ratio > flags.get_float("LUX_DELTA_COMPACT_RATIO"):
                t = threading.Thread(
                    target=self._compact_one, args=(snap, tid, on_compact),
                    name=f"lux-compact-v{snap.version}", daemon=True,
                )
                with self._lock:
                    self._compaction_threads.append(t)
                t.start()
        return snap

    def _compact_one(self, snap: Snapshot, trace_id, on_compact) -> None:
        with spans.adopt(trace_id):
            with spans.span("snapshot.compact", version=snap.version,
                            delta_edges=snap.delta.delta_edges):
                snap.compact()
                _compactions.inc()
        if on_compact is not None:
            on_compact(snap)

    def drain_compactions(self, timeout: float = 30.0) -> None:
        """Join outstanding compaction threads (tests / Session.close)."""
        with self._lock:
            threads = list(self._compaction_threads)
        for t in threads:
            t.join(timeout)
        with self._lock:
            self._compaction_threads = [
                t for t in self._compaction_threads if t.is_alive()
            ]
