"""Crash-safe write-ahead log of :class:`EdgeEdits` batches (format v1).

A process crash used to lose every edit applied since the base
checkpoint: snapshots live in RAM and ``apply_edits`` had no durability
story (ROADMAP item 3). The WAL closes that hole with the standard
database recipe — *log the edit, fsync, only then mint the version* — so
on restart :func:`replay` reconstructs a bitwise-identical graph from
the base plus the log.

Format v1 (``<wal_dir>/lux.wal``)::

    LUXWAL1\\n                                  # 8-byte magic
    [u32 len][u32 crc32(payload)][payload]      # repeated frames, LE

Each payload is an uncompressed ``np.savez`` archive holding a JSON
``meta`` record plus the edit arrays. Two record kinds:

- ``edits``  — one EdgeEdits batch, chained on ``base_fp``: the
  checkpoint fingerprint of the *last committed* graph state it applies
  to. Appended (and fsync'd) by ``SnapshotStore.enqueue`` **before** any
  version is minted.
- ``commit`` — version N+1 was minted from every ``edits`` record since
  the previous commit; carries the materialized graph's fingerprint so
  replay can verify parity record-by-record.

Torn-write policy: a frame that stops at end-of-file — short header,
short payload, or CRC mismatch *on the final frame* — is a torn tail
from a crash mid-append. Both :class:`Wal` open and :func:`replay`
truncate it and carry on (the edit was never acknowledged). A CRC
mismatch anywhere *before* the final frame means the log itself rotted
and raises :class:`WalCorruptError` — silently skipping interior records
would replay a wrong graph.

Fingerprint chaining makes compaction safe: :func:`replay` skips leading
records until one chains onto the fingerprint of the graph it was given,
so a log whose prefix was folded into a newer base checkpoint (or
dropped by :meth:`Wal.compact`) still replays exactly the un-compacted
suffix.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

from lux_tpu.graph.delta import DeltaGraph, EdgeEdits
from lux_tpu.graph.graph import Graph, W_DTYPE
from lux_tpu.utils import checkpoint, faults
from lux_tpu.utils.locks import make_lock
from lux_tpu.utils.logging import get_logger

MAGIC = b"LUXWAL1\n"
_FRAME = struct.Struct("<II")   # payload length, crc32(payload)

_log = get_logger("wal")


class WalCorruptError(RuntimeError):
    """The log is damaged somewhere replay cannot safely skip: a CRC or
    decode failure before the final frame, a record that does not chain
    on the preceding state, or a commit whose replayed fingerprint
    disagrees with the logged one."""


@dataclasses.dataclass(frozen=True)
class WalRecord:
    kind: str                        # "edits" | "commit"
    seq: int
    base_fp: Optional[str] = None    # edits: fingerprint chained on
    version: Optional[int] = None    # commit: version minted
    fingerprint: Optional[str] = None  # commit: fingerprint of that version
    edits: Optional[EdgeEdits] = None


@dataclasses.dataclass(frozen=True)
class RecoveryResult:
    graph: Graph            # state as of the last commit record (or base)
    version: int            # last committed WAL version (0 = none)
    fingerprint: str
    pending: Tuple[EdgeEdits, ...]   # logged but uncommitted batches
    replayed: int           # edits records folded into `graph`
    skipped: int            # already-compacted records before the anchor
    truncated: bool         # a torn tail record was dropped


def _pack(meta: dict, arrays: dict) -> bytes:
    bio = io.BytesIO()
    np.savez(bio, meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
             **arrays)
    return bio.getvalue()


def _unpack(payload: bytes) -> WalRecord:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        if meta["kind"] == "commit":
            return WalRecord(kind="commit", seq=int(meta["seq"]),
                             version=int(meta["version"]),
                             fingerprint=meta["fingerprint"])
        edits = EdgeEdits(
            ins_src=z["ins_src"].astype(np.int64),
            ins_dst=z["ins_dst"].astype(np.int64),
            ins_w=z["ins_w"].astype(W_DTYPE) if meta["weighted"] else None,
            del_src=z["del_src"].astype(np.int64),
            del_dst=z["del_dst"].astype(np.int64),
        )
        return WalRecord(kind="edits", seq=int(meta["seq"]),
                         base_fp=meta["base_fp"], edits=edits)


def _scan(buf: bytes) -> Tuple[List[bytes], int, bool]:
    """Split ``buf`` into CRC-verified frame payloads.

    Returns ``(payloads, valid_end, torn)`` where ``valid_end`` is the
    offset just past the last intact frame. Raises WalCorruptError for
    damage anywhere before the final frame (see module docstring)."""
    if not buf.startswith(MAGIC):
        raise WalCorruptError("bad WAL magic (not a lux.wal v1 file)")
    off, n = len(MAGIC), len(buf)
    payloads: List[bytes] = []
    while off < n:
        if off + _FRAME.size > n:
            return payloads, off, True          # torn header
        ln, crc = _FRAME.unpack_from(buf, off)
        end = off + _FRAME.size + ln
        if end > n:
            return payloads, off, True          # torn payload
        payload = buf[off + _FRAME.size:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            if end >= n:
                return payloads, off, True      # corrupted tail == torn
            raise WalCorruptError(
                f"CRC mismatch at offset {off} before end of log")
        payloads.append(payload)
        off = end
    return payloads, off, False


def read_records(path: str) -> Tuple[List[WalRecord], bool]:
    """Decode every intact record of ``path``; torn tails are dropped
    (flag returned), interior damage raises :class:`WalCorruptError`."""
    with open(path, "rb") as f:
        buf = f.read()
    payloads, _, torn = _scan(buf)
    records = []
    for i, p in enumerate(payloads):
        try:
            records.append(_unpack(p))
        except WalCorruptError:
            raise
        except Exception as e:
            # CRC passed but the archive will not decode: the bytes we
            # wrote were bad (e.g. corruption injected pre-CRC), which no
            # amount of tail-truncation makes safe to skip.
            raise WalCorruptError(
                f"record {i} failed to decode: {e!r}") from e
    return records, torn


class Wal:
    """Append-only handle over one ``lux.wal`` file.

    Appends are serialized under ``make_lock("wal")`` and each record is
    flushed + fsync'd before :meth:`append_edits`/:meth:`append_commit`
    return — durability is the whole point. Opening an existing file
    truncates a torn tail in place (the crash-recovery contract) and
    resumes the sequence numbering.
    """

    def __init__(self, wal_dir: str, name: str = "lux.wal"):
        os.makedirs(wal_dir, exist_ok=True)
        self.path = os.path.join(wal_dir, name)
        self._lock = make_lock("wal")
        self._seq = 0
        self._records = 0
        if not os.path.exists(self.path):
            with open(self.path, "wb") as f:
                f.write(MAGIC)
                f.flush()
                os.fsync(f.fileno())
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        payloads, valid_end, torn = _scan(buf)
        if torn:
            _log.warning("wal %s: truncating torn tail (%d -> %d bytes)",
                         self.path, len(buf), valid_end)
            os.truncate(self.path, valid_end)
            self._metric("lux_wal_truncated_total").inc()
        self._records = len(payloads)
        if payloads:
            self._seq = _unpack(payloads[-1]).seq

    @staticmethod
    def _metric(name: str, labels: Optional[dict] = None):
        from lux_tpu.obs import metrics
        return metrics.counter(name, labels)

    # -- appends ---------------------------------------------------------

    def append_edits(self, edits: EdgeEdits, base_fp: str) -> int:
        """Durably log one batch chained on ``base_fp``; returns its seq."""
        meta = {"kind": "edits", "seq": 0, "base_fp": base_fp,
                "weighted": edits.ins_w is not None}
        arrays = {"ins_src": edits.ins_src, "ins_dst": edits.ins_dst,
                  "del_src": edits.del_src, "del_dst": edits.del_dst,
                  "ins_w": (edits.ins_w if edits.ins_w is not None
                            else np.zeros(0, dtype=W_DTYPE))}
        return self._append("edits", meta, arrays)

    def append_commit(self, version: int, fingerprint: str) -> int:
        """Mark every edits record since the last commit as minted."""
        meta = {"kind": "commit", "seq": 0, "version": int(version),
                "fingerprint": fingerprint}
        return self._append("commit", meta, {})

    def _append(self, kind: str, meta: dict, arrays: dict) -> int:
        with self._lock:
            self._seq += 1
            meta["seq"] = self._seq
            payload = _pack(meta, arrays)
            crc = zlib.crc32(payload) & 0xFFFFFFFF
            # CRC is computed on the intended bytes *before* the fault
            # point, so an injected `corrupt` lands as a CRC-detectable
            # torn/rotted write — exactly what recovery must survive.
            payload = faults.point("wal.fsync", data=payload)
            with open(self.path, "ab") as f:
                f.write(_FRAME.pack(len(payload), crc))
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            self._records += 1
            seq = self._seq
        self._metric("lux_wal_records_total", {"kind": kind}).inc()
        self._metric("lux_wal_bytes_total").inc(
            _FRAME.size + len(payload))
        return seq

    # -- reads / maintenance ---------------------------------------------

    def records(self) -> List[WalRecord]:
        recs, _ = read_records(self.path)
        return recs

    def stats(self) -> dict:
        with self._lock:
            return {"path": self.path, "records": self._records,
                    "seq": self._seq,
                    "bytes": os.path.getsize(self.path)}

    def compact(self, upto_fingerprint: str) -> int:
        """Drop every record up to (and including) the last commit whose
        fingerprint is ``upto_fingerprint`` — callable once that state is
        durable elsewhere (e.g. a base checkpoint). Returns the number of
        records dropped. Atomic: rewrite + fsync + rename."""
        with self._lock:
            recs, _ = read_records(self.path)
            cut = None
            for i, r in enumerate(recs):
                if r.kind == "commit" and r.fingerprint == upto_fingerprint:
                    cut = i
            if cut is None:
                raise ValueError(
                    f"no commit record with fingerprint {upto_fingerprint!r}")
            keep = recs[cut + 1:]
            with open(self.path, "rb") as f:
                buf = f.read()
            payloads, _, _ = _scan(buf)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(MAGIC)
                for p in payloads[cut + 1:]:
                    f.write(_FRAME.pack(len(p), zlib.crc32(p) & 0xFFFFFFFF))
                    f.write(p)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._records = len(keep)
            return cut + 1


def replay(base: Graph, wal_dir: str, name: str = "lux.wal"
           ) -> RecoveryResult:
    """Reconstruct the last committed graph state from ``base`` + the log.

    Records are verified as they fold: every ``edits`` record must chain
    on the current fingerprint and every ``commit`` record's fingerprint
    must match the replayed graph bit-for-bit (the checkpoint fingerprint
    hashes the CSC arrays). Leading records that predate ``base`` —
    compacted away into it — are skipped until the chain anchors; a log
    that never anchors cannot belong to this graph and raises."""
    path = os.path.join(wal_dir, name)
    base_fp = checkpoint.fingerprint_hex(base)
    if not os.path.exists(path):
        return RecoveryResult(graph=base, version=0, fingerprint=base_fp,
                              pending=(), replayed=0, skipped=0,
                              truncated=False)
    records, torn = read_records(path)
    cur_fp = base_fp
    delta = DeltaGraph.fresh(base)
    committed, version = base, 0
    pending: List[EdgeEdits] = []
    anchored, skipped, replayed = False, 0, 0
    for r in records:
        if not anchored:
            if r.kind == "commit" and r.fingerprint == cur_fp:
                anchored, version = True, r.version
                continue
            if not (r.kind == "edits" and r.base_fp == cur_fp):
                skipped += 1
                continue
            anchored = True   # first record chaining on base: process it
        if r.kind == "edits":
            if r.base_fp != cur_fp:
                raise WalCorruptError(
                    f"edits seq {r.seq} chains on {r.base_fp[:12]}… but the "
                    f"replayed state is {cur_fp[:12]}…")
            delta = delta.stack(r.edits)
            pending.append(r.edits)
            replayed += 1
        else:
            g = delta.merged()
            fp = checkpoint.fingerprint_hex(g)
            if fp != r.fingerprint:
                raise WalCorruptError(
                    f"commit seq {r.seq} (version {r.version}) replays to "
                    f"{fp[:12]}… but the log recorded {r.fingerprint[:12]}…")
            committed, version, cur_fp = g, r.version, fp
            delta = DeltaGraph.fresh(g)
            pending = []
    if records and not anchored:
        raise WalCorruptError(
            "log does not chain onto the given base graph "
            f"(base fingerprint {base_fp[:12]}…)")
    if replayed or pending:
        Wal._metric("lux_wal_replayed_total").inc(replayed)
    _log.info("wal replay: %d records -> version %d (%d skipped, "
              "%d pending%s)", replayed, version, skipped, len(pending),
              ", torn tail dropped" if torn else "")
    return RecoveryResult(graph=committed, version=version,
                          fingerprint=cur_fp, pending=tuple(pending),
                          replayed=replayed, skipped=skipped, truncated=torn)
