"""The applications (the reference's pagerank/, sssp/, components/,
col_filter/ directories, re-expressed as vertex programs)."""

from lux_tpu.models.pagerank import PageRank
from lux_tpu.models.sssp import SSSP
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.colfilter import CollaborativeFiltering

# App registry: the one name → program mapping shared by the serving
# layer (serve/session.py routes queries by these names) and tools.
# Programs with ``rooted=True`` take a per-query root (``start``) and are
# eligible for multi-source micro-batching; root-free fixpoints are
# served from the result cache instead.
PROGRAMS = {
    "pagerank": PageRank,
    "sssp": SSSP,
    "components": ConnectedComponents,
    "colfilter": CollaborativeFiltering,
}

ROOTED_APPS = frozenset({"sssp"})

# Which executor kinds can run each program (the luxlint-IR trace
# matrix, analysis/ir.py — and the capability map cli/serve consult).
# tiled is spmv-only (sum combiner, identity contrib, scalar values);
# push needs a PushProgram; multi-source batching needs a rooted app.
ENGINE_KINDS = {
    "pagerank": ("pull", "tiled", "pull_sharded", "tiled_sharded"),
    "sssp": ("push", "push_multi", "push_incremental", "push_sharded",
             "push_multi_sharded"),
    "components": ("push", "push_incremental", "push_sharded"),
    "colfilter": ("pull", "pull_sharded"),
}


def engine_kinds(name: str):
    """Executor kinds capable of running the program named ``name``."""
    try:
        return ENGINE_KINDS[name]
    except KeyError:
        raise KeyError(
            f"unknown app {name!r}; registered: {sorted(ENGINE_KINDS)}"
        ) from None


def get_program(name: str):
    """Instantiate the vertex program registered under ``name``."""
    try:
        return PROGRAMS[name]()
    except KeyError:
        raise KeyError(
            f"unknown app {name!r}; registered: {sorted(PROGRAMS)}"
        ) from None


__all__ = [
    "PageRank",
    "SSSP",
    "ConnectedComponents",
    "CollaborativeFiltering",
    "PROGRAMS",
    "ROOTED_APPS",
    "ENGINE_KINDS",
    "engine_kinds",
    "get_program",
]
