"""The applications (the reference's pagerank/, sssp/, components/,
col_filter/ directories, re-expressed as vertex programs)."""

from lux_tpu.models.pagerank import PageRank

__all__ = ["PageRank"]
