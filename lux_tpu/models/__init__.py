"""The applications (the reference's pagerank/, sssp/, components/,
col_filter/ directories re-expressed as vertex programs, plus the GAS
registry widening: BFS, weighted delta-SSSP, label propagation, k-core).
"""

from lux_tpu.models.pagerank import PageRank
from lux_tpu.models.sssp import SSSP
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.colfilter import CollaborativeFiltering
from lux_tpu.models.bfs import BFS
from lux_tpu.models.sssp_delta import DeltaSSSP
from lux_tpu.models.labelprop import LabelPropagation
from lux_tpu.models.kcore import KCore

# App registry: the one name → program mapping shared by the serving
# layer (serve/session.py routes queries by these names) and tools.
# Programs with ``rooted=True`` take a per-query root (``start``) and are
# eligible for multi-source micro-batching; root-free fixpoints are
# served from the result cache instead.
PROGRAMS = {
    "pagerank": PageRank,
    "sssp": SSSP,
    "components": ConnectedComponents,
    "colfilter": CollaborativeFiltering,
    "bfs": BFS,
    "sssp_delta": DeltaSSSP,
    "labelprop": LabelPropagation,
    "kcore": KCore,
}

# Derived from each program's ``rooted`` declaration so a new rooted
# program can't silently miss multi-source batching by not being added
# to a hand-maintained set here.
ROOTED_APPS = frozenset(
    name for name, cls in PROGRAMS.items() if getattr(cls, "rooted", False)
)

# Which executor kinds can run each program (the luxlint-IR trace
# matrix, analysis/ir.py — and the capability map cli/serve consult).
# tiled is spmv-only (sum combiner, identity contrib, scalar values);
# push needs a PushProgram; multi-source batching needs a rooted app;
# gas runs every program (legacy models through the engine/program.py
# ``as_gas`` adapters — PullPrograms as frontier-less dense pull), and
# gas_sharded mirrors that universality on the mesh (frontier-less
# programs run its dense pull path); gas_multi / gas_multi_sharded need
# a rooted frontier program.
ENGINE_KINDS = {
    "pagerank": ("pull", "tiled", "pull_sharded", "tiled_sharded", "gas",
                 "gas_sharded"),
    "sssp": ("push", "push_multi", "push_incremental", "push_sharded",
             "push_multi_sharded", "gas", "gas_multi", "gas_sharded",
             "gas_multi_sharded"),
    "components": ("push", "push_incremental", "push_sharded", "gas",
                   "gas_sharded"),
    "colfilter": ("pull", "pull_sharded", "gas", "gas_sharded"),
    "bfs": ("gas", "gas_multi", "gas_sharded", "gas_multi_sharded"),
    "sssp_delta": ("gas", "gas_multi", "gas_sharded", "gas_multi_sharded"),
    "labelprop": ("gas", "gas_sharded"),
    "kcore": ("gas", "gas_sharded"),
}


def engine_kinds(name: str):
    """Executor kinds capable of running the program named ``name``."""
    try:
        return ENGINE_KINDS[name]
    except KeyError:
        raise KeyError(
            f"unknown app {name!r}; registered: {sorted(ENGINE_KINDS)}"
        ) from None


def get_program(name: str):
    """Instantiate the vertex program registered under ``name``."""
    try:
        return PROGRAMS[name]()
    except KeyError:
        raise KeyError(
            f"unknown app {name!r}; registered: {sorted(PROGRAMS)}"
        ) from None


__all__ = [
    "PageRank",
    "SSSP",
    "ConnectedComponents",
    "CollaborativeFiltering",
    "BFS",
    "DeltaSSSP",
    "LabelPropagation",
    "KCore",
    "PROGRAMS",
    "ROOTED_APPS",
    "ENGINE_KINDS",
    "engine_kinds",
    "get_program",
]
