"""The applications (the reference's pagerank/, sssp/, components/,
col_filter/ directories re-expressed as vertex programs, plus the GAS
registry widening: BFS, weighted delta-SSSP, label propagation, k-core).
"""

from lux_tpu.models.pagerank import PageRank
from lux_tpu.models.sssp import SSSP
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.colfilter import CollaborativeFiltering
from lux_tpu.models.bfs import BFS
from lux_tpu.models.sssp_delta import DeltaSSSP
from lux_tpu.models.labelprop import LabelPropagation
from lux_tpu.models.kcore import KCore

# App registry: the one name → program mapping shared by the serving
# layer (serve/session.py routes queries by these names) and tools.
# Programs with ``rooted=True`` take a per-query root (``start``) and are
# eligible for multi-source micro-batching; root-free fixpoints are
# served from the result cache instead.
PROGRAMS = {
    "pagerank": PageRank,
    "sssp": SSSP,
    "components": ConnectedComponents,
    "colfilter": CollaborativeFiltering,
    "bfs": BFS,
    "sssp_delta": DeltaSSSP,
    "labelprop": LabelPropagation,
    "kcore": KCore,
}

_CAPABILITY_REPORT = None


def capability_report(refresh: bool = False) -> dict:
    """The machine-checked capability matrix the registry trusts.

    Prefers the derived proof matrix from the ``gascap.v1`` artifact
    (``luxlint --programs``, analysis/gasck.py — honoring
    ``LUX_GASCAP_DIR``); falls back to the class-attr declarations when
    the artifact is missing or rejected (tampered artifacts raise inside
    gasck and land here as ``error``). Returns ``{source, artifact_id,
    error, programs: {name: {rooted, frontier_ok, incremental_ok}}}``
    with ``source`` one of ``artifact`` / ``declared``.
    """
    global _CAPABILITY_REPORT
    if _CAPABILITY_REPORT is not None and not refresh:
        return _CAPABILITY_REPORT
    declared = {
        name: {
            "rooted": bool(getattr(cls, "rooted", False)),
            "frontier_ok": bool(getattr(cls, "frontier_ok", False)),
            "incremental_ok": bool(getattr(cls, "incremental_ok", False)),
        }
        for name, cls in PROGRAMS.items()
    }
    report = {"source": "declared", "artifact_id": None, "error": None,
              "programs": declared}
    try:
        from lux_tpu.analysis import gasck

        art = gasck.load_capmap(gasck.capmap_path())
        programs = {}
        for name, caps in declared.items():
            entry = (art.get("programs") or {}).get(name)
            derived = entry.get("derived") if isinstance(entry, dict) \
                else None
            if isinstance(derived, dict):
                programs[name] = {
                    k: bool(derived.get(k, caps[k])) for k in caps
                }
            else:
                programs[name] = caps   # program newer than the artifact
        report = {"source": "artifact", "artifact_id": art.get("id"),
                  "error": None, "programs": programs}
    except FileNotFoundError:
        report["error"] = "artifact missing (run luxlint --programs)"
    except Exception as e:
        report["error"] = f"artifact rejected: {e!r}"
    _CAPABILITY_REPORT = report
    return report


def capabilities(refresh: bool = False) -> dict:
    """``{name: {rooted, frontier_ok, incremental_ok}}`` per program."""
    return capability_report(refresh)["programs"]


def frontier_ok(name: str) -> bool:
    """Proof-derived license for the frontier exchange / adaptive lanes."""
    return bool(capabilities().get(name, {}).get("frontier_ok", False))


def incremental_ok(name: str) -> bool:
    """Proof-derived license for IncrementalExecutor warm-starts."""
    return bool(capabilities().get(name, {}).get("incremental_ok", False))


def rooted_apps() -> frozenset:
    return frozenset(
        name for name, caps in capabilities().items() if caps["rooted"]
    )


# Derived from the gascap.v1 proof matrix (class-attr declarations as
# the no-artifact fallback) so a new rooted program can't silently miss
# multi-source batching by not being added to a hand-maintained set —
# and so a *claimed* root parameter that init_values ignores can't buy
# batching it can't serve (LUX606 keeps the two views in lockstep).
ROOTED_APPS = rooted_apps()

# Which executor kinds can run each program (the luxlint-IR trace
# matrix, analysis/ir.py — and the capability map cli/serve consult).
# tiled is spmv-only (sum combiner, identity contrib, scalar values);
# push needs a PushProgram; multi-source batching needs a rooted app;
# gas runs every program (legacy models through the engine/program.py
# ``as_gas`` adapters — PullPrograms as frontier-less dense pull), and
# gas_sharded mirrors that universality on the mesh (frontier-less
# programs run its dense pull path); gas_multi / gas_multi_sharded need
# a rooted frontier program.
ENGINE_KINDS = {
    "pagerank": ("pull", "tiled", "pull_sharded", "tiled_sharded", "gas",
                 "gas_sharded"),
    "sssp": ("push", "push_multi", "push_incremental", "push_sharded",
             "push_multi_sharded", "gas", "gas_multi", "gas_sharded",
             "gas_multi_sharded"),
    "components": ("push", "push_incremental", "push_sharded", "gas",
                   "gas_sharded"),
    "colfilter": ("pull", "pull_sharded", "gas", "gas_sharded"),
    "bfs": ("gas", "gas_multi", "gas_sharded", "gas_multi_sharded"),
    "sssp_delta": ("gas", "gas_multi", "gas_sharded", "gas_multi_sharded"),
    "labelprop": ("gas", "gas_sharded"),
    "kcore": ("gas", "gas_sharded"),
}


def engine_kinds(name: str):
    """Executor kinds capable of running the program named ``name``."""
    try:
        return ENGINE_KINDS[name]
    except KeyError:
        raise KeyError(
            f"unknown app {name!r}; registered: {sorted(ENGINE_KINDS)}"
        ) from None


def get_program(name: str):
    """Instantiate the vertex program registered under ``name``."""
    try:
        return PROGRAMS[name]()
    except KeyError:
        raise KeyError(
            f"unknown app {name!r}; registered: {sorted(PROGRAMS)}"
        ) from None


__all__ = [
    "PageRank",
    "SSSP",
    "ConnectedComponents",
    "CollaborativeFiltering",
    "BFS",
    "DeltaSSSP",
    "LabelPropagation",
    "KCore",
    "PROGRAMS",
    "ROOTED_APPS",
    "ENGINE_KINDS",
    "capability_report",
    "capabilities",
    "frontier_ok",
    "incremental_ok",
    "rooted_apps",
    "engine_kinds",
    "get_program",
]
