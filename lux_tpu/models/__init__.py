"""The applications (the reference's pagerank/, sssp/, components/,
col_filter/ directories, re-expressed as vertex programs)."""

from lux_tpu.models.pagerank import PageRank
from lux_tpu.models.sssp import SSSP
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.colfilter import CollaborativeFiltering

__all__ = [
    "PageRank",
    "SSSP",
    "ConnectedComponents",
    "CollaborativeFiltering",
]
