"""The applications (the reference's pagerank/, sssp/, components/,
col_filter/ directories, re-expressed as vertex programs)."""

from lux_tpu.models.pagerank import PageRank
from lux_tpu.models.sssp import SSSP
from lux_tpu.models.components import ConnectedComponents
from lux_tpu.models.colfilter import CollaborativeFiltering

# App registry: the one name → program mapping shared by the serving
# layer (serve/session.py routes queries by these names) and tools.
# Programs with ``rooted=True`` take a per-query root (``start``) and are
# eligible for multi-source micro-batching; root-free fixpoints are
# served from the result cache instead.
PROGRAMS = {
    "pagerank": PageRank,
    "sssp": SSSP,
    "components": ConnectedComponents,
    "colfilter": CollaborativeFiltering,
}

ROOTED_APPS = frozenset({"sssp"})


def get_program(name: str):
    """Instantiate the vertex program registered under ``name``."""
    try:
        return PROGRAMS[name]()
    except KeyError:
        raise KeyError(
            f"unknown app {name!r}; registered: {sorted(PROGRAMS)}"
        ) from None


__all__ = [
    "PageRank",
    "SSSP",
    "ConnectedComponents",
    "CollaborativeFiltering",
    "PROGRAMS",
    "ROOTED_APPS",
    "get_program",
]
