"""Breadth-first search with parent derivation (GAS model).

The canonical direction-optimizing workload (Beamer's BFS is the example
every direction-switching engine leads with): the frontier starts as one
vertex, explodes to a large fraction of the graph in the middle levels,
and collapses again in the tail — exactly the shape the adaptive
executor's density hysteresis exists for. Depths are the SSSP hop-count
fixpoint (same monotone min-relaxation, ``sssp_gpu.cu:48-61``); the
parent array is derived on the host *after* convergence with a
deterministic tie-break (minimum-id predecessor on a shortest path), so
it is reproducible across directions and engines — a device-side
parent-claiming race would not be.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.gas import GasProgram
from lux_tpu.graph.graph import Graph


class BFS(GasProgram):
    name = "bfs"
    combiner = "min"
    value_dtype = jnp.uint32
    rooted = True

    def init_values(self, graph: Graph, start: int = 0) -> np.ndarray:
        depth = np.full(graph.nv, graph.nv, dtype=np.uint32)  # ∞ == nv
        depth[start] = 0
        return depth

    def init_frontier(self, graph: Graph, start: int = 0) -> np.ndarray:
        fr = np.zeros(graph.nv, dtype=bool)
        fr[start] = True
        return fr

    def gather(self, src_vals, weights):
        return src_vals + jnp.uint32(1)

    def edge_invariant(self, src_vals, dst_vals, weights):
        return dst_vals <= src_vals + jnp.uint32(1)

    def finalize_host(self, graph: Graph, values: np.ndarray) -> dict:
        return {"parent": bfs_parents(graph, values)}


def bfs_parents(graph: Graph, depth: np.ndarray) -> np.ndarray:
    """Minimum-id shortest-path predecessor per reached vertex, from the
    converged depth array (the root parents itself; unreached vertices
    get nv). One vectorized pass over the CSC edge list; int64 host
    math, uint32 out."""
    nv = graph.nv
    d = depth.astype(np.int64)
    src = graph.col_src.astype(np.int64)
    dst = graph.col_dst.astype(np.int64)
    # Edge (u -> v) is a tree-edge candidate iff depth[u] + 1 == depth[v].
    cand = np.where(d[src] + 1 == d[dst], src, nv)
    parent = np.full(nv, nv, dtype=np.int64)
    np.minimum.at(parent, dst, cand)
    parent[d == 0] = np.flatnonzero(d == 0)   # the root parents itself
    parent[d >= nv] = nv                      # unreached
    return parent.astype(np.uint32)


def reference_bfs(graph: Graph, start: int = 0):
    """Host oracle: (depth, parent) with the same deterministic
    minimum-id tie-break."""
    from lux_tpu.models.sssp import reference_sssp

    depth = reference_sssp(graph, start)
    return depth, bfs_parents(graph, depth)


def main(argv=None):
    """CLI: python -m lux_tpu.models.bfs -file g.lux -start R"""
    from lux_tpu.models.cli import run_push_app

    return run_push_app(BFS(), argv, supports_start=True)


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
