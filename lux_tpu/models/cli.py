"""Shared CLI driver for the four applications.

Reproduces the reference CLI surface (README.md:41-54, parse_input_args in
each app driver): ``-file`` ``-ni`` ``-start`` ``-check`` ``-verbose``,
prints the memory advisory and ``ELAPSED TIME`` the same way
(pagerank/pagerank.cc:60-118). The ``-ll:gpu/-ll:fsize/-ll:zsize`` runtime
flags have no TPU meaning; their replacement is ``-parts N`` (how many mesh
devices to shard over; default 1 device) — the reference folds GPU and
node counts into a partition count the same way (pagerank.cc:51-53).

Additions over the reference: ``-gteps`` summary line, ``-save/-resume``
checkpointing, ``-profile DIR`` (jax.profiler trace).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

import numpy as np

from lux_tpu import obs
from lux_tpu.utils.logging import get_logger
from lux_tpu.utils.timing import Timer


def build_parser(name: str, push: bool) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=name, prefix_chars="-")
    p.add_argument("-file", required=True, help="input .lux graph")
    if push:
        p.add_argument(
            "-ni", type=int, default=0,
            help="max iterations (0 = run to fixpoint)",
        )
    else:
        p.add_argument("-ni", type=int, required=True, help="iterations")
    p.add_argument("-start", type=int, default=0, help="SSSP root vertex")
    p.add_argument("-check", action="store_true")
    p.add_argument("-verbose", action="store_true")
    p.add_argument(
        "-parts", "-ng", "-ll:gpu", type=int, default=1, dest="parts",
        help="mesh devices to shard over (1 = single device); -ng and "
        "-ll:gpu are the reference's aliases for its GPU count "
        "(pagerank.cc:127, README.md:47)",
    )
    # Accepted for drop-in compatibility with the reference's documented
    # invocations (README.md:43-49); Legion memory sizing has no TPU
    # equivalent — XLA owns HBM, and the advisory prints what is needed.
    p.add_argument("-ll:fsize", type=int, dest="ll_fsize",
                   help=argparse.SUPPRESS)
    p.add_argument("-ll:zsize", type=int, dest="ll_zsize",
                   help=argparse.SUPPRESS)
    p.add_argument(
        "-strategy", choices=["rowptr", "segment"], default="rowptr",
        help="sum-combiner reduction strategy (flat pull apps)",
    )
    p.add_argument(
        "-layout", choices=["auto", "flat", "tiled"], default="auto",
        help="pull engine: 'tiled' = strip/lane-select hybrid (the fast "
        "path for SpMV-shaped programs like PageRank), 'flat' = plain "
        "gather engine, 'auto' = tiled when the program supports it",
    )
    p.add_argument(
        "-levels", default="8/2",
        help="tiled layout strip cascade, e.g. '8/2' or '32/8,8/3,2/2'",
    )
    p.add_argument(
        "-tile-mb", type=int, default=8192, dest="tile_mb",
        help="tiled layout strip memory budget (MB)",
    )
    p.add_argument(
        "-plan-cache", dest="plan_cache",
        help="hybrid plan cache path (default: next to the graph file)",
    )
    p.add_argument("-save", help="write checkpoint npz after the run")
    p.add_argument("-resume", help="resume vertex state from checkpoint npz")
    p.add_argument("-profile", help="capture a device-timeline trace to "
                   "DIR (obs/prof.py; parse with tools/prof_summary.py)")
    p.add_argument(
        "-metrics", "--metrics", dest="metrics",
        help="append the run's telemetry (per-iteration records, "
        "compile/execute split) as one JSON line to PATH "
        "(equivalent to LUX_METRICS=PATH)",
    )
    p.add_argument(
        "-trace", "--trace", dest="trace",
        help="stream Chrome trace_event JSON-lines to PATH for Perfetto "
        "(equivalent to LUX_TRACE=PATH)",
    )
    return p


def setup_telemetry(args):
    """Map the -metrics/-trace flags onto the LUX_* env vars the obs
    subsystem is gated by, then re-read them."""
    if getattr(args, "metrics", None):
        os.environ["LUX_METRICS"] = args.metrics
    if getattr(args, "trace", None):
        os.environ["LUX_TRACE"] = args.trace
    obs.reconfigure()


def load_graph(path: str, program, log):
    from lux_tpu.native import io as native_io
    from lux_tpu.utils.platform import ensure_backend

    platform = ensure_backend()
    log.info("jax platform: %s", platform)
    with Timer() as t:
        g = native_io.read_lux(path)
    log.info("loaded %s: nv=%d ne=%d (%.2fs)", path, g.nv, g.ne, t.elapsed)
    return g


def memory_advisory(g, parts: int, value_bytes: int, push: bool):
    """The reference prints minimum FB/ZC sizes per GPU/node
    (pagerank.cc:60-85, sssp.cc:59-90); here: estimated HBM per device."""
    edge_bytes = 8 + (4 if g.weights is not None else 0)  # src idx + seg/ptr
    per_dev = (
        g.ne // max(parts, 1) * edge_bytes
        + g.nv // max(parts, 1) * (value_bytes * 2 + 8)
        + (g.nv * value_bytes * parts if parts > 1 else 0)  # gathered ghosts
    )
    print(
        f"memory advisory: ~{per_dev / 1e6:.0f} MB HBM per device "
        f"({parts} part{'s' if parts != 1 else ''})"
    )


def _parse_levels(spec: str):
    try:
        levels = tuple(
            tuple(int(v) for v in part.split("/"))
            for part in spec.split(",")
        )
        if not all(len(lv) == 2 for lv in levels):
            raise ValueError
        return levels
    except ValueError:
        raise SystemExit(
            f"error: -levels {spec!r} is malformed; expected "
            "'r/thr[,r/thr...]', e.g. '8/2' or '32/8,8/3,2/2'"
        )


def _tiled_plan(g, program, args, log):
    """Resolve the hybrid plan for a tiled run (cached next to the graph
    file, keyed by cascade + budget so different configs coexist)."""
    from lux_tpu.engine.tiled import get_cached_plan

    levels = _parse_levels(args.levels)
    budget = args.tile_mb << 20
    path = args.plan_cache or (
        args.file
        + ".plan_"
        + "_".join(f"{r}x{t}" for r, t in levels)
        + f"_{args.tile_mb}.luxplan"
    )
    with Timer() as t:
        plan = get_cached_plan(
            g, path, levels=levels, budget_bytes=budget, log=log.info
        )
    log.info(
        "hybrid plan: %d strips (%.2f GB), coverage=%.1f%% (%.1fs)",
        plan.num_strips, plan.strip_bytes / 1e9, plan.coverage * 100,
        t.elapsed,
    )
    return plan


def make_executor(g, program, args, log=None):
    """Pick the engine. Pull programs default to the tiled (strip/
    lane-select hybrid) executor when the program is SpMV-shaped — the
    reference likewise has exactly one entry point per app
    (pagerank.cc:32-119) with the fast kernel behind it; ``-layout flat``
    forces the plain gather engine."""
    if log is None:
        log = get_logger(program.name)
    from lux_tpu.engine.gas import AdaptiveExecutor, GasProgram

    if isinstance(program, GasProgram):
        # The adaptive executor owns its direction choice (LUX_GAS pins
        # it); layout/parts knobs belong to the legacy engines.
        if args.parts > 1:
            raise SystemExit(
                f"error: {program.name} (a GAS app) is single-device for "
                "now; drop -parts"
            )
        if args.layout != "auto":
            raise SystemExit(
                f"error: -layout {args.layout} has no effect on "
                f"{program.name} (a GAS app); use LUX_GAS=pull|push|adaptive"
            )
        return AdaptiveExecutor(g, program)
    is_push = hasattr(program, "init_frontier")
    use_tiled = False
    if is_push and args.layout != "auto":
        raise SystemExit(
            f"error: -layout {args.layout} has no effect on "
            f"{program.name} (a push-model app); drop the flag"
        )
    if not is_push:
        from lux_tpu.engine.tiled import spmv_capable

        if args.layout == "tiled":
            if not spmv_capable(program):
                raise SystemExit(
                    f"-layout tiled: {program.name} is not SpMV-shaped "
                    "(needs sum combiner + identity contribution)"
                )
            use_tiled = True
        elif args.layout == "auto":
            use_tiled = spmv_capable(program)

    if args.parts > 1:
        from lux_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.parts)
        if is_push:
            from lux_tpu.engine.push import ShardedPushExecutor

            return ShardedPushExecutor(g, program, mesh=mesh)
        if use_tiled:
            from lux_tpu.engine.tiled_sharded import ShardedTiledExecutor

            return ShardedTiledExecutor(
                g, program, mesh=mesh, plan=_tiled_plan(g, program, args, log)
            )
        from lux_tpu.engine.pull_sharded import ShardedPullExecutor

        return ShardedPullExecutor(
            g, program, mesh=mesh, sum_strategy=args.strategy
        )
    if is_push:
        from lux_tpu.engine.push import PushExecutor

        return PushExecutor(g, program)
    if use_tiled:
        from lux_tpu.engine.tiled import TiledPullExecutor

        return TiledPullExecutor(
            g, program, plan=_tiled_plan(g, program, args, log)
        )
    from lux_tpu.engine.pull import PullExecutor

    return PullExecutor(g, program, sum_strategy=args.strategy)


def _profiler(dirname: Optional[str]):
    """``-profile DIR`` capture window: obs/prof.py owns the arming
    semantics (nullcontext when unarmed, makedirs + jax.profiler.trace
    when armed), so the CLI, bench --profile, and POST /profilez all
    write identical artifacts."""
    from lux_tpu.obs import prof

    return prof.trace(dirname)


def final_values(ex, result) -> np.ndarray:
    if hasattr(ex, "gather_values"):
        return ex.gather_values(result)
    vals = result.values if hasattr(result, "values") else result
    return np.asarray(vals)


def print_gteps(g, iters: int, elapsed: float):
    if elapsed > 0 and iters > 0:
        # obs.gteps is THE definition (edges traversed / iteration time);
        # bench.py and every engine report through the same helper.
        print(
            f"GTEPS = {obs.gteps(g.ne, iters, elapsed):.4f} "
            f"({iters} iters x {g.ne} edges / {elapsed:.4f}s)"
        )


def run_pull_app(program, argv, oracle=None):
    """Driver for PageRank/CF. ``oracle(graph, ni) -> values`` enables
    ``-check`` (the reference has no pull-side checker; we add one)."""
    log = get_logger(program.name)
    args = build_parser(program.name, push=False).parse_args(argv)
    setup_telemetry(args)
    g = load_graph(args.file, program, log)
    if program.needs_weights and g.weights is None:
        print(f"error: {program.name} needs a weighted graph", file=sys.stderr)
        return 1
    # Advisory sizes use the LANE-PADDED width: K-vector executors store
    # and gather 128-lane-padded rows on device, so the unpadded size
    # would understate HBM by the pad factor (6.4x for K=20).
    from lux_tpu.engine.pull import lane_pad_width

    kreal, kpad = lane_pad_width(getattr(program, "value_shape", ()))
    value_bytes = int(np.dtype(np.float32).itemsize) * max(kpad or kreal, 1)
    memory_advisory(g, args.parts, value_bytes, push=False)
    ex = make_executor(g, program, args)

    vals = ex.init_values()
    start_iter = 0
    if args.resume:
        from lux_tpu.utils import checkpoint

        host_vals, start_iter, _ = checkpoint.load(args.resume, g)
        vals = _host_to_device(ex, host_vals)
        log.info("resumed at iteration %d", start_iter)
    remaining = max(args.ni - start_iter, 0)

    # Warm-up compile outside the timed region (the reference's CUDA
    # kernels are compiled at build time).
    ex.warmup()

    with _profiler(args.profile):
        if args.verbose:
            # Per-iteration timing (the reference's -verbose per-part
            # breakdown, sssp_gpu.cu:516-518). Disables pipelining: each
            # iteration is synced to be measurable; executors with a
            # phase_step additionally attribute the time to pipeline
            # phases (separately dispatched, so the sum runs slower than
            # the fused step).
            from lux_tpu.engine.pull import hard_sync

            has_phases = hasattr(ex, "phase_step")
            if has_phases and remaining:
                # Compile the phase jits outside the timed region (the
                # phase dispatches are separate executables from the
                # fused step that warmup() compiled).
                ex.phase_step(vals)
            # The verbose loop bypasses ex.run(), so it drives its own
            # recorder; every iteration is already host-synced here.
            rec = obs.recorder_for(obs.engine_label(ex), g, program)
            rec.start()
            if rec.enabled:
                rec.record_compile(obs.consume_compile_seconds(ex))
            with Timer() as t:
                for i in range(remaining):
                    if has_phases:
                        with Timer() as ti:
                            vals, ph = ex.phase_step(vals)
                        detail = " ".join(
                            f"{k} {v*1e6:.0f}us" for k, v in ph.items()
                        )
                        print(
                            f"iter {start_iter + i}: {detail} "
                            f"(total {ti.elapsed*1e3:.3f} ms)"
                        )
                    else:
                        with Timer() as ti:
                            vals = hard_sync(ex.step(vals))
                        print(
                            f"iter {start_iter + i}: {ti.elapsed*1e3:.3f} ms"
                        )
                    rec.flush(i + 1)
            rec.finish()
        else:
            with Timer() as t:
                vals = ex.run(remaining, vals=vals)
    t.print_elapsed()
    print_gteps(g, remaining, t.elapsed)

    host_vals = final_values(ex, vals)
    if args.save:
        from lux_tpu.utils import checkpoint

        checkpoint.save(args.save, g, host_vals, args.ni)
        log.info("checkpoint written to %s", args.save)
    if args.check:
        if oracle is None:
            print("[SKIP] no checker for this app")
        else:
            want = oracle(g, args.ni)
            ok = np.allclose(host_vals, want, rtol=1e-3, atol=1e-7)
            print(
                "[PASS] Check task passed!"
                if ok
                else "[FAIL] Check task failed!"
            )
            if not ok:
                return 1
    return 0


def _host_to_push_state(ex, host_vals, host_frontier):
    import jax
    import jax.numpy as jnp

    from lux_tpu.engine.push import PushState

    if hasattr(ex, "sg"):
        from lux_tpu.parallel.mesh import parts_sharding

        sh = parts_sharding(ex.mesh)
        return PushState(
            jax.device_put(jnp.asarray(ex.sg.to_padded(host_vals)), sh),
            jax.device_put(jnp.asarray(ex.sg.to_padded(host_frontier)), sh),
        )
    import jax.numpy as jnp

    return PushState(jnp.asarray(host_vals), jnp.asarray(host_frontier))


def _push_frontier_host(ex, state):
    import jax
    import numpy as np

    fr = np.asarray(jax.device_get(state.frontier))
    if hasattr(ex, "sg"):
        return ex.sg.from_padded(fr)
    return fr


def _host_to_device(ex, host_vals):
    import jax
    import jax.numpy as jnp

    if hasattr(ex, "host_to_device"):
        # One protocol: executors owning a custom device layout (padded
        # shard stacks, degree-sorted internal order, lane padding)
        # provide the converter themselves.
        return ex.host_to_device(host_vals)
    return jax.device_put(jnp.asarray(host_vals))


def _run_push_verbose(ex, state, max_iters, start_iter, init_kw):
    """Per-iteration `-verbose` loop for push apps, reproducing the
    reference's per-GPU breakdown (sssp/sssp_gpu.cu:516-518):

    - single device: `activeNodes, loadTime, compTime, updateTime` per
      iteration via the executor's separately-dispatched phase_step;
    - sharded: one `part p: activeNodes ... edges ...` line per part
      per iteration with the phase walls on each line. SPMD phases run
      in lockstep across the mesh, so the loadTime/compTime/updateTime
      walls are mesh-wide (unlike the reference's per-GPU kernels);
      per-shard skew shows in the activeNodes/edges counters.
    Disables chunked pipelining; timing is per-iteration synced."""
    import jax

    if state is None:
        state = ex.init_state(**init_kw)
    iters = 0
    # Compile outside the timed loop (warmup() only built the fused
    # chunk executable; the phase jits are separate executables). The
    # throwaway state absorbs any donation.
    ex.warmup_phases(ex.init_state(**init_kw))
    # The verbose loop bypasses ex.run(), so it drives its own recorder;
    # phase_step syncs every iteration.
    rec = obs.recorder_for(obs.engine_label(ex), ex.graph, ex.program)
    rec.start()
    if rec.enabled:
        rec.record_compile(obs.consume_compile_seconds(ex))
    with Timer() as t:
        while max_iters is None or iters < max_iters:
            state, cnt, ph = ex.phase_step(state)
            detail = (
                f"loadTime {ph['loadTime']*1e6:.0f}us "
                f"compTime {ph['compTime']*1e6:.0f}us "
                f"updateTime {ph['updateTime']*1e6:.0f}us"
            )
            for s in ph.get("shards", ()):
                print(
                    f"iter {start_iter + iters} part {s['part']}: "
                    f"activeNodes {s['activeNodes']} "
                    f"edges {s['edges']} {detail} [{ph['branch']}]"
                )
            print(
                f"iter {start_iter + iters}: activeNodes {cnt} "
                f"{detail} [{ph['branch']}]"
            )
            total = cnt
            iters += 1
            rec.flush(iters, frontier_sizes=[cnt])
            if total == 0:
                break
    rec.finish()
    return state, iters, t


def run_push_app(program, argv, supports_start: bool):
    from lux_tpu.engine.check import check as run_check

    log = get_logger(program.name)
    args = build_parser(program.name, push=True).parse_args(argv)
    setup_telemetry(args)
    g = load_graph(args.file, program, log)
    memory_advisory(g, args.parts, 4, push=True)
    ex = make_executor(g, program, args)
    init_kw = {"start": args.start} if supports_start else {}
    max_iters = args.ni if args.ni > 0 else None

    state = None
    start_iter = 0
    if args.resume:
        from lux_tpu.utils import checkpoint

        host_vals, start_iter, host_frontier = checkpoint.load(args.resume, g)
        if host_frontier is None:
            print(
                "error: push checkpoint has no frontier; cannot resume",
                file=sys.stderr,
            )
            return 1
        state = _host_to_push_state(ex, host_vals, host_frontier)
        log.info("resumed at iteration %d", start_iter)
        if max_iters is not None:
            max_iters = max(max_iters - start_iter, 0)

    # Warm-up (compile) outside the timed region.
    ex.warmup(**init_kw)

    with _profiler(args.profile):
        if args.verbose and hasattr(ex, "phase_step"):
            state, iters, t = _run_push_verbose(
                ex, state, max_iters, start_iter, init_kw
            )
        else:
            if args.verbose:
                log.info(
                    "per-phase -verbose breakdown is push-engine only; "
                    "running the fused loop (direction split lands in "
                    "telemetry/engobs)"
                )
            with Timer() as t:
                state, iters = ex.run(
                    max_iters=max_iters, state=state, **init_kw
                )
    t.print_elapsed()
    print(f"iterations = {iters}")
    print_gteps(g, iters, t.elapsed)

    host_vals = final_values(ex, state)
    if args.save:
        from lux_tpu.utils import checkpoint

        host_frontier = _push_frontier_host(ex, state)
        checkpoint.save(
            args.save, g, host_vals, start_iter + iters,
            frontier=host_frontier,
        )
        log.info("checkpoint written to %s", args.save)
    if args.check:
        if not run_check(g, host_vals, program):
            return 1
    return 0
