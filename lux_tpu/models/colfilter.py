"""Collaborative filtering: SGD matrix factorization on a weighted
bipartite graph (pull model).

Reference semantics (col_filter/colfilter_gpu.cu:32-104, app.h:25-28):
per-vertex latent vector v ∈ R^K (K=20), initialized to sqrt(1/K)
(colfilter_gpu.cu:260-263); one iteration updates every vertex from its
in-edges (ratings):

    err_e  = weight_e - <vec[src_e], vec[dst_e]>
    acc_v  = Σ_in err_e * vec[src_e]
    vec'_v = vec_v + GAMMA * (acc_v - LAMBDA * vec_v)

The reference stages src vectors through shared memory with a hand-rolled
coalescing dance (colfilter_gpu.cu:74-85); on TPU the whole thing is three
dense ops — gather (ne,K), einsum-style row dot, segment-sum — which XLA
fuses and vectorizes on the VPU/MXU natively.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import EdgeCtx, PullProgram, VertexCtx
from lux_tpu.graph.graph import Graph

K = 20            # col_filter/app.h:27
LAMBDA = 0.001    # col_filter/app.h:25
GAMMA = 0.00000035  # col_filter/app.h:26


class CollaborativeFiltering(PullProgram):
    name = "colfilter"
    combiner = "sum"
    value_dtype = jnp.float32
    value_shape = (K,)
    needs_weights = True
    servable = False   # training workload: CLI/bench only, not a query app

    def init_values(self, graph: Graph) -> np.ndarray:
        value = np.sqrt(1.0 / K).astype(np.float32)
        return np.full((graph.nv, K), value, dtype=np.float32)

    def edge_contrib(self, edge: EdgeCtx) -> jnp.ndarray:
        dot = jnp.sum(edge.src_vals * edge.dst_vals, axis=-1)  # (ne,)
        err = edge.weights.astype(jnp.float32) - dot
        return err[:, None] * edge.src_vals                    # (ne, K)

    def apply(self, old_vals, acc, ctx: VertexCtx):
        return old_vals + GAMMA * (acc - LAMBDA * old_vals)


def reference_colfilter(graph: Graph, num_iters: int) -> np.ndarray:
    """Host float64 oracle."""
    assert graph.weights is not None
    vec = np.full((graph.nv, K), np.sqrt(1.0 / K), dtype=np.float64)
    dst = graph.col_dst
    src = graph.col_src
    w = graph.weights.astype(np.float64)
    for _ in range(num_iters):
        sv = vec[src]
        dv = vec[dst]
        err = w - np.sum(sv * dv, axis=-1)
        acc = np.zeros_like(vec)
        np.add.at(acc, dst, err[:, None] * sv)
        vec = vec + GAMMA * (acc - LAMBDA * vec)
    return vec.astype(np.float32)


def rmse(graph: Graph, vec: np.ndarray) -> float:
    """Root-mean-square rating error — the quantity CF training reduces."""
    sv = vec[graph.col_src].astype(np.float64)
    dv = vec[graph.col_dst].astype(np.float64)
    err = graph.weights.astype(np.float64) - np.sum(sv * dv, axis=-1)
    return float(np.sqrt(np.mean(err**2)))


def main(argv=None):
    """CLI: python -m lux_tpu.models.colfilter -file g.lux -ni 10"""
    from lux_tpu.models.cli import run_pull_app

    return run_pull_app(
        CollaborativeFiltering(),
        argv,
        oracle=lambda g, ni: reference_colfilter(g, ni),
    )


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
