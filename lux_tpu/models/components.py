"""Connected components via label propagation (push model).

The reference propagates the **maximum** vertex id along directed edges
(atomicMax, components/components_gpu.cu:59,77,122), initial label = own
vertex id (components_gpu.cu:739), initial frontier = every vertex (dense
all-ones bitmap, components_gpu.cu:734-737). On a symmetrized graph the
fixpoint labels each component with its largest member id. Checker:
``label[dst] >= label[src]`` per edge (components_gpu.cu:788).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.push import PushProgram
from lux_tpu.graph.graph import Graph


class ConnectedComponents(PushProgram):
    name = "components"
    combiner = "max"
    value_dtype = jnp.uint32
    packable_values = True     # labels < nv < 2^31
    incremental_ok = True      # monotone max-merge, proven by LUX604

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        return np.arange(graph.nv, dtype=np.uint32)

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        return np.ones(graph.nv, dtype=bool)

    def relax(self, src_vals, weights):
        return src_vals

    def edge_invariant(self, src_vals, dst_vals, weights):
        return dst_vals >= src_vals


def reference_components(graph: Graph) -> np.ndarray:
    """Union-find oracle: label = max vertex id reachable along edges
    treated as undirected. Matches the reference fixpoint on symmetric
    graphs (its intended input class)."""
    parent = np.arange(graph.nv, dtype=np.int64)

    def find(x):
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    dst = graph.col_dst
    for u, v in zip(graph.col_src.tolist(), dst.tolist()):
        ru, rv = find(u), find(v)
        if ru != rv:
            parent[max(ru, rv)] = min(ru, rv)
    roots = np.array([find(v) for v in range(graph.nv)])
    # label = max id in each root's class
    label = np.zeros(graph.nv, dtype=np.uint32)
    np.maximum.at(label, roots, np.arange(graph.nv, dtype=np.uint32))
    return label[roots]


def main(argv=None):
    """CLI: python -m lux_tpu.models.components -file g.lux [-check]"""
    from lux_tpu.models.cli import run_push_app

    return run_push_app(ConnectedComponents(), argv, supports_start=False)


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
