"""k-core decomposition by peeling (GAS model).

Values are live in-degrees; the frontier is the set of vertices removed
this round. A removed vertex fires exactly once, sending one unit-count
message per out-edge; survivors decrement their degree by the received
count and join the next frontier iff that drops them below k. The
fixpoint's alive set is the k-core (the maximal subgraph where every
vertex keeps in-degree >= k), and the monotone one-shot firing is why
uint32 arithmetic is safe: cumulative decrements at a vertex never
exceed its initial in-degree, so alive degrees never underflow. Removed
vertices freeze at their at-removal degree (the where() in apply), which
also makes results bitwise-identical across push/pull/adaptive — both
directions deliver the same per-round counts.

Frontier shape: large first wave on sparse graphs, then a dwindling
cascade — another direction-switch workload, mirroring Gunrock's k-core
filter-iterate formulation (PAPERS.md, arXiv:1701.01170).

``k`` is a constructor parameter (a Python static), so each k compiles
its own executable; the serving layer keys engines by k and warms the
default (k=2).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.gas import GasProgram
from lux_tpu.graph.graph import Graph


class KCore(GasProgram):
    name = "kcore"
    combiner = "sum"
    value_dtype = jnp.uint32

    def __init__(self, k: int = 2):
        if int(k) < 1:
            raise ValueError(f"kcore needs k >= 1 (got {k})")
        self.k = int(k)

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        return graph.in_degrees.astype(np.uint32)

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        return (graph.in_degrees < self.k).astype(bool)

    def gather(self, src_vals, weights):
        return jnp.ones_like(src_vals)   # one decrement per removed in-edge

    def apply(self, old, acc):
        # Only still-alive vertices absorb decrements; removed ones stay
        # frozen (acc can exceed a removed vertex's count — the wrapped
        # subtraction is computed but discarded by the where).
        return jnp.where(old >= jnp.uint32(self.k), old - acc, old)

    def scatter(self, old, new):
        k = jnp.uint32(self.k)
        return (old >= k) & (new < k)

    def finalize_host(self, graph: Graph, values: np.ndarray) -> dict:
        alive = (values >= np.uint32(self.k)).astype(np.uint8)
        return {"alive": alive, "core_size": int(alive.sum())}


def reference_kcore(graph: Graph, k: int = 2) -> np.ndarray:
    """Host numpy peeling oracle with the identical in-degree rule;
    returns the frozen-degree array (values >= k <=> in the k-core)."""
    nv = graph.nv
    src = graph.col_src
    dst = graph.col_dst
    deg = graph.in_degrees.astype(np.int64).copy()
    frontier = deg < k
    while frontier.any():
        sel = frontier[src]
        dec = np.bincount(dst[sel], minlength=nv)
        alive = deg >= k
        new = np.where(alive, deg - dec, deg)
        frontier = alive & (new < k)
        deg = new
    return deg.astype(np.uint32)
