"""Label-propagation community detection (GAS model).

Classic async label propagation is order-dependent (ties broken by visit
order), which would make pinned-pull vs pinned-push runs diverge — a
non-starter for the adaptive executor's bitwise-parity contract. This
variant is the monotone max-id formulation with a bounded radius: each
vertex carries a packed ``(label << HOP_BITS) | hops_left`` word, seeded
with its own id and ``RADIUS`` hop credits; a message decays the hop
budget by one and a vertex adopts the numerically largest packed word it
ever sees. Because the label owns the high bits, a larger label wins
regardless of remaining hops — so every vertex converges to the largest
vertex id within ``RADIUS`` hops, and communities are the basins around
local id-maxima. Deterministic, direction-independent, and convergent in
at most ``RADIUS + 1`` iterations (after that every message's hop budget
is spent and decays to 0, the max identity).

The frontier starts *all-dense* (every vertex is a seed) and collapses
as labels settle — the inverse of BFS's grow-then-shrink curve, so
adaptive runs exercise the pull→push switch from the opposite end.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.gas import GasProgram
from lux_tpu.graph.graph import Graph

RADIUS = 16                 # seed hop budget = max propagation radius
HOP_BITS = 8
HOP_MASK = (1 << HOP_BITS) - 1
LABEL_BITS = 32 - HOP_BITS  # 24 bits of label (vertex id)


class LabelPropagation(GasProgram):
    name = "labelprop"
    combiner = "max"
    value_dtype = jnp.uint32

    def init_values(self, graph: Graph, **kw) -> np.ndarray:
        if graph.nv >= 1 << LABEL_BITS:
            raise ValueError(
                f"labelprop packs labels into {LABEL_BITS} bits; "
                f"nv={graph.nv} does not fit"
            )
        ids = np.arange(graph.nv, dtype=np.uint32)
        return (ids << HOP_BITS) | np.uint32(RADIUS)

    def init_frontier(self, graph: Graph, **kw) -> np.ndarray:
        return np.ones(graph.nv, dtype=bool)

    def gather(self, src_vals, weights):
        hops = src_vals & jnp.uint32(HOP_MASK)
        decayed = (src_vals & ~jnp.uint32(HOP_MASK)) | (
            hops - jnp.uint32(1)
        )
        # A spent hop budget propagates nothing: 0 is the max identity
        # (the hops-1 wraparound for hops == 0 is masked off here).
        return jnp.where(hops > 0, decayed, jnp.uint32(0))

    def finalize_host(self, graph: Graph, values: np.ndarray) -> dict:
        labels = (values >> np.uint32(HOP_BITS)).astype(np.uint32)
        return {
            "labels": labels,
            "num_communities": int(np.unique(labels).size),
        }


def reference_labelprop(graph: Graph) -> np.ndarray:
    """Host numpy oracle: the same monotone fixpoint via np.maximum.at
    (independent of the engine's direction machinery)."""
    nv = graph.nv
    src = graph.col_src
    dst = graph.col_dst
    vals = (np.arange(nv, dtype=np.uint32) << HOP_BITS) | np.uint32(RADIUS)
    frontier = np.ones(nv, dtype=bool)
    while frontier.any():
        sv = vals[src]
        hops = sv & HOP_MASK
        msg = (sv & ~np.uint32(HOP_MASK)) | ((hops - 1) & HOP_MASK)
        msg = np.where((hops > 0) & frontier[src], msg, 0).astype(np.uint32)
        acc = np.zeros(nv, dtype=np.uint32)
        np.maximum.at(acc, dst, msg)
        new = np.maximum(vals, acc)
        frontier = new != vals
        vals = new
    return vals
