"""PageRank (pull model).

Semantics match the reference exactly (pagerank/pagerank_gpu.cu:49-102 and
:239-245 for init; pagerank/app.h:24 for ALPHA):

- stored vertex value is the rank **pre-divided by out-degree**, so the
  gather side adds plain ``old[src]`` per in-edge;
- update:  ``r = (1-ALPHA)/nv + ALPHA * Σ_in old[src]``, then
  ``r /= out_degree`` unless the out-degree is zero;
- init:    ``(1/nv) / out_degree`` (plain ``1/nv`` for sinks).

Note the reference's unconventional damping orientation: ALPHA = 0.15
multiplies the *neighbor sum* (classic PageRank uses 0.85 there). We
reproduce the reference's formula for parity.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import EdgeCtx, PullProgram, VertexCtx

ALPHA = 0.15  # pagerank/app.h:24


class PageRank(PullProgram):
    name = "pagerank"
    combiner = "sum"
    value_dtype = jnp.float32
    identity_contrib = True  # gather side is plain old[src] (pre-divided)

    def init_values(self, graph) -> np.ndarray:
        rank = np.float32(1.0) / np.float32(graph.nv)
        deg = graph.out_degrees
        safe = np.maximum(deg, 1).astype(np.float32)
        return np.where(deg == 0, rank, rank / safe).astype(np.float32)

    def edge_contrib(self, edge: EdgeCtx) -> jnp.ndarray:
        return edge.src_vals

    def apply(self, old_vals, acc, ctx: VertexCtx):
        init_rank = (1.0 - ALPHA) / ctx.nv
        r = init_rank + ALPHA * acc
        deg = ctx.out_degrees.astype(r.dtype)
        return jnp.where(ctx.out_degrees == 0, r, r / deg)


def true_ranks(stored: np.ndarray, out_degrees: np.ndarray) -> np.ndarray:
    """Undo the pre-division: the actual PageRank mass per vertex."""
    return np.where(out_degrees == 0, stored, stored * out_degrees)


def reference_pagerank(graph, num_iters: int) -> np.ndarray:
    """Host numpy oracle (same stored-pre-divided convention)."""
    deg = graph.out_degrees.astype(np.float64)
    rank = np.full(graph.nv, 1.0 / graph.nv, dtype=np.float64)
    vals = np.where(deg == 0, rank, rank / np.maximum(deg, 1))
    dst = graph.col_dst
    for _ in range(num_iters):
        acc = np.zeros(graph.nv, dtype=np.float64)
        np.add.at(acc, dst, vals[graph.col_src])
        r = (1.0 - ALPHA) / graph.nv + ALPHA * acc
        vals = np.where(deg == 0, r, r / np.maximum(deg, 1))
    return vals.astype(np.float32)


def main(argv=None):
    """CLI: python -m lux_tpu.models.pagerank -file g.lux -ni 10 [-check]"""
    from lux_tpu.models.cli import run_pull_app

    return run_pull_app(
        PageRank(),
        argv,
        oracle=lambda g, ni: reference_pagerank(g, ni),
    )


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
