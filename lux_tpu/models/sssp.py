"""Single-source shortest paths (push model, unit weights).

The reference SSSP is Bellman-Ford over *hop counts*: its push edge struct
carries no weight (sssp/app.h:31) and relaxation is
``min(dist[dst], dist[src] + 1)`` (sssp/sssp_gpu.cu:48-61,86-130). Init:
``dist = nv`` everywhere ("infinity", sssp_gpu.cu:733-744), ``dist[start]
= 0``, frontier = {start}; `-start` flag parsed at sssp.cc:159-163.
Checker: ``dist[dst] <= dist[src] + 1`` per edge (sssp_gpu.cu:794).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.push import PushProgram
from lux_tpu.graph.graph import Graph


class SSSP(PushProgram):
    name = "sssp"
    combiner = "min"
    value_dtype = jnp.uint32
    rooted = True
    packable_values = True     # distances <= nv < 2^31
    incremental_ok = True      # monotone min-merge, proven by LUX604

    def init_values(self, graph: Graph, start: int = 0) -> np.ndarray:
        dist = np.full(graph.nv, graph.nv, dtype=np.uint32)  # ∞ == nv
        dist[start] = 0
        return dist

    def init_frontier(self, graph: Graph, start: int = 0) -> np.ndarray:
        fr = np.zeros(graph.nv, dtype=bool)
        fr[start] = True
        return fr

    def relax(self, src_vals, weights):
        return src_vals + jnp.uint32(1)

    def edge_invariant(self, src_vals, dst_vals, weights):
        return dst_vals <= src_vals + jnp.uint32(1)


def reference_sssp(graph: Graph, start: int = 0) -> np.ndarray:
    """Host BFS oracle (hop counts; unreached = nv, like the reference)."""
    csr = graph.csr()
    dist = np.full(graph.nv, graph.nv, dtype=np.uint32)
    dist[start] = 0
    frontier = [start]
    d = 0
    while frontier:
        d += 1
        nxt = []
        for u in frontier:
            for v in csr.col_dst[csr.row_ptr[u] : csr.row_ptr[u + 1]]:
                if dist[v] > d:
                    dist[v] = d
                    nxt.append(int(v))
        frontier = nxt
    return dist


def main(argv=None):
    """CLI: python -m lux_tpu.models.sssp -file g.lux -start R [-check]"""
    from lux_tpu.models.cli import run_push_app

    return run_push_app(SSSP(), argv, supports_start=True)


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
