"""Weighted single-source shortest paths, delta-stepping flavored
(GAS model).

The classic delta-stepping tradeoff — settle near buckets eagerly, defer
far relaxations — exists to keep the *active set* small on work-list
machines. On the dense-accelerator GAS engine the same knob is the
direction choice: a small active set runs push (work scales with
frontier out-edges, like a light-edge bucket pass), a large one runs
pull (one dense O(ne) sweep relaxing every deferred edge at once). So
this program is the monotone chunked Bellman-Ford whose fixpoint equals
delta-stepping's, with the bucket discipline subsumed by the executor's
density-adaptive switching rather than re-implemented as host-side
bucket queues.

Distances are float32 sums of int edge weights (generate.py weights are
1..100), so every reachable distance on graphs this engine targets is an
integer far below 2^24 — float32-exact, which keeps the host Dijkstra
oracle bitwise-comparable and the min-combiner reassociation-safe.
"""

from __future__ import annotations

import heapq

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.gas import GasProgram
from lux_tpu.graph.graph import Graph


class DeltaSSSP(GasProgram):
    name = "sssp_delta"
    combiner = "min"
    value_dtype = jnp.float32
    needs_weights = True
    rooted = True

    def init_values(self, graph: Graph, start: int = 0) -> np.ndarray:
        dist = np.full(graph.nv, np.inf, dtype=np.float32)
        dist[start] = 0.0
        return dist

    def init_frontier(self, graph: Graph, start: int = 0) -> np.ndarray:
        fr = np.zeros(graph.nv, dtype=bool)
        fr[start] = True
        return fr

    def gather(self, src_vals, weights):
        return src_vals + weights.astype(jnp.float32)

    def edge_invariant(self, src_vals, dst_vals, weights):
        return dst_vals <= src_vals + weights.astype(jnp.float32)


def reference_sssp_delta(graph: Graph, start: int = 0) -> np.ndarray:
    """Host Dijkstra oracle (float32 distances; unreached = +inf).
    Exact match with the engine: all distances are small-int sums, so
    float32 represents them without rounding."""
    assert graph.weights is not None
    csr = graph.csr()
    dist = np.full(graph.nv, np.inf, dtype=np.float32)
    dist[start] = 0.0
    heap = [(0.0, start)]
    while heap:
        d, u = heapq.heappop(heap)
        if d > dist[u]:
            continue
        for e in range(csr.row_ptr[u], csr.row_ptr[u + 1]):
            v = int(csr.col_dst[e])
            nd = np.float32(d + float(csr.weights[e]))
            if nd < dist[v]:
                dist[v] = nd
                heapq.heappush(heap, (float(nd), v))
    return dist


def main(argv=None):
    """CLI: python -m lux_tpu.models.sssp_delta -file g.lux -start R"""
    from lux_tpu.models.cli import run_push_app

    return run_push_app(DeltaSSSP(), argv, supports_start=True)


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
