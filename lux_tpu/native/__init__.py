"""Native (C++) fast paths for host-side runtime work.

The reference's host runtime work — partitioned parallel file loading
(core/pull_model.inl:253-320), the edge-list converter (tools/converter.cc),
CSR construction (sssp/sssp_gpu.cu:550-607) — is C++ there and C++ here.
The shared library is compiled on first use with g++ and exposed through
ctypes; every entry point has a numpy fallback so the framework works even
without a toolchain.
"""

from lux_tpu.native import io  # noqa: F401
