"""Lazy build + ctypes binding for lux_native.cc.

Compiled once into a cache directory keyed by a source hash; rebuilt
automatically when the source changes. All argtypes are configured here —
ctypes' default c_int conversion would truncate 64-bit pointers/sizes.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lux_native.cc")
_LOCK = threading.Lock()
_LIB = None
_FAILED = False


def _cache_dir() -> str:
    from lux_tpu.utils import flags

    base = flags.get("LUX_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "lux_tpu_native"
    )
    os.makedirs(base, exist_ok=True)
    return base


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.lux_load.restype = ctypes.c_int
    lib.lux_load.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.lux_convert_edge_list.restype = ctypes.c_int
    lib.lux_convert_edge_list.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.lux_build_csr.restype = ctypes.c_int
    lib.lux_build_csr.argtypes = [
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    return lib


def maybe_library():
    """load_library() or None — the shared soft-failure wrapper every
    native call site uses before falling back to numpy."""
    try:
        return load_library()
    except Exception:
        return None


def load_library() -> ctypes.CDLL:
    """Compile (if needed) and load the native library. Raises on any
    failure — callers fall back to numpy."""
    global _LIB, _FAILED
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _FAILED:
            raise RuntimeError("native build previously failed")
        try:
            with open(_SRC, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
            so_path = os.path.join(_cache_dir(), f"lux_native_{digest}.so")
            if not os.path.exists(so_path):
                tmp = so_path + f".tmp{os.getpid()}"
                subprocess.run(
                    [
                        "g++", "-O3", "-shared", "-fPIC", "-pthread",
                        "-std=c++17", "-o", tmp, _SRC,
                    ],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, so_path)
            _LIB = _configure(ctypes.CDLL(so_path))
            return _LIB
        except Exception:
            _FAILED = True
            raise
