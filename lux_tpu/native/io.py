"""IO entry points: converter / loader / CSR build.

Each function prefers the native C++ implementation (built lazily by
:mod:`lux_tpu.native.build`) and falls back to numpy.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from lux_tpu.graph import format as lux_format
from lux_tpu.graph.graph import Graph


def _native():
    try:
        from lux_tpu.native.build import load_library

        return load_library()
    except Exception:
        return None


def convert_edge_list(
    input_path: str,
    output_path: str,
    nv: int,
    ne: int,
    weighted: bool = False,
) -> None:
    lib = _native()
    if lib is not None:
        # Explicit width wrappers: bare Python ints default to 32-bit c_int
        # and would overflow for ne >= 2**31 (RMAT27 has ne == 2**31).
        rc = lib.lux_convert_edge_list(
            input_path.encode(),
            output_path.encode(),
            ctypes.c_uint32(nv),
            ctypes.c_uint64(ne),
            ctypes.c_int(int(weighted)),
        )
        if rc == 0:
            return
    lux_format.convert_edge_list(input_path, output_path, nv, ne, weighted=weighted)


def read_lux(path: str, weighted: Optional[bool] = None) -> Graph:
    """Load a .lux graph; native path does a multithreaded partitioned read
    (the TPU-host equivalent of the reference's per-part CPU load tasks,
    core/pull_model.inl:253-320)."""
    lib = _native()
    if lib is not None:
        nv, ne, has_w, _ = lux_format.detect_layout(path)
        if weighted is None:
            weighted = has_w
        row_ptr = np.zeros(nv + 1, dtype=np.int64)
        col_src = np.zeros(ne, dtype=np.int32)
        w = np.zeros(ne, dtype=np.int32) if weighted else None
        # Wrap raw addresses in c_void_p: bare Python ints would be
        # truncated to 32-bit c_int by ctypes' default conversion.
        rc = lib.lux_load(
            path.encode(),
            ctypes.c_uint32(nv),
            ctypes.c_uint64(ne),
            ctypes.c_void_p(row_ptr[1:].ctypes.data),
            ctypes.c_void_p(col_src.ctypes.data),
            ctypes.c_void_p(w.ctypes.data) if w is not None else None,
        )
        if rc == 0:
            lux_format.validate_row_ptr(row_ptr[1:], ne, path)
            return Graph(nv=nv, ne=ne, row_ptr=row_ptr, col_src=col_src, weights=w)
    return lux_format.read_lux(path, weighted=weighted)
