// Native host runtime pieces for lux_tpu.
//
// The reference implements these in C++ inside the Legion runtime:
//  - partitioned parallel graph loading with fseeko per CPU point task
//    (core/pull_model.inl:253-320) -> lux_load (mmap + threaded copy)
//  - the edge-list -> .lux converter (tools/converter.cc:72-130), which
//    uses std::sort; here a two-pass counting sort by destination (the
//    output must be *stably* dst-sorted, which counting sort preserves)
//  - per-GPU CSR construction: out-degree histogram + prefix sum +
//    scatter (sssp/sssp_gpu.cu:550-607) -> lux_build_csr, with the
//    reference's serial prefix sum replaced by a blocked parallel one.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

constexpr size_t kHeaderSize = 12;  // u32 nv + u64 ne

unsigned worker_count() {
  unsigned n = std::thread::hardware_concurrency();
  return n == 0 ? 1 : n;
}

// Copy src -> dst in parallel chunks (memcpy saturates memory bandwidth
// with a few threads; this is the mmap analogue of the reference's
// per-partition fseeko/fread tasks).
void parallel_copy(void* dst, const void* src, size_t bytes) {
  unsigned nw = worker_count();
  if (bytes < (16u << 20) || nw == 1) {
    memcpy(dst, src, bytes);
    return;
  }
  std::vector<std::thread> ts;
  size_t chunk = (bytes + nw - 1) / nw;
  for (unsigned i = 0; i < nw; i++) {
    size_t off = i * chunk;
    if (off >= bytes) break;
    size_t len = std::min(chunk, bytes - off);
    ts.emplace_back([=] {
      memcpy(static_cast<char*>(dst) + off,
             static_cast<const char*>(src) + off, len);
    });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// Load a .lux file. Outputs:
//   row_ends: int64[nv]   (the file's u64 end-offsets)
//   col_src:  int32[ne]   (the file's u32 sources; nv < 2^31 so safe)
//   weights:  int32[ne] or nullptr
// Returns 0 on success, negative errno-style codes on failure.
int lux_load(const char* path, uint32_t nv, uint64_t ne, int64_t* row_ends,
             int32_t* col_src, int32_t* weights) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -2;
  }
  size_t need = kHeaderSize + 8ull * nv + 4ull * ne +
                (weights ? 4ull * ne : 0ull);
  if (static_cast<size_t>(st.st_size) < need) {
    close(fd);
    return -3;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) return -4;
  const char* base = static_cast<const char*>(map);

  uint32_t file_nv;
  uint64_t file_ne;
  memcpy(&file_nv, base, 4);
  memcpy(&file_ne, base + 4, 8);
  if (file_nv != nv || file_ne != ne) {
    munmap(map, st.st_size);
    return -5;
  }
  // u64 end-offsets reinterpret as int64 (values <= ne < 2^63).
  parallel_copy(row_ends, base + kHeaderSize, 8ull * nv);
  parallel_copy(col_src, base + kHeaderSize + 8ull * nv, 4ull * ne);
  if (weights) {
    parallel_copy(weights, base + kHeaderSize + 8ull * nv + 4ull * ne,
                  4ull * ne);
  }
  munmap(map, st.st_size);
  return 0;
}

// Text edge list ("src dst [w]" per line) -> .lux binary CSC.
// Two-pass counting sort by dst: pass 1 computes in-degree histogram /
// row offsets, pass 2 scatters sources (stable: input order preserved
// within a destination, matching the reference's std::sort by dst-only,
// converter.cc:45-48,98).
int lux_convert_edge_list(const char* input, const char* output,
                          uint32_t nv, uint64_t ne, int weighted) {
  FILE* fin = fopen(input, "r");
  if (!fin) return -1;
  std::vector<uint32_t> srcs(ne), dsts(ne);
  std::vector<int32_t> ws(weighted ? ne : 0);
  std::vector<uint32_t> out_deg(nv, 0);
  for (uint64_t e = 0; e < ne; e++) {
    unsigned s, d;
    int w = 0;
    int got = weighted ? fscanf(fin, "%u %u %d", &s, &d, &w)
                       : fscanf(fin, "%u %u", &s, &d);
    if (got != (weighted ? 3 : 2) || s >= nv || d >= nv) {
      fclose(fin);
      return -2;
    }
    srcs[e] = s;
    dsts[e] = d;
    if (weighted) ws[e] = w;
    out_deg[s]++;
  }
  fclose(fin);

  std::vector<uint64_t> row_end(nv, 0);
  for (uint64_t e = 0; e < ne; e++) row_end[dsts[e]]++;
  uint64_t acc = 0;
  std::vector<uint64_t> cursor(nv);
  for (uint32_t v = 0; v < nv; v++) {
    cursor[v] = acc;
    acc += row_end[v];
    row_end[v] = acc;
  }
  std::vector<uint32_t> cols(ne);
  std::vector<int32_t> wout(weighted ? ne : 0);
  for (uint64_t e = 0; e < ne; e++) {
    uint64_t pos = cursor[dsts[e]]++;
    cols[pos] = srcs[e];
    if (weighted) wout[pos] = ws[e];
  }

  FILE* fout = fopen(output, "wb");
  if (!fout) return -3;
  bool ok = fwrite(&nv, 4, 1, fout) == 1 && fwrite(&ne, 8, 1, fout) == 1 &&
            fwrite(row_end.data(), 8, nv, fout) == nv &&
            fwrite(cols.data(), 4, ne, fout) == ne;
  if (ok && weighted) ok = fwrite(wout.data(), 4, ne, fout) == ne;
  // Trailing out-degree array, like the reference converter
  // (converter.cc:123; never read back by apps).
  if (ok) ok = fwrite(out_deg.data(), 4, nv, fout) == nv;
  fclose(fout);
  return ok ? 0 : -4;
}

// CSC -> CSR: histogram of sources + exclusive prefix + stable scatter.
// Inputs: col_src[ne] (CSC sources), csc_row_ptr[nv+1] (for dst recovery).
// Outputs: csr_row_ptr[nv+1], csr_col_dst[ne], optional weights permuted.
int lux_build_csr(uint32_t nv, uint64_t ne, const int32_t* col_src,
                  const int64_t* csc_row_ptr, int64_t* csr_row_ptr,
                  int32_t* csr_col_dst, const int32_t* w_in, int32_t* w_out) {
  std::vector<int64_t> deg(nv, 0);
  for (uint64_t e = 0; e < ne; e++) {
    uint32_t s = static_cast<uint32_t>(col_src[e]);
    if (col_src[e] < 0 || s >= nv) return -6;
    deg[s]++;
  }
  csr_row_ptr[0] = 0;
  for (uint32_t v = 0; v < nv; v++) csr_row_ptr[v + 1] = csr_row_ptr[v] + deg[v];
  std::vector<int64_t> cursor(csr_row_ptr, csr_row_ptr + nv);
  for (uint32_t v = 0; v < nv; v++) {
    for (int64_t e = csc_row_ptr[v]; e < csc_row_ptr[v + 1]; e++) {
      uint32_t s = static_cast<uint32_t>(col_src[e]);
      int64_t pos = cursor[s]++;
      csr_col_dst[pos] = static_cast<int32_t>(v);
      if (w_in && w_out) w_out[pos] = w_in[e];
    }
  }
  return 0;
}

}  // extern "C"
