"""Unified telemetry: metrics registry, Chrome-trace spans, per-iteration
run records, and end-of-run reports.

Environment knobs (all optional; everything is a no-op when unset):

- ``LUX_METRICS=<path>`` — append one JSON line per run: the
  ``lux.run_telemetry.v1`` summary with per-iteration records and a
  metrics-registry snapshot.
- ``LUX_TRACE=<path>`` — stream Chrome trace_event JSON-lines
  (Perfetto-loadable via ``tools/trace_summary.py --to-chrome``).
- ``LUX_LOG=<level>`` — log level for the ``lux.*`` categories,
  including the ``lux.perf`` run-report table.
- ``LUX_SPANS=0`` — disable request-scoped serve spans (obs/spans.py;
  default on).
- ``LUX_FLIGHT_DIR=<dir>`` — arm the flight recorder (obs/flight.py):
  ring-buffered traces + iteration records, ``flight.v1`` postmortem
  dumps on shed/reject/exception/SIGUSR1.
- ``LUX_FLIGHT_CAPACITY=<n>`` / ``LUX_STATUSZ_WINDOWS=<s,s>`` — flight
  ring size and /statusz rolling-window lengths.
- ``LUX_PROF_DIR=<dir>`` — arm the device-timeline profiler
  (obs/prof.py): capture windows (bench ``--profile``, ``POST
  /profilez``, SIGUSR2 toggle) write TensorBoard artifacts and
  ``profile.v1`` reports under this directory.
"""

from ..utils import logging as _logging
from . import flight, ledger, metrics, prof, report, slo, spans, trace
from .iterlog import (
    NULL_RECORDER,
    IterationRecorder,
    consume_compile_seconds,
    engine_label,
    gteps,
    note_compile_seconds,
    recorder_for,
    telemetry_enabled,
)

__all__ = [
    "metrics", "trace", "report", "spans", "flight", "slo", "prof",
    "ledger",
    "IterationRecorder", "NULL_RECORDER", "recorder_for",
    "telemetry_enabled", "gteps", "engine_label",
    "note_compile_seconds", "consume_compile_seconds",
    "reconfigure",
]


def reconfigure():
    """Re-read LUX_TRACE and LUX_LOG after the environment changed
    (CLI flags set env vars post-import)."""
    trace.reconfigure()
    flight.reconfigure()
    _logging.reconfigure()
