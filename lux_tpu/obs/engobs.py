"""Engine performance observatory (``LUX_ENGOBS=1``).

Three measurement surfaces the sharded engines could not report before:

- **Phase timing.** ``run_pull_phased`` / ``run_push_phased`` drive a
  run through the executor's ``phase_step`` — separately-dispatched,
  hard-synced sub-iteration brackets — so every iteration splits into
  exchange (all_gather/collective) wall time vs local compute wall
  time. Fencing breaks XLA fusion, so this is a measurement mode: with
  ``LUX_ENGOBS`` unset or ``0`` the executors dispatch the exact same
  fused programs as before this module existed (zero added compiles,
  asserted by the recompile sentinel in tests/test_engobs.py).
- **Exchange ledger.** ``useful_exchange`` reads the partition plan's
  remote-read index (ShardedGraph.remote_read_counts — the same
  structure the ROADMAP item-1 needed-rows optimization will consume)
  and prices the all_gather against the rows some receiving part
  actually reads: ``ratio`` is the fraction of exchanged bytes that
  were not waste.
- **Roofline inputs.** ``hbm_bytes_per_iter`` is the first-order
  per-iteration HBM traffic model every engine reports so
  obs/report.py can place a run against the HBM/ICI peaks.

The module also keeps a process-wide "latest per engine" table
(``note``/``latest``) that /statusz's mesh block publishes, so a serving
process shows the live phase split and useful-bytes ratio per engine
without a metrics dump.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..utils import flags
from ..utils.locks import make_lock
from ..utils.timing import Timer

_lock = make_lock("obs.engobs")
_latest: Dict[str, dict] = {}


def enabled() -> bool:
    """True when ``LUX_ENGOBS`` asks for phase-fenced measurement runs.

    Off is the default and costs one flag read per ``run()``: the
    executors never build the phase executables, so the fused program —
    and the zero-recompile serving contract — is bit-for-bit the
    pre-observatory one.
    """
    return flags.get_bool("LUX_ENGOBS")


def note(engine: str, **fields):
    """Merge ``fields`` into the process-wide latest-telemetry table for
    ``engine`` (phase split, useful-bytes ratio, frontier density)."""
    with _lock:
        d = _latest.setdefault(engine, {})
        d.update(fields)


def latest() -> Dict[str, dict]:
    """Copy of the latest per-engine telemetry (the /statusz mesh-block
    ``engobs`` entry; {} until an instrumented run has happened)."""
    with _lock:
        return {k: dict(v) for k, v in _latest.items()}


def reset():
    with _lock:
        _latest.clear()


# -- exchange ledger -------------------------------------------------------


def useful_exchange(sg, row_bytes: int,
                    exchanged_rows: Optional[int] = None) -> Optional[dict]:
    """Price one iteration's exchange against the remote-read index.

    The full path broadcasts each part's whole ``max_nv``-row shard to
    the P-1 others; only the rows some receiver's local edges actually
    index are useful. Pass ``exchanged_rows`` to price a compacted
    exchange instead (the packed-capacity row count that actually
    crosses the interconnect). Returns ``{useful_rows, exchanged_rows,
    useful_bytes_per_iter, ratio}`` or None when the plan's edge arrays
    were already released (ShardedGraph.release_edge_arrays) and the
    index was never built.
    """
    counts = sg.remote_read_counts()
    if counts is None:
        return None
    p = sg.num_parts
    if exchanged_rows is None:
        exchanged_rows = p * (p - 1) * sg.max_nv
    exchanged_rows = int(exchanged_rows)
    # Off-diagonal entries only: a part's reads of its own rows never
    # cross the interconnect.
    useful_rows = int(counts.sum() - counts.trace())
    ratio = useful_rows / exchanged_rows if exchanged_rows else 0.0
    return {
        "useful_rows": useful_rows,
        "exchanged_rows": exchanged_rows,
        "useful_bytes_per_iter": useful_rows * int(row_bytes),
        "ratio": ratio,
    }


# -- roofline input model --------------------------------------------------


def hbm_bytes_per_iter(nv: int, ne: int, value_bytes: int = 4,
                       k: int = 1) -> int:
    """First-order HBM traffic of one dense iteration: per edge one
    gathered value row plus one int32 index read, per vertex one read
    and one write of the value row plus the degree read. A model, not a
    measurement — report.py labels the resulting fractions as such."""
    row = value_bytes * max(k, 1)
    return ne * (row + 4) + nv * (3 * row + 4)


# -- phase-fenced runners --------------------------------------------------


def _split(times: dict) -> tuple:
    """(exchange_s, compute_s) from a phase_step times dict. The sharded
    pull family names its collective bracket "exchange"; the sharded
    push family's all_gather lives in "loadTime"."""
    exchange = 0.0
    compute = 0.0
    for key, val in times.items():
        if not isinstance(val, (int, float)):
            continue
        if key in ("exchange", "loadTime"):
            exchange += val
        else:
            compute += val
    return exchange, compute


def run_pull_phased(ex, vals, num_iters: int, rec):
    """Fixed-iteration phase-fenced loop for the sharded pull family
    (ShardedPullExecutor / ShardedTiledExecutor): one exchange/compute
    split per iteration via ``phase_step``. Returns the final values."""
    if not hasattr(ex, "_pjits"):
        # First phase_step compiles every phase executable; keep that
        # out of the per-iteration walls (phase jits do not donate, so
        # the throwaway step leaves ``vals`` intact).
        with Timer() as t:
            ex.phase_step(vals)
        rec.record_compile(t.elapsed)
    for i in range(int(num_iters)):
        vals, times = ex.phase_step(vals)
        exchange, compute = _split(times)
        rec.record_phase(i + 1, exchange, compute, detail=times)
    return vals


def run_push_phased(ex, state, max_iters, rec):
    """Phase-fenced fixpoint for the sharded push engine: per-iteration
    exchange/compute split plus the frontier count and dense/sparse
    branch from ``phase_step``. Returns (state, iterations_run,
    sparse_iterations)."""
    with Timer() as t:
        ex.warmup_phases(state)
    rec.record_compile(t.elapsed)
    total = 0
    sparse_total = 0
    limit = None if max_iters is None else int(max_iters)
    while limit is None or total < limit:
        state, cnt, times = ex.phase_step(state)
        exchange, compute = _split(times)
        branch = times.get("branch")
        if isinstance(branch, str) and branch.startswith("sparse"):
            sparse_total += 1
        total += 1
        rec.record_phase(total, exchange, compute, frontier=cnt,
                         branch=branch, detail=times)
        if cnt == 0:
            break
    return state, total, sparse_total


def run_gas_phased(ex, state, max_iters, rec):
    """Phase-fenced fixpoint for the sharded direction-adaptive GAS
    engine: per-iteration exchange/compute/merge split, the branch
    taken (``push`` | ``pull`` | ``pull/frontier`` | ``pull/downgraded``
    | ``pull/dense``), direction switches, and frontier-exchange
    downgrades. Returns (state, iterations_run, push_iterations,
    direction_switches, exchange_downgrades)."""
    with Timer() as t:
        ex.warmup_phases(state)
    rec.record_compile(t.elapsed)
    total = 0
    push_total = 0
    switches = 0
    downgrades = 0
    prev_push = None
    limit = None if max_iters is None else int(max_iters)
    while limit is None or total < limit:
        state, cnt, times = ex.phase_step(state)
        # Metadata, not a wall: pop before _split sums numeric values.
        downgrades += int(times.pop("downgraded", 0) or 0)
        exchange, compute = _split(times)
        branch = times.get("branch")
        is_push = isinstance(branch, str) and branch.startswith("push")
        if is_push:
            push_total += 1
        if prev_push is not None and is_push != prev_push:
            switches += 1
        prev_push = is_push
        total += 1
        rec.record_phase(total, exchange, compute, frontier=cnt,
                         branch=branch, detail=times)
        if cnt == 0:
            break
    return state, total, push_total, switches, downgrades
