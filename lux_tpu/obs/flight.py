"""Flight recorder: bounded postmortem rings + flight.v1 dumps.

When a serving process sheds a deadline (504), rejects on backpressure
(429), throws inside an engine, or receives SIGUSR1, the interesting
state is what happened *just before* — and by then the registry
histograms have averaged it away. This module keeps two bounded rings:

- the last N completed request traces (fed by obs/spans.py as a sink);
- the last N engine iteration records (fed by IterationRecorder.flush),
  so an in-flight sweep's per-iteration tail is visible even though its
  run-level summary never finalized.

``dump(reason)`` writes one self-contained ``flight.v1`` JSON to
``LUX_FLIGHT_DIR``: both rings, a metrics-registry snapshot, every
registered context block (the serve Session registers sentinel state and
pool/batcher stats), and the full LUX_* flag table — everything a
postmortem needs with no access to the dead process.
``tools/flight_summary.py`` renders it.

Armed by ``LUX_FLIGHT_DIR``; unarmed, every hook is a cheap predicate.
Ring capacity is ``LUX_FLIGHT_CAPACITY``. Dumps are debounced per reason
(an overloaded server sheds thousands of deadlines per second; one dump
a second carries the same evidence). Stdlib only; no jax.
"""

from __future__ import annotations

import json
import os
import signal
import time
import itertools
from collections import deque
from typing import Callable, Dict, Optional

from ..utils import flags
from ..utils.locks import make_lock
from . import metrics, spans

DEBOUNCE_S = 1.0

_lock = make_lock("obs.flight")
_capacity = int(flags.default("LUX_FLIGHT_CAPACITY"))
_traces: deque = deque(maxlen=_capacity)
_iterations: deque = deque(maxlen=_capacity)
_context: Dict[str, Callable[[], dict]] = {}
_last_dump: Dict[str, float] = {}
# Filename uniqueness within one millisecond (forced back-to-back dumps).
_dump_seq = itertools.count()


def enabled() -> bool:
    return bool(flags.get("LUX_FLIGHT_DIR"))


def reconfigure():
    """Re-read LUX_FLIGHT_CAPACITY (tests and CLIs set env post-import);
    resizing keeps the newest records."""
    global _capacity, _traces, _iterations
    cap = max(1, flags.get_int("LUX_FLIGHT_CAPACITY"))
    with _lock:
        if cap != _capacity:
            _capacity = cap
            _traces = deque(_traces, maxlen=cap)
            _iterations = deque(_iterations, maxlen=cap)


def reset():
    """Drop rings and debounce state (tests)."""
    with _lock:
        _traces.clear()
        _iterations.clear()
        _last_dump.clear()


def note_trace(record: dict):
    """Spans sink: remember one completed request trace."""
    if not enabled():
        return
    with _lock:
        _traces.append(record)


def note_iteration(record: dict):
    """Remember one engine iteration record (IterationRecorder.flush)."""
    if not enabled():
        return
    with _lock:
        _iterations.append(record)


def add_context(name: str, provider: Callable[[], dict]):
    """Register a context block for every future dump (e.g. the serve
    Session's sentinel stats). Re-registering a name replaces it."""
    with _lock:
        _context[name] = provider


def remove_context(name: str):
    with _lock:
        _context.pop(name, None)


def counts() -> dict:
    with _lock:
        return {"traces": len(_traces), "iterations": len(_iterations),
                "capacity": _capacity}


def _flag_table() -> dict:
    return {name: flags.get(name) for name in flags.names()}


def dump(reason: str, detail: Optional[str] = None,
         force: bool = False) -> Optional[str]:
    """Write one flight.v1 postmortem; returns the path, or None when
    unarmed or debounced. Never raises — a postmortem failure must not
    compound the failure being recorded."""
    directory = flags.get("LUX_FLIGHT_DIR")
    if not directory:
        return None
    now = spans.monotonic()
    with _lock:
        if not force and now - _last_dump.get(reason, -DEBOUNCE_S) < DEBOUNCE_S:
            return None
        _last_dump[reason] = now
        traces = list(_traces)
        iterations = list(_iterations)
        providers = dict(_context)
    context = {}
    for name, provider in providers.items():
        try:
            context[name] = provider()
        except Exception as e:
            context[name] = {"error": repr(e)}
    doc = {
        "schema": "flight.v1",
        "reason": reason,
        "detail": detail,
        "unix_time_s": time.time(),
        "pid": os.getpid(),
        "traces": traces,
        "iterations": iterations,
        "metrics": metrics.snapshot(),
        "context": context,
        "flags": _flag_table(),
    }
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory,
            f"flight-{int(time.time() * 1e3)}-{os.getpid()}"
            f"-{next(_dump_seq):04d}-{reason}.json",
        )
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=1, default=str)
        return path
    except OSError:
        return None


def install_signal_handler(signum=None) -> bool:
    """SIGUSR1 -> dump("sigusr1"): postmortem-on-demand for a live
    server. Returns False where signals cannot be installed (non-main
    thread, platforms without SIGUSR1)."""
    if signum is None:
        signum = getattr(signal, "SIGUSR1", None)
        if signum is None:
            return False

    def _handler(_sig, _frame):
        dump("sigusr1", force=True)

    try:
        signal.signal(signum, _handler)
        return True
    except ValueError:
        return False


# Completed traces flow in via the spans layer; the sink gates itself on
# enabled(), so an unarmed process pays one predicate per root span.
spans.add_sink(note_trace)
