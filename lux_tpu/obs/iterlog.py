"""Per-iteration run telemetry: the ``IterationRecorder`` hook.

Every executor ``run()`` drives one recorder. The contract that keeps
XLA fusion intact: engines call ``flush(iters_done)`` only at points
where the host has already synced (after ``block_until_ready`` in
``run_pipelined``, after the chunk ``device_get`` in the push fixpoint,
after the final ``hard_sync`` of a fused dispatch) — the recorder itself
never touches device values. Within a fused ``fori_loop`` there is
nothing to observe per iteration, so a flush window spanning n
iterations amortizes its wall time over those n records.

When neither ``LUX_METRICS`` nor ``LUX_TRACE`` is set,
``recorder_for()`` returns the shared ``NULL_RECORDER`` whose every
method is a no-op — one predicate check per *flush*, not per iteration,
is the total disabled-mode overhead.

GTEPS is defined here, once, for every engine and for bench.py:
edges traversed / iteration time (``gteps()``).
"""

from __future__ import annotations

import time

from ..utils import flags
from . import engobs, flight, ledger, metrics, trace
from .spans import SPAN_BUCKETS


def gteps(ne: int, iters: int, seconds: float) -> float:
    """Traversed-edges-per-second in units of 1e9: ``ne`` edges visited
    per iteration, ``iters`` iterations, over ``seconds`` of iteration
    (execute) time. The single GTEPS definition for all engines."""
    if seconds <= 0 or iters <= 0:
        return 0.0
    return ne * iters / seconds / 1e9


class _NullRecorder:
    """Disabled-mode recorder: every hook is a constant no-op."""

    enabled = False

    def start(self):
        return self

    def record_compile(self, seconds):
        pass

    def flush(self, iters_done, frontier_sizes=None, active_edges=None,
              residual=None, sparse_flags=None, directions=None):
        pass

    def record_phase(self, iters_done, exchange_s, compute_s, detail=None,
                     frontier=None, branch=None):
        pass

    def set_exchange_bytes(self, per_iter, note=None, parts=None):
        pass

    def set_overlap(self, enabled):
        pass

    def set_useful_bytes(self, per_iter, ratio, note=None):
        pass

    def set_hbm_bytes(self, per_iter):
        pass

    def finish(self):
        return None

    def summary(self):
        return None


NULL_RECORDER = _NullRecorder()


def telemetry_enabled() -> bool:
    # The flight recorder needs iteration records flowing even with no
    # metrics path / trace writer: an armed LUX_FLIGHT_DIR turns the
    # recorders on so in-flight sweeps appear in postmortems. Likewise
    # LUX_ENGOBS: a phase-fenced run exists to be recorded. And an armed
    # run ledger: every run must land a runrec.v1 observation.
    return bool(flags.get("LUX_METRICS")) or trace.enabled() \
        or flight.enabled() or engobs.enabled() or ledger.enabled()


def recorder_for(engine: str, graph, program=None):
    """Recorder for one ``run()`` call: a live ``IterationRecorder`` when
    telemetry is on, else the shared no-op ``NULL_RECORDER``."""
    if not telemetry_enabled():
        return NULL_RECORDER
    prog = type(program).__name__ if program is not None else ""
    return IterationRecorder(
        engine, int(graph.nv), int(graph.ne), program=prog,
    )


def engine_label(ex) -> str:
    """Short engine name for an executor instance (telemetry labels)."""
    name = type(ex).__name__
    return {
        "PullExecutor": "pull",
        "TiledPullExecutor": "tiled",
        "ShardedPullExecutor": "pull_sharded",
        "ShardedTiledExecutor": "tiled_sharded",
        "PushExecutor": "push",
        "ShardedPushExecutor": "push_sharded",
        "MultiSourcePushExecutor": "push_multi",
        "ShardedMultiSourcePushExecutor": "push_multi_sharded",
        "IncrementalExecutor": "incremental",
        "AdaptiveExecutor": "gas",
        "MultiSourceGasExecutor": "gas_multi",
    }.get(name, name.lower())


def note_compile_seconds(ex, seconds: float):
    """Stash warmup/compile seconds on an executor so the next ``run()``
    can report them (warmup happens before the recorder exists)."""
    ex._obs_compile_s = getattr(ex, "_obs_compile_s", 0.0) + float(seconds)


def consume_compile_seconds(ex) -> float:
    s = getattr(ex, "_obs_compile_s", 0.0)
    ex._obs_compile_s = 0.0
    return s


class IterationRecorder:
    """Accumulates per-iteration records for one run; emits trace spans
    and metrics at flush granularity; hands the summary to report.py."""

    enabled = True

    def __init__(self, engine: str, nv: int, ne: int, program: str = ""):
        self.engine = engine
        self.nv = nv
        self.ne = ne
        self.program = program
        self.compile_s = 0.0
        self.execute_s = 0.0
        self.exchange_bytes_per_iter = 0
        self.exchange_note = None
        self.parts = None
        self.useful_bytes_per_iter = None
        self.useful_ratio = None
        self.hbm_bytes_per_iter = None
        self.overlap = False
        self.phase_s = {"exchange": 0.0, "compute": 0.0}
        self.crossovers = []
        self.iterations = []
        self._iters = 0
        self._flushes = 0
        self._t0 = None
        self._t_last = None
        self._last_branch = None
        self._finished = False
        # Metric handles resolved once per run, not once per flush: each
        # registry factory call takes the registry lock (LUX008).
        lbl = {"engine": engine}
        self._m_compile_s = metrics.histogram("lux_compile_seconds", lbl)
        self._m_exch_per_iter = metrics.gauge(
            "lux_exchange_bytes_per_iter", lbl)
        self._m_iters_total = metrics.counter("lux_iterations_total", lbl)
        self._m_iter_s = metrics.histogram("lux_iteration_seconds", lbl)
        self._m_useful_per_iter = metrics.gauge(
            "lux_exchange_useful_bytes_per_iter", lbl)
        self._m_useful_ratio = metrics.gauge(
            "lux_exchange_useful_ratio", lbl)
        self._m_frontier_density = metrics.gauge(
            "lux_frontier_density", lbl)
        # Fenced engine phases live in the sub-millisecond decades —
        # share the span histogram family (and its fine buckets).
        self._h_phase = {
            ph: metrics.histogram(
                "lux_span_seconds", {"span": f"{engine}.{ph}"},
                buckets=SPAN_BUCKETS)
            for ph in ("exchange", "compute")
        }

    def start(self):
        self._t0 = self._t_last = time.perf_counter()
        trace.begin(f"{self.engine}.run", cat="run",
                    args={"program": self.program, "nv": self.nv,
                          "ne": self.ne})
        return self

    def record_compile(self, seconds):
        """Credit compile/warmup time, kept out of every flush window."""
        seconds = float(seconds)
        if seconds <= 0:
            return
        now = time.perf_counter()
        if self._t_last is not None and now - seconds >= self._t0:
            trace.pair(f"{self.engine}.compile", now - seconds, now,
                       cat="compile")
        self.compile_s += seconds
        if self._t_last is not None:
            self._t_last = now
        self._m_compile_s.observe(seconds)

    def set_exchange_bytes(self, per_iter, note=None, parts=None):
        self.exchange_bytes_per_iter = int(per_iter)
        self.exchange_note = note
        if parts is not None:
            self.parts = int(parts)
        self._m_exch_per_iter.set(per_iter)

    def set_overlap(self, enabled):
        """Mark the run's exchange as compute-overlapped (the compact
        path issues the collective before the local-edge contribution,
        letting XLA hide one under the other). Phase-fenced runs then
        report ``exchange_hidden_frac`` — the fraction of measured
        exchange wall that concurrent compute could cover,
        ``min(exchange_s, compute_s) / exchange_s``. The fenced split
        serializes the phases, so this is the overlap *budget* the fused
        program can exploit, not a direct measurement of it."""
        self.overlap = bool(enabled)

    def set_useful_bytes(self, per_iter, ratio, note=None):
        """Exchange-ledger useful-bytes: of ``exchange_bytes_per_iter``,
        how much lands on rows some receiving part actually reads
        (engobs.useful_exchange over the plan's remote-read index)."""
        self.useful_bytes_per_iter = int(per_iter)
        self.useful_ratio = float(ratio)
        self._m_useful_per_iter.set(per_iter)
        self._m_useful_ratio.set(ratio)
        engobs.note(self.engine, useful_bytes_per_iter=int(per_iter),
                    useful_ratio=float(ratio),
                    exchange_bytes_per_iter=self.exchange_bytes_per_iter)

    def set_hbm_bytes(self, per_iter):
        """First-order HBM bytes moved per iteration (model, not
        measurement) — the roofline ledger's numerator."""
        self.hbm_bytes_per_iter = int(per_iter)

    def _branch_into(self, rec, branch, frontier):
        """Shared frontier/branch bookkeeping for record_phase and the
        sparse_flags flush path: frontier density plus dense/sparse
        crossover records (the ROADMAP item-3 direction signal)."""
        if frontier is not None:
            frontier = int(frontier)
            rec["frontier"] = frontier
            if self.nv:
                rec["frontier_density"] = frontier / self.nv
        if branch is not None:
            rec["branch"] = branch
            if self._last_branch is not None and branch != self._last_branch:
                rec["crossover"] = f"{self._last_branch}->{branch}"
                self.crossovers.append({
                    "iter": rec["iter"], "from": self._last_branch,
                    "to": branch,
                    "frontier_density": rec.get("frontier_density"),
                })
            self._last_branch = branch

    def record_phase(self, iters_done, exchange_s, compute_s, detail=None,
                     frontier=None, branch=None):
        """Record one phase-fenced iteration (LUX_ENGOBS runs): the
        exchange (collective) vs local-compute wall split measured by the
        executor's ``phase_step``. Call right after the phase brackets'
        final host sync; ``iters_done`` is cumulative."""
        iters_done = int(iters_done)
        n = iters_done - self._iters
        if n <= 0:
            return
        now = time.perf_counter()
        dt = now - self._t_last
        self._t_last = now
        self.execute_s += dt
        self._flushes += 1
        self._iters = iters_done
        exchange_s = float(exchange_s)
        compute_s = float(compute_s)
        self.phase_s["exchange"] += exchange_s
        self.phase_s["compute"] += compute_s
        phased = exchange_s + compute_s
        rec = {
            "iter": iters_done - 1,
            "t_iter_s": dt / n,
            "t_cum_s": self.execute_s,
            "flush_span": self._flushes,
            "active_edges": self.ne,
            "gteps": gteps(self.ne, 1, phased if phased > 0 else dt),
            "exchange_s": exchange_s,
            "compute_s": compute_s,
            "exchange_frac": exchange_s / phased if phased > 0 else 0.0,
        }
        if self.overlap:
            rec["exchange_hidden_frac"] = (
                min(exchange_s, compute_s) / exchange_s
                if exchange_s > 0 else 1.0)
        self._branch_into(rec, branch, frontier)
        if detail:
            rec["phase_detail"] = {
                k: v for k, v in detail.items()
                if isinstance(v, (int, float)) and k not in
                ("exchange", "loadTime")
            }
        self.iterations.append(rec)
        if flight.enabled():
            flight.note_iteration({
                "engine": self.engine, "program": self.program, **rec,
            })
        # Phase brackets run exchange first: backfill the two spans from
        # the sync stamp, and stream the per-iteration series as Chrome
        # counter tracks.
        trace.pair(f"{self.engine}.exchange", now - dt,
                   now - dt + exchange_s, cat="phase")
        trace.pair(f"{self.engine}.compute", now - compute_s, now,
                   cat="phase")
        counters = {"exchange_ms": exchange_s * 1e3,
                    "compute_ms": compute_s * 1e3}
        if "frontier_density" in rec:
            counters["frontier_density"] = rec["frontier_density"]
            self._m_frontier_density.set(rec["frontier_density"])
        trace.counter(f"{self.engine}.phases", counters, cat="phase")
        self._h_phase["exchange"].observe(exchange_s)
        self._h_phase["compute"].observe(compute_s)
        self._m_iters_total.inc(n)
        self._m_iter_s.observe(dt / n)
        engobs.note(self.engine, iter=iters_done - 1,
                    exchange_s=exchange_s, compute_s=compute_s,
                    exchange_frac=rec["exchange_frac"],
                    frontier_density=rec.get("frontier_density"),
                    branch=branch)

    def flush(self, iters_done, frontier_sizes=None, active_edges=None,
              residual=None, sparse_flags=None, directions=None):
        """Record the window since the previous flush. Call only right
        after a host sync; ``iters_done`` is the cumulative iteration
        count for the run so far. ``sparse_flags`` (push fixpoints) marks
        which window iterations took the sparse branch, adding per-record
        branch, frontier-density, and dense/sparse crossover fields.
        ``directions`` (GAS adaptive fixpoints) likewise marks which
        window iterations ran push (1) vs pull (0) — the same branch/
        crossover machinery then records every direction switch."""
        iters_done = int(iters_done)
        n = iters_done - self._iters
        if n <= 0:
            return
        now = time.perf_counter()
        dt = now - self._t_last
        self._t_last = now
        self.execute_s += dt
        self._flushes += 1
        per = dt / n
        for j in range(n):
            it = self._iters + j
            frontier = None
            if frontier_sizes is not None and j < len(frontier_sizes):
                frontier = int(frontier_sizes[j])
            branch = None
            if sparse_flags is not None and j < len(sparse_flags):
                branch = "sparse" if sparse_flags[j] else "dense"
            if directions is not None and j < len(directions):
                branch = "push" if directions[j] else "pull"
            ae = int(active_edges) if active_edges is not None else self.ne
            rec = {
                "iter": it,
                "t_iter_s": per,
                "t_cum_s": self.execute_s - dt + per * (j + 1),
                "flush_span": self._flushes,
                "active_edges": ae,
                "gteps": gteps(ae, 1, per),
            }
            self._branch_into(rec, branch, frontier)
            if residual is not None and j == n - 1:
                rec["residual"] = float(residual)
            self.iterations.append(rec)
            if flight.enabled():
                flight.note_iteration({
                    "engine": self.engine, "program": self.program, **rec,
                })
        last = self.iterations[-1]
        if "frontier_density" in last:
            self._m_frontier_density.set(last["frontier_density"])
            trace.counter(f"{self.engine}.frontier",
                          {"frontier_density": last["frontier_density"]},
                          cat="phase")
            engobs.note(self.engine, iter=last["iter"],
                        frontier_density=last["frontier_density"],
                        branch=last.get("branch"))
        self._iters = iters_done
        trace.pair(f"{self.engine}.flush", now - dt, now, cat="execute",
                   args={"iters": n, "iters_done": iters_done})
        self._m_iters_total.inc(n)
        self._m_iter_s.observe(per)

    def summary(self) -> dict:
        out = {
            "schema": "lux.run_telemetry.v1",
            "engine": self.engine,
            "program": self.program,
            "nv": self.nv,
            "ne": self.ne,
            "num_iters": self._iters,
            "compile_s": self.compile_s,
            "execute_s": self.execute_s,
            "gteps": gteps(self.ne, self._iters, self.execute_s),
            "exchange_bytes_per_iter": self.exchange_bytes_per_iter,
            "exchange_bytes_total": self.exchange_bytes_per_iter * self._iters,
            "iterations": self.iterations,
        }
        if self.parts is not None:
            out["parts"] = self.parts
        if self.phase_s["exchange"] or self.phase_s["compute"]:
            phased = self.phase_s["exchange"] + self.phase_s["compute"]
            out["phases"] = {
                "exchange_s": self.phase_s["exchange"],
                "compute_s": self.phase_s["compute"],
                "exchange_frac": (self.phase_s["exchange"] / phased
                                  if phased > 0 else 0.0),
            }
            if self.overlap:
                ex_s = self.phase_s["exchange"]
                out["phases"]["exchange_hidden_frac"] = (
                    min(ex_s, self.phase_s["compute"]) / ex_s
                    if ex_s > 0 else 1.0)
        if self.useful_bytes_per_iter is not None:
            out["useful_bytes_per_iter"] = self.useful_bytes_per_iter
            out["useful_ratio"] = self.useful_ratio
        if self.hbm_bytes_per_iter is not None:
            out["hbm_bytes_per_iter"] = self.hbm_bytes_per_iter
        if self.crossovers:
            out["crossovers"] = self.crossovers
        return out

    def finish(self) -> dict:
        """Close the run span and publish the report; idempotent."""
        if self._finished:
            return self.summary()
        self._finished = True
        trace.end(f"{self.engine}.run", cat="run")
        summary = self.summary()
        if self.exchange_bytes_per_iter:
            metrics.counter(
                "lux_exchange_bytes_total", {"engine": self.engine},
            ).inc(summary["exchange_bytes_total"])
        if "phases" in summary:
            engobs.note(self.engine, run_exchange_s=self.phase_s["exchange"],
                        run_compute_s=self.phase_s["compute"],
                        run_exchange_frac=summary["phases"]["exchange_frac"],
                        run_exchange_hidden_frac=summary["phases"].get(
                            "exchange_hidden_frac"),
                        num_iters=self._iters)
        from . import report
        report.finalize(summary)
        return summary
