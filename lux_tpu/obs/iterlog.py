"""Per-iteration run telemetry: the ``IterationRecorder`` hook.

Every executor ``run()`` drives one recorder. The contract that keeps
XLA fusion intact: engines call ``flush(iters_done)`` only at points
where the host has already synced (after ``block_until_ready`` in
``run_pipelined``, after the chunk ``device_get`` in the push fixpoint,
after the final ``hard_sync`` of a fused dispatch) — the recorder itself
never touches device values. Within a fused ``fori_loop`` there is
nothing to observe per iteration, so a flush window spanning n
iterations amortizes its wall time over those n records.

When neither ``LUX_METRICS`` nor ``LUX_TRACE`` is set,
``recorder_for()`` returns the shared ``NULL_RECORDER`` whose every
method is a no-op — one predicate check per *flush*, not per iteration,
is the total disabled-mode overhead.

GTEPS is defined here, once, for every engine and for bench.py:
edges traversed / iteration time (``gteps()``).
"""

from __future__ import annotations

import time

from ..utils import flags
from . import flight, metrics, trace


def gteps(ne: int, iters: int, seconds: float) -> float:
    """Traversed-edges-per-second in units of 1e9: ``ne`` edges visited
    per iteration, ``iters`` iterations, over ``seconds`` of iteration
    (execute) time. The single GTEPS definition for all engines."""
    if seconds <= 0 or iters <= 0:
        return 0.0
    return ne * iters / seconds / 1e9


class _NullRecorder:
    """Disabled-mode recorder: every hook is a constant no-op."""

    enabled = False

    def start(self):
        return self

    def record_compile(self, seconds):
        pass

    def flush(self, iters_done, frontier_sizes=None, active_edges=None,
              residual=None):
        pass

    def set_exchange_bytes(self, per_iter, note=None):
        pass

    def finish(self):
        return None

    def summary(self):
        return None


NULL_RECORDER = _NullRecorder()


def telemetry_enabled() -> bool:
    # The flight recorder needs iteration records flowing even with no
    # metrics path / trace writer: an armed LUX_FLIGHT_DIR turns the
    # recorders on so in-flight sweeps appear in postmortems.
    return bool(flags.get("LUX_METRICS")) or trace.enabled() \
        or flight.enabled()


def recorder_for(engine: str, graph, program=None):
    """Recorder for one ``run()`` call: a live ``IterationRecorder`` when
    telemetry is on, else the shared no-op ``NULL_RECORDER``."""
    if not telemetry_enabled():
        return NULL_RECORDER
    prog = type(program).__name__ if program is not None else ""
    return IterationRecorder(
        engine, int(graph.nv), int(graph.ne), program=prog,
    )


def engine_label(ex) -> str:
    """Short engine name for an executor instance (telemetry labels)."""
    name = type(ex).__name__
    return {
        "PullExecutor": "pull",
        "TiledPullExecutor": "tiled",
        "ShardedPullExecutor": "pull_sharded",
        "ShardedTiledExecutor": "tiled_sharded",
        "PushExecutor": "push",
        "ShardedPushExecutor": "push_sharded",
    }.get(name, name.lower())


def note_compile_seconds(ex, seconds: float):
    """Stash warmup/compile seconds on an executor so the next ``run()``
    can report them (warmup happens before the recorder exists)."""
    ex._obs_compile_s = getattr(ex, "_obs_compile_s", 0.0) + float(seconds)


def consume_compile_seconds(ex) -> float:
    s = getattr(ex, "_obs_compile_s", 0.0)
    ex._obs_compile_s = 0.0
    return s


class IterationRecorder:
    """Accumulates per-iteration records for one run; emits trace spans
    and metrics at flush granularity; hands the summary to report.py."""

    enabled = True

    def __init__(self, engine: str, nv: int, ne: int, program: str = ""):
        self.engine = engine
        self.nv = nv
        self.ne = ne
        self.program = program
        self.compile_s = 0.0
        self.execute_s = 0.0
        self.exchange_bytes_per_iter = 0
        self.exchange_note = None
        self.iterations = []
        self._iters = 0
        self._flushes = 0
        self._t0 = None
        self._t_last = None
        self._finished = False

    def start(self):
        self._t0 = self._t_last = time.perf_counter()
        trace.begin(f"{self.engine}.run", cat="run",
                    args={"program": self.program, "nv": self.nv,
                          "ne": self.ne})
        return self

    def record_compile(self, seconds):
        """Credit compile/warmup time, kept out of every flush window."""
        seconds = float(seconds)
        if seconds <= 0:
            return
        now = time.perf_counter()
        if self._t_last is not None and now - seconds >= self._t0:
            trace.pair(f"{self.engine}.compile", now - seconds, now,
                       cat="compile")
        self.compile_s += seconds
        if self._t_last is not None:
            self._t_last = now
        metrics.histogram(
            "lux_compile_seconds", {"engine": self.engine},
        ).observe(seconds)

    def set_exchange_bytes(self, per_iter, note=None):
        self.exchange_bytes_per_iter = int(per_iter)
        self.exchange_note = note
        metrics.gauge(
            "lux_exchange_bytes_per_iter", {"engine": self.engine},
        ).set(per_iter)

    def flush(self, iters_done, frontier_sizes=None, active_edges=None,
              residual=None):
        """Record the window since the previous flush. Call only right
        after a host sync; ``iters_done`` is the cumulative iteration
        count for the run so far."""
        iters_done = int(iters_done)
        n = iters_done - self._iters
        if n <= 0:
            return
        now = time.perf_counter()
        dt = now - self._t_last
        self._t_last = now
        self.execute_s += dt
        self._flushes += 1
        per = dt / n
        for j in range(n):
            it = self._iters + j
            frontier = None
            if frontier_sizes is not None and j < len(frontier_sizes):
                frontier = int(frontier_sizes[j])
            ae = int(active_edges) if active_edges is not None else self.ne
            rec = {
                "iter": it,
                "t_iter_s": per,
                "t_cum_s": self.execute_s - dt + per * (j + 1),
                "flush_span": self._flushes,
                "active_edges": ae,
                "gteps": gteps(ae, 1, per),
            }
            if frontier is not None:
                rec["frontier"] = frontier
            if residual is not None and j == n - 1:
                rec["residual"] = float(residual)
            self.iterations.append(rec)
            if flight.enabled():
                flight.note_iteration({
                    "engine": self.engine, "program": self.program, **rec,
                })
        self._iters = iters_done
        trace.pair(f"{self.engine}.flush", now - dt, now, cat="execute",
                   args={"iters": n, "iters_done": iters_done})
        metrics.counter(
            "lux_iterations_total", {"engine": self.engine},
        ).inc(n)
        metrics.histogram(
            "lux_iteration_seconds", {"engine": self.engine},
        ).observe(per)

    def summary(self) -> dict:
        return {
            "schema": "lux.run_telemetry.v1",
            "engine": self.engine,
            "program": self.program,
            "nv": self.nv,
            "ne": self.ne,
            "num_iters": self._iters,
            "compile_s": self.compile_s,
            "execute_s": self.execute_s,
            "gteps": gteps(self.ne, self._iters, self.execute_s),
            "exchange_bytes_per_iter": self.exchange_bytes_per_iter,
            "exchange_bytes_total": self.exchange_bytes_per_iter * self._iters,
            "iterations": self.iterations,
        }

    def finish(self) -> dict:
        """Close the run span and publish the report; idempotent."""
        if self._finished:
            return self.summary()
        self._finished = True
        trace.end(f"{self.engine}.run", cat="run")
        summary = self.summary()
        if self.exchange_bytes_per_iter:
            metrics.counter(
                "lux_exchange_bytes_total", {"engine": self.engine},
            ).inc(summary["exchange_bytes_total"])
        from . import report
        report.finalize(summary)
        return summary
