"""Run ledger: durable, append-only ``runrec.v1`` observations.

Every other obs surface is ephemeral — engobs tables, the metrics
registry, flight rings all evaporate with the process, so the repo
measures everything and remembers nothing (ROADMAP item 2). The ledger
is the durable side: when ``LUX_LEDGER_DIR`` is set, every engine run
(via report.finalize), bench entry, serve warmup, and /profilez capture
appends ONE JSON line keyed by

    (graph_fingerprint, program, engine_kind, mesh_shape, config_hash)

where ``config_hash`` comes from :func:`flags.config_hash`. A record is
therefore a reproducible (config -> metrics) observation: the corpus
the planned auto-tuner searches over, and the A/B evidence
``tools/lux_doctor.py`` attributes regressions from.

Storage follows the WAL idiom (graph/wal.py), line-oriented so
concurrent ``O_APPEND`` writers interleave safely at line granularity:

    LUXRR1 <crc32-hex8> <json>\\n

- Segments are ``runrec-NNNNNN.jsonl`` under the ledger dir; a segment
  at or past ``LUX_LEDGER_ROTATE_BYTES`` is sealed and the next number
  opens.
- Reopen-for-append validates the tail: a torn FINAL line (missing
  newline, bad frame, or bad CRC — the crash-mid-write shapes) is
  truncated away; an interior bad line is real corruption and raises on
  strict reads (lenient reads skip and count it).
- ``latest.json`` (atomic temp+rename) maps each key string to its most
  recent record id — a best-effort index, always rebuildable by
  scanning the segments.

Unarmed (no ``LUX_LEDGER_DIR``), :func:`record_run` is a None return
and no file is ever touched — the zero-cost default.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from ..utils import flags
from ..utils.locks import make_lock

__all__ = [
    "LedgerCorruptError", "RunLedger", "enabled", "record_run",
    "read_all", "validate_dir", "key_string", "reset",
]

SCHEMA = "runrec.v1"
_PREFIX = "LUXRR1"
_SEG_FMT = "runrec-{:06d}.jsonl"
_INDEX = "latest.json"


class LedgerCorruptError(RuntimeError):
    """An interior (non-tail) ledger line failed its CRC frame."""


def enabled() -> bool:
    return bool(flags.get("LUX_LEDGER_DIR"))


def key_string(graph_fingerprint: str, program: str, engine_kind: str,
               mesh_shape: str, config_hash: str) -> str:
    return "|".join(
        (graph_fingerprint, program, engine_kind, mesh_shape, config_hash)
    )


def _frame(payload: bytes) -> bytes:
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return b"%s %08x %s\n" % (_PREFIX.encode(), crc, payload)


def _parse_line(line: bytes) -> Optional[dict]:
    """Decode one framed line; None when the frame or CRC is bad."""
    parts = line.split(b" ", 2)
    if len(parts) != 3 or parts[0] != _PREFIX.encode():
        return None
    try:
        want = int(parts[1], 16)
    except ValueError:
        return None
    if (zlib.crc32(parts[2]) & 0xFFFFFFFF) != want:
        return None
    try:
        return json.loads(parts[2])
    except ValueError:
        return None


def _scan_segment(path: str) -> Tuple[List[dict], int, int, bool]:
    """(records, keep_end_offset, interior_bad, torn_tail).

    ``torn_tail`` covers the crash-mid-append shapes — a final chunk
    with no newline, or a CRC-bad FINAL complete line — both
    recoverable by truncating to ``keep_end_offset``. ``interior_bad``
    counts CRC-bad lines that valid lines FOLLOW: real corruption, not
    a torn write, so repair never truncates it away.
    """
    with open(path, "rb") as f:
        buf = f.read()
    parsed: List[Tuple[int, Optional[dict]]] = []   # (end_offset, record)
    pos = 0
    torn = False
    while pos < len(buf):
        nl = buf.find(b"\n", pos)
        if nl < 0:
            torn = True                  # no newline: torn tail
            break
        parsed.append((nl + 1, _parse_line(buf[pos:nl])))
        pos = nl + 1
    if parsed and not torn and parsed[-1][1] is None:
        torn = True                      # bad final line: torn, drop it
        parsed.pop()
    records = [r for _end, r in parsed if r is not None]
    interior_bad = sum(1 for _end, r in parsed if r is None)
    keep_end = parsed[-1][0] if parsed else 0
    return records, keep_end, interior_bad, torn


class RunLedger:
    """Append/read handle on one ledger directory. Thread-safe within
    the process; cross-process appends stay line-atomic via O_APPEND."""

    def __init__(self, root: str):
        self.root = root
        self._lock = make_lock("obs.ledger")
        self._seq = 0
        os.makedirs(root, exist_ok=True)

    # -- segment bookkeeping ------------------------------------------

    def segments(self) -> List[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.root)
                if n.startswith("runrec-") and n.endswith(".jsonl")
            )
        except OSError:
            return []
        return [os.path.join(self.root, n) for n in names]

    def _active_segment(self) -> str:
        segs = self.segments()
        rotate = flags.get_int("LUX_LEDGER_ROTATE_BYTES")
        if segs:
            last = segs[-1]
            try:
                if os.path.getsize(last) < rotate:
                    return last
            except OSError:
                pass
            num = int(os.path.basename(last)[7:13]) + 1
        else:
            num = 0
        return os.path.join(self.root, _SEG_FMT.format(num))

    def _repair_tail(self, path: str):
        """WAL reopen policy: truncate a torn final line before the
        next append lands after it (interior corruption is left for
        readers to report — truncating it would silently drop records
        that valid later lines prove were once durable)."""
        if not os.path.exists(path):
            return
        _records, keep_end, interior_bad, torn = _scan_segment(path)
        if torn and interior_bad == 0:
            size = os.path.getsize(path)
            if keep_end < size:
                with open(path, "r+b") as f:
                    f.truncate(keep_end)

    # -- append / read ------------------------------------------------

    def append(self, record: dict) -> str:
        with self._lock:
            rid = record.get("id")
            if not rid:
                self._seq += 1
                rid = "rr-%x-%06x-%x" % (
                    os.getpid(), self._seq, int(time.time()) & 0xFFFFFF
                )
                record = dict(record, id=rid)
            path = self._active_segment()
            self._repair_tail(path)
            payload = json.dumps(
                record, sort_keys=True, separators=(",", ":")
            ).encode("utf-8")
            with open(path, "ab") as f:
                f.write(_frame(payload))   # one write: line-atomic
                f.flush()
                os.fsync(f.fileno())
            key = record.get("key_string")
            if key:
                self._update_index(key, rid, os.path.basename(path))
            return rid

    def _update_index(self, key: str, rid: str, segment: str):
        idx_path = os.path.join(self.root, _INDEX)
        idx = self.read_index()
        idx[key] = {"record_id": rid, "segment": segment}
        tmp = idx_path + ".tmp.%d" % os.getpid()
        try:
            with open(tmp, "w") as f:
                json.dump(idx, f, indent=1, sort_keys=True)
            os.replace(tmp, idx_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def read_index(self) -> Dict[str, dict]:
        try:
            with open(os.path.join(self.root, _INDEX)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def latest(self, key: str) -> Optional[dict]:
        """Most recent record for a key string (index fast path, full
        scan fallback — the index is best-effort)."""
        ref = self.read_index().get(key)
        hit = None
        for rec in self.iter_records():
            if rec.get("key_string") == key:
                if ref and rec.get("id") == ref.get("record_id"):
                    return rec
                hit = rec
        return hit

    def iter_records(self, strict: bool = False) -> Iterator[dict]:
        for path in self.segments():
            records, _end, interior_bad, _torn = _scan_segment(path)
            if interior_bad and strict:
                raise LedgerCorruptError(
                    f"{path}: {interior_bad} interior crc-bad line(s)"
                )
            for rec in records:
                yield rec

    def read(self, strict: bool = False) -> List[dict]:
        return list(self.iter_records(strict=strict))

    def validate(self) -> Dict[str, int]:
        """(ok, interior_bad, torn) counts across all segments."""
        ok = bad = torn_n = 0
        for path in self.segments():
            records, _end, interior_bad, torn = _scan_segment(path)
            ok += len(records)
            bad += interior_bad
            torn_n += 1 if torn else 0
        return {"ok": ok, "interior_bad": bad, "torn_segments": torn_n,
                "segments": len(self.segments())}


# -- module-level singleton (the armed ledger) ------------------------

_LEDGER: Optional[RunLedger] = None
_LOCK = make_lock("obs.ledger.singleton")


def _ledger() -> Optional[RunLedger]:
    global _LEDGER
    root = flags.get("LUX_LEDGER_DIR")
    if not root:
        return None
    with _LOCK:
        if _LEDGER is None or _LEDGER.root != root:
            _LEDGER = RunLedger(root)
        return _LEDGER


def reset():
    """Drop the cached handle (tests repoint LUX_LEDGER_DIR)."""
    global _LEDGER
    with _LOCK:
        _LEDGER = None


def record_run(kind: str, metrics: dict, *,
               graph_fingerprint: Optional[str] = None,
               program: str = "?", engine_kind: str = "?",
               mesh_shape: str = "1", **extra) -> Optional[str]:
    """Append one runrec.v1 observation; None when unarmed.

    ``graph_fingerprint`` defaults to a weak nv/ne-derived key when the
    caller only has a run summary (engine feed-in via report.finalize);
    serve/bench paths pass the real checkpoint.fingerprint_hex.
    """
    led = _ledger()
    if led is None:
        return None
    if graph_fingerprint is None:
        graph_fingerprint = "nv%s-ne%s" % (
            metrics.get("nv", "?"), metrics.get("ne", "?")
        )
    chash = flags.config_hash()
    key = key_string(graph_fingerprint, program, engine_kind,
                     str(mesh_shape), chash)
    record = {
        "schema": SCHEMA,
        "kind": kind,
        "at": time.time(),
        "key": {
            "graph_fingerprint": graph_fingerprint,
            "program": program,
            "engine_kind": engine_kind,
            "mesh_shape": str(mesh_shape),
            "config_hash": chash,
        },
        "key_string": key,
        "config": flags.snapshot(),
        "metrics": metrics,
    }
    if extra:
        record.update(extra)
    try:
        return led.append(record)
    except OSError:
        return None      # a full disk must never fail the run it logs


def read_all(root: Optional[str] = None, strict: bool = False) -> List[dict]:
    """All records under ``root`` (default: the armed dir); [] unarmed."""
    if root:
        return RunLedger(root).read(strict=strict)
    led = _ledger()
    return led.read(strict=strict) if led else []


def validate_dir(root: str) -> Dict[str, int]:
    return RunLedger(root).validate()
