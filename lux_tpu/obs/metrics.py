"""Process-wide metrics registry: counters, gauges, histograms.

The reference has no metrics layer at all — its only instrumentation is
the wall-clock bracket around the iteration loop (pagerank.cc:108-118).
This registry follows the Prometheus client data model (dependency-free:
the container bakes nothing beyond the jax toolchain) so the run report
(obs/report.py) can dump every counter the engines touched alongside the
per-iteration log.

Identity semantics: a metric is keyed by ``(name, sorted(labels))``;
requesting the same key twice returns the SAME object (label dedup), and
re-requesting a name under a different metric kind raises — silent kind
drift is how counters get overwritten by gauges in long-lived processes.

Everything here is plain Python on the host; nothing imports jax. The
engines only touch the registry at flush granularity (obs/iterlog.py), so
cost is irrelevant to fused device loops.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

# Histogram bucket upper bounds (seconds-oriented: compile and iteration
# walls span ~100us CPU-test steps to minutes-long remote compiles).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, float("inf"),
)


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class Counter:
    """Monotonically increasing count (iterations run, flushes, bytes)."""

    kind = "counter"

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self.value += amount

    def snapshot(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "labels": self.labels,
            "value": self.value,
        }


class Gauge:
    """Point-in-time value (exchange bytes per iteration, frontier size)."""

    kind = "gauge"

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, value: float):
        self.value = float(value)

    def inc(self, amount: float = 1.0):
        self.value += amount

    def dec(self, amount: float = 1.0):
        self.value -= amount

    def snapshot(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "labels": self.labels,
            "value": self.value,
        }


class Histogram:
    """Distribution of observations (per-iteration seconds, compile
    seconds) as cumulative bucket counts plus count/sum."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        labels: Dict[str, str],
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        bounds = tuple(sorted(buckets))
        if not bounds or bounds[-1] != float("inf"):
            bounds = bounds + (float("inf"),)
        self.name = name
        self.labels = labels
        self.bounds = bounds
        self.bucket_counts = [0] * len(bounds)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float):
        self.count += 1
        self.sum += value
        for i, b in enumerate(self.bounds):
            if value <= b:
                self.bucket_counts[i] += 1
                break

    def quantile(self, q: float) -> float:
        """Approximate q-quantile (0..1) from the bucket counts, linearly
        interpolated within the winning bucket (the standard
        histogram_quantile estimate). Serving latency SLOs (p50/p99 in
        /stats and tools/serve_bench.py) read this; exact quantiles would
        need the raw observations we deliberately don't keep."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        lo = 0.0
        for b, c in zip(self.bounds, self.bucket_counts):
            if seen + c >= rank and c > 0:
                if b == float("inf"):
                    return lo  # open-ended bucket: report its lower bound
                frac = (rank - seen) / c
                return lo + (b - lo) * frac
            seen += c
            lo = b if b != float("inf") else lo
        return lo

    def snapshot(self) -> dict:
        return {
            "name": self.name, "kind": self.kind, "labels": self.labels,
            "count": self.count, "sum": self.sum,
            "buckets": [
                # inf serializes as a string: json.dumps(float('inf'))
                # emits the non-standard literal `Infinity`.
                {"le": b if b != float("inf") else "+Inf", "count": c}
                for b, c in zip(self.bounds, self.bucket_counts)
            ],
        }


class MetricsRegistry:
    """Thread-safe metric store; one per process (module-level REGISTRY)."""

    def __init__(self):
        self._metrics: Dict[tuple, object] = {}
        # Deliberately a bare Lock, not utils/locks.make_lock: this
        # registry is the substrate WatchedLock reports into — a watched
        # registry lock would re-enter _get from its own release path.
        self._lock = threading.Lock()

    def _get(self, cls, name: str, labels: Optional[Dict[str, str]], **kw):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, dict(labels or {}), **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str, labels=None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels=None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels=None, buckets=DEFAULT_BUCKETS):
        return self._get(Histogram, name, labels, buckets=buckets)

    def snapshot(self) -> list:
        """JSON-ready dump of every registered metric, sorted by name so
        dumps diff cleanly across runs."""
        with self._lock:
            metrics = list(self._metrics.values())
        return sorted(
            (m.snapshot() for m in metrics),
            key=lambda s: (s["name"], sorted(s["labels"].items())),
        )

    def reset(self):
        """Drop every metric (tests; a fresh process needs nothing)."""
        with self._lock:
            self._metrics.clear()


def _prom_label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _prom_num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(snap: Optional[list] = None) -> str:
    """Prometheus text exposition (version 0.0.4) of a registry snapshot.

    Dependency-free renderer for the serve ``/metrics`` endpoint: one
    ``# TYPE`` line per metric family, histograms as CUMULATIVE
    ``_bucket{le=...}`` series plus ``_sum``/``_count`` (the registry
    stores per-bucket counts; Prometheus semantics require the running
    total). Families sort by name, so scrapes diff cleanly.
    """
    if snap is None:
        snap = REGISTRY.snapshot()
    lines = []
    typed = set()
    for m in snap:
        name, kind = m["name"], m["kind"]
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")
        labels = m["labels"]
        if kind in ("counter", "gauge"):
            lines.append(
                f"{name}{_prom_label_str(labels)} {_prom_num(m['value'])}"
            )
            continue
        cum = 0
        for b in m["buckets"]:
            cum += b["count"]
            le = b["le"] if b["le"] == "+Inf" else _prom_num(b["le"])
            lines.append(
                f"{name}_bucket{_prom_label_str(dict(labels, le=le))} {cum}"
            )
        lines.append(f"{name}_sum{_prom_label_str(labels)} "
                     f"{repr(float(m['sum']))}")
        lines.append(f"{name}_count{_prom_label_str(labels)} {m['count']}")
    return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()

# Module-level conveniences bound to the process registry.
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
snapshot = REGISTRY.snapshot
reset = REGISTRY.reset
