"""Device-timeline profiling: capture windows, region tags, and the
``profile.v1`` report.

The engobs phase fencing (iterlog.set_overlap) reports an overlap
*budget* — ``min(exchange_s, compute_s) / exchange_s`` on serialized
phases. This module measures the *realized* overlap from an actual
device timeline:

- ``region(name)`` wraps a code block in BOTH ``jax.named_scope`` (tags
  the lowered HLO ops, so device-stream events can be joined back to
  the region) and ``jax.profiler.TraceAnnotation`` (a host span when a
  capture is live). Names must match ``lux.[a-z0-9_.]+`` — the grammar
  the parser classifies on (luxlint LUX009 enforces it statically).
  Zero-cost when no profiler is armed: annotations inside jitted code
  only run at trace time, and the names are static strings, so arming
  a capture never changes an executable cache key (no recompiles).
- ``trace(dirname)`` / ``profile_window(run)`` / SIGUSR2 (see
  ``install_signal_handler``) open programmatic capture windows via
  ``jax.profiler``; bench.py ``--profile`` and the serve ``POST
  /profilez`` endpoint ride these.
- ``parse_dir`` / ``parse`` read the captured TensorBoard artifact
  (``*.trace.json.gz`` Chrome events — stdlib ``gzip`` + ``json``
  only) into a ``profile.v1`` report: per-device interval-union wall
  time for exchange- vs compute-tagged ops, their intersection →
  ``realized_hidden_frac`` (directly comparable to the engobs budget),
  device idle fraction, a top-K op table, and a steps-per-second
  cross-check against an iterlog summary.

Joining device events to regions: ``jax.named_scope`` does not name
trace events directly — it lands in the compiled HLO's per-instruction
``op_name`` metadata, while each device trace event carries its HLO
instruction name in ``args.hlo_op``. ``op_map_from_hlo`` parses the
compiled module text (``jitted.lower(...).compile().as_text()``) into
an instruction → region-tag map the parser joins against. NOTE: that
AOT ``.compile()`` costs one backend compile — run it inside a
sentinel ``expect`` window, never under ``watch``.

Malformed artifacts (truncated gzip, broken JSON, non-numeric
timestamps) raise ``ProfileParseError`` loudly — a profile that cannot
be trusted must never quietly report a wrong overlap number.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import itertools
import json
import os
import re
import signal
import threading

from ..utils import flags
from ..utils.locks import make_lock
from ..utils.logging import get_logger

_LOG = get_logger("prof")

# The region-name grammar. The parser classifies tags by their
# ``.exchange`` / ``.compute`` components, so every region threaded
# through an engine must fit this shape (LUX009).
NAME_RE = re.compile(r"lux\.[a-z0-9_.]+")

_EPS_US = 1e-3          # float-microsecond tolerance for invariants


class ProfileParseError(RuntimeError):
    """A captured artifact could not be parsed into a trustworthy
    report (truncated gzip, malformed JSON, non-numeric event fields,
    inconsistent interval math)."""


class CaptureBusyError(RuntimeError):
    """A profile capture window is already in flight in this process
    (jax.profiler supports one live session)."""


# -- region tagging --------------------------------------------------------


class _Region:
    """``named_scope`` + ``TraceAnnotation`` as one context manager.
    jax is imported lazily so ``lux_tpu.obs`` stays importable (and
    cheap) before backend configuration."""

    __slots__ = ("name", "_cms")

    def __init__(self, name: str):
        self.name = name
        self._cms = ()

    def __enter__(self):
        import jax

        self._cms = (jax.named_scope(self.name),
                     jax.profiler.TraceAnnotation(self.name))
        for cm in self._cms:
            cm.__enter__()
        return self

    def __exit__(self, *exc):
        for cm in reversed(self._cms):
            cm.__exit__(*exc)
        return False


def region(name: str) -> _Region:
    """Tag a code block as a named engine region (e.g.
    ``lux.pull_sharded.exchange``). Inside jitted code the scope tags
    the lowered ops; on the host it opens a profiler annotation span.
    The name must match ``lux.[a-z0-9_.]+``."""
    if not NAME_RE.fullmatch(name):
        raise ValueError(
            f"region name {name!r} breaks the lux.[a-z0-9_.]+ grammar "
            "the profile parser classifies on")
    return _Region(name)


# -- capture windows -------------------------------------------------------

_CAP_IDS = itertools.count(1)
_capture_lock = threading.Lock()
_latest_lock = make_lock("obs.prof")
_latest_report = None
_sig_state = {"dir": None}


def trace(dirname):
    """Capture-window context manager: ``jax.profiler.trace`` into
    ``dirname``, or an inert ``nullcontext`` when ``dirname`` is falsy
    (the models/cli.py ``-profile`` contract)."""
    if not dirname:
        return contextlib.nullcontext()
    import jax

    os.makedirs(dirname, exist_ok=True)
    return jax.profiler.trace(dirname)


def profile_window(run, dirname=None, steps=None, op_maps=None,
                   iterlog_summary=None, top_k=10):
    """Run ``run()`` inside a fresh capture window under ``dirname``
    (default ``LUX_PROF_DIR``), parse the artifact, publish it as
    ``latest()``, and return ``(run_result, report)``.

    One window at a time per process: a second concurrent call raises
    ``CaptureBusyError`` instead of corrupting the live session."""
    d = dirname or flags.get("LUX_PROF_DIR")
    if not d:
        raise ValueError(
            "profiling is not armed: set LUX_PROF_DIR or pass dirname")
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusyError(
            "a profile capture window is already in flight")
    try:
        sub = os.path.join(d, f"cap_{os.getpid()}_{next(_CAP_IDS)}")
        with trace(sub):
            out = run()
        rep = parse_dir(sub, op_maps=op_maps, steps=steps,
                        iterlog_summary=iterlog_summary, top_k=top_k)
        rep["capture_dir"] = sub
        _set_latest(rep)
        return out, rep
    finally:
        _capture_lock.release()


def latest():
    """The most recent ``profile.v1`` report captured in this process
    (``profile_window`` or the SIGUSR2 toggle), or None."""
    with _latest_lock:
        return _latest_report


def latest_realized():
    """``realized_hidden_frac`` of the latest captured profile, or None
    — surfaced next to the engobs budget so the two are never
    conflated."""
    rep = latest()
    if rep is None:
        return None
    return rep.get("realized_hidden_frac")


def _set_latest(rep):
    global _latest_report
    with _latest_lock:
        _latest_report = rep


def install_signal_handler(signum=None) -> bool:
    """Arm the capture toggle on ``signum`` (default SIGUSR2, riding
    next to the flight recorder's SIGUSR1): first signal starts a
    capture into ``LUX_PROF_DIR``, the second stops it, parses the
    artifact, writes ``profile_v1.json`` next to it, and publishes
    ``latest()``. Returns False (no-op) off the main thread."""
    signum = signal.SIGUSR2 if signum is None else signum
    try:
        signal.signal(signum, _on_signal)
        return True
    except ValueError:
        return False


def _on_signal(signum, frame):
    # Signal context: never raise.
    try:
        _toggle_capture()
    except Exception as e:
        _LOG.warning("profile capture toggle failed: %r", e)


def _toggle_capture():
    d = flags.get("LUX_PROF_DIR")
    if not d:
        _LOG.warning("SIGUSR2 ignored: LUX_PROF_DIR is not set")
        return
    import jax

    if _sig_state["dir"] is None:
        if not _capture_lock.acquire(blocking=False):
            _LOG.warning("SIGUSR2 ignored: a capture is already live")
            return
        sub = os.path.join(d, f"sig_{os.getpid()}_{next(_CAP_IDS)}")
        os.makedirs(sub, exist_ok=True)
        try:
            jax.profiler.start_trace(sub)
        except Exception:
            _capture_lock.release()
            raise
        _sig_state["dir"] = sub
        _LOG.info("profile capture started -> %s (SIGUSR2 again to "
                  "stop)", sub)
        return
    sub, _sig_state["dir"] = _sig_state["dir"], None
    try:
        jax.profiler.stop_trace()
        rep = parse_dir(sub)
        rep["capture_dir"] = sub
        out = os.path.join(sub, "profile_v1.json")
        with open(out, "w") as f:
            json.dump(rep, f, indent=1)
        _set_latest(rep)
        _LOG.info("profile capture stopped: %s (realized_hidden_frac="
                  "%s)", out, rep.get("realized_hidden_frac"))
    finally:
        _capture_lock.release()


# -- artifact discovery + loading ------------------------------------------


def find_trace_artifact(dirname: str) -> str:
    """Newest ``*.trace.json.gz`` under ``dirname`` (jax writes
    ``<dir>/plugins/profile/<run>/<host>.trace.json.gz``)."""
    pats = (os.path.join(dirname, "**", "*.trace.json.gz"),
            os.path.join(dirname, "*.trace.json.gz"))
    cands = sorted({p for pat in pats for p in glob.glob(pat,
                                                         recursive=True)})
    if not cands:
        raise ProfileParseError(
            f"no *.trace.json.gz artifact under {dirname!r} — did the "
            "capture window actually run?")
    return max(cands, key=os.path.getmtime)


def load_chrome_trace(path: str) -> dict:
    """gzip+json load of a Chrome-trace artifact. Truncated or
    corrupt data raises ``ProfileParseError`` — never a wrong report."""
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, EOFError, ValueError, UnicodeDecodeError) as e:
        raise ProfileParseError(
            f"cannot read Chrome trace {path!r}: {e!r}") from e
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ProfileParseError(
            f"{path!r} is not a Chrome trace (no traceEvents list)")
    return doc


# -- HLO op-name join ------------------------------------------------------

_HLO_MODULE_RE = re.compile(r"^HloModule\s+([^,\s]+)", re.M)
_HLO_OP_RE = re.compile(r"%([\w.-]+)\s*=\s*[^\n]*?op_name=\"([^\"]+)\"")


def op_map_from_hlo(hlo_text: str) -> dict:
    """Instruction-name → innermost region tag for one compiled module
    (``jitted.lower(...).compile().as_text()``). Device trace events
    carry their HLO instruction name in ``args.hlo_op``; this is the
    join key that puts region tags on device-stream intervals."""
    m = _HLO_MODULE_RE.search(hlo_text)
    ops = {}
    for im in _HLO_OP_RE.finditer(hlo_text):
        tags = NAME_RE.findall(im.group(2))
        if tags:
            ops[im.group(1)] = tags[-1]       # innermost scope wins
    return {"module": m.group(1) if m else None, "ops": ops}


def op_map_for(jitted, *args, **kwargs) -> dict:
    """``op_map_from_hlo`` over an AOT-compiled jitted callable.
    COSTS ONE BACKEND COMPILE — call under ``sentinel.expect``."""
    text = jitted.lower(*args, **kwargs).compile().as_text()
    return op_map_from_hlo(text)


def _merge_op_maps(op_maps):
    by_module = {}
    by_op = {}
    for om in op_maps or ():
        module = om.get("module")
        for op, tag in (om.get("ops") or {}).items():
            by_module[(module, op)] = tag
            if op in by_op and by_op[op] != tag:
                by_op[op] = None              # ambiguous across modules
            else:
                by_op.setdefault(op, tag)
    return by_module, by_op


# -- interval math ---------------------------------------------------------


def merge_intervals(intervals):
    """Sorted, coalesced (start, end) list; tolerates out-of-order
    input and zero-length intervals."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    out = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def union_total(merged) -> float:
    return sum(e - s for s, e in merged)


def intersect_merged(a, b):
    """Intersection of two merged interval lists (two-pointer walk)."""
    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            out.append((s, e))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


# -- parsing ---------------------------------------------------------------


def _num(ev, key, default=None):
    v = ev.get(key, default)
    if v is None:
        return default
    try:
        return float(v)
    except (TypeError, ValueError):
        raise ProfileParseError(
            f"event {ev.get('name')!r} has non-numeric {key}={v!r}")


def parse(path: str, op_maps=None, steps=None, iterlog_summary=None,
          top_k: int = 10) -> dict:
    """Parse one Chrome-trace artifact into a ``profile.v1`` report."""
    return parse_events(load_chrome_trace(path), op_maps=op_maps,
                        steps=steps, iterlog_summary=iterlog_summary,
                        top_k=top_k)


def parse_dir(dirname: str, op_maps=None, steps=None,
              iterlog_summary=None, top_k: int = 10) -> dict:
    """``parse`` over the newest artifact under a capture directory."""
    return parse(find_trace_artifact(dirname), op_maps=op_maps,
                 steps=steps, iterlog_summary=iterlog_summary,
                 top_k=top_k)


def _phase_of(tag):
    if tag is None:
        return None
    if ".exchange" in tag:
        return "exchange"
    if ".compute" in tag:
        return "compute"
    return None


def parse_events(doc: dict, op_maps=None, steps=None,
                 iterlog_summary=None, top_k: int = 10) -> dict:
    """The ``profile.v1`` builder over an in-memory Chrome-trace doc.

    Device streams are keyed by pid (one pid per device in TPU
    captures; the shared host process in CPU captures). Only events
    carrying ``args.hlo_op`` count as device work — host-side
    ``TraceAnnotation`` spans are tracked separately (async dispatch
    would otherwise fake overlap that never happened on the device)."""
    by_module, by_op = _merge_op_maps(op_maps)
    procs, threads = {}, {}
    dev = {}                 # pid -> phase -> [(s, e)]
    host_regions = {}
    top = {}
    for ev in doc["traceEvents"]:
        if not isinstance(ev, dict):
            raise ProfileParseError(f"non-object trace event: {ev!r}")
        ph = ev.get("ph")
        if ph == "M":
            a = ev.get("args") or {}
            if ev.get("name") == "process_name":
                procs[ev.get("pid")] = a.get("name")
            elif ev.get("name") == "thread_name":
                threads[(ev.get("pid"), ev.get("tid"))] = a.get("name")
            continue
        if ph != "X":
            continue
        name = ev.get("name")
        ts = _num(ev, "ts")
        if ts is None:
            raise ProfileParseError(f"X event {name!r} has no ts")
        dur = _num(ev, "dur", 0.0) or 0.0
        args = ev.get("args") or {}
        hlo_op = args.get("hlo_op")
        if hlo_op is None and isinstance(name, str) \
                and NAME_RE.fullmatch(name):
            rec = host_regions.setdefault(
                name, {"count": 0, "total_us": 0.0})
            rec["count"] += 1
            rec["total_us"] += dur
            continue
        if hlo_op is None:
            continue
        tag = by_module.get((args.get("hlo_module"), hlo_op))
        if tag is None:
            tag = by_op.get(hlo_op)
        d = dev.setdefault(ev.get("pid"), {
            "exchange": [], "compute": [], "busy": []})
        d["busy"].append((ts, ts + dur))
        phase = _phase_of(tag)
        if phase:
            d[phase].append((ts, ts + dur))
        t = top.setdefault(name, {"op": name, "total_us": 0.0,
                                  "count": 0, "tag": tag})
        t["total_us"] += dur
        t["count"] += 1
        if t["tag"] is None:
            t["tag"] = tag

    devices = {}
    tot_ex = tot_ov = 0.0
    span_lo, span_hi = None, None
    for pid, d in dev.items():
        ex = merge_intervals(d["exchange"])
        co = merge_intervals(d["compute"])
        busy = merge_intervals(d["busy"])
        both = merge_intervals(d["exchange"] + d["compute"])
        ex_us, co_us = union_total(ex), union_total(co)
        ov_us = union_total(intersect_merged(ex, co))
        un_us = union_total(both)
        busy_us = union_total(busy)
        lo = min(s for s, _ in busy) if busy else 0.0
        hi = max(e for _, e in busy) if busy else 0.0
        span_us = hi - lo
        if busy:
            span_lo = lo if span_lo is None else min(span_lo, lo)
            span_hi = hi if span_hi is None else max(span_hi, hi)
        frac = min(max(ov_us / ex_us, 0.0), 1.0) if ex_us > 0 else None
        devices[str(pid)] = {
            "device": procs.get(pid) or f"pid:{pid}",
            "exchange_us": ex_us,
            "compute_us": co_us,
            "overlap_us": ov_us,
            "union_us": un_us,
            "busy_us": busy_us,
            "span_us": span_us,
            "idle_frac": (min(max(1.0 - busy_us / span_us, 0.0), 1.0)
                          if span_us > 0 else None),
            "realized_hidden_frac": frac,
        }
        tot_ex += ex_us
        tot_ov += ov_us

    report = {
        "schema": "profile.v1",
        "devices": devices,
        "host_regions": host_regions,
        "tags": sorted(
            {t["tag"] for t in top.values() if t["tag"]}
            | set(host_regions)),
        "top_ops": sorted(top.values(), key=lambda t: -t["total_us"])
        [:max(int(top_k), 0)],
        "realized_hidden_frac": (
            min(max(tot_ov / tot_ex, 0.0), 1.0) if tot_ex > 0 else None),
    }
    span_s = ((span_hi - span_lo) / 1e6
              if span_lo is not None and span_hi > span_lo else None)
    steps_block = {"device_span_s": span_s}
    if steps is not None:
        steps_block["captured"] = int(steps)
        if span_s:
            steps_block["steps_per_s"] = int(steps) / span_s
    if iterlog_summary:
        n = iterlog_summary.get("num_iters") or 0
        ex_s = iterlog_summary.get("execute_s") or 0.0
        steps_block["iterlog"] = {
            "num_iters": n, "execute_s": ex_s,
            "steps_per_s": (n / ex_s) if ex_s > 0 else None,
        }
    report["steps"] = steps_block
    return validate(report)


def validate(report: dict) -> dict:
    """Check a ``profile.v1`` report's schema and interval invariants;
    raises ``ProfileParseError`` on any violation, returns the report
    unchanged otherwise."""
    if not isinstance(report, dict) or report.get("schema") != "profile.v1":
        raise ProfileParseError(
            f"not a profile.v1 report: schema={report.get('schema')!r}"
            if isinstance(report, dict) else
            f"not a profile.v1 report: {type(report).__name__}")
    devices = report.get("devices")
    if not isinstance(devices, dict):
        raise ProfileParseError("profile.v1 report has no devices map")
    for pid, d in devices.items():
        ex, co = d.get("exchange_us"), d.get("compute_us")
        ov, un = d.get("overlap_us"), d.get("union_us")
        for key, v in (("exchange_us", ex), ("compute_us", co),
                       ("overlap_us", ov), ("union_us", un)):
            if not isinstance(v, (int, float)) or v < 0:
                raise ProfileParseError(
                    f"device {pid}: bad {key}={v!r}")
        if un + _EPS_US < max(ex, co):
            raise ProfileParseError(
                f"device {pid}: union {un} < max phase {max(ex, co)}")
        if un > ex + co + _EPS_US:
            raise ProfileParseError(
                f"device {pid}: union {un} > exchange+compute {ex + co}")
        if ov > min(ex, co) + _EPS_US:
            raise ProfileParseError(
                f"device {pid}: overlap {ov} > min phase {min(ex, co)}")
        for key in ("realized_hidden_frac", "idle_frac"):
            v = d.get(key)
            if v is not None and not 0.0 <= v <= 1.0:
                raise ProfileParseError(
                    f"device {pid}: {key}={v!r} outside [0, 1]")
    frac = report.get("realized_hidden_frac")
    if frac is not None and not 0.0 <= frac <= 1.0:
        raise ProfileParseError(
            f"realized_hidden_frac={frac!r} outside [0, 1]")
    return report


# -- rendering -------------------------------------------------------------


def format_report(report: dict) -> str:
    """Compact human rendering of a ``profile.v1`` report (shared by
    tools/prof_summary.py and trace_summary.py ``--phases``)."""
    lines = ["profile.v1 device timeline:"]
    frac = report.get("realized_hidden_frac")
    lines.append(
        "  realized_hidden_frac={} (device-measured; compare to the "
        "engobs budget, an upper bound)".format(
            "n/a" if frac is None else f"{frac:.3f}"))
    lines.append("  {:<26} {:>12} {:>12} {:>11} {:>10} {:>9}".format(
        "device", "exchange_us", "compute_us", "overlap_us",
        "realized", "idle"))
    for pid in sorted(report.get("devices") or {}):
        d = report["devices"][pid]
        lines.append(
            "  {:<26} {:>12.0f} {:>12.0f} {:>11.0f} {:>10} {:>9}".format(
                str(d.get("device"))[:26], d["exchange_us"],
                d["compute_us"], d["overlap_us"],
                "-" if d.get("realized_hidden_frac") is None
                else f"{d['realized_hidden_frac']:.3f}",
                "-" if d.get("idle_frac") is None
                else f"{d['idle_frac']:.3f}"))
    if report.get("host_regions"):
        lines.append("  host regions:")
        for name in sorted(report["host_regions"]):
            rec = report["host_regions"][name]
            lines.append(
                f"    {name:<32} x{rec['count']:<5} "
                f"{rec['total_us']:.0f} us")
    if report.get("top_ops"):
        lines.append("  top ops:")
        for t in report["top_ops"]:
            lines.append(
                "    {:<38} {:>10.0f} us x{:<5} {}".format(
                    str(t["op"])[:38], t["total_us"], t["count"],
                    t.get("tag") or "-"))
    st = report.get("steps") or {}
    if st.get("captured") is not None:
        rate = st.get("steps_per_s")
        lines.append(
            "  steps: {} captured over {} of device span ({})".format(
                st["captured"],
                "n/a" if st.get("device_span_s") is None
                else f"{st['device_span_s']:.4f}s",
                "n/a" if rate is None else f"{rate:.1f} steps/s"))
        il = st.get("iterlog")
        if il:
            lines.append(
                "  iterlog cross-check: {num_iters} iters / "
                "{execute_s:.4f}s execute ({rate})".format(
                    rate=("n/a" if il.get("steps_per_s") is None
                          else f"{il['steps_per_s']:.1f} steps/s"),
                    **{k: il[k] for k in ("num_iters", "execute_s")}))
    return "\n".join(lines)
