"""End-of-run reporting: ``lux.perf`` log table + ``LUX_METRICS`` dump.

``finalize(summary)`` is called by ``IterationRecorder.finish()`` with
the ``lux.run_telemetry.v1`` summary dict. It renders a compact table to
the ``lux.perf`` logger and, when ``LUX_METRICS=<path>`` is set, appends
one JSON line (the summary plus a metrics-registry snapshot) to that
path. JSON-lines append means warmup-free repeated runs in one process
coexist; readers take the last line for the headline run.
"""

from __future__ import annotations

import json

from ..utils import flags
from ..utils.logging import get_logger
from . import metrics

# Cap the per-iteration rows logged to lux.perf; the JSON dump always
# carries every record.
_LOG_ROWS_HEAD = 24
_LOG_ROWS_TAIL = 8

# Roofline peaks. HBM matches bench.py's v5e single-chip figure; ICI is
# the per-chip v5e interconnect estimate (4 links x ~46.5 GB/s usable).
# Both are ceilings for *fractions* — the ledger labels results as
# model-derived, not measured, on CPU meshes.
HBM_PEAK_GBPS = 819.0
ICI_PEAK_GBPS = 186.0


def roofline(summary: dict) -> dict:
    """Achieved-vs-peak HBM and ICI fractions for one run summary.

    HBM: the engine's first-order bytes-per-iteration model
    (``hbm_bytes_per_iter``, from engobs.hbm_bytes_per_iter) over execute
    time. ICI: exchange bytes over exchange time — phase-measured
    exchange seconds when the run was phase-fenced (LUX_ENGOBS), else
    total execute time (a lower bound on the fraction) — divided across
    the mesh's parts, since per-iter exchange bytes count all P shards'
    collectives while the peak is per chip.
    """
    out = {}
    iters = summary.get("num_iters") or 0
    exec_s = summary.get("execute_s") or 0.0
    hbm = summary.get("hbm_bytes_per_iter")
    if hbm and iters and exec_s > 0:
        gbps = hbm * iters / exec_s / 1e9
        out["hbm_gbps"] = gbps
        out["hbm_frac"] = gbps / HBM_PEAK_GBPS
    exch = summary.get("exchange_bytes_per_iter")
    if exch and iters:
        phases = summary.get("phases") or {}
        exch_s = phases.get("exchange_s") or exec_s
        parts = summary.get("parts") or 1
        if exch_s > 0:
            gbps = exch * iters / exch_s / 1e9 / max(parts, 1)
            out["ici_gbps_per_chip"] = gbps
            out["ici_frac"] = gbps / ICI_PEAK_GBPS
            out["ici_measured"] = bool(phases)
    return out


def _format_table(summary: dict) -> str:
    lines = [
        "run report: engine={engine} program={program} nv={nv} ne={ne}".format(
            **summary),
        "  iters={num_iters} compile={compile_s:.4f}s "
        "execute={execute_s:.4f}s gteps={gteps:.4f}".format(**summary),
    ]
    if summary.get("exchange_bytes_per_iter"):
        line = ("  exchange: {exchange_bytes_per_iter} B/iter, "
                "{exchange_bytes_total} B total".format(**summary))
        if summary.get("useful_bytes_per_iter") is not None:
            line += " (useful {useful_bytes_per_iter} B/iter, " \
                "ratio {useful_ratio:.3f})".format(**summary)
        lines.append(line)
    if summary.get("phases"):
        lines.append(
            "  phases: exchange={exchange_s:.4f}s compute={compute_s:.4f}s "
            "exchange_frac={exchange_frac:.3f}".format(**summary["phases"]))
    roof = summary.get("roofline")
    if roof:
        bits = []
        if "hbm_frac" in roof:
            bits.append("HBM {hbm_gbps:.1f} GB/s ({hbm_frac:.3f} of "
                        "peak)".format(**roof))
        if "ici_frac" in roof:
            bits.append("ICI {ici_gbps_per_chip:.1f} GB/s/chip "
                        "({ici_frac:.3f} of peak{})".format(
                            "" if roof.get("ici_measured")
                            else ", bound", **roof))
        if bits:
            lines.append("  roofline: " + "; ".join(bits))
    rows = summary.get("iterations") or []
    if rows:
        lines.append(
            "  {:>6} {:>12} {:>12} {:>10} {:>9}".format(
                "iter", "t_iter_s", "t_cum_s", "frontier", "gteps"))
        shown = rows
        elided = 0
        if len(rows) > _LOG_ROWS_HEAD + _LOG_ROWS_TAIL:
            shown = rows[:_LOG_ROWS_HEAD]
            elided = len(rows) - _LOG_ROWS_HEAD - _LOG_ROWS_TAIL
        for r in shown:
            lines.append(_format_row(r))
        if elided:
            lines.append(f"  ... {elided} rows elided ...")
            for r in rows[-_LOG_ROWS_TAIL:]:
                lines.append(_format_row(r))
    return "\n".join(lines)


def _format_row(r: dict) -> str:
    frontier = r.get("frontier")
    return "  {:>6} {:>12.6f} {:>12.6f} {:>10} {:>9.4f}".format(
        r["iter"], r["t_iter_s"], r["t_cum_s"],
        "-" if frontier is None else frontier, r["gteps"])


def finalize(summary: dict):
    roof = roofline(summary)
    if roof:
        summary["roofline"] = roof
    log = get_logger("perf")
    log.info("%s", _format_table(summary))
    path = flags.get("LUX_METRICS")
    if not path:
        return
    record = dict(summary)
    record["metrics"] = metrics.snapshot()
    with open(path, "a") as f:
        f.write(json.dumps(record, separators=(",", ":")) + "\n")


def read_last(path: str) -> dict:
    """Read the most recent run record from a ``LUX_METRICS`` dump."""
    last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                last = line
    if last is None:
        raise ValueError(f"no run records in {path}")
    return json.loads(last)
