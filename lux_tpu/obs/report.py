"""End-of-run reporting: ``lux.perf`` log table + ``LUX_METRICS`` dump.

``finalize(summary)`` is called by ``IterationRecorder.finish()`` with
the ``lux.run_telemetry.v1`` summary dict. It renders a compact table to
the ``lux.perf`` logger and, when ``LUX_METRICS=<path>`` is set, appends
one JSON line (the summary plus a metrics-registry snapshot) to that
path. JSON-lines append means warmup-free repeated runs in one process
coexist; readers take the last line for the headline run.
"""

from __future__ import annotations

import json

from ..utils import flags
from ..utils.logging import get_logger
from . import metrics

# Cap the per-iteration rows logged to lux.perf; the JSON dump always
# carries every record.
_LOG_ROWS_HEAD = 24
_LOG_ROWS_TAIL = 8


def _format_table(summary: dict) -> str:
    lines = [
        "run report: engine={engine} program={program} nv={nv} ne={ne}".format(
            **summary),
        "  iters={num_iters} compile={compile_s:.4f}s "
        "execute={execute_s:.4f}s gteps={gteps:.4f}".format(**summary),
    ]
    if summary.get("exchange_bytes_per_iter"):
        lines.append(
            "  exchange: {exchange_bytes_per_iter} B/iter, "
            "{exchange_bytes_total} B total".format(**summary))
    rows = summary.get("iterations") or []
    if rows:
        lines.append(
            "  {:>6} {:>12} {:>12} {:>10} {:>9}".format(
                "iter", "t_iter_s", "t_cum_s", "frontier", "gteps"))
        shown = rows
        elided = 0
        if len(rows) > _LOG_ROWS_HEAD + _LOG_ROWS_TAIL:
            shown = rows[:_LOG_ROWS_HEAD]
            elided = len(rows) - _LOG_ROWS_HEAD - _LOG_ROWS_TAIL
        for r in shown:
            lines.append(_format_row(r))
        if elided:
            lines.append(f"  ... {elided} rows elided ...")
            for r in rows[-_LOG_ROWS_TAIL:]:
                lines.append(_format_row(r))
    return "\n".join(lines)


def _format_row(r: dict) -> str:
    frontier = r.get("frontier")
    return "  {:>6} {:>12.6f} {:>12.6f} {:>10} {:>9.4f}".format(
        r["iter"], r["t_iter_s"], r["t_cum_s"],
        "-" if frontier is None else frontier, r["gteps"])


def finalize(summary: dict):
    log = get_logger("perf")
    log.info("%s", _format_table(summary))
    path = flags.get("LUX_METRICS")
    if not path:
        return
    record = dict(summary)
    record["metrics"] = metrics.snapshot()
    with open(path, "a") as f:
        f.write(json.dumps(record, separators=(",", ":")) + "\n")


def read_last(path: str) -> dict:
    """Read the most recent run record from a ``LUX_METRICS`` dump."""
    last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                last = line
    if last is None:
        raise ValueError(f"no run records in {path}")
    return json.loads(last)
