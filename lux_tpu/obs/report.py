"""End-of-run reporting: ``lux.perf`` log table + ``LUX_METRICS`` dump.

``finalize(summary)`` is called by ``IterationRecorder.finish()`` with
the ``lux.run_telemetry.v1`` summary dict. It renders a compact table to
the ``lux.perf`` logger and, when ``LUX_METRICS=<path>`` is set, appends
one JSON line (the summary plus a metrics-registry snapshot) to that
path. JSON-lines append means warmup-free repeated runs in one process
coexist; readers take the last line for the headline run.
"""

from __future__ import annotations

import json

from ..utils import flags
from ..utils.logging import get_logger
from . import ledger, metrics

# Cap the per-iteration rows logged to lux.perf; the JSON dump always
# carries every record.
_LOG_ROWS_HEAD = 24
_LOG_ROWS_TAIL = 8

# Roofline peak-rate registry, keyed on jax's device_kind:
# (hbm_peak_gbps, ici_peak_gbps, hbm_capacity_bytes). HBM rows are the
# public per-chip HBM bandwidths; ICI rows the per-chip interconnect
# estimates (v5e: 4 links x ~46.5 GB/s usable); capacities the public
# per-chip HBM sizes (v5e 16 GiB, v5p 95 GiB, v4 32 GiB) — the ceiling
# luxlint --memory's LUX703 budgets against and the serve pool's
# admission derives its default byte budget from. Rates are ceilings
# for *fractions* only. A CPU host has neither HBM nor ICI, so its row
# deliberately prices nothing — and an UNKNOWN kind reports None plus a
# one-time warning instead of silently assuming v5e (the pre-PR-15
# behavior priced every chip against the v5e constants).
_DEVICE_PROFILES = {
    "TPU v5e": (819.0, 186.0, 16 << 30),
    "TPU v5 lite": (819.0, 186.0, 16 << 30),  # v5e's kind on some stacks
    "TPU v5p": (2765.0, 600.0, 95 << 30),
    "TPU v5": (2765.0, 600.0, 95 << 30),
    "TPU v4": (1228.0, 300.0, 32 << 30),
    "cpu": (None, None, None),
    "Cpu": (None, None, None),
}

_kind_cache = []
_warned_kinds = set()


def _device_kind() -> str:
    """``jax.devices()[0].device_kind``, cached; 'unknown' when no
    backend is reachable (pure-host tools)."""
    if not _kind_cache:
        try:
            import jax

            _kind_cache.append(jax.devices()[0].device_kind)
        except Exception:
            _kind_cache.append("unknown")
    return _kind_cache[0]


def device_profile(kind: str = None) -> dict:
    """The roofline peak-rate row for ``kind`` (default: the live
    backend's device_kind): ``{device_kind, hbm_peak_gbps,
    ici_peak_gbps, hbm_capacity_bytes, known}``. ``LUX_HBM_PEAK_GBPS``
    / ``LUX_ICI_PEAK_GBPS`` override either rate and
    ``LUX_HBM_CAPACITY_BYTES`` the capacity (e.g. a chip the registry
    predates — also the only way cpu runs get a capacity for LUX703).
    An unknown kind without overrides yields None peaks — roofline
    fractions then stay None rather than pricing against the wrong
    chip — and warns once per kind."""
    if kind is None:
        kind = _device_kind()
    row = _DEVICE_PROFILES.get(kind)
    hbm, ici, cap = row if row else (None, None, None)
    hbm_env = flags.get("LUX_HBM_PEAK_GBPS")
    ici_env = flags.get("LUX_ICI_PEAK_GBPS")
    cap_env = flags.get("LUX_HBM_CAPACITY_BYTES")
    if hbm_env:
        hbm = float(hbm_env)
    if ici_env:
        ici = float(ici_env)
    if cap_env:
        cap = int(cap_env)
    if row is None and not (hbm_env or ici_env) \
            and kind not in _warned_kinds:
        _warned_kinds.add(kind)
        get_logger("perf").warning(
            "no device profile for device_kind=%r: roofline fractions "
            "will be None (set LUX_HBM_PEAK_GBPS/LUX_ICI_PEAK_GBPS to "
            "price this chip)", kind)
    return {"device_kind": kind, "hbm_peak_gbps": hbm,
            "ici_peak_gbps": ici, "hbm_capacity_bytes": cap,
            "known": row is not None}


def roofline(summary: dict) -> dict:
    """Achieved-vs-peak HBM and ICI fractions for one run summary.

    HBM: the engine's first-order bytes-per-iteration model
    (``hbm_bytes_per_iter``, from engobs.hbm_bytes_per_iter) over execute
    time. ICI: exchange bytes over exchange time — phase-measured
    exchange seconds when the run was phase-fenced (LUX_ENGOBS), else
    total execute time (a lower bound on the fraction) — divided across
    the mesh's parts, since per-iter exchange bytes count all P shards'
    collectives while the peak is per chip.
    """
    out = {}
    prof_row = device_profile()
    out["device_kind"] = prof_row["device_kind"]
    if prof_row["hbm_capacity_bytes"]:
        out["hbm_capacity_bytes"] = prof_row["hbm_capacity_bytes"]
    iters = summary.get("num_iters") or 0
    exec_s = summary.get("execute_s") or 0.0
    hbm = summary.get("hbm_bytes_per_iter")
    if hbm and iters and exec_s > 0:
        gbps = hbm * iters / exec_s / 1e9
        out["hbm_gbps"] = gbps
        peak = prof_row["hbm_peak_gbps"]
        out["hbm_frac"] = gbps / peak if peak else None
    exch = summary.get("exchange_bytes_per_iter")
    if exch and iters:
        phases = summary.get("phases") or {}
        exch_s = phases.get("exchange_s") or exec_s
        parts = summary.get("parts") or 1
        if exch_s > 0:
            gbps = exch * iters / exch_s / 1e9 / max(parts, 1)
            out["ici_gbps_per_chip"] = gbps
            peak = prof_row["ici_peak_gbps"]
            out["ici_frac"] = gbps / peak if peak else None
            out["ici_measured"] = bool(phases)
    return out


def _format_table(summary: dict) -> str:
    lines = [
        "run report: engine={engine} program={program} nv={nv} ne={ne}".format(
            **summary),
        "  iters={num_iters} compile={compile_s:.4f}s "
        "execute={execute_s:.4f}s gteps={gteps:.4f}".format(**summary),
    ]
    if summary.get("exchange_bytes_per_iter"):
        line = ("  exchange: {exchange_bytes_per_iter} B/iter, "
                "{exchange_bytes_total} B total".format(**summary))
        if summary.get("useful_bytes_per_iter") is not None:
            line += " (useful {useful_bytes_per_iter} B/iter, " \
                "ratio {useful_ratio:.3f})".format(**summary)
        lines.append(line)
    if summary.get("phases"):
        lines.append(
            "  phases: exchange={exchange_s:.4f}s compute={compute_s:.4f}s "
            "exchange_frac={exchange_frac:.3f}".format(**summary["phases"]))
    roof = summary.get("roofline")
    if roof:
        bits = []
        if "hbm_gbps" in roof:
            frac = roof.get("hbm_frac")
            bits.append("HBM {:.1f} GB/s ({} of peak)".format(
                roof["hbm_gbps"],
                "n/a" if frac is None else f"{frac:.3f}"))
        if "ici_gbps_per_chip" in roof:
            frac = roof.get("ici_frac")
            bits.append("ICI {:.1f} GB/s/chip ({} of peak{})".format(
                roof["ici_gbps_per_chip"],
                "n/a" if frac is None else f"{frac:.3f}",
                "" if roof.get("ici_measured") else ", bound"))
        if bits:
            lines.append("  roofline: " + "; ".join(bits))
    rows = summary.get("iterations") or []
    if rows:
        lines.append(
            "  {:>6} {:>12} {:>12} {:>10} {:>9}".format(
                "iter", "t_iter_s", "t_cum_s", "frontier", "gteps"))
        shown = rows
        elided = 0
        if len(rows) > _LOG_ROWS_HEAD + _LOG_ROWS_TAIL:
            shown = rows[:_LOG_ROWS_HEAD]
            elided = len(rows) - _LOG_ROWS_HEAD - _LOG_ROWS_TAIL
        for r in shown:
            lines.append(_format_row(r))
        if elided:
            lines.append(f"  ... {elided} rows elided ...")
            for r in rows[-_LOG_ROWS_TAIL:]:
                lines.append(_format_row(r))
    return "\n".join(lines)


def _format_row(r: dict) -> str:
    frontier = r.get("frontier")
    return "  {:>6} {:>12.6f} {:>12.6f} {:>10} {:>9.4f}".format(
        r["iter"], r["t_iter_s"], r["t_cum_s"],
        "-" if frontier is None else frontier, r["gteps"])


def finalize(summary: dict):
    roof = roofline(summary)
    if roof:
        summary["roofline"] = roof
    log = get_logger("perf")
    log.info("%s", _format_table(summary))
    # Every finished run becomes one durable runrec.v1 observation when
    # the ledger is armed — this is THE engine-run feed-in point: every
    # executor that runs through IterationRecorder.finish() lands here.
    # Per-iteration rows stay in the LUX_METRICS dump; the ledger keeps
    # the (config -> aggregate metrics) observation compact.
    obs = {k: v for k, v in summary.items() if k != "iterations"}
    ledger.record_run(
        "engine_run", obs,
        program=str(summary.get("program", "?")),
        engine_kind=str(summary.get("engine", "?")),
        mesh_shape=str(summary.get("parts", 1)),
    )
    path = flags.get("LUX_METRICS")
    if not path:
        return
    record = dict(summary)
    record["metrics"] = metrics.snapshot()
    with open(path, "a") as f:
        f.write(json.dumps(record, separators=(",", ":")) + "\n")


def read_last(path: str) -> dict:
    """Read the most recent run record from a ``LUX_METRICS`` dump."""
    last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                last = line
    if last is None:
        raise ValueError(f"no run records in {path}")
    return json.loads(last)
