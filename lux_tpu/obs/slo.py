"""Rolling SLO windows: p50/p95/p99 per app over the last 1/5 minutes.

The registry histogram (``lux_serve_request_seconds``) is cumulative
since process start — useless for "is the server slow *right now*".
``SloWindows`` keeps the raw (timestamp, latency) observations of the
last ``max(windows)`` seconds per app (bounded deque) and computes exact
quantiles per window on demand, which is what ``/statusz`` serves.

Window lengths come from ``LUX_STATUSZ_WINDOWS`` (default "60,300");
``now`` is injectable so tests can replay a seeded latency stream and
check the window math deterministically. Thread-safe; stdlib only.
"""

from __future__ import annotations

import bisect
from collections import deque
from typing import Callable, Dict, Optional, Sequence

from ..utils import flags
from ..utils.locks import make_lock
from . import spans

# Per-app retention cap: at 10k qps and a 300 s window this truncates,
# but /statusz quantiles over the *newest* 64k observations are still
# the right operational signal — and memory stays bounded.
MAX_OBSERVATIONS = 65536


def windows_from_flags() -> tuple:
    """Parse LUX_STATUSZ_WINDOWS ("60,300") into sorted unique seconds."""
    raw = flags.get("LUX_STATUSZ_WINDOWS") or ""
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            w = float(part)
        except ValueError:
            continue     # malformed entry: fall through to the default
        if w > 0:
            out.append(w)
    return tuple(sorted(set(out))) or (60.0, 300.0)


def _quantile(sorted_xs: Sequence[float], q: float) -> float:
    """Exact linear-interpolation quantile of a sorted sample."""
    n = len(sorted_xs)
    if n == 0:
        return 0.0
    if n == 1:
        return sorted_xs[0]
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_xs[lo] * (1 - frac) + sorted_xs[hi] * frac


class SloWindows:
    """Timestamped latency ring per app; quantiles per rolling window."""

    def __init__(
        self,
        windows: Optional[Sequence[float]] = None,
        now: Optional[Callable[[], float]] = None,
        quantiles: Sequence[float] = (0.5, 0.95, 0.99),
    ):
        self.windows = tuple(sorted(windows)) if windows \
            else windows_from_flags()
        self.quantiles = tuple(quantiles)
        self._now = now if now is not None else spans.monotonic
        self._obs: Dict[str, deque] = {}
        self._lock = make_lock("obs.slo")

    def observe(self, app: str, seconds: float):
        t = self._now()
        with self._lock:
            d = self._obs.get(app)
            if d is None:
                d = self._obs[app] = deque(maxlen=MAX_OBSERVATIONS)
            d.append((t, float(seconds)))
            self._prune(d, t)

    def _prune(self, d: deque, now: float):
        horizon = now - self.windows[-1]
        while d and d[0][0] < horizon:
            d.popleft()

    def snapshot(self) -> dict:
        """``{"60s": {app: {count, p50, p95, p99}, ...}, "300s": ...}`` —
        the /statusz windows block."""
        now = self._now()
        with self._lock:
            per_app = {
                app: [(t, v) for (t, v) in d if t >= now - self.windows[-1]]
                for app, d in self._obs.items()
            }
        out = {}
        for w in self.windows:
            label = f"{w:g}s"
            block = {}
            horizon = now - w
            for app, obs in per_app.items():
                # obs is time-ordered; bisect to the window start.
                times = [t for (t, _) in obs]
                i = bisect.bisect_left(times, horizon)
                xs = sorted(v for (_, v) in obs[i:])
                if not xs:
                    continue
                entry = {"count": len(xs)}
                for q in self.quantiles:
                    entry[f"p{int(q * 100)}"] = _quantile(xs, q)
                block[app] = entry
            out[label] = block
        return out
