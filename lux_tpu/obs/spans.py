"""Request-scoped spans: one trace-id through the whole serve path.

A query crosses three threads — the HTTP handler thread (admission,
cache probe), the batcher worker (queue-wait, batch assembly, engine
execute), and back — and whole-run telemetry (iterlog) cannot say where
*one request's* time went. This module threads a trace-id through that
path:

- ``span(name, **attrs)`` — context manager. With no ambient trace-id it
  opens a ROOT span: a fresh trace-id is minted, propagated via a
  contextvar, and the trace's record is finalized (and handed to sinks,
  e.g. the flight recorder) when the root exits. Nested spans join the
  ambient trace.
- ``adopt(trace_id)`` — continue a trace on another thread (the batcher
  worker adopts the lead request's trace-id before executing a batch).
- ``complete(name, dur_s, ...)`` — record a span retrospectively
  (queue-wait is only known at dequeue).

Every span emits three things: a sync B/E pair on its own thread lane
plus an async "b"/"e" pair keyed by trace-id in the Chrome trace
(obs/trace.py — Perfetto draws the request as one lane across threads),
and a ``lux_span_seconds{span=...}`` histogram observation.

Clock helpers live here too: LUX006 (analysis/rules.py) bans direct
``time.*`` clock reads in serve/ and engine/ so every latency number and
span shares one clock pair — ``clock()`` (perf_counter, durations and
trace stamps) and ``monotonic()`` (deadlines, wall scheduling).

Gated by ``LUX_SPANS`` (default on); when off, ``span`` is a
pass-through and nothing is recorded. Pure stdlib; no jax.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from ..utils import flags
from ..utils.locks import make_lock
from . import metrics, trace

_TRACE_ID: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "lux_trace_id", default=None
)
_seq = itertools.count(1)

_lock = make_lock("obs.spans")
# trace_id -> open trace record; bounded so an abandoned future can never
# grow this without limit (oldest open trace is dropped, not dumped).
_MAX_OPEN = 1024
_open: "OrderedDict[str, dict]" = OrderedDict()
_sinks: List[Callable[[dict], None]] = []

# Span-latency buckets: engine phases run ~10us (a fenced exchange on a
# tiny mesh) through serve phases to seconds (cold engine sweep). The
# old bounds jumped 1e-4 -> 5e-4 -> 1e-3, collapsing the sub-millisecond
# band the engine observatory lives in into three coarse buckets; the
# 2-5-10 ladder below keeps quantile interpolation within ~2.5x of truth
# down to 10us while the top decades stay serving-scale.
SPAN_BUCKETS = (
    1e-5, 2e-5, 5e-5, 1e-4, 2e-4, 5e-4, 1e-3, 2e-3, 5e-3, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0,
    float("inf"),
)


# -- clock discipline (the LUX006 contract) --------------------------------


def clock() -> float:
    """Duration/trace clock (perf_counter): same epoch as obs/trace.py
    stamps, so retrospective spans land where live ones do."""
    return time.perf_counter()


def monotonic() -> float:
    """Deadline/scheduling clock (monotonic): comparable across threads,
    immune to wall-clock steps."""
    return time.monotonic()


# -- trace-id plumbing -----------------------------------------------------


def enabled() -> bool:
    return flags.get_bool("LUX_SPANS")


def current_trace_id() -> Optional[str]:
    return _TRACE_ID.get()


def new_trace_id() -> str:
    return f"lux-{os.getpid():x}-{next(_seq):06x}"


def _begin_trace(tid: str) -> dict:
    rec = {
        "trace_id": tid,
        "started_unix_s": time.time(),
        "started_pc_s": clock(),
        "spans": [],
    }
    with _lock:
        _open[tid] = rec
        while len(_open) > _MAX_OPEN:
            _open.popitem(last=False)
    return rec


def _finish_trace(tid: str):
    with _lock:
        rec = _open.pop(tid, None)
        sinks = list(_sinks)
    if rec is None:
        return
    rec["finished_pc_s"] = clock()
    rec["duration_s"] = rec["finished_pc_s"] - rec["started_pc_s"]
    for fn in sinks:
        try:
            fn(rec)
        except Exception:   # a broken sink must never fail a request
            pass


def _note_span(tid, name, t0, t1, attrs):
    with _lock:
        rec = _open.get(tid)
        if rec is None:     # root already finished (late batch tail)
            return
        rec["spans"].append({
            "name": name,
            "t0_s": round(t0 - rec["started_pc_s"], 9),
            "dur_s": round(t1 - t0, 9),
            "thread": threading.current_thread().name,
            **({"attrs": attrs} if attrs else {}),
        })


def add_sink(fn: Callable[[dict], None]):
    """Register a completed-trace consumer (flight recorder)."""
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_sink(fn: Callable[[dict], None]):
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)


# -- the span API ----------------------------------------------------------


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a phase of the current request. Root when no trace is
    ambient: mints the trace-id and finalizes the trace record on exit."""
    if not enabled():
        yield None
        return
    tid = _TRACE_ID.get()
    token = None
    root = tid is None
    if root:
        tid = new_trace_id()
        token = _TRACE_ID.set(tid)
        _begin_trace(tid)
    t0 = clock()
    trace.begin(name, cat="span", args=dict(attrs, trace_id=tid) if attrs
                else {"trace_id": tid})
    trace.async_begin(name, tid, cat="span", args=attrs or None)
    try:
        yield tid
    finally:
        t1 = clock()
        trace.async_end(name, tid, cat="span")
        trace.end(name, cat="span")
        metrics.histogram(
            "lux_span_seconds", {"span": name}, buckets=SPAN_BUCKETS
        ).observe(t1 - t0)
        _note_span(tid, name, t0, t1, attrs)
        if root:
            _TRACE_ID.reset(token)
            _finish_trace(tid)


@contextlib.contextmanager
def adopt(trace_id: Optional[str]):
    """Continue ``trace_id`` on this thread (batcher worker executing a
    request admitted elsewhere). No-op when ``trace_id`` is None; never
    finalizes the trace — the originating root (or ``open_trace``
    finisher) owns that."""
    if not enabled() or trace_id is None:
        yield
        return
    token = _TRACE_ID.set(trace_id)
    try:
        yield
    finally:
        _TRACE_ID.reset(token)


def complete(name: str, dur_s: float, end: Optional[float] = None,
             trace_id: Optional[str] = None, **attrs):
    """Record a span retrospectively: it ended at ``end`` (perf_counter
    stamp; default now) and lasted ``dur_s``."""
    if not enabled():
        return
    tid = trace_id if trace_id is not None else _TRACE_ID.get()
    t1 = clock() if end is None else end
    t0 = t1 - max(0.0, dur_s)
    if tid is not None:
        trace.async_pair(name, tid, t0, t1, cat="span", args=attrs or None)
    trace.pair(name, t0, t1, cat="span", args=attrs or None)
    metrics.histogram(
        "lux_span_seconds", {"span": name}, buckets=SPAN_BUCKETS
    ).observe(t1 - t0)
    if tid is not None:
        _note_span(tid, name, t0, t1, attrs)


def open_trace():
    """Explicitly opened trace for callers that cannot scope the request
    in one ``with`` block (Session.submit returns a Future): returns
    ``(trace_id, finish)``; call ``finish()`` when the request resolves.
    Finishing twice (or racing a dropped record) is a no-op."""
    if not enabled():
        return None, lambda: None
    tid = new_trace_id()
    _begin_trace(tid)
    return tid, lambda: _finish_trace(tid)


def activate(trace_id: Optional[str]):
    """Set the ambient trace-id; returns a token for ``deactivate``."""
    return _TRACE_ID.set(trace_id)


def deactivate(token):
    _TRACE_ID.reset(token)
