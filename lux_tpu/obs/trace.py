"""Chrome trace_event writer (JSON-lines), gated by ``LUX_TRACE=<path>``.

Each line is one event object from the Trace Event Format that Perfetto
and chrome://tracing consume. We write JSON-lines rather than the
``{"traceEvents": [...]}`` envelope so a crashed run still leaves a
parseable prefix; ``tools/trace_summary.py --to-chrome`` wraps a file in
the envelope for direct UI loading (Perfetto's JSON importer also accepts
a bare event array).

Timestamps are microseconds of ``time.perf_counter()`` since module
import, so spans recorded retrospectively from perf_counter stamps
(``pair``) land on the same clock as live ``span``/``begin``/``end``
events. Stdlib-only; no jax imports.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from contextlib import contextmanager

from ..utils import flags
from ..utils.locks import make_lock

_EPOCH = time.perf_counter()

_lock = make_lock("obs.trace")
_path = None
_writer = None


def _open_writer(path):
    global _path, _writer
    if _writer is not None:
        try:
            _writer.close()
        except OSError:
            pass
    _writer = None
    _path = path
    if path:
        # Line-buffered so a killed run keeps every completed event.
        _writer = open(path, "w", buffering=1)
        _emit_locked({
            "ph": "M", "name": "process_name", "pid": os.getpid(), "tid": 0,
            "args": {"name": "lux_tpu"},
        })


def reconfigure():
    """Re-read ``LUX_TRACE`` (CLI flags set the env var then call this)."""
    with _lock:
        path = flags.get("LUX_TRACE") or None
        if path != _path or (path and _writer is None):
            _open_writer(path)


def enabled() -> bool:
    return _writer is not None


def _now_us() -> float:
    return (time.perf_counter() - _EPOCH) * 1e6


def _emit_locked(ev: dict):
    if _writer is not None:
        _writer.write(json.dumps(ev, separators=(",", ":")) + "\n")


def _emit(ev: dict):
    with _lock:
        _emit_locked(ev)


def _base(name, cat):
    return {
        "name": name, "cat": cat, "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
    }


def begin(name: str, cat: str = "lux", args: dict = None):
    if _writer is None:
        return
    ev = _base(name, cat)
    ev.update(ph="B", ts=_now_us())
    if args:
        ev["args"] = args
    _emit(ev)


def end(name: str, cat: str = "lux", args: dict = None):
    if _writer is None:
        return
    ev = _base(name, cat)
    ev.update(ph="E", ts=_now_us())
    if args:
        ev["args"] = args
    _emit(ev)


def pair(name: str, t0: float, t1: float, cat: str = "lux", args: dict = None):
    """Record a completed span from two ``time.perf_counter()`` stamps.

    The engines time work with perf_counter and only know the span after a
    host sync returns; this backfills matching B/E events at the stamped
    times instead of the (later) emission time.
    """
    if _writer is None:
        return
    b = _base(name, cat)
    e = dict(b)
    b.update(ph="B", ts=(t0 - _EPOCH) * 1e6)
    if args:
        b["args"] = args
    e.update(ph="E", ts=(t1 - _EPOCH) * 1e6)
    with _lock:
        _emit_locked(b)
        _emit_locked(e)


def async_begin(name: str, id_: str, cat: str = "lux", args: dict = None,
                ts: float = None):
    """Async-span start (ph "b"): events with one ``id`` form a request
    lane in Perfetto regardless of which thread emits them — the serve
    layer keys these by trace-id so one query's admission, batch, engine,
    and cache phases line up even though three threads touch it."""
    if _writer is None:
        return
    ev = _base(name, cat)
    ev.update(ph="b", id=id_, ts=_now_us() if ts is None else ts)
    if args:
        ev["args"] = args
    _emit(ev)


def async_end(name: str, id_: str, cat: str = "lux", args: dict = None,
              ts: float = None):
    """Async-span end (ph "e"); matched to its "b" by (name, cat, id)."""
    if _writer is None:
        return
    ev = _base(name, cat)
    ev.update(ph="e", id=id_, ts=_now_us() if ts is None else ts)
    if args:
        ev["args"] = args
    _emit(ev)


def async_pair(name: str, id_: str, t0: float, t1: float, cat: str = "lux",
               args: dict = None):
    """Retrospective async span from two perf_counter stamps (the
    queue-wait span is only known at dequeue)."""
    if _writer is None:
        return
    async_begin(name, id_, cat, args, ts=(t0 - _EPOCH) * 1e6)
    async_end(name, id_, cat, None, ts=(t1 - _EPOCH) * 1e6)


def counter(name: str, values: dict, cat: str = "lux", ts: float = None):
    """Counter event (ph "C"): Perfetto renders each key of ``values`` as
    a stacked track under ``name``. The engine observatory streams
    per-iteration series this way (exchange/compute seconds, frontier
    density, useful-bytes ratio); ``ts`` is an optional perf_counter
    stamp for retrospective points."""
    if _writer is None:
        return
    ev = _base(name, cat)
    ev.update(ph="C", ts=_now_us() if ts is None else (ts - _EPOCH) * 1e6,
              args={k: v for k, v in values.items()
                    if isinstance(v, (int, float))})
    _emit(ev)


def instant(name: str, cat: str = "lux", args: dict = None):
    if _writer is None:
        return
    ev = _base(name, cat)
    ev.update(ph="i", ts=_now_us(), s="t")
    if args:
        ev["args"] = args
    _emit(ev)


@contextmanager
def span(name: str, cat: str = "lux", **args):
    """Context manager emitting a B/E pair around the block (host-side
    work only — device work must be synced before exit to be credited)."""
    begin(name, cat, args or None)
    try:
        yield
    finally:
        end(name, cat)


def _close():
    with _lock:
        if _writer is not None:
            try:
                _writer.close()
            except OSError:
                pass


atexit.register(_close)

# Honor LUX_TRACE already present at import (env-var-only usage, no CLI).
reconfigure()
