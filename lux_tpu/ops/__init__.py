from lux_tpu.ops.segment import (
    COMBINER_IDENTITY,
    segment_reduce,
    segment_sum_by_rowptr,
)

__all__ = ["segment_reduce", "segment_sum_by_rowptr", "COMBINER_IDENTITY"]
