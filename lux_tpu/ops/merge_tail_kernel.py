"""Two-mode (merge/copy) grouped-tail level kernel and device plan.

Executes a :class:`~lux_tpu.ops.merge_tail_plan.GroupedTailPlan`: one
pass per level over a (rows, 128) f32 stream. Output row o reads ONE
full input row per side — ``arow[o]`` / ``brow[o]`` scalar-prefetched
int32 offsets — and the int8 code plane routes lanes (v >= 0: side-A
lane v; v < 0: side-B lane v & 127). MERGE rows and COPY rows are the
same instruction sequence; a copy row is simply one whose codes are
single-sided (both offsets then point at the same row, so the second
gather is a free duplicate). That uniformity is what lets the
scheduler emit full-rate 128-slot copy rows wherever the merged order
is single-sided instead of stalling at the 64/64 merge rate.

Level 0 is the x2d gather level: ``arow`` is a source-block id into
the (nvb, 128) value operand and every row is a copy row, so one row
gather serves up to 128 tail edges of the block's run.

Two executors with identical semantics:

- :func:`level_apply_ref` — pure ``jax.numpy`` (row gather +
  ``take_along_axis`` + ``where``), used off-TPU so the whole pipeline
  is exact and testable on the CPU tier-1 mesh;
- the Pallas path — derived from the validated probe kernel
  (tools/probe_merge_kernel.py ``k_merge``): grid (S,), (1, 128)
  blocks, ``pltpu.PrefetchScalarGridSpec(num_scalar_prefetch=2)`` with
  per-row dynamic input offsets. (An 8-row-batched variant with
  (8, 128) blocks and block-aligned offsets is the obvious next step
  once row batching lands in the planner output; the per-row form is
  the one the plan contract guarantees today.)

Intermediate pad lanes are never masked — the planner's code planes
only ever address lanes that hold reals (asserted by the host
simulator) — so masking happens once, at the root, before the per-dst
segment reduction.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.ops.merge_tail_plan import GroupedTailPlan
from lux_tpu.ops.segment import segment_sum_by_rowptr
from lux_tpu.utils import flags

BLOCK = 128


def grouped_tail_enabled() -> bool:
    """Opt-in flag for the grouped (merge-network) tail phase."""
    return flags.get_bool("LUX_GROUPED_TAIL")


@dataclasses.dataclass(eq=False)
class DeviceGroupedTail:
    """Device-resident grouped-tail plan (a pytree: jit-traceable).

    ``arow``/``brow``/``codes`` are per-level tuples — level 0 first
    (the x2d gather level), root last. Only the root stream carries a
    validity mask; ``dst_row_ptr`` are final-slot segment boundaries
    for the per-destination reduction.
    """

    arow: Tuple[jnp.ndarray, ...]    # (S_k,) int32 per level
    brow: Tuple[jnp.ndarray, ...]    # (S_k,) int32
    codes: Tuple[jnp.ndarray, ...]   # (S_k, 128) int8
    nvalid_root: jnp.ndarray         # (S_root,) int32
    dst_row_ptr: jnp.ndarray         # (nv+1,) int32 final-slot offsets
    n_levels: int                    # merge levels (excl. level 0)

    @staticmethod
    def build(plan: GroupedTailPlan, device=None) -> "DeviceGroupedTail":
        put = lambda x: jax.device_put(jnp.asarray(x), device)
        nlev = plan.n_levels
        root_rows = int(plan.level_ptr[-1] - plan.level_ptr[-2])
        assert root_rows * BLOCK < 2 ** 31, "root stream exceeds int32 slots"
        arow, brow, codes = [], [], []
        for k in range(nlev + 1):
            a, b, c, nv_, _ = plan.level(k)
            arow.append(put(np.ascontiguousarray(a)))
            brow.append(put(np.ascontiguousarray(b)))
            codes.append(put(np.ascontiguousarray(c)))
        return DeviceGroupedTail(
            arow=tuple(arow), brow=tuple(brow), codes=tuple(codes),
            nvalid_root=put(np.ascontiguousarray(nv_).astype(np.int32)),
            dst_row_ptr=put(
                np.asarray(plan.dst_row_ptr).astype(np.int32)),
            n_levels=nlev,
        )


def level_apply_ref(x, arow, brow, codes):
    """One network level in plain jax.numpy (exact, any backend)."""
    lane = codes.astype(jnp.int32) & 127
    ga = jnp.take_along_axis(x[arow], lane, axis=1)
    gb = jnp.take_along_axis(x[brow], lane, axis=1)
    return jnp.where(codes >= 0, ga, gb)


def _k_level(arow_ref, brow_ref, a_ref, b_ref, c_ref, o_ref):
    v = c_ref[...].astype(jnp.int32)   # int8 bitwise ops don't lower
    lane = v & 127
    ga = jnp.take_along_axis(a_ref[...], lane, axis=1)
    gb = jnp.take_along_axis(b_ref[...], lane, axis=1)
    o_ref[...] = jnp.where(v >= 0, ga, gb)


def level_apply_pallas(x, arow, brow, codes):
    """One network level as a Pallas call with per-row scalar-prefetched
    input offsets (probe-validated pattern)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = codes.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, BLOCK), lambda g, ar, br: (ar[g], 0)),
            pl.BlockSpec((1, BLOCK), lambda g, ar, br: (br[g], 0)),
            pl.BlockSpec((1, BLOCK), lambda g, ar, br: (g, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK), lambda g, ar, br: (g, 0)),
    )
    return pl.pallas_call(
        _k_level,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, BLOCK), jnp.float32),
    )(arow, brow, x, x, codes)


def level_apply(x, arow, brow, codes, use_pallas=None):
    if codes.shape[0] == 0:
        return jnp.zeros((0, BLOCK), x.dtype)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        return level_apply_pallas(x, arow, brow, codes)
    return level_apply_ref(x, arow, brow, codes)


def root_reduce(x, nvalid_root, dst_row_ptr):
    """Mask the root stream's pad lanes (the one masking point in the
    network) and reduce to per-destination sums."""
    live = (jnp.arange(BLOCK, dtype=jnp.int32)[None, :]
            < nvalid_root[:, None])
    flat = jnp.where(live, x, 0.0).reshape(-1)
    return segment_sum_by_rowptr(flat, dst_row_ptr)


def grouped_tail_sums(x2d, gt: DeviceGroupedTail, use_pallas=None):
    """Per-destination sums of tail-edge source values via the merge
    network; (nv,) f32. Drop-in for
    :func:`~lux_tpu.ops.tiled_spmv.lane_select_tail_sums`."""
    x = x2d.astype(jnp.float32)
    for k in range(gt.n_levels + 1):
        x = level_apply(x, gt.arow[k], gt.brow[k], gt.codes[k],
                        use_pallas=use_pallas)
    return root_reduce(x, gt.nvalid_root, gt.dst_row_ptr)


jax.tree_util.register_dataclass(
    DeviceGroupedTail,
    data_fields=["arow", "brow", "codes", "nvalid_root", "dst_row_ptr"],
    meta_fields=["n_levels"],
)
