"""Production planner for the grouped-tail merge network.

Vectorized (numpy) equivalent of the copy-window reference walk in
:mod:`lux_tpu.ops.merge_tail_ref` — the reference is a per-real Python
loop and RMAT22 has 34.4M tail reals, so the planner must never touch
individual reals from Python. The only Python-rate loop left is one
array lookup per OUTPUT ROW (~n/100 iterations) over a fully
precomputed next-cut jump table; everything per-real is numpy.

Pipeline (:func:`plan_grouped_tail`):

1. group tail edges into runs by source block (``tail_sb``) — one
   gathered x2d row then serves up to 128 edges of the run per stream
   row (the whole point of the grouped tail);
2. skew mitigation, measured-best in PERF.md (24-27x -> 1.85x):
   INTERLEAVED splitting of big runs (piece k takes every s-th element
   so every piece spans the full dst range) + size-sorted pairing
   (leaf i of the merge tree is the i-th largest piece, so siblings at
   every level are size-matched);
3. level-0 layout: each leaf dense from an 8-row-aligned base (Mosaic
   block indexing is in whole 8-row units), with sub-8-row remainders
   BIN-PACKED into shared aligned bins — runs become two-segment
   (body + remainder) instead of padding every ~p50=2.2-row run to 8
   rows, which would near-double the stream;
4. per merge level, the copy-window walk (see
   :func:`merge_tail_ref.schedule_grouped` for the contract): output
   row o reads one full input row per side (``arow[o]``/``brow[o]``)
   and closes on 128 reals or an input-row crossing; single-sided rows
   are COPY rows streaming a drained side at full rate.

The result is a :class:`GroupedTailPlan`: per-level int8 routing
planes + int32 scalar-prefetch row-offset arrays, flat-concatenated
with a ``level_ptr`` so the artifact is a handful of arrays that
round-trip through :func:`save_grouped_plan` / :func:`load_grouped_plan`
(same dir-of-npy + meta.json shape as the tiled plan cache).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Dict, Optional, Tuple

import numpy as np

from lux_tpu.ops.merge_tail_ref import BLOCK, _tree_size

ALIGN_ROWS = 8            # Mosaic block granularity (rows)
# Interleaved run splitting is OFF by default: under the copy-window
# contract a dominant side streams at full rate, so size skew is
# nearly free and splitting only adds row-granularity overhead
# (measured on the PERF.md heavy-tail synthetic: no-split 1.11x mean
# inflation vs 1.45x at split_rows=32; geometric sizes 1.01x vs 2.13x).
# The knob remains for distributions where dst-interleaving stalls
# dominate.
DEFAULT_SPLIT_ROWS = 0    # max leaf piece size in 128-slot rows; 0 = off


@dataclasses.dataclass(eq=False)
class GroupedTailPlan:
    """Host-side grouped-tail plan (numpy, internal vertex ids).

    Levels 0..n_levels are concatenated along the row axis; level k
    spans rows ``level_ptr[k]:level_ptr[k+1]``. Level 0 is the x2d
    gather level (``arow`` = source block id, all-copy); levels >= 1
    read the previous level's output stream.
    """

    n_edges: int
    n_levels: int            # merge levels (tree depth), excl. level 0
    arow: np.ndarray         # (S,) int32 per-row side-A input row
    brow: np.ndarray         # (S,) int32 per-row side-B input row
    codes: np.ndarray        # (S, 128) int8 lane routing plane
    nvalid: np.ndarray       # (S,) int32 reals per row (prefix-dense)
    mode: np.ndarray         # (S,) int8 0=merge 1=copy-A 2=copy-B
    level_ptr: np.ndarray    # (n_levels + 2,) int64 row offsets
    dst_row_ptr: np.ndarray  # (nv + 1,) int64 final-slot dst boundaries
    stats: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def level_rows(self) -> np.ndarray:
        return np.diff(self.level_ptr)

    def level(self, k: int) -> Tuple[np.ndarray, ...]:
        s = slice(self.level_ptr[k], self.level_ptr[k + 1])
        return (self.arow[s], self.brow[s], self.codes[s],
                self.nvalid[s], self.mode[s])


# -- skew mitigations --------------------------------------------------

def split_runs_interleaved(run_of, pos_in_run, sizes, max_len: int):
    """Split runs longer than ``max_len`` into interleaved pieces.

    Piece k of a run split s ways takes elements k, k+s, k+2s, ... —
    every piece spans the run's full dst range, which is what makes
    size-sorted pairing effective (dst-RANGE chunks pair into
    disjoint-range siblings that merge sequentially, PERF.md).
    Returns (piece_of, pos_in_piece, piece_sizes); pieces stay
    dst-sorted because they are subsequences.
    """
    sizes = np.asarray(sizes, np.int64)
    nsplit = np.maximum(1, -(-sizes // max(max_len, 1)))   # ceil
    piece_base = np.concatenate([[0], np.cumsum(nsplit)])[:-1]
    s = nsplit[run_of]
    piece_of = piece_base[run_of] + pos_in_run % s
    pos_in_piece = pos_in_run // s
    npieces = int(nsplit.sum())
    piece_sizes = np.zeros(npieces, np.int64)
    np.add.at(piece_sizes, piece_of, 1)
    return piece_of, pos_in_piece, piece_sizes


def pair_runs_sorted(piece_sizes) -> np.ndarray:
    """Tree-leaf assignment: leaf i is the i-th largest piece.

    Descending size order makes siblings size-matched at EVERY level
    (adjacent pairs stay sorted after pairwise summation), which is
    the measured-effective half of the skew mitigation.
    Returns leaf_of_piece (npieces,) int64.
    """
    order = np.argsort(np.asarray(piece_sizes), kind="stable")[::-1]
    leaf_of_piece = np.empty(order.shape[0], np.int64)
    leaf_of_piece[order] = np.arange(order.shape[0])
    return leaf_of_piece


# -- level-0 layout (8-row alignment + remainder bin-packing) ----------

def layout_leaf_streams(leaf_sizes, align_rows: int = ALIGN_ROWS):
    """Slot layout for the leaf streams under the Mosaic alignment rule.

    Every leaf's body (whole multiples of ``align_rows`` rows) sits at
    an aligned base; the sub-``align_rows`` remainder row groups are
    first-fit-decreasing bin-packed into shared aligned bins, making
    small leaves two-segment instead of padding each to a full block.
    Returns (body_base, rem_base, body_rows, total_rows): per-leaf row
    bases (rem_base = -1 when there is no remainder).
    """
    leaf_sizes = np.asarray(leaf_sizes, np.int64)
    rows = -(-leaf_sizes // BLOCK)
    if align_rows <= 1:
        base = np.concatenate([[0], np.cumsum(rows)])
        return base[:-1], np.full(rows.shape[0], -1, np.int64), rows, int(
            base[-1])
    rem = rows % align_rows
    body = rows - rem
    body_base = np.concatenate([[0], np.cumsum(body)])[:-1]
    bins_start = int(body.sum())
    # FFD via capacity stacks: O(n) — remainder sizes are 1..align-1,
    # bins have capacity align_rows.
    rem_base = np.full(rows.shape[0], -1, np.int64)
    open_bins = {c: [] for c in range(1, align_rows + 1)}  # free cap -> bases
    next_bin = bins_start
    for leaf in np.argsort(rem, kind="stable")[::-1]:
        r = int(rem[leaf])
        if r == 0:
            continue
        cap = next(
            (c for c in range(r, align_rows + 1) if open_bins[c]), None)
        if cap is None:
            b = next_bin
            next_bin += align_rows
            cap = align_rows
            open_bins[cap].append(b + align_rows)  # store bin END
        end = open_bins[cap].pop()
        rem_base[leaf] = end - cap
        left = cap - r
        if left:
            open_bins[left].append(end)
    return body_base, rem_base, body, next_bin


def _leaf_slots(pos, leaf_of, body_base, rem_base, body_rows):
    """Per-real level-0 (row, lane) from position-in-leaf."""
    body_slots = body_rows[leaf_of] * BLOCK
    in_body = pos < body_slots
    row = np.where(
        in_body,
        body_base[leaf_of] + pos // BLOCK,
        rem_base[leaf_of] + (pos - body_slots) // BLOCK,
    )
    return row.astype(np.int64), (pos % BLOCK).astype(np.int64)


# -- the vectorized copy-window walk (one merge level) -----------------

def _prev_same_group(group) -> np.ndarray:
    """prev[i] = largest j < i with group[j] == group[i], else -1."""
    n = group.shape[0]
    order = np.argsort(group, kind="stable")
    prev = np.full(n, -1, np.int64)
    same = np.empty(n, bool)
    same[:1] = False
    same[1:] = group[order[1:]] == group[order[:-1]]
    prev[order[1:]] = np.where(same[1:], order[:-1], -1)
    return prev


def walk_level(node, side, row, lane, align_rows: int = 1):
    """Schedule one merge level over reals given in merged order.

    Inputs are per-real arrays in GLOBAL merged order (dst-major,
    leaf tiebreak): ``node`` (this level's node id, non-decreasing
    within the processing groups is NOT required — reals are grouped
    by a stable node sort internally), ``side`` (0=A, 1=B), and the
    real's (row, lane) in the level's input stream. Returns
    (planes, out_row, out_lane) with planes = dict of per-out-row
    arrays and out_row/out_lane the real's placement in the output
    stream (global order).

    Walk contract (identical to merge_tail_ref.schedule_grouped): a
    row closes at 128 reals, at a node boundary, or when the merged
    order needs a real whose input row differs from the row its side
    is reading — computed without a per-real loop via a next-cut jump
    table F where F[c] is the first real whose same-side predecessor
    is >= c on a different input row.
    """
    n = node.shape[0]
    if n == 0:
        planes = {
            "arow": np.zeros(0, np.int32), "brow": np.zeros(0, np.int32),
            "codes": np.zeros((0, BLOCK), np.int8),
            "nvalid": np.zeros(0, np.int32), "mode": np.zeros(0, np.int8),
        }
        return planes, np.zeros(0, np.int64), np.zeros(0, np.int64), 0
    order = np.argsort(node, kind="stable")
    nd, sd, rw, ln = node[order], side[order], row[order], lane[order]

    # Node boundaries (forced cuts) and per-real node end.
    starts = np.concatenate([[0], np.flatnonzero(np.diff(nd)) + 1, [n]])
    node_end = np.repeat(starts[1:], np.diff(starts))

    # marker: taking real i in a chunk that already holds its same-side
    # predecessor would cross an input row.
    prev = _prev_same_group(nd * 2 + sd)
    marked = (prev >= 0) & (rw != np.where(prev >= 0, rw[prev], 0))
    # F[c] = min marked i with prev[i] >= c  (suffix-min over prev).
    g = np.full(n + 1, n, np.int64)
    mi = np.flatnonzero(marked)
    if mi.size:
        np.minimum.at(g, prev[mi], mi)
    f = np.minimum.accumulate(g[::-1])[::-1]

    # Cut loop: one lookup per OUTPUT ROW (the only non-vectorized
    # part; ~n/100 iterations).
    cuts = [0]
    c = 0
    while c < n:
        c = min(c + BLOCK, int(f[c]), int(node_end[c]))
        cuts.append(c)
    cuts = np.asarray(cuts, np.int64)
    nchunks = cuts.shape[0] - 1

    rid = np.searchsorted(cuts, np.arange(n), side="right") - 1
    offset = np.arange(n) - cuts[rid]

    # Per-chunk first real of each side -> arow/brow/mode.
    first = np.full((2, nchunks), n, np.int64)
    for s in (0, 1):
        i = np.flatnonzero(sd == s)
        np.minimum.at(first[s], rid[i], i)
    has_a, has_b = first[0] < n, first[1] < n
    ar = np.where(has_a, rw[np.minimum(first[0], n - 1)], 0)
    br = np.where(has_b, rw[np.minimum(first[1], n - 1)], 0)
    arow_c = np.where(has_a, ar, br)
    brow_c = np.where(has_b, br, ar)
    mode_c = np.where(has_a & has_b, 0, np.where(has_a, 1, 2)).astype(np.int8)

    # Output row ids with per-node alignment (pad rows materialized).
    cn = nd[cuts[:-1]]
    cstarts = np.concatenate([[0], np.flatnonzero(np.diff(cn)) + 1, [nchunks]])
    per_node = np.diff(cstarts)
    if align_rows > 1:
        aligned = -(-per_node // align_rows) * align_rows
    else:
        aligned = per_node
    nbase = np.concatenate([[0], np.cumsum(aligned)])
    local = np.arange(nchunks) - np.repeat(cstarts[:-1], per_node)
    grow = np.repeat(nbase[:-1], per_node) + local
    total_rows = int(nbase[-1])

    planes = {
        "arow": np.zeros(total_rows, np.int32),
        "brow": np.zeros(total_rows, np.int32),
        "codes": np.zeros((total_rows, BLOCK), np.int8),
        "nvalid": np.zeros(total_rows, np.int32),
        "mode": np.zeros(total_rows, np.int8),
    }
    planes["arow"][grow] = arow_c.astype(np.int32)
    planes["brow"][grow] = brow_c.astype(np.int32)
    planes["nvalid"][grow] = np.diff(cuts).astype(np.int32)
    planes["mode"][grow] = mode_c
    planes["codes"][grow[rid], offset] = (ln - BLOCK * sd).astype(np.int8)

    out_row = np.empty(n, np.int64)
    out_lane = np.empty(n, np.int64)
    out_row[order] = grow[rid]
    out_lane[order] = offset
    return planes, out_row, out_lane, total_rows


# -- full network ------------------------------------------------------

def plan_merge_network(dst, leaf, row, lane, nleaves: int,
                       align_rows: int = 1):
    """Schedule all merge levels bottom-up from a leaf-stream layout.

    Per-real inputs must be sorted by (dst, leaf) — the global merged
    order. Returns (levels list of plane dicts, final (row, lane),
    per-level row counts). ``nleaves`` fixes the tree width (padded to
    a power of two, floor 2 — same as the reference).
    """
    R = _tree_size(nleaves)
    L = R.bit_length() - 1
    levels, rows_per_level = [], []
    for lev in range(1, L + 1):
        node = leaf >> lev
        side = (leaf >> (lev - 1)) & 1
        planes, row, lane, total = walk_level(
            node, side, row, lane, align_rows=align_rows)
        levels.append(planes)
        rows_per_level.append(total)
    return levels, row, lane, rows_per_level


def plan_grouped_tail(
    tail_sb, tail_lane, tail_row_ptr, *,
    align_rows: int = ALIGN_ROWS,
    split_rows: int = DEFAULT_SPLIT_ROWS,
) -> GroupedTailPlan:
    """Plan the full grouped tail for one hybrid plan's tail edge set.

    Inputs are the tiled plan's tail arrays (CSC / dst-sorted order,
    internal vertex ids): ``tail_sb`` (M,) source block per edge,
    ``tail_lane`` (M,) source lane, ``tail_row_ptr`` (nv+1,) per-dst
    edge offsets.
    """
    tail_sb = np.asarray(tail_sb, np.int64)
    tail_lane = np.asarray(tail_lane, np.int64) & (BLOCK - 1)
    tail_row_ptr = np.asarray(tail_row_ptr, np.int64)
    m = tail_sb.shape[0]
    nv = tail_row_ptr.shape[0] - 1
    dst = np.repeat(np.arange(nv, dtype=np.int64), np.diff(tail_row_ptr))

    # Runs: edges grouped by source block, dst order preserved (the
    # input is dst-sorted; a stable sb sort keeps it within each run).
    order = np.argsort(tail_sb, kind="stable")
    sb_s, lane_s, dst_s = tail_sb[order], tail_lane[order], dst[order]
    uniq, run_of, counts = np.unique(
        sb_s, return_inverse=True, return_counts=True)
    pos_in_run = np.arange(m) - np.concatenate(
        [[0], np.cumsum(counts)])[:-1][run_of]

    if split_rows > 0:
        piece_of, pos, piece_sizes = split_runs_interleaved(
            run_of, pos_in_run, counts, split_rows * BLOCK)
    else:
        piece_of, pos = run_of, pos_in_run
        piece_sizes = counts.astype(np.int64)
    leaf_of_piece = pair_runs_sorted(piece_sizes)
    leaf = leaf_of_piece[piece_of]
    nleaves = piece_sizes.shape[0]
    R = _tree_size(nleaves)
    leaf_sizes = np.zeros(R, np.int64)
    np.add.at(leaf_sizes, leaf, 1)
    leaf_sb = np.zeros(R, np.int64)
    leaf_sb[leaf] = uniq[run_of]

    body_base, rem_base, body_rows, rows0 = layout_leaf_streams(
        leaf_sizes, align_rows)
    row, lane0 = _leaf_slots(pos, leaf, body_base, rem_base, body_rows)

    # Level-0 plane: one x2d row gather per stream row (all copy-A).
    lv0 = {
        "arow": np.zeros(rows0, np.int32),
        "brow": np.zeros(rows0, np.int32),
        "codes": np.zeros((rows0, BLOCK), np.int8),
        "nvalid": np.zeros(rows0, np.int32),
        "mode": np.zeros(rows0, np.int8),
    }
    lv0["arow"][row] = leaf_sb[leaf].astype(np.int32)
    lv0["brow"][row] = lv0["arow"][row]
    lv0["codes"][row, lane0] = lane_s.astype(np.int8)  # lanes 0..127 >= 0
    np.add.at(lv0["nvalid"], row, 1)
    lv0["mode"][lv0["nvalid"] > 0] = 1
    # Positions are dense within each leaf segment, so every level-0
    # row is prefix-dense like the merge levels: nvalid doubles as the
    # live-lane count.

    # Global merged order for the network: (dst, leaf), stable in pos.
    g = np.argsort(leaf + dst_s * R, kind="stable")
    levels, frow, flane, rows_per_level = plan_merge_network(
        dst_s[g], leaf[g], row[g], lane0[g], nleaves,
        align_rows=align_rows)

    # Final-slot dst boundaries (pads between segments are masked to
    # zero on device, so closed ranges are safe to sum).
    final_slot = frow * BLOCK + flane
    rows_root = rows_per_level[-1] if rows_per_level else 0
    if m:
        idx = np.searchsorted(dst_s[g], np.arange(nv + 1))
        dst_row_ptr = np.where(
            idx < m, final_slot[np.minimum(idx, m - 1)],
            rows_root * BLOCK).astype(np.int64)
    else:
        dst_row_ptr = np.zeros(nv + 1, np.int64)

    all_levels = [lv0] + levels
    level_ptr = np.concatenate(
        [[0], np.cumsum([lv["arow"].shape[0] for lv in all_levels])]
    ).astype(np.int64)
    cat = {
        k: (np.concatenate([lv[k] for lv in all_levels])
            if level_ptr[-1] else all_levels[0][k])
        for k in ("arow", "brow", "codes", "nvalid", "mode")
    }
    n_levels = len(levels)

    rows = np.diff(level_ptr).astype(np.float64)
    ideal = max(m, 1) / BLOCK
    per_level_inflation = rows / ideal
    stats = {
        "n_edges": float(m),
        "n_levels": float(n_levels),
        "n_runs": float(uniq.shape[0]),
        "n_leaves": float(nleaves),
        "mean_inflation": float(per_level_inflation.mean())
        if rows.size else 0.0,
        "max_level_inflation": float(per_level_inflation.max())
        if rows.size else 0.0,
        "root_inflation": float(per_level_inflation[-1])
        if rows.size else 0.0,
        "copy_rows": float(np.count_nonzero(cat["mode"] > 0)),
        "merge_rows": float(
            np.count_nonzero((cat["mode"] == 0) & (cat["nvalid"] > 0))),
        "pad_rows": float(np.count_nonzero(cat["nvalid"] == 0)),
        "total_rows": float(level_ptr[-1]),
    }
    return GroupedTailPlan(
        n_edges=m, n_levels=n_levels,
        arow=cat["arow"], brow=cat["brow"], codes=cat["codes"],
        nvalid=cat["nvalid"], mode=cat["mode"],
        level_ptr=level_ptr, dst_row_ptr=dst_row_ptr, stats=stats,
    )


# -- plan cache (same dir-of-npy + meta.json shape as save_plan) -------

_PLAN_ARRAYS = (
    "arow", "brow", "codes", "nvalid", "mode", "level_ptr", "dst_row_ptr",
)
_FORMAT = 1

# Public artifact-format contract: analysis/planck.py carries a jax-free
# mirror of these so `luxlint --plans` never imports this package;
# test_ir.py asserts the mirror and this source of truth stay identical.
PLAN_ARRAYS = _PLAN_ARRAYS
PLAN_FORMAT = _FORMAT


def save_grouped_plan(path: str, plan: GroupedTailPlan) -> None:
    """Write the plan as a directory of raw .npy files + meta.json,
    built in a temp dir and renamed into place (a partially-written
    cache must never be loadable)."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".gtail_plan_", dir=parent)
    try:
        for name in _PLAN_ARRAYS:
            np.save(os.path.join(tmp, name + ".npy"),
                    getattr(plan, name), allow_pickle=False)
        meta = {
            "format": _FORMAT,
            "n_edges": int(plan.n_edges),
            "n_levels": int(plan.n_levels),
            "stats": plan.stats,
        }
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump(meta, fh)
        if os.path.isdir(path):
            import shutil
            shutil.rmtree(path)
        os.rename(tmp, path)
    except BaseException:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_grouped_plan(path: str, mmap: bool = True) -> GroupedTailPlan:
    with open(os.path.join(path, "meta.json")) as fh:
        meta = json.load(fh)
    if meta.get("format") != _FORMAT:
        raise ValueError(
            f"grouped plan {path}: unknown format {meta.get('format')}")
    arrs = {
        name: np.load(os.path.join(path, name + ".npy"),
                      mmap_mode="r" if mmap else None)
        for name in _PLAN_ARRAYS
    }
    return GroupedTailPlan(
        n_edges=int(meta["n_edges"]), n_levels=int(meta["n_levels"]),
        stats=dict(meta.get("stats", {})), **arrs,
    )
